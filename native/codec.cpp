// fedtpu native codec — host-side kernels for the DCN-edge wire path.
//
// The reference's only "native" muscle is in its dependencies (gRPC C-core,
// protobuf, ATen — SURVEY §2c); its own compression is transport gzip over
// base64 (src/server.py:104-107). fedtpu's edge codec instead ships sparse
// top-k / int8 payloads; the selection and packing below are the host-side
// hot loops (the on-device path uses Pallas kernels, fedtpu/ops/pallas_kernels.py).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)
// ABI: plain C, loaded via ctypes (no pybind11 in this environment).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// k-th largest |x| over n elements (k >= 1): the keep-threshold for top-k
// sparsification. O(n) average via nth_element, vs O(n log n) for a sort.
float fedtpu_kth_magnitude(const float* x, int64_t n, int64_t k) {
  if (n <= 0) return 0.0f;
  if (k < 1) k = 1;
  if (k > n) k = n;
  std::vector<float> mag(n);
  for (int64_t i = 0; i < n; ++i) mag[i] = std::fabs(x[i]);
  std::nth_element(mag.begin(), mag.begin() + (k - 1), mag.end(),
                   std::greater<float>());
  return mag[k - 1];
}

// Pack entries with |x| >= thresh into (idx, vals); returns count written
// (capped at cap). Single pass, branch-light.
int64_t fedtpu_pack_sparse(const float* x, int64_t n, float thresh,
                           int32_t* idx, float* vals, int64_t cap) {
  int64_t m = 0;
  for (int64_t i = 0; i < n && m < cap; ++i) {
    float v = x[i];
    if (std::fabs(v) >= thresh) {
      idx[m] = static_cast<int32_t>(i);
      vals[m] = v;
      ++m;
    }
  }
  return m;
}

// Scatter (idx, vals) into out[n]; out must be zero-initialised by caller.
void fedtpu_unpack_sparse(const int32_t* idx, const float* vals, int64_t nnz,
                          float* out) {
  for (int64_t i = 0; i < nnz; ++i) out[idx[i]] = vals[i];
}

// Symmetric int8 quantisation: round(x / scale) clamped to [-127, 127].
// scale == 0 (all-zero input) yields all-zero codes.
void fedtpu_quant_int8(const float* x, int64_t n, float scale, int8_t* out) {
  if (scale <= 0.0f) {
    std::memset(out, 0, static_cast<size_t>(n));
    return;
  }
  const float inv = 1.0f / scale;
  for (int64_t i = 0; i < n; ++i) {
    float q = std::nearbyint(x[i] * inv);
    q = q > 127.0f ? 127.0f : (q < -127.0f ? -127.0f : q);
    out[i] = static_cast<int8_t>(q);
  }
}

void fedtpu_dequant_int8(const int8_t* x, int64_t n, float scale, float* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = scale * static_cast<float>(x[i]);
}

// Fused residual update for error feedback on the edge: given the dense
// delta d and threshold t, write kept entries to (idx, vals) and the dropped
// mass to residual (residual[i] = d[i] where |d[i]| < t, else 0).
int64_t fedtpu_pack_sparse_with_residual(const float* d, int64_t n,
                                         float thresh, int32_t* idx,
                                         float* vals, int64_t cap,
                                         float* residual) {
  int64_t m = 0;
  for (int64_t i = 0; i < n; ++i) {
    float v = d[i];
    if (std::fabs(v) >= thresh && m < cap) {
      idx[m] = static_cast<int32_t>(i);
      vals[m] = v;
      residual[i] = 0.0f;
      ++m;
    } else {
      residual[i] = v;
    }
  }
  return m;
}

}  // extern "C"
