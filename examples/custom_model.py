"""Register a custom flax model and federate it.

Any ``flax.linen.Module`` whose ``__call__(x, train=...)`` returns logits
can join the zoo via ``fedtpu.models.register`` and then be selected by name
in ``RoundConfig.model`` — the same extension point the reference lacks (its
architecture is hardcoded in two places, ``src/main.py:69`` and
``src/server.py:158``).

    python examples/custom_model.py
"""

import sys

sys.path.insert(0, ".")

import flax.linen as nn

from fedtpu import DataConfig, FedConfig, Federation, OptimizerConfig, RoundConfig
from fedtpu.models import register


@register("tinynet")
class TinyNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(16, (3, 3), padding=1)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


def main():
    if "--tpu" not in sys.argv:
        # CPU by default: a wedged remote TPU backend would otherwise hang
        # this demo at the first device query.
        import jax

        jax.config.update("jax_platforms", "cpu")
    cfg = RoundConfig(
        model="tinynet",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05),
        data=DataConfig(dataset="synthetic", batch_size=16, num_examples=512,
                        partition="iid"),
        fed=FedConfig(num_clients=4),
        steps_per_round=4,
    )
    fed = Federation(cfg, seed=0)
    for r in range(5):
        m = fed.step()
        print(f"round {r}: loss={float(m.loss):.4f} acc={float(m.accuracy):.4f}")


if __name__ == "__main__":
    main()
