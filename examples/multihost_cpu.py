#!/usr/bin/env python
"""Two-process multi-controller smoke: the REAL ``jax.distributed`` path.

Run one copy of this per "host" (here: local processes standing in for TPU
hosts; on a real slice each host runs the same program and the coordinator
address comes from the environment):

    python examples/multihost_cpu.py --process-id 0 --port 29500 &
    python examples/multihost_cpu.py --process-id 1 --port 29500

Each process brings up 4 virtual CPU devices, joins the 2-process cluster via
``fedtpu.parallel.multihost.initialize`` (the exact call a pod deployment
makes), builds one global 8-device ``clients`` mesh, and executes one full
sharded federated round — cross-process FedAvg psum included. This is the
CPU stand-in for the reference's multi-machine launch matrix
(``README.md:6-17``), with collectives instead of gRPC.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Platform pinning must precede any jax backend initialisation: the
# environment's TPU plugin ignores JAX_PLATFORMS (tests/conftest.py).
from fedtpu.utils.platform import force_host_device_count  # noqa: E402

force_host_device_count(4)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig  # noqa: E402
from fedtpu import models  # noqa: E402
from fedtpu.core import round as round_lib  # noqa: E402
from fedtpu.parallel import (  # noqa: E402
    client_mesh,
    make_sharded_round_step,
    multihost,
    shard_batch,
    shard_state,
)

NUM_PROCESSES = 2
NUM_CLIENTS = 8


def run_engine(args, n_dev):
    """Drive the high-level engine across both processes: Federation with a
    global mesh — per-client state and assignment sharded, dataset
    replicated, the on-device gather + psum FedAvg in one shard_map program
    per round. Every host executes the same code; only process 0 would do
    IO in a real deployment (multihost.is_coordinator)."""
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=2,
    )
    fed = Federation(cfg, seed=0, mesh=client_mesh(axis_name=cfg.mesh_axis))
    losses = []
    for _ in range(3):
        m = fed.step()
        losses.append(round(float(m.loss), 6))
    assert int(m.num_active) == NUM_CLIENTS
    assert losses[-1] < losses[0], losses
    # The fused multi-round scan over the SAME multi-controller mesh: 2 more
    # rounds as one shard_map program, per-round psum over both processes.
    stacked = fed.run_on_device(2)
    fused = [round(float(stacked.loss[i]), 6) for i in range(2)]
    assert int(fed.state.round_idx) == 5
    assert fused[-1] <= losses[-1] + 1e-6, (losses, fused)
    print(
        f"multihost engine ok: process {args.process_id}/{NUM_PROCESSES}, "
        f"{n_dev} global devices, losses={losses}, fused={fused}",
        flush=True,
    )


def run_loss_sampling(args, n_dev):
    """Loss-proportional participation sampling across two controllers: the
    per-client loss vector is sharded by process, so each controller
    allgathers the full vector and the round-seeded draw must yield the
    SAME mask on every host — the property that makes the feature
    multihost-safe (engine._alive_for_round)."""
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="iid",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=NUM_CLIENTS, participation_fraction=0.5,
                      participation_sampling="loss"),
        steps_per_round=2,
    )
    fed = Federation(cfg, seed=0, mesh=client_mesh(axis_name=cfg.mesh_axis))
    masks = []
    for r in range(4):
        m = fed.step()
        # Round 0 samples uniformly (no loss observed yet); later rounds
        # weight by the allgathered loss vector.
        assert int(m.num_active) == NUM_CLIENTS // 2
        masks.append("".join(
            "1" if v else "0" for v in fed._alive_for_round(r + 1)))
    print(
        f"multihost loss-sampling ok: process {args.process_id}, "
        f"{n_dev} global devices, masks={masks}",
        flush=True,
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--port", type=int, default=29500)
    p.add_argument("--engine", action="store_true",
                   help="drive Federation(mesh=...) instead of the raw "
                   "sharded round step")
    p.add_argument("--loss-sampling", action="store_true",
                   help="drive loss-proportional participation sampling "
                   "across both controllers (allgathered loss vector, "
                   "deterministic shared mask)")
    p.add_argument("--all", action="store_true",
                   help="run all three legs (raw round, engine, "
                   "loss-sampling) in one process pair")
    args = p.parse_args()

    multihost.initialize(
        f"localhost:{args.port}",
        num_processes=NUM_PROCESSES,
        process_id=args.process_id,
    )
    assert jax.process_count() == NUM_PROCESSES, jax.process_count()
    n_dev = len(jax.devices())
    assert n_dev == 4 * NUM_PROCESSES, n_dev
    if args.all:
        # Checked FIRST so --all always means all three legs, even combined
        # with a single-leg flag. One process pair covers everything (each
        # spawn costs ~20 s of jax import + gloo bring-up per process on
        # this 1-core host).
        run_raw(args, n_dev)
        run_engine(args, n_dev)
        return run_loss_sampling(args, n_dev)
    if args.engine:
        return run_engine(args, n_dev)
    if args.loss_sampling:
        return run_loss_sampling(args, n_dev)
    return run_raw(args, n_dev)


def run_raw(args, n_dev):
    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=4),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=2,
    )
    mdl = models.create(cfg.model, num_classes=cfg.num_classes)
    # Same seed on every host -> identical host-global state/data, of which
    # each process materialises only its local devices' shards.
    state = round_lib.init_state(
        mdl, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.float32)
    )
    rng = np.random.default_rng(0)
    n, s, b = NUM_CLIENTS, cfg.steps_per_round, cfg.data.batch_size
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 16, 16, 3)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 10, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool),
    )

    mesh = client_mesh(axis_name=cfg.mesh_axis)  # spans BOTH processes
    local = multihost.local_client_slice(NUM_CLIENTS)
    assert (local.stop - local.start) == NUM_CLIENTS // NUM_PROCESSES

    step = make_sharded_round_step(mdl, cfg, mesh, donate=False)
    new_state, metrics = step(
        shard_state(state, mesh, cfg.mesh_axis),
        shard_batch(batch, mesh, cfg.mesh_axis),
    )
    jax.block_until_ready(new_state)
    assert int(metrics.num_active) == NUM_CLIENTS
    print(
        f"multihost ok: process {args.process_id}/{NUM_PROCESSES}, "
        f"{n_dev} global devices, {NUM_CLIENTS} clients, "
        f"loss={float(metrics.loss):.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
