#!/usr/bin/env bash
# The reference's full process topology on localhost: backup + primary +
# two client agents over gRPC (README.md of the reference, its de facto
# integration test), with compressed sparse-delta updates and per-round
# checkpointing. Everything shuts down when the primary finishes.
set -euo pipefail
cd "$(dirname "$0")/.."

COMMON="--model mlp --dataset synthetic --num-examples 512 --batch-size 16 --lr 0.05 -c Y"

python -m fedtpu.cli.client -a localhost:50051 $COMMON --seed 1 &
C1=$!
python -m fedtpu.cli.client -a localhost:50052 $COMMON --seed 2 &
C2=$!
python -m fedtpu.cli.server $COMMON --listen localhost:50060 &
B=$!
trap 'kill $C1 $C2 $B 2>/dev/null || true' EXIT

echo "waiting for agents to come up..."
sleep 20

python -m fedtpu.cli.server --p y $COMMON --rounds 5 \
    --clients localhost:50051,localhost:50052 \
    --backupAddress localhost --backupPort 50060 \
    --checkpoint-dir ./checkpoint/demo
