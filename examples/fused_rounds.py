#!/usr/bin/env python
"""The fused multi-round scan — the feature behind the headline bench.

The reference pays a full host round-trip per round: thread fan-out,
blocking RPCs, checkpoint files (``src/server.py:120-153``). fedtpu's
``Federation.run_on_device(R)`` runs R COMPLETE FedAvg rounds as ONE XLA
program (``lax.scan`` over the round body — per-round batch extraction from
the HBM-resident presharded dataset, vmapped local SGD, aggregation), with
per-round metrics coming back stacked. On the round-4 live TPU v5e this is
what measured 597.6 client-epochs/sec/chip (2.99x the 200/s north star,
``artifacts/BENCH_LIVE_r04_bf16.json``).

Runs on 8 virtual CPU devices so the mesh path is shown too:

    python examples/fused_rounds.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedtpu.utils.platform import force_host_device_count

force_host_device_count(8)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from fedtpu import DataConfig, FedConfig, Federation, OptimizerConfig, RoundConfig
from fedtpu.parallel import client_mesh

cfg = RoundConfig(
    model="mlp",  # seconds-scale XLA:CPU compile; the bench runs smallcnn
    num_classes=10,
    opt=OptimizerConfig(learning_rate=0.05),
    data=DataConfig(dataset="cifar10", batch_size=16, partition="iid",
                    num_examples=2048),
    fed=FedConfig(num_clients=16),
    steps_per_round=4,
    dtype="bfloat16",  # device dataset is stored bf16 too (bit-identical)
)

# Single-program path: 10 rounds, one dispatch.
fed = Federation(cfg, seed=0)
metrics = fed.run_on_device(10)
print("single-program fused 10 rounds:")
print("  per-round loss:", np.round(np.asarray(metrics.loss), 3))
print("  per-round acc :", np.round(np.asarray(metrics.accuracy), 3))

# Mesh path: same program under shard_map over a clients mesh — state and
# presharded data shard by client, FedAvg becomes one psum per round over
# the mesh axis. On real hardware the axis spans chips over ICI.
fed_mesh = Federation(cfg, seed=0, mesh=client_mesh(8, cfg.mesh_axis))
m2 = fed_mesh.run_on_device(10)
print(f"mesh (8 devices) fused 10 rounds: final loss "
      f"{float(m2.loss[-1]):.4f}, final acc {float(m2.accuracy[-1]):.4f}")

# The two paths are the same math: sequential stepping and the fused scan
# are test-pinned equal, and the sharded program is bit-parity tested
# against the single-program one (tests/test_sharded.py).
