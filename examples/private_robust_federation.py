#!/usr/bin/env python
"""Capabilities beyond the reference, composed from the library API.

Three short runs on the same synthetic federated workload:

  1. FedAvgM (server momentum) — the FedOpt family.
  2. Coordinate-wise median aggregation with one adversarial client — the
     poisoned update does not capture the global model.
  3. DP-FedAvg (per-client clipping + seeded server noise) — uniform
     weighting, BatchNorm-free model, as the guards require.

Run: ``python examples/private_robust_federation.py`` (CPU-safe: pins the
platform before any backend query).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation


def base_cfg(**fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.03, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=16, partition="dirichlet",
            num_examples=1024,
        ),
        fed=FedConfig(num_clients=8, **fed_kw),
        steps_per_round=4,
    )


def run(tag, cfg, rounds=8, data=None):
    fed = Federation(cfg, seed=0, data=data)
    fed.run_on_device(rounds)  # one XLA program for the whole run
    # Judge the GLOBAL model, not the per-client training loss — a poisoned
    # client's own diverged loss pollutes the train metric either way; what
    # the aggregator protects is the model everyone receives.
    from fedtpu.data import load

    test_loss, test_acc = fed.evaluate(*load("synthetic", "test", num=512))
    finite = all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in jax.tree_util.tree_leaves(fed.state.params)
    )
    print(f"{tag:28s} test_acc {test_acc:.3f}  params_finite={finite}")
    return fed


# 1. Server momentum (FedAvgM).
run("fedavgm(server_lr=0.7)",
    base_cfg(server_optimizer="momentum", server_lr=0.7))

# 2. Median aggregation vs a poisoned client.
cfg = base_cfg(aggregator="median")
probe = Federation(cfg, seed=0)
imgs = np.asarray(probe.images).copy()
labels = np.asarray(probe.labels).copy()
own = probe.client_idx[0][probe.client_mask[0]]
imgs[own] *= 100.0  # client 0 ships garbage
run("median w/ poisoned client", cfg, data=(imgs, labels))
run("mean   w/ poisoned client", base_cfg(), data=(imgs, labels))

# 3. DP-FedAvg.
run("dp(clip=0.1, sigma=0.3)",
    base_cfg(weighted=False, dp_clip_norm=0.1, dp_noise_multiplier=0.3))
