"""64-client FedAvg on CIFAR-10, all simulated in one XLA program.

The TPU-native deployment mode: clients are an array axis, the whole round
(local SGD for every client + weighted aggregation) is one jitted step.

    python examples/simulate_fedavg.py            # full run
    python examples/simulate_fedavg.py --smoke    # 30-second CPU check
"""

import argparse
import sys
import time

sys.path.insert(0, ".")

from fedtpu import DataConfig, FedConfig, Federation, OptimizerConfig, RoundConfig
from fedtpu.data import load


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true")
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="pin the jax platform (--smoke implies cpu); without a pin a "
        "wedged remote TPU backend can hang the process",
    )
    args = p.parse_args()
    if args.platform or args.smoke:
        import jax

        jax.config.update("jax_platforms", args.platform or "cpu")

    cfg = RoundConfig(
        model="smallcnn" if args.smoke else "MobileNet",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.1),
        data=DataConfig(
            dataset="cifar10",
            batch_size=32 if args.smoke else 128,
            partition="dirichlet",
            num_examples=2048 if args.smoke else None,
        ),
        fed=FedConfig(num_clients=8 if args.smoke else 64),
        steps_per_round=2 if args.smoke else 6,
    )
    fed = Federation(cfg, seed=0)
    test = load("cifar10", "test", num=cfg.data.num_examples)

    rounds = 3 if args.smoke else 20
    for r in range(rounds):
        t0 = time.time()
        m = fed.step()
        print(
            f"round {r}: loss={float(m.loss):.4f} acc={float(m.accuracy):.4f} "
            f"({time.time() - t0:.2f}s)"
        )
    print("test (loss, acc):", fed.evaluate(*test))


if __name__ == "__main__":
    main()
