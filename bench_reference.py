#!/usr/bin/env python
"""Reference-semantics gRPC/torch baseline for the BASELINE.md parity table.

The reference publishes no numbers and cannot run unmodified in this
environment (no torchvision, no multipledispatch, no network for the CIFAR
download), so this harness re-creates its measured path faithfully — written
from scratch, behavior cited to the reference — and measures it on CPU:

- federated clients are gRPC servers hosting a Trainer servicer
  (``src/client.py:38-52``); the federated server dials out and pushes work
  (``src/server.py:113-153``);
- StartTrain runs one local epoch of torch SGD(momentum=0.9, wd=5e-4) over
  the client's round-robin batch shard — batch ``i`` kept iff
  ``(i + 1) % world == rank`` (``src/main.py:140-151``);
- ALL model movement is pickle->disk->base64->proto-string
  (``src/client.py:19-29``, ``src/server.py:55-58``): the checkpoint file IS
  the message, with the 33% base64 inflation;
- aggregation loads every client's checkpoint into a fresh model and
  averages state_dicts uniformly on the host (``src/server.py:155-179``);
- ``-c Y`` is transport-level gzip (``src/server.py:104-107``).

The wire protocol reuses :mod:`fedtpu.transport` (hand-rolled codec that is
wire-compatible with the reference's ``federated.proto``). Client processes
are packed into one subprocess (N servicers on N ports): this host has ONE
core, so process-per-client buys no parallelism and the packing only removes
redundant interpreter overhead — favoring the baseline.

Configs mirror ``bench_parity.py --cpu-scale`` exactly (same model family,
dataset, partition rule, client count, 64 examples/client, batch 32), so the
two outputs are same-host same-workload columns of the parity table. The
reference has no FedProx and no top-k compression; config 3 falls back to
its plain FedAvg and config 5 to gzip (its actual ``-c Y``), as noted in the
emitted JSON. The reference's per-broadcast client evaluation
(``src/client.py:30``: every SendModel triggers a full test pass) is
OMITTED here — another concession in the baseline's favor.

One JSON line per config.
"""

import argparse
import base64
import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# ----------------------------------------------------------------- models
# Torch twins of the fedtpu parity models (fedtpu/models/{mlp,smallcnn}.py)
# so both columns train the same architecture.
TORCH_MODELS = """
import torch
import torch.nn as nn
import torch.nn.functional as F


class TorchMLP(nn.Module):
    def __init__(self, num_classes=10, in_features=784, hidden=256):
        super().__init__()
        self.fc1 = nn.Linear(in_features, hidden)
        self.fc2 = nn.Linear(hidden, num_classes)

    def forward(self, x):
        x = x.reshape(x.size(0), -1)
        return self.fc2(F.relu(self.fc1(x)))


class TorchSmallCNN(nn.Module):
    def __init__(self, num_classes=10, in_ch=3, spatial=32):
        super().__init__()
        self.c1 = nn.Conv2d(in_ch, 32, 3, padding=1)
        self.c2 = nn.Conv2d(32, 64, 3, padding=1)
        self.fc1 = nn.Linear(64 * (spatial // 4) * (spatial // 4), 128)
        self.fc2 = nn.Linear(128, num_classes)

    def forward(self, x):
        x = F.max_pool2d(F.relu(self.c1(x)), 2)
        x = F.max_pool2d(F.relu(self.c2(x)), 2)
        x = x.reshape(x.size(0), -1)
        return self.fc2(F.relu(self.fc1(x)))


class TorchBasicBlock(nn.Module):
    def __init__(self, in_ch, ch, stride=1):
        super().__init__()
        self.c1 = nn.Conv2d(in_ch, ch, 3, stride=stride, padding=1, bias=False)
        self.b1 = nn.BatchNorm2d(ch)
        self.c2 = nn.Conv2d(ch, ch, 3, padding=1, bias=False)
        self.b2 = nn.BatchNorm2d(ch)
        self.short = None
        if stride != 1 or in_ch != ch:
            self.short = nn.Sequential(
                nn.Conv2d(in_ch, ch, 1, stride=stride, bias=False),
                nn.BatchNorm2d(ch),
            )

    def forward(self, x):
        r = x if self.short is None else self.short(x)
        y = F.relu(self.b1(self.c1(x)))
        y = self.b2(self.c2(y))
        return F.relu(y + r)


class TorchResNet18(nn.Module):
    # CIFAR-style ResNet-18, the torch twin of fedtpu/models/resnet.py
    # (3x3/64 stem, BasicBlock stages (64,128,256,512)x2, strides 1/2/2/2,
    # global average pool + dense head).
    def __init__(self, num_classes=10, in_ch=3):
        super().__init__()
        self.stem = nn.Conv2d(in_ch, 64, 3, padding=1, bias=False)
        self.bn = nn.BatchNorm2d(64)
        layers = []
        c_in = 64
        for stage, ch in enumerate((64, 128, 256, 512)):
            for i in range(2):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                layers.append(TorchBasicBlock(c_in, ch, stride))
                c_in = ch
        self.blocks = nn.Sequential(*layers)
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = F.relu(self.bn(self.stem(x)))
        x = self.blocks(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def build_model(spec):
    if spec["model"] == "mlp":
        shape = spec["input_shape"]
        feat = shape[0] * shape[1] * shape[2]
        return TorchMLP(spec["num_classes"], in_features=feat)
    if spec["model"] == "resnet18":
        return TorchResNet18(spec["num_classes"], in_ch=spec["input_shape"][2])
    return TorchSmallCNN(
        spec["num_classes"], in_ch=spec["input_shape"][2],
        spatial=spec["input_shape"][0],
    )
"""

# ------------------------------------------------------------ client side
# Runs in a separate process: N Trainer servicers on N ports, one shared
# dataset, per-client checkpoint file + persistent optimizer (the reference
# keeps its optimizer as a module global across StartTrain calls,
# src/main.py:99,130-134).
CLIENT_MAIN = TORCH_MODELS + """
import base64, io, json, os, sys, threading
import numpy as np
import torch
import torch.nn.functional as F

sys.path.insert(0, REPO)
from fedtpu.transport import proto, service


def batches(x, y, batch):
    n = x.shape[0] // batch
    for i in range(n):
        yield i, x[i * batch:(i + 1) * batch], y[i * batch:(i + 1) * batch]


class ClientTrainer(service.TrainerServicer):
    def __init__(self, spec, x, y, ckpt_path):
        self.spec, self.x, self.y, self.ckpt = spec, x, y, ckpt_path
        self.net = build_model(spec)
        self.opt = torch.optim.SGD(
            self.net.parameters(), lr=spec["lr"], momentum=0.9,
            weight_decay=5e-4,
        )
        # Seed round 0, like the reference's init-checkpoint loop
        # (src/main.py:231-239).
        torch.save({"net": self.net.state_dict()}, self.ckpt)

    def StartTrain(self, request, context):
        # Reload the global model, keep the optimizer (src/main.py:130-134).
        self.net.load_state_dict(torch.load(self.ckpt)["net"])
        self.net.train()
        # local_epochs > 1 repeats the epoch loop (parity config 4; the
        # fedtpu engine folds epochs into steps the same way).
        per_client = self.spec.get("per_client", False)
        for _ in range(self.spec["local_epochs"]):
            count = 0
            for i, bx, by in batches(self.x, self.y, self.spec["batch"]):
                if not per_client:
                    count = (count + 1) % request.world
                    if count != request.rank:
                        continue  # round-robin rule, src/main.py:141-144
                # per_client mode: self.x IS this client's engine-identical
                # shard (iid/dirichlet) — train every batch of it.
                self.opt.zero_grad()
                loss = F.cross_entropy(self.net(bx), by)
                loss.backward()
                self.opt.step()
        torch.save({"net": self.net.state_dict()}, self.ckpt)
        with open(self.ckpt, "rb") as fh:  # file -> base64 -> proto string
            payload = base64.b64encode(fh.read())  # bytes; proto3 wire-identical to string
        return proto.TrainReply(message=payload)

    def SendModel(self, request, context):
        with open(self.ckpt, "wb") as fh:
            fh.write(base64.b64decode(request.model))
        return proto.SendModelReply(reply=b"ok")

    def HeartBeat(self, request, context):
        return proto.HeartBeatResponse(status=1)


def main():
    spec = json.loads(sys.argv[1])
    data = np.load(spec["data_file"])
    x = torch.from_numpy(data["x"].transpose(0, 3, 1, 2).copy())  # NHWC->NCHW
    y = torch.from_numpy(data["y"].astype(np.int64))
    torch.manual_seed(0)
    servers = []
    for i, addr in enumerate(spec["addresses"]):
        if spec.get("per_client", False):
            own = torch.from_numpy(data[f"shard_{i}"].astype(np.int64))
            cx, cy = x[own], y[own]
        else:
            cx, cy = x, y
        t = ClientTrainer(spec, cx, cy, os.path.join(spec["dir"], f"client_{i}.pth"))
        srv = service.create_server(
            addr, t, compress=spec["gzip"], max_workers=2
        )
        srv.start()
        servers.append(srv)
    print("READY", flush=True)
    for s in servers:
        s.wait_for_termination()


main()
"""


def _server_round(stubs, world, workdir, proto, build, spec):
    """One synchronous round, reference mechanics (src/server.py:120-153)."""
    import torch

    replies = [None] * world

    def train_one(rank, stub):
        try:
            replies[rank] = stub.StartTrain(
                proto.TrainRequest(rank=rank, world=world)
            )
        except Exception as e:  # surfaced after the join barrier
            replies[rank] = e

    threads = [
        threading.Thread(target=train_one, args=(i, s)) for i, s in enumerate(stubs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, r in enumerate(replies):
        if isinstance(r, Exception) or r is None:
            raise RuntimeError(f"client {i} StartTrain failed: {r!r}")

    # Decode each reply to Primary/test_<rank>.pth (src/server.py:55-58).
    for i, r in enumerate(replies):
        with open(os.path.join(workdir, f"test_{i}.pth"), "wb") as fh:
            fh.write(base64.b64decode(r.message))

    # allreduce(): fresh model per client, uniform keywise mean
    # (src/server.py:155-179).
    states = []
    for i in range(world):
        m = build(spec)
        m.load_state_dict(
            torch.load(os.path.join(workdir, f"test_{i}.pth"))["net"]
        )
        states.append(m.state_dict())
    avg = {k: sum(s[k] for s in states) / float(world) for k in states[0]}
    opt_path = os.path.join(workdir, "optimizedModel.pth")
    torch.save({"net": avg}, opt_path)

    # Broadcast (src/server.py:144-153).
    with open(opt_path, "rb") as fh:
        payload = base64.b64encode(fh.read())

    errs = [None] * world

    def send_one(rank, stub):
        try:
            stub.SendModel(proto.SendModelRequest(model=payload))
        except Exception as e:
            errs[rank] = e

    threads = [
        threading.Thread(target=send_one, args=(i, s)) for i, s in enumerate(stubs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, e in enumerate(errs):
        if e is not None:
            raise RuntimeError(f"client {i} SendModel failed: {e!r}")
    return avg


def run_config(name, parity_cfg, note="", curve_out=None,
               engine_partition=False):
    """``curve_out``: open file — appends one JSON line per round with the
    global model's test accuracy (the per-round eval parity surface,
    ``src/main.py:167-191``), for convergence-overlay artifacts.
    ``engine_partition``: give each torch client the engine-identical
    iid/dirichlet shard instead of the reference's round-robin rank rule
    (accuracy-parity mode — identical data distributions both sides)."""
    import numpy as np
    import torch
    import torch.nn.functional as F

    from fedtpu.data import load
    from fedtpu.transport import proto, service

    cfg = parity_cfg
    n_clients = cfg.fed.num_clients
    gzip_on = cfg.fed.compression != "none"  # reference -c Y == gzip
    workdir = tempfile.mkdtemp(prefix="fedref_")
    # Ephemeral free-port probe per client: hard-coded ranges cross-talk
    # with orphaned servers from a killed previous run. All probe sockets are
    # held open while probing so the kernel cannot hand the same port to two
    # clients, then released together right before the child binds.
    import socket

    probes = []
    for _ in range(n_clients):
        s = socket.socket()
        s.bind(("localhost", 0))
        probes.append(s)
    addresses = [f"localhost:{s.getsockname()[1]}" for s in probes]
    for s in probes:
        s.close()

    x, y = load(cfg.data.dataset, "train", seed=cfg.data.seed,
                num=cfg.data.num_examples)
    data_file = os.path.join(workdir, "data.npz")
    extra = {}
    if engine_partition:
        # Accuracy-parity mode: ship each client the EXACT shard the fedtpu
        # engine assigns it (same partitioner, same seed), so both systems
        # optimize over identical per-client data distributions. The speed
        # configs keep the reference's own round-robin rank sharding — that
        # IS its measured mechanic (src/main.py:140-144).
        from fedtpu.data import partition as partition_mod

        if cfg.data.partition == "dirichlet":
            idx, maskm = partition_mod.dirichlet(
                y, n_clients, alpha=cfg.data.dirichlet_alpha,
                seed=cfg.data.seed,
            )
        else:
            idx, maskm = partition_mod.iid(
                len(x), n_clients, seed=cfg.data.seed
            )
        for i in range(n_clients):
            extra[f"shard_{i}"] = np.asarray(idx[i][maskm[i]], np.int64)
    np.savez(data_file, x=x.astype(np.float32), y=y, **extra)

    spec = {
        "model": cfg.model if cfg.model in ("mlp", "resnet18") else "smallcnn",
        "num_classes": cfg.num_classes,
        "input_shape": list(x.shape[1:]),
        "lr": cfg.opt.learning_rate,
        "batch": cfg.data.batch_size,
        "local_epochs": max(1, cfg.fed.local_epochs),
        "addresses": addresses,
        "dir": workdir,
        "gzip": gzip_on,
        "data_file": data_file,
        "per_client": engine_partition,
    }
    child_src = f"REPO = {os.path.dirname(os.path.abspath(__file__))!r}\n" + CLIENT_MAIN
    child = subprocess.Popen(
        [sys.executable, "-c", child_src, json.dumps(spec)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        # Wait for READY, then heartbeat every client.
        line = child.stdout.readline()
        if "READY" not in line:
            raise RuntimeError(f"client process failed: {child.stderr.read()[:2000]}")
        channels = [service.create_channel(a, compress=gzip_on) for a in addresses]
        stubs = [service.TrainerStub(ch) for ch in channels]
        deadline = time.time() + 60
        for s in stubs:
            while service.probe(s) is None:
                if time.time() > deadline:
                    raise RuntimeError("clients never became healthy")
                time.sleep(0.2)

        ns = {}
        exec(TORCH_MODELS, ns)
        build = ns["build_model"]

        tx, ty = load(cfg.data.dataset, "test", seed=cfg.data.seed,
                      num=cfg.data.num_examples)
        tx_t = torch.from_numpy(tx.transpose(0, 3, 1, 2).copy())
        eval_model = build(spec)

        if engine_partition:
            # Accuracy-parity mode: broadcast ONE common init before round
            # 0. The real reference starts its epoch loop with StartTrain
            # directly (src/server.py:113-153 — no initial sync), so its
            # first allreduce averages N DIFFERENTLY-initialised models;
            # random-sign cancellation shrinks the average ~1/sqrt(N) and at
            # 32 clients the network needs dozens of rounds to recover
            # (measured: flat at chance for 30 rounds). That wart stays
            # faithfully measured in the speed table; the accuracy columns
            # compare LEARNING DYNAMICS, so both systems start from a
            # common init here (fedtpu's engine always does; our own
            # distributed PrimaryServer.sync_clients does the same).
            torch.manual_seed(1234)
            init_net = build(spec)
            init_path = os.path.join(workdir, "common_init.pth")
            torch.save({"net": init_net.state_dict()}, init_path)
            with open(init_path, "rb") as fh:
                init_payload = base64.b64encode(fh.read())
            for s_ in stubs:
                s_.SendModel(proto.SendModelRequest(model=init_payload))

        def _eval(avg_state):
            eval_model.load_state_dict(avg_state)
            eval_model.eval()
            with torch.no_grad():
                logits = eval_model(tx_t)
            return float((logits.argmax(1).numpy() == ty).mean())

        # Warmup round, then timed rounds (same shape as bench_parity).
        # Curve rows are written per round; evals run OUTSIDE the timer so
        # the rounds/sec column stays comparable to the no-curve runs.
        avg = _server_round(stubs, n_clients, workdir, proto, build, spec)
        if curve_out is not None:
            curve_out.write(json.dumps(
                {"system": "reference_grpc_torch", "config": name,
                 "round": 0, "test_acc": round(_eval(avg), 4)}) + "\n")
            curve_out.flush()
        timed = cfg.fed.num_rounds - 1
        dt = 0.0
        for r in range(timed):
            t0 = time.perf_counter()
            avg = _server_round(stubs, n_clients, workdir, proto, build, spec)
            dt += time.perf_counter() - t0
            if curve_out is not None:
                curve_out.write(json.dumps(
                    {"system": "reference_grpc_torch", "config": name,
                     "round": r + 1, "test_acc": round(_eval(avg), 4)}) + "\n")
                curve_out.flush()

        acc = _eval(avg)

        wire_bytes = 2 * n_clients * len(
            base64.b64encode(open(os.path.join(workdir, "optimizedModel.pth"), "rb").read())
        )
        return {
            "config": name,
            "system": "reference_grpc_torch",
            "rounds_per_sec": round(timed / max(dt, 1e-9), 4),
            "test_acc": round(acc, 4),
            "num_clients": n_clients,
            "model": spec["model"],
            "dataset": cfg.data.dataset,
            "gzip": gzip_on,
            "wire_bytes_per_round": wire_bytes,
            "partition": (
                f"engine-identical {cfg.data.partition}" if engine_partition
                else "reference round-robin"
            ),
            "initial_sync": engine_partition,
            "note": note,
        }
    finally:
        child.kill()
        child.wait()
        shutil.rmtree(workdir, ignore_errors=True)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--only", default=None)
    p.add_argument("--acc-scale", action="store_true",
                   help="run bench_parity's accuracy-parity configs (the "
                   "specified conv models on the non-saturating *_hard "
                   "tasks) instead of the --cpu-scale speed configs")
    p.add_argument("--acc-full", action="store_true",
                   help="bench_parity's --acc-full config 4 sizing "
                   "(climbing-curve resnet18/cifar100_hard)")
    p.add_argument("--curve-out", default=None,
                   help="append per-round test-acc JSONL rows to this file")
    args = p.parse_args()

    import bench_parity

    notes = {
        "3_fedprox_cnn_cifar10_32c": "reference has no FedProx; baseline is its plain FedAvg",
        "3_acc_fedprox_smallcnn_cifar10h_32c": "reference has no FedProx; baseline is its plain FedAvg",
        "5_topk_compressed_fedavg_128c": "reference -c Y == transport gzip (no top-k)",
    }
    if args.acc_full:
        gen = bench_parity.acc_full_configs()
    elif args.acc_scale:
        gen = bench_parity.acc_configs()
    else:
        gen = bench_parity.configs(quick=False, cpu_scale=True)
    curve = open(args.curve_out, "a") if args.curve_out else None
    try:
        for name, cfg in gen:
            if args.only and args.only not in name:
                continue
            print(json.dumps(
                run_config(name, cfg, notes.get(name, ""), curve_out=curve,
                           engine_partition=args.acc_scale or args.acc_full)
            ), flush=True)
    finally:
        if curve is not None:
            curve.close()


if __name__ == "__main__":
    raise SystemExit(main())
