#!/usr/bin/env python
"""The 5 parity configs from BASELINE.md, end to end.

Each config runs through the simulated engine (the TPU-native path) and
reports rounds/sec + accuracies as one JSON line per config. ``--quick``
shrinks datasets/rounds for smoke runs on CPU; the full mode is sized for the
real chip. The reference publishes no numbers (BASELINE.md), so these are the
framework-side columns of the parity table.

Reference round semantics are preserved: one round = every client trains its
shard for `local_epochs` epochs (folded into steps_per_round), then one
weighted aggregation.
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.data import load


_TRAIN_SIZE = {"mnist": 60000, "cifar10": 50000, "cifar100": 50000}


def cpu_scale_examples(clients: int) -> int:
    """Dataset truncation for cpu-scale parity runs: 64 examples/client."""
    return 64 * clients


def configs(quick: bool, cpu_scale: bool = False):
    # Quick mode is a CPU smoke pass: tiny data, batch 16, augmentation off,
    # client counts /16, a couple of steps per round — it checks the configs
    # *run*, not their numbers. Full mode preserves the reference's round
    # semantics: one round = `local_epochs` full passes over the client's
    # shard (steps_per_round computed from dataset size / clients / batch).
    #
    # cpu-scale mode (for the BASELINE.md table when no chip is reachable):
    # FULL client counts and true round semantics (partitioner, algorithm,
    # local epochs, compression), but the dataset truncated to 64
    # examples/client at batch 32 and the model pinned to MLP — measured on
    # this host, torch's oneDNN conv kernels are ~30x faster than XLA:CPU's,
    # so any conv config on CPU benchmarks kernel libraries rather than the
    # two systems; matmuls are same-order (~2.8x) on both. The conv-model
    # TPU story is carried by PALLAS_TPU_COMPILE.json and the driver bench.
    # bench_reference.py runs the gRPC/torch baseline at EXACTLY this sizing,
    # so the two columns are same-host, same-workload comparable.
    n = 512 if quick else None  # dataset truncation
    rounds = 4 if quick else 20
    scale = 16 if quick else 1
    if cpu_scale:
        rounds = 6
        scale = 1

    def mk(name, model, dataset, clients, quick_steps, partition="iid",
           local_epochs=1, **fed_kw):
        data_kw = {}
        if partition == "dirichlet":
            data_kw["dirichlet_alpha"] = 0.5
        clients = max(2, clients // scale)
        batch = 16 if quick else 128
        if cpu_scale:
            batch = 32
            n_local = cpu_scale_examples(clients)
            shard = n_local // clients
            # ONE epoch per steps_per_round; local_epochs rides FedConfig so
            # BOTH systems honor it (the engine folds it into steps, and
            # bench_reference's client loop repeats its epoch the same way —
            # multiplying here instead used to give fedtpu local_epochs x
            # the reference's local work).
            steps = max(1, math.ceil(shard / batch))
            return name, RoundConfig(
                model="mlp",
                num_classes=100 if dataset == "cifar100" else 10,
                opt=OptimizerConfig(learning_rate=0.05, schedule="constant"),
                data=DataConfig(
                    dataset=dataset,
                    batch_size=batch,
                    partition=partition,
                    num_examples=n_local,
                    augment=False,
                    # Committed parity artifacts were measured under the
                    # exact per-round permutation shuffle; pin it so re-runs
                    # reproduce them (the engine default is now the faster
                    # rotation layout, fedtpu/data/device.py).
                    device_layout="gather",
                    **data_kw,
                ),
                fed=FedConfig(num_clients=clients, num_rounds=rounds,
                              local_epochs=local_epochs, **fed_kw),
                steps_per_round=steps,
            )
        if quick:
            steps = max(1, quick_steps // 2)
        else:
            shard = _TRAIN_SIZE[dataset] // clients
            steps = max(1, math.ceil(shard / batch))
        return name, RoundConfig(
            model=model,
            num_classes=100 if dataset == "cifar100" else 10,
            # Constant LR: the reference never steps its cosine scheduler
            # (src/main.py:231-242), so parity runs pin the effective
            # constant-0.05 behavior.
            opt=OptimizerConfig(learning_rate=0.05, schedule="constant"),
            data=DataConfig(
                dataset=dataset,
                batch_size=batch,
                partition=partition,
                num_examples=n,
                augment=not quick,
                device_layout="gather",  # pin committed-artifact semantics
                **data_kw,
            ),
            fed=FedConfig(num_clients=clients, num_rounds=rounds,
                          local_epochs=1 if quick else local_epochs,
                          **fed_kw),
            steps_per_round=steps,
        )

    yield mk("1_fedavg_mlp_mnist_2c_iid", "mlp", "mnist", 2, 4)
    yield mk("2_fedavg_cnn_cifar10_8c_dirichlet", "smallcnn", "cifar10", 8, 4,
             partition="dirichlet")
    yield mk("3_fedprox_cnn_cifar10_32c", "smallcnn", "cifar10", 32, 2,
             algorithm="fedprox", fedprox_mu=0.01)
    # Config 4 is "5 local epochs": steps_per_round covers the whole shard
    # 5x (the engine folds local epochs into steps, fedtpu/core/engine.py).
    # Quick and cpu-scale modes swap resnet18 -> smallcnn: XLA's CPU compile
    # of the vmapped resnet18 train step alone takes ~10 min on this host
    # (the zoo tests cover resnet18 correctness; tools/compile_pallas_tpu.py
    # AOT-proves the 64-client resnet18/cifar100 round step for the v5e
    # target both sharded over 4 chips and on one chip with
    # remat + streaming gather — naively it exceeds one v5e's HBM).
    yield mk("4_fedavg_resnet18_cifar100_64c_5ep",
             "smallcnn" if (quick or cpu_scale) else "resnet18",
             "cifar100", 64, 5, local_epochs=5)
    yield mk("5_topk_compressed_fedavg_128c", "smallcnn", "cifar10", 128, 2,
             compression="topk", topk_fraction=0.01)


def acc_configs():
    """Accuracy/convergence parity at the SPECIFIED conv architectures
    (VERDICT r3 weak #2): BASELINE configs 2-4 with their real model
    families on the non-saturating ``*_hard`` tasks
    (:func:`fedtpu.data.datasets._synthetic_hard` — subspace signal + 10%
    label noise, so test-acc lands meaningfully below 1.0 and climbs over
    rounds). Scale is reduced only where XLA:CPU compile time forces it
    (client count for the vmapped resnet18) — never the model family. The
    speed columns for these configs remain the --cpu-scale MLP rows with
    their oneDNN-vs-XLA:CPU kernel-gap rationale (BASELINE.md)."""

    def mk(name, model, dataset, clients, ex_per_client, rounds,
           partition="iid", local_epochs=1, batch=32, **fed_kw):
        data_kw = {}
        if partition == "dirichlet":
            data_kw["dirichlet_alpha"] = 0.5
        # One epoch of steps; local_epochs rides FedConfig (both systems).
        steps = max(1, math.ceil(ex_per_client / batch))
        return name, RoundConfig(
            model=model,
            num_classes=100 if "cifar100" in dataset else 10,
            opt=OptimizerConfig(learning_rate=0.05, schedule="constant"),
            data=DataConfig(
                dataset=dataset,
                batch_size=batch,
                partition=partition,
                num_examples=ex_per_client * clients,
                augment=False,
                device_layout="gather",  # pin committed-artifact semantics
                **data_kw,
            ),
            fed=FedConfig(num_clients=clients, num_rounds=rounds,
                          local_epochs=local_epochs, **fed_kw),
            steps_per_round=steps,
        )

    yield mk("2_acc_smallcnn_cifar10h_8c_dirichlet", "smallcnn",
             "cifar10_hard", 8, 128, 25, partition="dirichlet")
    # 128 examples/client (4 batches/round): at 64 the averaged per-round
    # movement across 32 clients is too small to leave chance within the
    # round budget — both systems flatline at 0.11 and the parity column
    # would compare noise with noise (measured before this sizing).
    yield mk("3_acc_fedprox_smallcnn_cifar10h_32c", "smallcnn",
             "cifar10_hard", 32, 128, 30, algorithm="fedprox",
             fedprox_mu=0.01)
    # ResNet-18 on XLA:CPU costs ~30-60 s per batch-32 train step (single
    # core, measured) — the acc run keeps the config's defining trait
    # (5 local epochs) and shrinks everything else to the edge of
    # feasibility: 2 clients, 64 examples each, 4 rounds (20 train batches
    # per round; a 256-example sizing still needed multiple hours). The
    # full-scale TPU evidence for this config is the AOT-compiled
    # 64-client program (tools/compile_pallas_tpu.py, stream+remat).
    yield mk("4_acc_resnet18_cifar100h_2c_5ep", "resnet18",
             "cifar100_hard", 2, 64, 4, local_epochs=5)


def acc_full_configs():
    """Config 4 at a sizing whose curves actually climb — runnable when a
    REAL accelerator is live for the fedtpu side (the XLA:CPU fallback costs
    30-60 s per resnet18 batch; on a v5e the whole run is seconds of device
    time). The torch side stays on CPU where oneDNN convs are ~30x XLA:CPU
    (BASELINE.md kernel-gap note): 4 clients x 4 batches x 5 epochs x 12
    rounds = 960 batch-32 steps, ~20-40 min on this 1-core host.

    ``FEDTPU_SMOKE=1`` swaps in an MLP seconds-scale version of the same
    shape so the capture wrapper (``tools/run_accfull_tpu.py``) can be
    exercised end-to-end on CPU without burning a TPU window on a wrapper
    bug; the wrapper redirects its artifacts when smoking."""

    def mk4(name, model, classes, dataset, clients, ex_per_client, rounds,
            local_epochs):
        steps = max(1, math.ceil(ex_per_client / 32))
        return name, RoundConfig(
            model=model,
            num_classes=classes,
            opt=OptimizerConfig(learning_rate=0.05, schedule="constant"),
            data=DataConfig(
                dataset=dataset, batch_size=32, partition="iid",
                num_examples=ex_per_client * clients, augment=False,
                device_layout="gather",
            ),
            fed=FedConfig(num_clients=clients, num_rounds=rounds,
                          local_epochs=local_epochs),
            steps_per_round=steps,
        )

    if os.environ.get("FEDTPU_SMOKE"):
        yield mk4("4_accfull_SMOKE_mlp", "mlp", 10, "cifar10_hard",
                  2, 64, 3, 2)
        return
    yield mk4("4_accfull_resnet18_cifar100h_4c_5ep", "resnet18", 100,
              "cifar100_hard", 4, 128, 12, 5)


def run_one(name: str, cfg: RoundConfig, curve_out=None) -> dict:
    """``curve_out``: open file — appends one JSON line per round with the
    global model's test accuracy (per-round eval parity,
    ``src/main.py:167-191``). Evals run outside the timer."""
    fed = Federation(cfg, seed=0)
    test = load(cfg.data.dataset, "test", seed=cfg.data.seed,
                num=cfg.data.num_examples)

    def _curve(r):
        if curve_out is not None:
            _, ta = fed.evaluate(*test)
            curve_out.write(json.dumps(
                {"system": "fedtpu", "config": name, "round": r,
                 "test_acc": round(ta, 4)}) + "\n")
            curve_out.flush()

    # Warmup (compile) round, then timed rounds with a forced host sync.
    m = fed.step()
    float(m.loss)
    _curve(0)
    dt = 0.0
    for r in range(cfg.fed.num_rounds - 1):
        t0 = time.perf_counter()
        m = fed.step()
        float(m.loss)
        dt += time.perf_counter() - t0
        _curve(r + 1)
    test_loss, test_acc = fed.evaluate(*test)
    return {
        "config": name,
        "data_source": fed.data_source,
        "rounds_per_sec": round((cfg.fed.num_rounds - 1) / max(dt, 1e-9), 3),
        "train_acc": round(float(m.accuracy), 4),
        "test_acc": round(test_acc, 4),
        "num_clients": cfg.fed.num_clients,
        "model": cfg.model,
        "dataset": cfg.data.dataset,
        "algorithm": cfg.fed.algorithm,
        "compression": cfg.fed.compression,
        "devices": len(jax.devices()),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true",
                   help="small data/rounds for CPU smoke runs")
    p.add_argument("--cpu-scale", action="store_true",
                   help="full client counts, 64 examples/client — the sizing "
                   "bench_reference.py mirrors for the BASELINE.md table")
    p.add_argument("--acc-scale", action="store_true",
                   help="accuracy/convergence parity at the SPECIFIED conv "
                   "models (configs 2-4) on the non-saturating *_hard tasks")
    p.add_argument("--acc-full", action="store_true",
                   help="config 4 (resnet18/cifar100_hard, 5 local epochs) "
                   "at climbing-curve sizing; fedtpu side wants a live "
                   "accelerator (platform NOT pinned to cpu)")
    p.add_argument("--curve-out", default=None,
                   help="append per-round test-acc JSONL rows to this file")
    p.add_argument("--only", default=None,
                   help="substring filter on config names")
    from fedtpu.cli.common import add_platform_flag, apply_platform_flag

    add_platform_flag(p)
    args = p.parse_args()
    # Quick/cpu-scale/acc-scale modes are CPU workloads by definition; pin
    # the platform so a wedged remote TPU backend can't hang them at
    # jax.devices().
    if args.platform is None and (
        args.quick or args.cpu_scale or args.acc_scale
        or (args.acc_full and os.environ.get("FEDTPU_SMOKE"))
    ):
        args.platform = "cpu"
    apply_platform_flag(args)
    if args.acc_full:
        gen = acc_full_configs()
    elif args.acc_scale:
        gen = acc_configs()
    else:
        gen = configs(args.quick, cpu_scale=args.cpu_scale)
    curve = open(args.curve_out, "a") if args.curve_out else None
    try:
        for name, cfg in gen:
            if args.only and args.only not in name:
                continue
            print(json.dumps(run_one(name, cfg, curve_out=curve)), flush=True)
    finally:
        if curve is not None:
            curve.close()


if __name__ == "__main__":
    main()
