#!/usr/bin/env python
"""Generate the committed CIFAR-format fixture (VERDICT r4 #7).

Writes ``tests/fixtures/cifar10_fixture/cifar-10-batches-py/`` in the
GENUINE CIFAR-10 python-version byte layout — the exact on-disk format
torchvision's downloader produces and the reference trains from
(``/root/reference/src/main.py:48-56``): per-batch python pickles holding
``{b'batch_label', b'labels', b'data', b'filenames'}`` with ``data`` a
``uint8 [N, 3072]`` array in row-major CHW order. 40 examples per train
batch (5 batches) + 64 test examples keeps the committed weight under
1 MB while exercising the multi-file concatenation path.

Content is a deterministic class-structured image family (one coarse color
pattern per class + noise) so the e2e smoke can verify actual LEARNING
through the real loader, not just decoding. Deterministic: re-running this
script reproduces the fixture byte-for-byte (pickle protocol pinned).
"""

import os
import pickle

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tests", "fixtures", "cifar10_fixture",
                   "cifar-10-batches-py")
PER_TRAIN_BATCH = 40
TEST_N = 64


def _images(rng, labels):
    """uint8 [N, 3, 32, 32] class-structured images."""
    protos = rng.integers(40, 216, size=(10, 3, 8, 8)).astype(np.uint8)
    up = protos.repeat(4, axis=2).repeat(4, axis=3)  # [10, 3, 32, 32]
    noise = rng.integers(-30, 31, size=(len(labels), 3, 32, 32))
    x = up[labels].astype(np.int32) + noise
    return np.clip(x, 0, 255).astype(np.uint8)


def _write(path, labels, data, batch_label):
    obj = {
        b"batch_label": batch_label.encode(),
        b"labels": [int(v) for v in labels],
        b"data": data.reshape(len(labels), 3072),
        b"filenames": [f"fixture_{i:05d}.png".encode()
                       for i in range(len(labels))],
    }
    with open(path, "wb") as fh:
        pickle.dump(obj, fh, protocol=2)  # the historical CIFAR protocol


def main():
    os.makedirs(OUT, exist_ok=True)
    rng = np.random.default_rng(2026_07_31)
    for b in range(1, 6):
        labels = rng.integers(0, 10, size=PER_TRAIN_BATCH).astype(np.int64)
        _write(os.path.join(OUT, f"data_batch_{b}"), labels,
               _images(rng, labels), f"training batch {b} of 5")
    labels = rng.integers(0, 10, size=TEST_N).astype(np.int64)
    _write(os.path.join(OUT, "test_batch"), labels,
           _images(rng, labels), "testing batch 1 of 1")
    with open(os.path.join(OUT, "batches.meta"), "wb") as fh:
        pickle.dump({b"label_names": [
            b"airplane", b"automobile", b"bird", b"cat", b"deer",
            b"dog", b"frog", b"horse", b"ship", b"truck"],
            b"num_cases_per_batch": PER_TRAIN_BATCH,
            b"num_vis": 3072}, fh, protocol=2)
    print(f"wrote fixture to {OUT}")


if __name__ == "__main__":
    main()
