#!/usr/bin/env python
"""Background TPU-window watcher for the wedge-prone tunnel backend.

The axon tunnel to the one real TPU chip wedges for hours at a time and
recovers unpredictably (round-3 observation: one ~20-minute live window in a
~12 h session). This watcher makes sure a live window is never wasted:

  * It probes tunnel health at a modest cadence with a bounded tiny-matmul
    child process (a wedged tunnel hangs ANY device query, so everything runs
    in subprocesses with hard timeouts — the watcher itself can never hang).
  * Long quiet periods between probes: repeatedly killing clients mid-init
    appears to prolong the wedge, so the default cadence is 20 min of total
    silence between probes.
  * On the FIRST healthy probe it immediately runs the job queue, serialized
    (never two TPU processes at once, guarded by an exclusive flock):
      1. ``bench.py``              -> artifacts/BENCH_LIVE_r04.json
      2. ``tools/run_pallas_tpu.py``  -> artifacts/PALLAS_TPU_RUN.json
      3. ``tools/bench_profile_tpu.py`` (if present) -> MFU profile artifacts
    Jobs that succeed are recorded in a state file so a restarted watcher (or
    a later window after a partial capture) only runs what is still missing.
  * All artifacts are written atomically (tmp + os.replace); every action is
    appended to a timestamped log that is itself the round's evidence that
    the watcher ran (VERDICT r3, "Next round" #2).

Exit: 0 once every job has succeeded, 4 on deadline with jobs still pending.

Usage::

    nohup python tools/tpu_watch.py --max-hours 11 >> artifacts/tpu_watch_r04.log 2>&1 &
"""

import argparse
import fcntl
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")
LOCK_PATH = os.path.join(REPO, ".tpu_access.lock")
STATE_PATH = os.path.join(ART, "tpu_watch_state.json")

_PROBE_CHILD = """
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((256, 256))
import numpy as np
print(d.device_kind, "|", float(np.asarray(x @ x).sum()))
"""


def log(msg):
    ts = time.strftime("%Y-%m-%dT%H:%M:%S")
    print(f"[tpu_watch {ts}] {msg}", flush=True)


def atomic_write(path, text):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def load_state():
    try:
        with open(STATE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"done": [], "history": []}


def save_state(state):
    atomic_write(STATE_PATH, json.dumps(state, indent=2))


def probe(timeout_s):
    """(healthy, detail) — tiny on-device matmul in a bounded child."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CHILD],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, f"probe timeout {timeout_s}s (wedged)"
    if proc.returncode != 0:
        return False, f"probe rc={proc.returncode}: {proc.stderr.strip()[-300:]}"
    return True, proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "ok"


def _bench_job(artifact, env=None, budget_s=300, min_mfu=None):
    """Run bench.py; success = a JSON line with value > 0, saved as the live
    artifact (bench.py itself is already subprocess-isolated + bounded).
    ``env`` selects a variant leg (FEDTPU_BENCH_MODEL / FEDTPU_MOMENTUM_DTYPE
    / FEDTPU_COMPUTE_DTYPE / FEDTPU_MEGABATCH_CLIENTS — see bench.py); the
    default is the driver's exact parity run.
    ``budget_s`` is the job's HARD wall-clock budget: a healthy window
    completes the measurement in ~2-4 min (persistent compile cache), so a
    job past its budget means the tunnel re-wedged — kill it and keep the
    window for the rest of the queue (VERDICT r5 "Next round" #1).
    ``min_mfu`` makes the measured MFU part of the pass condition: the leg
    FAILS (and re-queues for the next window) when the capture's ``mfu``
    field is missing or below the floor — for legs whose whole point is an
    MFU claim (the bf16+megabatch >= 10% gate), a capture below the gate is
    a negative result, not a success."""
    def run():
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=budget_s,
            env=dict(os.environ, **(env or {})),
        )
        from jsontail import last_json_line

        line = last_json_line(proc.stdout)
        if not line:
            return False, f"no JSON from bench.py (rc={proc.returncode})"
        if line.get("value", 0) <= 0:
            return False, f"bench diagnostic: {line.get('error', line)}"
        if min_mfu is not None:
            mfu = line.get("mfu")
            if not isinstance(mfu, (int, float)) or mfu < min_mfu:
                # Still bank the capture (it is evidence either way) but do
                # not mark the gate passed.
                line["mfu_gate"] = {"min_mfu": min_mfu, "passed": False}
                line["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
                line["captured_by"] = "tools/tpu_watch.py"
                if env:
                    line["captured_env"] = dict(env)
                atomic_write(
                    os.path.join(ART, artifact), json.dumps(line, indent=2))
                return False, (
                    f"mfu gate FAILED: mfu={mfu} < {min_mfu} "
                    f"(capture saved to {artifact})")
            line["mfu_gate"] = {"min_mfu": min_mfu, "passed": True}
        line["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        # Provenance keys on the ARTIFACT name (jobs carry their round in
        # the filename); the watcher itself is round-agnostic.
        line["captured_by"] = "tools/tpu_watch.py"
        if env:
            line["captured_env"] = dict(env)
        atomic_write(os.path.join(ART, artifact), json.dumps(line, indent=2))
        return True, f"value={line['value']} {line.get('unit', '')} mfu={line.get('mfu')}"
    run.budget_s = budget_s
    run.env = dict(env) if env else {}
    run.min_mfu = min_mfu
    return run


def _script_job(rel, budget_s, artifact, env=None):
    """``budget_s`` is the job's hard wall-clock budget (see _bench_job)."""
    def run():
        run_env = dict(os.environ, **(env or {}))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, rel)],
            capture_output=True, text=True, timeout=budget_s, cwd=REPO,
            env=run_env,
        )
        ok = proc.returncode == 0 and os.path.exists(os.path.join(ART, artifact))
        tail = (proc.stderr or proc.stdout).strip()[-300:]
        return ok, f"rc={proc.returncode} {tail}" if not ok else f"wrote {artifact}"
    # Expose the script path so run_pending can SKIP (not fail) jobs whose
    # script hasn't landed yet — a missing script would otherwise trip
    # stop-on-first-failure and starve the rest of the queue for the window.
    run.script_path = os.path.join(REPO, rel)
    run.budget_s = budget_s
    return run


JOBS = [
    # Round-6 queue (2026-08-04), restructured for guaranteed capture
    # (VERDICT r5 "Next round" #1): the driver-path headline bench is job
    # #1 with a hard ~5-minute budget, so ANY window >= 5 min yields the
    # BENCH_LIVE_r06 capture instead of wedging mid-acc_full like round 5's
    # 04:12 probe. Every job carries a hard per-job wall-clock budget — one
    # hung job can no longer eat a whole window; the expensive acc-full
    # parity run goes LAST, after every quick win is banked.
    # 1: the driver's exact bench path, captured live.
    ("bench_fused_r06", _bench_job("BENCH_LIVE_r06.json", budget_s=300)),
    # 2-3: the two on-chip model headline rows (VERDICT r5 #2) — each a
    # single fused measurement, budgeted like the headline.
    ("mobilenet_bench",
     _script_job("tools/bench_model_tpu.py", 300, "BENCH_MOBILENET_TPU.json")),
    ("resnet18_bench",
     _script_job("tools/bench_resnet_tpu.py", 420, "BENCH_RESNET_TPU.json")),
    # 4-5: the two roofline experiments (VERDICT r5 #4) — optimizer-state
    # traffic (bf16 momentum) and pool cost (avg-pool ablation), each an
    # end-to-end bench so they're kept/rejected on data like the round-4
    # negatives.
    ("bench_mom_bf16",
     _bench_job("BENCH_LIVE_r06_mombf16.json", budget_s=300,
                env={"FEDTPU_MOMENTUM_DTYPE": "bfloat16"})),
    ("bench_avgpool",
     _bench_job("BENCH_LIVE_r06_avgpool.json", budget_s=300,
                env={"FEDTPU_BENCH_MODEL": "smallcnn_avgpool"})),
    # 6: a fresh profile at whatever the round's best config turns out to be.
    ("mfu_profile_r06",
     _script_job("tools/bench_profile_tpu.py", 420, "MFU_PROFILE_r06.json",
                 env={"FEDTPU_PROFILE_TAG": "r06"})),
    # 7: cheap follow-on — deeper fusion (40 rounds per dispatch amortises
    # the ~70 ms tunnel dispatch floor further).
    ("bench_fused40",
     _bench_job("BENCH_LIVE_r06_fused40.json", budget_s=300,
                env={"FEDTPU_BENCH_TIMED_ROUNDS": "40"})),
    # 8 (round 7, 2026-08-05): the mixed-precision tentpole's on-chip
    # verdict — bf16 device residency + megabatched MXU passes, the two
    # levers the analytic model says cut bytes_per_round >= 1.8x
    # (artifacts/MIXED_PRECISION_MICROBENCH.json). Pass condition is the
    # ISSUE's acceptance gate: measured MFU >= 10% (vs the 1.31% f32
    # headline). A capture below the gate is banked as evidence but the
    # leg stays pending for a retuned retry.
    ("bench_bf16mega_r07",
     _bench_job("BENCH_LIVE_r07_bf16mega.json", budget_s=300, min_mfu=0.10,
                env={"FEDTPU_COMPUTE_DTYPE": "bfloat16_mixed",
                     "FEDTPU_MEGABATCH_CLIENTS": "8"})),
    # 9: the long acc-full parity run, LAST — it only fires in a window
    # that has already banked everything above, and its budget caps the
    # worst case at ~25 min instead of wedging the whole window.
    ("acc_full_fedtpu",
     _script_job("tools/run_accfull_tpu.py", 1500, "PARITY_ACC_FULL.jsonl")),
]


def run_pending(state, lock_file):
    """Run every not-yet-done job, serialized under the exclusive lock."""
    fcntl.flock(lock_file, fcntl.LOCK_EX)
    # Reload AFTER acquiring the lock: another watcher may have completed
    # jobs while we blocked, and acting on the pre-wait snapshot would
    # re-run them (burning the scarce TPU window) and clobber its done-list.
    fresh = load_state()
    for name in fresh["done"]:
        if name not in state["done"]:
            state["done"].append(name)
    state["history"] = fresh["history"] + [
        h for h in state["history"] if h not in fresh["history"]
    ]
    try:
        for name, job in JOBS:
            if name in state["done"]:
                continue
            script = getattr(job, "script_path", None)
            if script and not os.path.exists(script):
                log(f"job {name}: script {os.path.relpath(script, REPO)} "
                    "not present yet, skipping this window")
                continue
            log(f"job {name}: starting")
            t0 = time.time()
            try:
                ok, detail = job()
            except subprocess.TimeoutExpired:
                ok, detail = False, "job timeout (tunnel likely re-wedged)"
            except Exception as exc:  # noqa: BLE001 - watcher must survive anything
                ok, detail = False, f"exception: {exc!r}"
            dt = round(time.time() - t0, 1)
            log(f"job {name}: {'OK' if ok else 'FAILED'} in {dt}s — {detail}")
            state["history"].append(
                {"job": name, "ok": ok, "detail": detail, "secs": dt,
                 "at": time.strftime("%Y-%m-%dT%H:%M:%S")})
            if ok:
                state["done"].append(name)
            save_state(state)
            if not ok:
                # Tunnel likely dropped mid-job — stop burning it; re-probe later.
                return False
        return True
    finally:
        fcntl.flock(lock_file, fcntl.LOCK_UN)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--interval-s", type=float, default=1200.0,
                   help="quiet seconds between probes (default 20 min)")
    p.add_argument("--probe-timeout", type=float, default=120.0)
    p.add_argument("--max-hours", type=float, default=11.0)
    p.add_argument("--once", action="store_true", help="single probe+run, no loop")
    args = p.parse_args()

    os.makedirs(ART, exist_ok=True)
    state = load_state()
    deadline = time.time() + args.max_hours * 3600
    lock_file = open(LOCK_PATH, "w")

    required = {n for n, _ in JOBS}
    log(f"watcher start: jobs done={state['done']}, interval={args.interval_s}s, "
        f"max_hours={args.max_hours}")
    while time.time() < deadline:
        if required <= set(state["done"]):
            log("all jobs captured — exiting, leaving the tunnel quiet")
            return 0
        healthy, detail = probe(args.probe_timeout)
        log(f"probe: {'LIVE' if healthy else 'down'} — {detail}")
        state["history"].append(
            {"probe": healthy, "detail": detail,
             "at": time.strftime("%Y-%m-%dT%H:%M:%S")})
        save_state(state)
        if healthy:
            all_done = run_pending(state, lock_file)
            if all_done and required <= set(state["done"]):
                log("all jobs captured — exiting")
                return 0
        if args.once:
            break
        time.sleep(args.interval_s)
    pending = sorted(required - set(state["done"]))
    log(f"deadline reached; pending jobs: {pending}")
    return 4 if pending else 0


if __name__ == "__main__":
    raise SystemExit(main())
