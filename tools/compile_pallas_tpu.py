#!/usr/bin/env python
"""Deviceless AOT compilation check against a REAL TPU target (v5e).

The driver environment exposes the TPU chip only through a remote tunnel
that is not always reachable, so "does this lower through Mosaic / XLA:TPU?"
must not depend on holding the chip. jax + libtpu can compile for a TPU
*topology* without any device attached (``jax.experimental.topologies``);
this script AOT-compiles, for a v5e:2x2 target:

1. the Pallas compression kernels at MobileNet scale (64 clients x ~3.2M
   params — the ``-c Y`` hot path) with ``interpret=False``, proving Mosaic
   lowering + VMEM fit;
2. the full single-chip federated round step (bench.py's exact config);
3. the sharded 4-chip round step (shard_map + psum over the clients mesh) —
   the multichip program compiled for actual TPU hardware, not just the
   virtual CPU mesh.

Writes one JSON line per artifact to stdout and (with ``--out``) a combined
JSON file. Run: ``python tools/compile_pallas_tpu.py --out PALLAS_TPU_COMPILE.json``
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # never touch the tunnel backend

import jax.numpy as jnp
import numpy as np
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MOBILENET_PARAMS = 3_217_226  # param count of the reference default model
NUM_CLIENTS = 64


def _mem(compiled):
    try:
        ma = compiled.memory_analysis()
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
        }
    except Exception:
        return {}


def _flops(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def compile_kernels(dev):
    from fedtpu.ops import pallas_kernels as pk

    s = jax.sharding.SingleDeviceSharding(dev)
    y = jax.ShapeDtypeStruct((NUM_CLIENTS, MOBILENET_PARAMS), jnp.float32, sharding=s)
    t = jax.ShapeDtypeStruct((NUM_CLIENTS,), jnp.float32, sharding=s)
    results = []
    for name, fn in (
        ("threshold_with_feedback", lambda a, b: pk.threshold_with_feedback(a, b, interpret=False)),
        ("quantdequant_int8", lambda a, b: pk.quantdequant_int8(a, b, interpret=False)),
    ):
        t0 = time.perf_counter()
        compiled = jax.jit(fn).lower(y, t).compile()
        results.append(
            {
                "artifact": f"pallas:{name}",
                "target": dev.device_kind,
                "shape": [NUM_CLIENTS, MOBILENET_PARAMS],
                "compile_s": round(time.perf_counter() - t0, 2),
                "ok": True,
                **_mem(compiled),
            }
        )
    return results


def _bench_inputs(cfg, sharding_for, compressor=None):
    """ShapeDtypeStructs for (state, batch) under a sharding-assignment fn."""
    from fedtpu.core import round as round_lib
    from fedtpu import models

    model = models.create(cfg.model, num_classes=cfg.num_classes, remat=cfg.remat)
    state = jax.eval_shape(
        lambda r: round_lib.init_state(
            model, cfg, r, jnp.zeros((1, 32, 32, 3), jnp.float32), compressor
        ),
        jax.random.PRNGKey(0),
    )
    n, s, b = cfg.fed.num_clients, cfg.steps_per_round, cfg.data.batch_size
    batch = round_lib.RoundBatch(
        x=jax.ShapeDtypeStruct((n, s, b, 32, 32, 3), jnp.float32),
        y=jax.ShapeDtypeStruct((n, s, b), jnp.int32),
        step_mask=jax.ShapeDtypeStruct((n, s), jnp.bool_),
        weights=jax.ShapeDtypeStruct((n,), jnp.float32),
        alive=jax.ShapeDtypeStruct((n,), jnp.bool_),
    )
    put = lambda tree, spec_tree: jax.tree.map(
        lambda l, sp: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=sharding_for(sp)),
        tree,
        spec_tree,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    return model, state, batch, put


def compile_round_step(
    dev,
    compression="none",
    model_name="smallcnn",
    dataset="cifar10",
    num_classes=10,
    steps=391 // NUM_CLIENTS,
    batch=128,
    tag="bench_config",
    remat=False,
):
    """bench.py's exact single-chip config (optionally with the ``-c Y``
    top-k compression path, whose Pallas kernels then compile *inside* the
    full round program), AOT for the TPU target. ``model_name``/``steps``
    overrides cover the parity configs (e.g. resnet18/cifar100 — config 4's
    TPU-side evidence, since XLA:CPU compiles it far too slowly to bench)."""
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import round as round_lib
    from fedtpu import models

    cfg = RoundConfig(
        model=model_name,
        num_classes=num_classes,
        opt=OptimizerConfig(),
        data=DataConfig(dataset=dataset, batch_size=batch),
        fed=FedConfig(num_clients=NUM_CLIENTS, compression=compression),
        steps_per_round=steps,
        dtype="bfloat16",
        remat=remat,
    )
    compressor = None
    if compression != "none":
        from fedtpu.ops.compression import make_compressor
        from fedtpu.ops import pallas_kernels as pk

        # Force Mosaic lowering for the kernels nested inside the round
        # program (default_backend() is cpu during deviceless TPU AOT).
        pk.set_interpret_default(False)
        compressor = make_compressor(cfg.fed)
    s = jax.sharding.SingleDeviceSharding(dev)
    model, state, batch, put = _bench_inputs(cfg, lambda spec: s, compressor)
    same = lambda tree: jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        tree,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    step = jax.jit(
        round_lib.make_round_step(model, cfg, compressor), donate_argnums=(0,)
    )
    t0 = time.perf_counter()
    compiled = step.lower(same(state), same(batch)).compile()
    return {
        "artifact": f"round_step:{tag}_single_chip"
        + ("" if compression == "none" else f"_{compression}")
        + ("_remat" if remat else ""),
        "target": dev.device_kind,
        "model": model_name,
        "num_clients": NUM_CLIENTS,
        "compile_s": round(time.perf_counter() - t0, 2),
        "flops_per_round": _flops(compiled),
        "ok": True,
        **_mem(compiled),
    }


def _data_path_inputs(dev, cfg, model, total, num_rounds=None,
                      layout="presharded"):
    """ShapeDtypeStruct args for the device-resident data-path programs
    (``make_data_round_step`` / ``make_multi_round_step``): dataset in HBM
    (per-client ``[n, 2L, F]`` presharded rows by default, flat ``[N, F]``
    for the gather layout), per-client assignment, weights/alive/key.
    ``num_rounds`` switches ``alive`` to the fused scan's
    ``[rounds, clients]`` layout."""
    from fedtpu.core import round as round_lib

    state = jax.eval_shape(
        lambda r: round_lib.init_state(
            model, cfg, r, jnp.zeros((1, 32, 32, 3), jnp.float32)
        ),
        jax.random.PRNGKey(0),
    )
    s = jax.sharding.SingleDeviceSharding(dev)
    sds = lambda shape, dtype: jax.ShapeDtypeStruct(shape, dtype, sharding=s)
    place = lambda tree: jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        tree,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    n = cfg.fed.num_clients
    shard = total // n
    alive = (
        sds((n,), jnp.bool_)
        if num_rounds is None
        else sds((num_rounds, n), jnp.bool_)
    )
    if layout == "presharded":
        images = sds((n, 2 * shard, 32 * 32 * 3), jnp.float32)
        labels = sds((n, 2 * shard), jnp.int32)
    else:
        images = sds((total, 32 * 32 * 3), jnp.float32)
        labels = sds((total,), jnp.int32)
    return (
        place(state),
        images,
        labels,
        sds((n, shard), jnp.int32),
        sds((n, shard), jnp.bool_),
        sds((n,), jnp.float32),
        alive,
        sds((2,), jnp.uint32),  # data key
    )


def compile_streaming_round_step(
    dev,
    model_name="resnet18",
    dataset="cifar100",
    num_classes=100,
    steps=40,
    batch=32,
    remat=True,
    tag="parity4_resnet18_cifar100_stream",
):
    """The engine's actual big-model path on ONE chip: device-resident
    dataset, per-step gather inside the scan (``stream``), per-block remat.
    This is the configuration that brings 64-client resnet18 rounds back
    under one v5e's HBM after the non-stream form measurably OOMed."""
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.data.device import make_data_round_step
    from fedtpu import models

    cfg = RoundConfig(
        model=model_name,
        num_classes=num_classes,
        opt=OptimizerConfig(),
        data=DataConfig(dataset=dataset, batch_size=batch),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=steps,
        dtype="bfloat16",
        remat=remat,
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes, remat=cfg.remat)
    args = _data_path_inputs(dev, cfg, model, total=50000, layout="presharded")
    step_fn = jax.jit(
        make_data_round_step(
            model, cfg, steps, shuffle=True, stream=True,
            image_shape=(32, 32, 3),
        ),
        donate_argnums=(0,),
    )
    t0 = time.perf_counter()
    compiled = step_fn.lower(*args).compile()
    return {
        "artifact": f"round_step:{tag}_single_chip",
        "target": dev.device_kind,
        "model": model_name,
        "num_clients": NUM_CLIENTS,
        "remat": remat,
        "stream": True,
        "compile_s": round(time.perf_counter() - t0, 2),
        "flops_per_round": _flops(compiled),
        "ok": True,
        **_mem(compiled),
    }


def compile_fused_multi_round(
    dev,
    num_rounds=10,
    steps=391 // NUM_CLIENTS,
    batch=128,
    tag="bench_fused10",
):
    """bench.py's headline program: the engine's fused ``num_rounds``-round
    scan (per-round on-device gather + vmapped local SGD + aggregation as ONE
    XLA program), AOT for the TPU target. ``flops_per_round`` comes from the
    single-round program of the SAME config — XLA cost analysis counts a
    lax.scan body once regardless of trip count today, and deriving from the
    unfused program (bench.py does the same) keeps the field honest if that
    convention ever changes; the raw fused number is reported alongside."""
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.data.device import make_data_round_step, make_multi_round_step
    from fedtpu import models

    n = NUM_CLIENTS
    total = n * steps * batch
    cfg = RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=total,
        ),
        fed=FedConfig(num_clients=n),
        steps_per_round=steps,
        dtype="bfloat16",
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    multi_args = _data_path_inputs(dev, cfg, model, total,
                                   num_rounds=num_rounds, layout="presharded")
    single_args = _data_path_inputs(dev, cfg, model, total, layout="presharded")
    multi = jax.jit(
        make_multi_round_step(
            model, cfg, steps, num_rounds, shuffle=True,
            image_shape=(32, 32, 3),
        ),
        donate_argnums=(0,),
    )
    single = jax.jit(
        make_data_round_step(
            model, cfg, steps, shuffle=True, image_shape=(32, 32, 3)
        ),
        donate_argnums=(0,),
    )
    t0 = time.perf_counter()
    compiled = multi.lower(*multi_args).compile()
    compile_s = round(time.perf_counter() - t0, 2)
    single_flops = _flops(single.lower(*single_args).compile())
    return {
        "artifact": f"multi_round:{tag}_single_chip",
        "target": dev.device_kind,
        "model": "smallcnn",
        "num_clients": n,
        "num_rounds": num_rounds,
        "compile_s": compile_s,
        "flops_per_round": single_flops,
        "fused_program_flops": _flops(compiled),
        "ok": True,
        **_mem(compiled),
    }


def compile_async_tick(
    dev,
    num_ticks=10,
    steps=391 // NUM_CLIENTS,
    batch=128,
    tag="async_fused10",
):
    """The engine-side FedBuff program (fedtpu.core.async_engine): a fused
    ``num_ticks``-tick scan where every client trains its OWN diverged model
    copy and ``buffer_k`` staleness-discounted arrivals aggregate per tick —
    AOT for the TPU target, proving the async study tool lowers to the chip
    (it cannot be speed-tested on XLA:CPU at 64 clients)."""
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core.async_engine import init_async_state, make_multi_async_step
    from fedtpu import models

    n = NUM_CLIENTS
    total = n * steps * batch
    cfg = RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=total,
        ),
        fed=FedConfig(num_clients=n),
        steps_per_round=steps,
        dtype="bfloat16",
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    state = jax.eval_shape(
        lambda r: init_async_state(
            model, cfg, r, jnp.zeros((1, 32, 32, 3), jnp.float32)
        ),
        jax.random.PRNGKey(0),
    )
    s = jax.sharding.SingleDeviceSharding(dev)
    sds = lambda shape, dtype: jax.ShapeDtypeStruct(shape, dtype, sharding=s)
    place = lambda tree: jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        tree,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    shard = total // n
    args_ = (
        place(state),
        sds((n, 2 * shard, 32 * 32 * 3), jnp.float32),  # presharded rows
        sds((n, 2 * shard), jnp.int32),
        sds((n, shard), jnp.int32),
        sds((n, shard), jnp.bool_),
        sds((n,), jnp.float32),
        sds((num_ticks, n), jnp.bool_),  # arrive
        sds((num_ticks, n), jnp.bool_),  # alive
        sds((2,), jnp.uint32),
    )
    multi = jax.jit(
        make_multi_async_step(
            model, cfg, steps, num_ticks, shuffle=True,
            image_shape=(32, 32, 3),
        ),
        donate_argnums=(0,),
    )
    t0 = time.perf_counter()
    compiled = multi.lower(*args_).compile()
    return {
        "artifact": f"async_tick:{tag}_single_chip",
        "target": dev.device_kind,
        "model": "smallcnn",
        "num_clients": n,
        "num_ticks": num_ticks,
        "compile_s": round(time.perf_counter() - t0, 2),
        "fused_program_flops": _flops(compiled),
        "ok": True,
        **_mem(compiled),
    }


def compile_sharded_round_step(
    topo,
    model_name="smallcnn",
    dataset="cifar10",
    num_classes=10,
    steps=391 // NUM_CLIENTS,
    batch=128,
    tag="",
):
    """The multichip shard_map program compiled for real v5e chips."""
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.parallel import make_sharded_round_step
    from fedtpu.parallel.sharded import batch_specs, state_specs

    n_dev = len(topo.devices)
    cfg = RoundConfig(
        model=model_name,
        num_classes=num_classes,
        opt=OptimizerConfig(),
        data=DataConfig(dataset=dataset, batch_size=batch),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=steps,
        dtype="bfloat16",
    )
    mesh = Mesh(np.array(topo.devices), (cfg.mesh_axis,))
    from fedtpu import models

    model = models.create(cfg.model, num_classes=cfg.num_classes, remat=cfg.remat)
    _, state, batch, _ = _bench_inputs(cfg, None)
    state_in = _with_specs(state, state_specs(cfg.mesh_axis), mesh)
    batch_in = _with_specs(batch, batch_specs(cfg.mesh_axis), mesh)
    step = make_sharded_round_step(model, cfg, mesh, donate=False)
    t0 = time.perf_counter()
    compiled = step.lower(state_in, batch_in).compile()
    return {
        "artifact": f"round_step:{tag}sharded_{n_dev}chip",
        "target": topo.devices[0].device_kind,
        "model": model_name,
        "n_devices": n_dev,
        "num_clients": NUM_CLIENTS,
        "compile_s": round(time.perf_counter() - t0, 2),
        "flops_per_round": _flops(compiled),
        "ok": True,
        **_mem(compiled),
    }


def _with_specs(tree, specs, mesh):
    """Attach NamedShardings from a matching PartitionSpec tree. Spec trees
    are a prefix of the value tree (one spec per state field covers every
    leaf under it), so broadcast specs down to the leaves."""

    def attach(spec, sub):
        return jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                l.shape, l.dtype, sharding=NamedSharding(mesh, spec)
            ),
            sub,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )

    return jax.tree.map(
        attach, specs, tree, is_leaf=lambda x: isinstance(x, P)
    )


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--topology", default="v5e:2x2")
    p.add_argument("--out", default=None)
    args = p.parse_args()

    topo = topologies.get_topology_desc(platform="tpu", topology_name=args.topology)
    dev = topo.devices[0]
    results = []
    for fn in (
        lambda: compile_kernels(dev),
        lambda: [compile_round_step(dev)],
        lambda: [compile_round_step(dev, compression="topk")],
        # The flagship model (MobileNet — the reference's hardcoded default,
        # src/main.py:69) at the bench scale, single chip.
        lambda: [
            compile_round_step(
                dev, model_name="mobilenet", tag="flagship_mobilenet"
            )
        ],
        # Parity config 4's TPU-side evidence, two deployment shapes:
        # (a) single chip with per-block remat + per-step streaming gather —
        #     the engine's actual big-model path. Without these, this config
        #     measurably exceeds one v5e's 16 GB HBM (capacity result
        #     recorded in BASELINE.md);
        # (b) SHARDED over 4 chips (16 clients per chip), no remat needed.
        lambda: [compile_streaming_round_step(dev)],
        lambda: [
            compile_sharded_round_step(
                topo,
                model_name="resnet18",
                dataset="cifar100",
                num_classes=100,
                steps=40,  # 5 local epochs x 8 batches of 32 per shard
                batch=32,
                tag="parity4_resnet18_cifar100_",
            )
        ],
        lambda: [compile_sharded_round_step(topo)],
        # The headline-bench program: 10 fused rounds as one XLA program.
        lambda: [compile_fused_multi_round(dev)],
        # Engine-side FedBuff: 10 fused async ticks (per-client diverged
        # model copies, buffered staleness-weighted aggregation).
        lambda: [compile_async_tick(dev)],
    ):
        try:
            out = fn()
        except Exception as e:
            out = [{"artifact": "error", "ok": False, "error": f"{type(e).__name__}: {e}"[:800]}]
        for r in out:
            print(json.dumps(r), flush=True)
            results.append(r)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(
                {"topology": args.topology, "results": results}, fh, indent=1
            )
    return 0 if all(r.get("ok") for r in results) else 1


if __name__ == "__main__":
    raise SystemExit(main())
