#!/usr/bin/env python
"""Profile the fused multi-round FedAvg program on the real TPU chip.

VERDICT r3 weak #1/#3: the 225.55 client-epochs/s live number was measured
with a host sync every round over the tunnel — MFU 0.49%, i.e. the chip was
~99.5% idle and the claim proved was "tunnel latency survived". This tool
answers "what does the chip actually do when the host is out of the way":

  1. Times the engine's fused 10-round scan (one dispatch = 10 complete
     FedAvg rounds, the same program ``bench.py`` measures) at the bench
     config (smallcnn, 64 clients, batch 128, bf16).
  2. Sweeps per-client batch size upward (256, 512) at fixed
     steps-per-round to show where the MXU saturates — the bench config's
     batch is pinned by reference parity (``src/main.py:47``, batch 128),
     not by what the hardware can do.
  3. Computes a roofline placement per config from XLA cost analysis
     (flops + bytes accessed vs the chip's peak FLOPs and HBM bandwidth):
     reported arithmetic intensity vs the ridge point says whether the
     program is compute- or bandwidth-bound, and utilization says how far
     from that bound the measurement landed.
  4. Captures a ``jax.profiler`` trace of one fused dispatch (bench config)
     under ``artifacts/profile_r04/`` for offline op-level inspection.

Writes ``artifacts/MFU_PROFILE_r04.json`` and prints it. Timing discipline
per the tunnel's quirks: operands live on device, every timed dispatch
fetches a program output (``block_until_ready`` alone does not reliably
block over the tunnel), median of 3 trials.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

NUM_CLIENTS = 64
STEPS_PER_ROUND = 391 // NUM_CLIENTS
TIMED_ROUNDS = 10
TRIALS = 3
BATCHES = (128, 256, 512)

# FEDTPU_SMOKE=1: tiny shapes so the full code path (compile, time, roofline,
# incremental persist) can be exercised on the CPU backend in seconds. The
# op-trace leg defaults OFF in smoke mode: jax.profiler instrumentation of
# the fused program on the CPU backend runs >300x slower than untraced
# (observed wedged >5 min on a <1 s dispatch); FEDTPU_PROFILE_TRACE=1/0
# overrides either default.
if os.environ.get("FEDTPU_SMOKE"):
    NUM_CLIENTS, STEPS_PER_ROUND, TIMED_ROUNDS, BATCHES = 8, 2, 2, (16, 32)
    # float32 + a single trial: CPU bf16 emulation is ~30x slower than f32
    # (measured 17.7 s for a 2-round smallcnn dispatch) — smoke is about
    # exercising the code path, not the MXU numerics.
    TRIALS, DTYPE = 1, "float32"
    TRACE_DISPATCH = os.environ.get("FEDTPU_PROFILE_TRACE", "0") == "1"
else:
    DTYPE = "bfloat16"
    TRACE_DISPATCH = os.environ.get("FEDTPU_PROFILE_TRACE", "1") == "1"

def _log(msg):
    print(f"[bench_profile_tpu] {msg}", file=sys.stderr, flush=True)


def _measure_config(batch, profile_dir=None):
    import jax
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.obs.profile import device_peaks, roofline

    cfg = RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(
            dataset="cifar10",
            batch_size=batch,
            partition="iid",
            num_examples=NUM_CLIENTS * STEPS_PER_ROUND * batch,
        ),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=STEPS_PER_ROUND,
        dtype=DTYPE,
    )
    fed = Federation(cfg, seed=0)
    d_images, d_labels, d_idx, d_mask = fed._ensure_device_data()
    import jax.numpy as jnp

    alive = jnp.ones((TIMED_ROUNDS, NUM_CLIENTS), bool)
    multi = fed._multi_step(TIMED_ROUNDS)
    args = (fed.state, d_images, d_labels, d_idx, d_mask, fed.weights,
            alive, fed._data_key)
    _log(f"batch={batch}: compiling fused {TIMED_ROUNDS}-round program")
    step = multi.lower(*args).compile()

    # Roofline inputs from the SINGLE-round program (scan bodies are counted
    # once by cost analysis regardless of trip count — bench.py's convention).
    flops = by = None
    try:
        single = fed._data_step.lower(
            fed.state, d_images, d_labels, d_idx, d_mask, fed.weights,
            jnp.ones((NUM_CLIENTS,), bool), fed._data_key,
        ).compile()
        an = single.cost_analysis()
        if isinstance(an, (list, tuple)):
            an = an[0] if an else {}
        flops = float(an.get("flops", 0.0)) or None
        by = float(an.get("bytes accessed", 0.0)) or None
    except Exception as exc:
        _log(f"cost analysis unavailable: {exc}")

    state = fed.state

    def dispatch(state):
        state, m = step(state, d_images, d_labels, d_idx, d_mask,
                        fed.weights, alive, fed._data_key)
        np.asarray(m.loss)  # honest sync: fetch a program output
        return state

    _log(f"batch={batch}: warmup dispatch")
    state = dispatch(state)
    times = []
    for i in range(TRIALS):
        t0 = time.perf_counter()
        state = dispatch(state)
        times.append(time.perf_counter() - t0)
    if profile_dir and TRACE_DISPATCH:
        os.makedirs(profile_dir, exist_ok=True)
        _log(f"batch={batch}: tracing one dispatch -> {profile_dir}")
        with jax.profiler.trace(profile_dir):
            state = dispatch(state)
    times.sort()
    sec_per_dispatch = times[len(times) // 2]
    rounds_per_sec = TIMED_ROUNDS / sec_per_dispatch

    kind = jax.devices()[0].device_kind
    # Shared peak table + roofline math (fedtpu.obs.profile) — the same
    # numbers the engine's continuous MFU accounting uses, so a hand sweep
    # and the per-round fedtpu_mfu_ratio gauge can never disagree on peaks.
    peak_f, peak_b = device_peaks(kind)
    row = {
        "batch": batch,
        "rounds_per_sec": round(rounds_per_sec, 3),
        "client_epochs_per_sec_per_chip": round(rounds_per_sec * NUM_CLIENTS, 2),
        "sec_per_fused_dispatch": round(sec_per_dispatch, 4),
        "trial_times_s": [round(t, 4) for t in times],
        "device_kind": kind,
    }
    if flops:
        row["flops_per_round"] = flops
        if peak_f:
            row["mfu"] = round(rounds_per_sec * flops / peak_f, 4)
    if by:
        row["bytes_per_round"] = by
        if peak_b:
            row["hbm_util"] = round(rounds_per_sec * by / peak_b, 4)
    if flops and by and peak_f and peak_b:
        roof = roofline(
            flops, by, peak_f, peak_b,
            achieved_flops_per_s=rounds_per_sec * flops,
        )
        row.update({k: v for k, v in roof.items() if v is not None})
    return row


def run(tag=None):
    """The full sweep: measure every batch config, persist the artifact
    incrementally, return the result dict. ``bench.py --mfu-profile`` calls
    this; ``main()`` below is the standalone CLI wrapper."""
    # FEDTPU_PLATFORM=cpu pins the platform for smoke-testing this script
    # off-chip (the axon TPU plugin ignores JAX_PLATFORMS; only the config
    # update before any device query works — see tests/conftest.py).
    plat = os.environ.get("FEDTPU_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # FEDTPU_PROFILE_TAG distinguishes re-measurements (e.g. the presharded
    # data layout vs the r04 gather-layout baseline) without overwriting the
    # earlier artifact.
    if tag is None:
        tag = os.environ.get("FEDTPU_PROFILE_TAG", "r04")
    art = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "artifacts")
    os.makedirs(art, exist_ok=True)
    result = {"timed_rounds_per_dispatch": TIMED_ROUNDS,
              "num_clients": NUM_CLIENTS,
              "steps_per_round": STEPS_PER_ROUND,
              "configs": []}
    profile_dir = os.path.join(art, f"profile_{tag}")
    for i, batch in enumerate(BATCHES):
        try:
            result["configs"].append(
                _measure_config(batch, profile_dir=profile_dir if i == 0 else None)
            )
        except Exception as exc:  # OOM at large batch is a finding, not a crash
            _log(f"batch={batch} failed: {exc!r}")
            result["configs"].append({"batch": batch, "error": repr(exc)[:500]})
        # Persist incrementally: a tunnel re-wedge mid-sweep keeps the rows
        # measured so far.
        out = os.path.join(art, f"MFU_PROFILE_{tag}.json")
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=2)
        os.replace(tmp, out)
    return result


def main():
    print(json.dumps(run()))


if __name__ == "__main__":
    main()
