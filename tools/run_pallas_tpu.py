#!/usr/bin/env python
"""Execute the Pallas compression kernels COMPILED (Mosaic) on a real TPU.

Closes the round-2 verdict's "Pallas never executed compiled" gap: the
deviceless AOT check (``tools/compile_pallas_tpu.py``) proved Mosaic lowering;
this script proves execution + numerics + timing on hardware. For each kernel
(`threshold_with_feedback`, `quantdequant_int8`) at MobileNet scale (64
clients x ~3.2M params — the reference default model, ``src/main.py:69``,
``src/models/mobilenet.py:26-44``) it:

  1. runs the Mosaic-compiled pallas_call (``interpret=False``),
  2. runs the plain-jnp/XLA equivalent,
  3. asserts bitwise-equal outputs,
  4. reports median wall time + effective HBM bandwidth for both.

Writes one JSON object to ``artifacts/PALLAS_TPU_RUN.json`` and prints it.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

ROWS = 64  # clients
COLS = 3_217_152 // 64 * 64  # ~MobileNet param count, lane-friendly
TRIALS = 10


def _log(msg):
    print(f"[run_pallas_tpu] {msg}", file=sys.stderr, flush=True)


def _median_time(fn, *args):
    out = fn(*args)
    jax_block(out)
    _log("warmup done")
    ts = []
    for i in range(TRIALS):
        t0 = time.perf_counter()
        out = fn(*args)
        jax_block(out)
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2], out


def jax_block(tree):
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        leaf.block_until_ready()


def main():
    import jax
    import jax.numpy as jnp

    from fedtpu.ops import pallas_kernels as pk

    _log("enumerating devices")
    dev = jax.devices()[0]
    _log(f"device: {dev.device_kind}")
    result = {
        "device_kind": dev.device_kind,
        "backend": jax.default_backend(),
        "rows": ROWS,
        "cols": COLS,
        "kernels": {},
    }

    # Generate operands ON DEVICE: an 800 MB host->device upload over the
    # remote tunnel takes longer than the whole measurement (observed: >15
    # min); jax.random on the chip takes milliseconds.
    @jax.jit
    def _make_inputs(key):
        y = jax.random.normal(key, (ROWS, COLS), jnp.float32)
        # Per-row 99th-percentile |y| (the top-k threshold shape) without a
        # full O(n log n) sort: max of |y| over all but the top 1% via
        # top_k on a per-row basis is still a sort on TPU — use the cheap
        # normal-distribution quantile instead (z_{0.99} ~= 2.326); the
        # kernels only need SOME per-row threshold, not an exact one.
        thresh = jnp.full((ROWS,), 2.326, jnp.float32)
        scale = jnp.max(jnp.abs(y), axis=1) / 127.0
        return y, thresh, scale

    y, thresh, scale = _make_inputs(jax.random.PRNGKey(0))
    jax_block((y, thresh, scale))
    _log("inputs generated on device")

    nbytes = y.size * 4

    # --- threshold_with_feedback: reads y (+ thresh), writes out + new_e.
    _log("threshold kernel: compiling mosaic")
    t_mosaic, (out_m, e_m) = _median_time(
        lambda a, b: pk.threshold_with_feedback(a, b, interpret=False), y, thresh
    )

    def _jnp_thresh(a, b):
        out = jnp.where(jnp.abs(a) >= b[:, None], a, jnp.zeros_like(a))
        return out, a - out

    jnp_thresh = jax.jit(_jnp_thresh)
    t_xla, (out_x, e_x) = _median_time(jnp_thresh, y, thresh)
    ok = bool(
        jnp.array_equal(out_m, out_x).item() and jnp.array_equal(e_m, e_x).item()
    )
    result["kernels"]["threshold_with_feedback"] = {
        "bitwise_equal_vs_xla": ok,
        "mosaic_ms": round(t_mosaic * 1e3, 3),
        "xla_ms": round(t_xla * 1e3, 3),
        # 1 read (y) + 2 writes (out, new_e); thresh is negligible.
        "mosaic_gbps": round(3 * nbytes / t_mosaic / 1e9, 1),
        "xla_gbps": round(3 * nbytes / t_xla / 1e9, 1),
    }

    # --- quantdequant_int8: reads x, writes out.
    _log("quant kernel: compiling mosaic")
    t_mosaic, q_m = _median_time(
        lambda a, b: pk.quantdequant_int8(a, b, interpret=False), y, scale
    )

    def _jnp_q(a, b):
        s = b[:, None]
        safe = jnp.where(s > 0, s, jnp.ones_like(s))
        return jnp.clip(jnp.round(a / safe), -127.0, 127.0) * safe

    jnp_q = jax.jit(_jnp_q)
    t_xla, q_x = _median_time(jnp_q, y, scale)
    ok = bool(jnp.array_equal(q_m, q_x).item())
    result["kernels"]["quantdequant_int8"] = {
        "bitwise_equal_vs_xla": ok,
        "mosaic_ms": round(t_mosaic * 1e3, 3),
        "xla_ms": round(t_xla * 1e3, 3),
        "mosaic_gbps": round(2 * nbytes / t_mosaic / 1e9, 1),
        "xla_gbps": round(2 * nbytes / t_xla / 1e9, 1),
    }

    result["all_bitwise_equal"] = all(
        k["bitwise_equal_vs_xla"] for k in result["kernels"].values()
    )
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "artifacts",
        "PALLAS_TPU_RUN.json",
    )
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    if not result["all_bitwise_equal"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
