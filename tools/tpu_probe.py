#!/usr/bin/env python
"""Bounded TPU health probe for the wedge-prone tunnel backend.

The axon tunnel device can wedge indefinitely (observed: concurrent access,
or killing a client mid-operation) — after which even ``jax.devices()``
hangs. This probe runs the check in a child process with a hard timeout so
it can NEVER hang the caller, and exits 0 (healthy: prints device kind +
matmul result), 2 (unreachable/wedged), or 3 (backend error).

Usage: ``python tools/tpu_probe.py [--timeout 90]``
"""

import argparse
import json
import subprocess
import sys

_CHILD = """
import jax, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.ones((256, 256))
print(d.device_kind, "|", float((x @ x).sum()))
"""


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--timeout", type=float, default=90.0)
    args = p.parse_args()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD],
            capture_output=True,
            text=True,
            timeout=args.timeout,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({"healthy": False, "reason": f"timeout {args.timeout}s (wedged)"}))
        return 2
    if proc.returncode != 0:
        print(json.dumps({"healthy": False, "reason": proc.stderr.strip()[-500:]}))
        return 3
    print(json.dumps({"healthy": True, "probe": proc.stdout.strip().splitlines()[-1]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
