#!/usr/bin/env python
"""Summarise the accuracy-parity artifacts into BASELINE.md-ready text.

Reads ``artifacts/PARITY_ACC_CONV.jsonl`` (summary rows from both systems)
and ``artifacts/convergence_hard_r04.jsonl`` (per-round test-acc curves) and
prints: a markdown table pairing fedtpu vs reference per config, and a
compact per-config curve digest (first / takeoff / final accuracy) showing
both systems' dynamics side by side.
"""

import json
import os
import sys
from collections import defaultdict

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "artifacts")


def _rows(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def main():
    summaries = _rows(os.path.join(ART, "PARITY_ACC_CONV.jsonl"))
    curves = _rows(os.path.join(ART, "convergence_hard_r04.jsonl"))

    by_cfg = defaultdict(dict)
    for r in summaries:
        system = "fedtpu" if r.get("system", "fedtpu") == "fedtpu" else "ref"
        # bench_parity rows have no "system" field; bench_reference's do.
        if "system" not in r:
            system = "fedtpu"
        by_cfg[r["config"]][system] = r

    print("### Accuracy parity at the specified conv models "
          "(non-saturating task)\n")
    print("| config | model | clients | fedtpu test-acc | reference "
          "test-acc | gap |")
    print("|---|---|---|---|---|---|")
    for cfg in sorted(by_cfg):
        pair = by_cfg[cfg]
        f, r = pair.get("fedtpu"), pair.get("ref")
        fa = f["test_acc"] if f else float("nan")
        ra = r["test_acc"] if r else float("nan")
        model = (f or r or {}).get("model", "?")
        clients = (f or r or {}).get("num_clients", "?")
        gap = fa - ra if f and r else float("nan")
        print(f"| {cfg} | {model} | {clients} | {fa:.3f} | {ra:.3f} "
              f"| {gap:+.3f} |")

    curve_by = defaultdict(lambda: defaultdict(list))
    for c in curves:
        curve_by[c["config"]][c["system"]].append((c["round"], c["test_acc"]))

    print("\n### Convergence dynamics (per-round test accuracy)\n")
    for cfg in sorted(curve_by):
        print(f"**{cfg}**")
        for system, pts in sorted(curve_by[cfg].items()):
            pts.sort()
            accs = [a for _, a in pts]
            takeoff = next(
                (i for i, a in enumerate(accs) if a > accs[0] + 0.1),
                None,
            )
            print(f"  - {system}: start {accs[0]:.2f} -> final "
                  f"{accs[-1]:.2f} over {len(accs)} rounds"
                  + (f", takeoff ~round {takeoff}" if takeoff is not None
                     else ", no takeoff"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
