#!/usr/bin/env python
"""Summarise the accuracy-parity artifacts into BASELINE.md-ready text.

Reads ``artifacts/PARITY_ACC_CONV.jsonl`` + ``PARITY_ACC_FULL.jsonl``
(summary rows from both systems) and ``artifacts/convergence_hard_r04.jsonl``
+ ``convergence_full_r04.jsonl`` (per-round test-acc curves) and prints: a
markdown table pairing fedtpu vs reference per config, and a compact
per-config curve digest (first / takeoff / final accuracy) showing both
systems' dynamics side by side.
"""

import json
import os
import sys
from collections import defaultdict

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "artifacts")


def _rows(path):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def main():
    summaries = (_rows(os.path.join(ART, "PARITY_ACC_CONV.jsonl"))
                 + _rows(os.path.join(ART, "PARITY_ACC_FULL.jsonl")))
    curves = (_rows(os.path.join(ART, "convergence_hard_r04.jsonl"))
              + _rows(os.path.join(ART, "convergence_full_r04.jsonl")))

    by_cfg = defaultdict(dict)
    for r in summaries:
        system = "fedtpu" if r.get("system", "fedtpu") == "fedtpu" else "ref"
        # bench_parity rows have no "system" field; bench_reference's do.
        if "system" not in r:
            system = "fedtpu"
        by_cfg[r["config"]][system] = r

    curve_by = defaultdict(lambda: defaultdict(list))
    for c in curves:
        curve_by[c["config"]][c["system"]].append((c["round"], c["test_acc"]))

    def final_acc(cfg, system_key, summary_row):
        """Summary-row accuracy, else the curve's final round (a run whose
        summary was lost to a timeout still has its full curve)."""
        if summary_row is not None:
            return summary_row["test_acc"], ""
        name = "fedtpu" if system_key == "fedtpu" else "reference_grpc_torch"
        pts = sorted(curve_by.get(cfg, {}).get(name, []))
        if pts:
            return pts[-1][1], " (curve final)"
        return float("nan"), ""

    print("### Accuracy parity at the specified conv models "
          "(non-saturating task)\n")
    print("| config | model | clients | fedtpu test-acc | reference "
          "test-acc | gap |")
    print("|---|---|---|---|---|---|")
    for cfg in sorted(set(by_cfg) | set(curve_by)):
        pair = by_cfg.get(cfg, {})
        f, r = pair.get("fedtpu"), pair.get("ref")
        fa, fnote = final_acc(cfg, "fedtpu", f)
        ra, rnote = final_acc(cfg, "ref", r)
        model = (f or r or {}).get("model", "?")
        clients = (f or r or {}).get("num_clients", "?")
        gap = fa - ra
        print(f"| {cfg} | {model} | {clients} | {fa:.3f}{fnote} "
              f"| {ra:.3f}{rnote} | {gap:+.3f} |")

    print("\n### Convergence dynamics (per-round test accuracy)\n")
    for cfg in sorted(curve_by):
        print(f"**{cfg}**")
        for system, pts in sorted(curve_by[cfg].items()):
            pts.sort()
            accs = [a for _, a in pts]
            takeoff = next(
                (i for i, a in enumerate(accs) if a > accs[0] + 0.1),
                None,
            )
            print(f"  - {system}: start {accs[0]:.2f} -> final "
                  f"{accs[-1]:.2f} over {len(accs)} rounds"
                  + (f", takeoff ~round {takeoff}" if takeoff is not None
                     else ", no takeoff"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
