#!/usr/bin/env python
"""Run the fedtpu side of parity config 4 at climbing-curve sizing on a live
accelerator (``bench_parity.py --acc-full``), appending curves and the
summary row next to the torch reference's (already-committed) run.

The torch side of ``4_accfull_resnet18_cifar100h_4c_5ep`` runs on CPU in
~40 min and was captured 2026-07-31 (``artifacts/PARITY_ACC_FULL.jsonl``,
``convergence_full_r04.jsonl``: chance 0.01 -> 0.1406 over 12 rounds). The
fedtpu side needs a live chip (XLA:CPU resnet18 is 30-60 s/batch); this
wrapper is watcher-runnable: bounded, and the shared artifacts are only
appended to AFTER a fully successful run (curves go to a scratch file
first — a wedge mid-run would otherwise leave partial fedtpu curves that a
later retry duplicates with conflicting values).
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from jsontail import last_json_line  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")
if os.environ.get("FEDTPU_SMOKE"):
    # Smoke mode (CPU, seconds): exercise the whole capture path — scratch
    # curves, append-on-success — WITHOUT touching the committed artifacts.
    ART = os.path.join("/tmp", "fedtpu_accfull_smoke")
    os.makedirs(ART, exist_ok=True)
ROWS = os.path.join(ART, "PARITY_ACC_FULL.jsonl")
CURVES = os.path.join(ART, "convergence_full_r04.jsonl")
TIMEOUT_S = 3000


def main():
    scratch = CURVES + ".inflight"
    if os.path.exists(scratch):
        os.remove(scratch)
    cmd = [sys.executable, os.path.join(REPO, "bench_parity.py"),
           "--acc-full", "--curve-out", scratch]
    if os.environ.get("FEDTPU_SMOKE"):
        cmd += ["--platform", "cpu"]  # smoke must not touch a wedged tunnel
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=TIMEOUT_S, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({"error": f"timeout after {TIMEOUT_S}s"}))
        return 4
    row = last_json_line(proc.stdout)
    if row is None:
        print(json.dumps({"error": f"rc={proc.returncode}: "
                          + proc.stderr.strip()[-400:]}))
        return 4
    row["system"] = "fedtpu"
    row["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(scratch) as f:
        curves = f.read()
    with open(CURVES, "a") as f:
        f.write(curves)
    os.remove(scratch)
    with open(ROWS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
