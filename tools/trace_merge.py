#!/usr/bin/env python
"""Stitch per-process fedtpu Chrome-trace dumps into ONE Perfetto timeline.

Each federation process exports its own trace (``--trace-out`` /
``Telemetry.export_trace``) with a ``metadata`` block carrying the
federation ``trace_id``, its ``role`` ("primary", "client:<addr>", ...)
and ``wall_start`` (wall-clock time of its monotonic zero). This tool
merges any number of those files into a single Chrome trace where:

- every process gets its own lane: ``pid`` = a per-file lane id with a
  ``process_name`` metadata event naming the role (Perfetto renders one
  process track per role; ``tid`` stays the original worker thread);
- timestamps are aligned onto one wall-clock timeline via ``wall_start``
  deltas (files without the metadata keep their own zero and are listed
  under ``metadata.unaligned``);
- span ids are qualified ``<role>/<local id>`` so per-process counters
  can never collide, and the propagated cross-process links
  (``args.remote_parent`` + ``args.remote_role``, written by the
  receiving client from the ``fedtpu-trace-bin`` metadata) are resolved
  into ordinary ``args.parent_id`` references — after the merge a client
  ``client_train`` span's parent chain walks through the coordinator's
  ``client_rpc`` span up to its ``round`` span;
- ``--device-trace DIR`` ingests a ``jax.profiler`` capture (the CLIs'
  ``--profile-rounds``, fedtpu.obs.profile.CaptureWindow): XLA device-op
  executions land on extra ``device:*`` lanes — one per chip (TPU) or one
  for the XLA CPU executor threads — wall-clock aligned with the host
  spans via the capture's ``profile_meta.json`` sidecar, every event
  tagged ``cat="device"`` so ``tools/gap_analyze.py`` can separate device
  busy time from host phases.

Import-free of fedtpu (stdlib only), like the other ``tools/`` readers.

Usage:
    python tools/trace_merge.py primary.json client0.json client1.json \
        [--device-trace capture_dir] -o merged.json [--check]

``--check`` additionally verifies every ``client_train`` span reaches a
``round`` root through the merged parent chain (and, with
``--device-trace``, that at least one device lane carries ops) and exits
non-zero otherwise (the CI assertion, see tests/test_obs_propagation.py).
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_doc(path: str) -> dict:
    """Read one Chrome-trace dump; bare-array files get an empty
    metadata block (both forms are valid Chrome trace JSON)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    doc.setdefault("metadata", {})
    return doc


def _qualify(role: str, span_id) -> str:
    return f"{role}/{span_id}"


# ------------------------------------------------------ device-trace input
PROFILE_META = "profile_meta.json"  # fedtpu.obs.profile sidecar name


def find_device_trace(trace_dir: str) -> Optional[str]:
    """Newest ``*.trace.json[.gz]`` under a ``jax.profiler`` output dir
    (layout: ``plugins/profile/<run>/<host>.trace.json.gz``)."""
    hits = []
    for dirpath, _dirs, files in os.walk(trace_dir):
        for f in files:
            if f.endswith(".trace.json.gz") or f.endswith(".trace.json"):
                hits.append(os.path.join(dirpath, f))
    return max(hits, key=os.path.getmtime) if hits else None


def _find_sidecar(start_dir: str) -> Optional[dict]:
    """Walk up from the trace file's dir looking for the capture sidecar
    (the file sits 2-3 levels below the dir the sidecar was written to)."""
    d = os.path.abspath(start_dir)
    for _ in range(4):
        p = os.path.join(d, PROFILE_META)
        if os.path.exists(p):
            try:
                with open(p) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def load_device_trace(path: str) -> dict:
    """Load a ``jax.profiler`` Chrome trace (dir or file, .gz or plain)
    plus its ``profile_meta.json`` sidecar. Returns the trace doc with
    ``metadata.wall_start``/``role`` filled from the sidecar when found
    (profiler timestamps are relative to the capture open, which is when
    the sidecar stamps its wall clock)."""
    if os.path.isdir(path):
        hit = find_device_trace(path)
        if hit is None:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] under {path} (is this a "
                "--profile-rounds / jax.profiler output dir?)"
            )
        path = hit
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    doc.setdefault("metadata", {})
    sidecar = _find_sidecar(os.path.dirname(os.path.abspath(path)))
    if sidecar:
        doc["metadata"].setdefault("wall_start", sidecar.get("wall_start"))
        doc["metadata"].setdefault(
            "role", sidecar.get("role") or "device"
        )
    return doc


def extract_device_lanes(doc: dict) -> List[Tuple[str, List[dict]]]:
    """``[(lane_name, X-events)]`` for the device work in a profiler trace.

    TPU/GPU captures name their op lanes ``/device:TPU:0`` etc. in
    ``process_name`` metadata — one merged lane per chip. CPU captures
    have no device process; there the XLA executor's op executions run on
    host threads named ``tf_XLA...``, so when no ``/device:`` lane exists
    those threads become one synthetic ``XLA:CPU`` lane (real HLO op
    names, same idle-gap semantics)."""
    pid_name: Dict[object, str] = {}
    thread_name: Dict[Tuple[object, object], str] = {}
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_name[e.get("pid")] = str(e.get("args", {}).get("name", ""))
        elif e.get("name") == "thread_name":
            thread_name[(e.get("pid"), e.get("tid"))] = str(
                e.get("args", {}).get("name", "")
            )
    device_pids = {
        pid for pid, name in pid_name.items() if "/device:" in name
    }
    lanes: Dict[str, List[dict]] = {}
    if device_pids:
        for e in doc.get("traceEvents", []):
            if e.get("ph") == "X" and e.get("pid") in device_pids:
                lanes.setdefault(pid_name[e["pid"]], []).append(e)
    else:
        for e in doc.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            tname = thread_name.get((e.get("pid"), e.get("tid")), "")
            if tname.startswith("tf_XLA"):
                lanes.setdefault("XLA:CPU", []).append(e)
    return sorted(lanes.items())


def merge_docs(docs: List[dict], device_docs: List[dict] = ()) -> dict:
    """Merge loaded trace docs (see module docstring). Order fixes lane
    numbering; roles are deduplicated with a ``#n`` suffix if two files
    claim the same one."""
    merged: List[dict] = []
    seen_roles: Dict[str, int] = {}
    roles: List[str] = []
    device_lanes: List[str] = []
    trace_ids = []
    unaligned = []
    wall_starts = [
        d["metadata"].get("wall_start")
        for d in list(docs) + list(device_docs)
        if d["metadata"].get("wall_start") is not None
    ]
    base_wall = min(wall_starts) if wall_starts else None

    for lane, doc in enumerate(docs, start=1):
        meta = doc["metadata"]
        role = str(meta.get("role") or f"proc{lane}")
        if role in seen_roles:
            seen_roles[role] += 1
            role = f"{role}#{seen_roles[role]}"
        else:
            seen_roles[role] = 0
        roles.append(role)
        tid = meta.get("trace_id")
        if tid and tid not in trace_ids:
            trace_ids.append(tid)
        offset_us = 0.0
        if base_wall is not None and meta.get("wall_start") is not None:
            offset_us = (meta["wall_start"] - base_wall) * 1e6
        elif base_wall is not None:
            unaligned.append(role)
        merged.append({
            "name": "process_name",
            "ph": "M",
            "pid": lane,
            "args": {"name": role},
        })
        for event in doc.get("traceEvents", []):
            if event.get("ph") == "M":
                continue  # per-file metadata is superseded by the lane's
            ev = dict(event)
            ev["pid"] = lane
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + offset_us, 3)
            args = dict(ev.get("args", {}))
            if "span_id" in args:
                args["span_id"] = _qualify(role, args["span_id"])
            if "parent_id" in args:
                args["parent_id"] = _qualify(role, args["parent_id"])
            elif "remote_parent" in args:
                # The propagated cross-process link becomes a first-class
                # parent reference in the merged id namespace.
                args["parent_id"] = _qualify(
                    str(args.get("remote_role", "")), args["remote_parent"]
                )
                args["parent_is_remote"] = True
            ev["args"] = args
            merged.append(ev)

    # Device lanes ride after the host lanes: one pid per chip (or the
    # synthetic XLA:CPU executor lane), events tagged cat="device" so
    # downstream readers (gap_analyze) can tell device busy time from
    # host spans without name heuristics.
    lane = len(docs)
    for doc in device_docs:
        meta = doc["metadata"]
        role = str(meta.get("role") or "device")
        offset_us = 0.0
        if base_wall is not None and meta.get("wall_start") is not None:
            offset_us = (meta["wall_start"] - base_wall) * 1e6
        elif base_wall is not None:
            unaligned.append(f"device:{role}")
        for lane_name, events in extract_device_lanes(doc):
            lane += 1
            full = f"device:{lane_name} ({role})"
            device_lanes.append(full)
            merged.append({
                "name": "process_name",
                "ph": "M",
                "pid": lane,
                "args": {"name": full},
            })
            for event in events:
                ev = dict(event)
                ev["pid"] = lane
                ev["cat"] = "device"
                if "ts" in ev:
                    ev["ts"] = round(ev["ts"] + offset_us, 3)
                merged.append(ev)

    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged_roles": roles,
            "device_lanes": device_lanes,
            "trace_ids": trace_ids,
            "unaligned": unaligned,
        },
    }


def span_index(doc: dict) -> Dict[str, dict]:
    """{qualified span_id: event} over a merged doc's span events."""
    return {
        e["args"]["span_id"]: e
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "X" and "span_id" in e.get("args", {})
    }


def root_of(index: Dict[str, dict], event: dict) -> Optional[dict]:
    """Walk the merged parent chain to its root (None on a dangling
    reference — e.g. a parent from a file that wasn't merged)."""
    seen = set()
    while True:
        parent = event.get("args", {}).get("parent_id")
        if parent is None:
            return event
        if parent in seen or parent not in index:
            return None
        seen.add(parent)
        event = index[parent]


def check_client_train_nesting(doc: dict) -> List[str]:
    """Problem strings (empty = pass): every ``client_train`` span must
    resolve through the merged parent chain to a ``round`` root."""
    index = span_index(doc)
    problems = []
    trains = [
        e for e in doc.get("traceEvents", [])
        if e.get("name") == "client_train"
    ]
    if not trains:
        problems.append("no client_train spans in merged trace")
    for e in trains:
        root = root_of(index, e)
        if root is None:
            problems.append(
                f"client_train {e['args'].get('span_id')}: dangling parent "
                "chain"
            )
        elif root.get("name") != "round":
            problems.append(
                f"client_train {e['args'].get('span_id')}: roots at "
                f"{root.get('name')!r}, not 'round'"
            )
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("traces", nargs="+",
                   help="per-process Chrome-trace JSON dumps (put the "
                   "coordinator's first for lane ordering)")
    p.add_argument("-o", "--out", required=True, help="merged trace path")
    p.add_argument(
        "--device-trace", action="append", default=[], metavar="DIR",
        help="ingest a jax.profiler capture (--profile-rounds output dir "
        "or a *.trace.json[.gz] file) as wall-clock-aligned device lanes; "
        "repeatable",
    )
    p.add_argument("--check", action="store_true",
                   help="fail unless every client_train span roots in a "
                   "round span through the merged parent chain (and any "
                   "--device-trace contributed at least one device op)")
    args = p.parse_args(argv)

    doc = merge_docs(
        [load_doc(path) for path in args.traces],
        device_docs=[load_device_trace(p) for p in args.device_trace],
    )
    with open(args.out, "w") as fh:
        json.dump(doc, fh)
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_dev = sum(
        1 for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("cat") == "device"
    )
    print(
        f"merged {len(args.traces)} traces -> {args.out}: {n} spans "
        f"({n_dev} device ops), "
        f"lanes {doc['metadata']['merged_roles']}"
        f"{' + ' + str(doc['metadata']['device_lanes']) if doc['metadata']['device_lanes'] else ''}, "
        f"trace_ids {doc['metadata']['trace_ids']}",
        file=sys.stderr,
    )
    if args.check:
        problems = check_client_train_nesting(doc)
        if args.device_trace and n_dev == 0:
            problems.append(
                "device traces given but no device ops made it into the "
                "merge (empty capture window?)"
            )
        if doc["metadata"]["unaligned"]:
            problems.append(
                f"unaligned files (no wall_start): "
                f"{doc['metadata']['unaligned']}"
            )
        if len(doc["metadata"]["trace_ids"]) > 1:
            problems.append(
                f"multiple trace ids: {doc['metadata']['trace_ids']} "
                "(files from different federation runs?)"
            )
        for problem in problems:
            print(f"CHECK FAILED: {problem}", file=sys.stderr)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
