#!/usr/bin/env python
"""Convergence comparison of the server-optimizer family (FedOpt).

Runs the same federated workload under server_optimizer = none (FedAvg,
reference semantics) / momentum (FedAvgM) / adam (FedAdam) and writes one
JSONL row per (optimizer, round) with train loss/acc and test accuracy to
``artifacts/SERVER_OPT_CONVERGENCE.jsonl``. CPU-friendly scale; data is the
deterministic synthetic surrogate (tagged in every row — no real datasets
exist in this environment).
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def main():
    import jax

    # CPU by default: even QUERYING the default backend initialises the
    # remote TPU plugin, which hangs indefinitely when the tunnel is wedged.
    # Pass --tpu to run on the chip.
    if "--tpu" not in sys.argv:
        jax.config.update("jax_platforms", "cpu")

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation
    from fedtpu.data import load

    rows = []
    for name, server_lr in (("none", 1.0), ("momentum", 0.7), ("adam", 0.02)):
        cfg = RoundConfig(
            model="mlp",
            num_classes=10,
            opt=OptimizerConfig(learning_rate=0.02, weight_decay=0.0),
            data=DataConfig(
                dataset="cifar10", batch_size=32, partition="dirichlet",
                num_examples=4096,
            ),
            fed=FedConfig(
                num_clients=16, server_optimizer=name, server_lr=server_lr
            ),
            steps_per_round=4,
        )
        fed = Federation(cfg, seed=0)
        test = load("cifar10", "test", num=2048)
        for r in range(30):
            m = fed.step()
            row = {
                "server_optimizer": name,
                "server_lr": server_lr,
                "round": r,
                "loss": round(float(m.loss), 5),
                "acc": round(float(m.accuracy), 5),
                "dataset": cfg.data.dataset,
                "data_source": fed.data_source,
            }
            if (r + 1) % 5 == 0:
                tl, ta = fed.evaluate(*test)
                row["test_loss"], row["test_acc"] = round(tl, 5), round(ta, 5)
            rows.append(row)
        print(f"{name}: final loss {rows[-1]['loss']}", file=sys.stderr)

    out = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "artifacts",
        "SERVER_OPT_CONVERGENCE.jsonl",
    )
    with open(out, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
