#!/usr/bin/env python
"""Diagnose the FedBuff k=2 sigma=0 smallcnn stall ON the stalling config.

Round-4's ``ASYNC_SYNC_CONVERGENCE.jsonl`` showed fedbuff_k2_sigma0 flat at
chance (0.103 after 25 ticks) on the smallcnn/cifar10_hard study config
while sigma=1 reached 0.718 and the sync barrier 0.89 — and the round-4
claim that this is "not an engine defect" rested on an MLP analogy, not on
an experiment on the stalling configuration (VERDICT r4 weak #2). This
sweeps the three levers FedBuff theory says govern staleness-induced
divergence, each as a single change from the stalling config:

  * ``staleness_power`` (arrival discount (1+s)^-p): 0.5 (stall) -> 1.0, 2.0
  * client ``learning_rate``: 0.05 (stall) -> 0.01
  * server discount (apply only a fraction of the buffer mean:
    ``server_optimizer='momentum'``, momentum 0, ``server_lr`` < 1):
    1.0 (stall) -> 0.25

(one point per lever at the theory-preferred setting, 15 ticks each — this
host has one core and XLA:CPU convs are ~30x oneDNN, see main()) plus the
unmodified stalling run extended to 30 ticks (does it EVER
recover?) with per-tick train loss and update norms — the divergence
signature (loss exploding vs hovering) distinguishes instability from a
too-discounted crawl. Appends rows to ``ASYNC_SYNC_CONVERGENCE.jsonl``.

Run (CPU): ``python tools/fedbuff_stall_study.py``
"""

import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # tunnel-safe; this is a CPU study

from async_convergence_study import cfg_for  # the exact stalling config
from fedtpu.core import AsyncFederation
from fedtpu.data import load

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "artifacts")
TICKS = 25


def run(mode, cfg, ticks=TICKS, staleness_power=0.5, out=None,
        speed_sigma=0.0, damping=False):
    asyn = AsyncFederation(cfg, seed=0, buffer_k=2,
                           staleness_power=staleness_power,
                           speed_sigma=speed_sigma,
                           staleness_damping=damping)
    test = load("cifar10_hard", "test", num=1024)
    accs = []
    for t in range(ticks):
        m = asyn.tick()
        _, acc = asyn.evaluate(*test)
        accs.append(round(acc, 4))
        row = {"mode": mode, "round": t, "test_acc": accs[-1],
               "train_loss": round(float(m.loss), 4),
               "update_norm": round(float(m.update_norm), 4),
               "staleness_mean": round(float(m.staleness_mean), 2)}
        print(row, file=sys.stderr, flush=True)
        if out is not None:
            out.write(json.dumps(row) + "\n")
            out.flush()
    summary = {"mode": mode, "summary": True, "ticks": ticks,
               "final_test_acc": accs[-1], "best_test_acc": max(accs)}
    if out is not None:
        out.write(json.dumps(summary) + "\n")
        out.flush()
    print(json.dumps(summary), flush=True)
    return summary


def main():
    # This host has ONE core and XLA:CPU convs are ~30x oneDNN (BASELINE.md
    # kernel-gap note): each tick+eval costs tens of seconds, so the sweep
    # keeps one point per lever at the theory-preferred setting and 15 ticks
    # per leg — enough to separate "recovers" from "still at chance" on a
    # task where the sync curve leaves chance by round ~8.
    #
    # Every leg here pins damping=False: this sweep DIAGNOSES the round-4
    # (weight-normalized) semantics. The fix the diagnosis led to —
    # staleness_damping, now the engine default — is measured by --damped.
    base = cfg_for()
    out_path = os.path.join(ART, "ASYNC_SYNC_CONVERGENCE.jsonl")
    if "--damped" in sys.argv:
        with open(out_path, "a") as out:
            # The stalling config under the engine-default damping, the
            # strong-damping point (with damping, sp is a true magnitude
            # knob), and sigma=1 under damping to check the healthy regime.
            run("fedbuff_k2_sigma0_damped", base, ticks=25, damping=True,
                out=out)
            run("fedbuff_k2_sigma0_damped_sp2", base, ticks=20,
                staleness_power=2.0, damping=True, out=out)
            run("fedbuff_k2_sigma1_damped", base, ticks=25, damping=True,
                speed_sigma=1.0, out=out)
        return
    with open(out_path, "a") as out:
        # The stalling config, longer — recovery or true stall?
        run("fedbuff_k2_sigma0_30ticks", base, ticks=30, out=out)
        # Lever 1: arrival staleness discount (sp=2 ~ quadratic damping).
        for sp in (1.0, 2.0):
            run(f"fedbuff_k2_sigma0_sp{sp:g}", base, ticks=15,
                staleness_power=sp, out=out)
        # Lever 2: client learning rate (the async-SGD stability knob).
        for lr in (0.01,):
            cfg = dataclasses.replace(
                base, opt=dataclasses.replace(base.opt, learning_rate=lr))
            run(f"fedbuff_k2_sigma0_lr{lr:g}", cfg, ticks=15, out=out)
        # Lever 3: server-side discount of the buffer mean.
        for slr in (0.25,):
            cfg = dataclasses.replace(
                base, fed=dataclasses.replace(
                    base.fed, server_optimizer="momentum",
                    server_momentum=0.0, server_lr=slr))
            run(f"fedbuff_k2_sigma0_serverlr{slr:g}", cfg, ticks=15, out=out)


if __name__ == "__main__":
    main()
