#!/usr/bin/env python
"""Rolling coordinator upgrade drill: primary -> backup -> primary with
ZERO lost rounds and a final global model BIT-IDENTICAL to an unupgraded
control run.

The scripted handover an operator performs to upgrade a coordinator in
place (docs/FAULT_TOLERANCE.md runbook):

1. **Drain gen 1.** The old primary finishes its current round completely
   (aggregate + replicate + broadcast) and stops cleanly at a round
   boundary — no round is half-done, and the backup holds a replica of the
   exact post-round state (model, FedOpt moments, lineage round counter,
   membership roster).
2. **Backup bridges.** The backup's watchdog notices the silence, promotes,
   and keeps committing rounds from the replicated state while the new
   binary rolls out — the federation never stops training.
3. **Gen 2 takes over.** The upgraded primary announces itself
   (recovering ping), the acting primary drains at a round boundary and
   demotes, gen 2 pulls the newer state via FetchModel and finishes the
   run.

What the drill asserts:

- **Zero lost, zero repeated rounds.** Committed round records across all
  three generations carry the LINEAGE round index (the counter rides the
  replica); their concatenation must be exactly ``0..rounds-1``, strictly
  monotone. Every client's local round count equals ``rounds`` — no round
  was retrained either.
- **Bit-identical model.** The final global model equals an unupgraded
  control run byte-for-byte (same seeds, same fleet, same mid-run join) —
  the upgrade is invisible to the training trajectory.
- **Membership rides the replica.** A client admitted mid-run through
  ``admit_client`` (the Join path) must appear in gen 2's roster after the
  two handovers.

Topology: client agents, backup, and both primary generations in THIS
process over real gRPC on localhost — generations are separate
PrimaryServer instances (the process-shaped drill with a SIGKILL instead
of a drain is ``tools/chaos_soak.py``; this drill is about *exactness*,
which needs readable coordinator state).

Usage::

    python tools/rolling_upgrade.py                    # default 12 rounds
    python tools/rolling_upgrade.py --rounds 8 --upgrade-round 3

Writes ``artifacts/ROLLING_UPGRADE.json`` and exits non-zero on any failed
assertion. The tier-1 leg runs this at a reduced scale
(``tests/test_membership.py``).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(num_clients: int, rounds: int, **fed_kw):
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )

    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(
            num_clients=num_clients, num_rounds=rounds,
            # The background heartbeat thread must not revive clients at
            # wall-clock-dependent moments: drills tick the monitor
            # explicitly so churn stays deterministic (and bit-comparable
            # against a control run).
            ft_heartbeat_period_s=1e6,
            **fed_kw,
        ),
        steps_per_round=2,
    )


def build_fleet(cfg, n: int, seed0: int = 0):
    """n in-process client agents over real gRPC; (addrs, servers, agents)."""
    from fedtpu.transport.federation import serve_client

    addrs, servers, agents = [], [], []
    for i in range(n):
        addr = f"localhost:{free_port()}"
        server, agent = serve_client(addr, cfg, seed=seed0 + i)
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    return addrs, servers, agents


def stop_fleet(servers) -> None:
    for s in servers:
        s.stop(0)


def model_fingerprint(primary):
    """Flat host copy of the global model for exact comparison."""
    import jax
    import numpy as np

    leaves = jax.tree_util.tree_leaves(
        {"params": primary.params, "batch_stats": primary.batch_stats}
    )
    return [np.asarray(leaf) for leaf in leaves]


def bit_identical(a, b) -> bool:
    import numpy as np

    return len(a) == len(b) and all(
        x.shape == y.shape and np.array_equal(x, y) for x, y in zip(a, b)
    )


def run_upgrade_drill(
    rounds: int = 12,
    upgrade_round: int = 5,
    clients: int = 3,
    join_round: int = 1,
    acting_window: int = 2,
    watchdog_s: float = 1.5,
    seed: int = 0,
    verbose: bool = True,
) -> dict:
    """The drill + its control run; returns the assertion/result dict."""
    from fedtpu.transport.federation import BackupServer, PrimaryServer

    assert 0 < upgrade_round < rounds, "upgrade must fall inside the run"
    # FedAvgM: the drill must prove the MOMENTS ride the handover too — a
    # plain-FedAvg drill would pass even if they were dropped.
    fed_kw = dict(server_optimizer="momentum")

    def note(msg):
        if verbose:
            print(f"[upgrade] {msg}", flush=True)

    t_start = time.monotonic()
    result: dict = {"config": {
        "rounds": rounds, "upgrade_round": upgrade_round,
        "clients": clients, "join_round": join_round,
        "watchdog_s": watchdog_s, "seed": seed,
    }}

    def run_one(upgraded: bool):
        """One full federation run over a fresh fleet; returns
        (records, fingerprint, agents' round counts, roster, extras)."""
        cfg = tiny_cfg(clients, rounds, **fed_kw)
        addrs, servers, agents = build_fleet(cfg, clients, seed0=seed)
        # The mid-run joiner: a real serving agent NOT in the startup
        # roster; admitted through the membership path at join_round in
        # both runs (so the control stays bit-comparable).
        j_addrs, j_servers, j_agents = build_fleet(cfg, 1, seed0=seed + clients)
        join_addr = j_addrs[0]
        servers.append(j_servers[0])
        agents.append(j_agents[0])
        records = []
        gens: dict = {"gen1": 0, "acting": 0, "gen2": 0}

        def on_round(which):
            def cb(r, rec):
                if not rec.get("aborted"):
                    records.append(rec)
                    gens[which] += 1
                    if rec["round"] == join_round:
                        current[0].admit_client(join_addr)
            return cb

        backup_srv = backup = None
        try:
            if not upgraded:
                primary = PrimaryServer(cfg, addrs)
                current = [primary]
                primary.run(num_rounds=rounds, on_round=on_round("gen1"))
                roster = primary.registry.status()
                return (records, model_fingerprint(primary),
                        [a.trainer.round_idx for a in agents], roster,
                        join_addr, gens)

            backup_addr = f"localhost:{free_port()}"
            backup = BackupServer(
                cfg, addrs, watchdog_timeout=watchdog_s,
                on_acting_round=lambda r, rec: on_round("acting")(r, rec),
            )
            backup_srv = backup.start(backup_addr)
            note(f"gen 1: {upgrade_round} rounds, then drain")
            gen1 = PrimaryServer(cfg, addrs, backup_address=backup_addr)
            current = [gen1]
            gen1.run(num_rounds=upgrade_round, on_round=on_round("gen1"))
            # gen 1 stopped pinging -> the watchdog bridges the gap.
            note("waiting for backup promotion + acting rounds")
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                acting = backup.acting
                if acting is not None:
                    current[0] = acting
                    if gens["acting"] >= acting_window:
                        break
                time.sleep(0.1)
            assert backup.acting is not None, "backup never promoted"
            assert gens["acting"] >= 1, "acting primary committed no rounds"
            note("gen 2: recovering ping -> demote, pull state, finish")
            gen2 = PrimaryServer(cfg, addrs, backup_address=backup_addr)
            gen2.pinger.tick()  # demote + FetchModel drain + install
            current[0] = gen2
            remaining = rounds - gen2._round_counter
            assert remaining >= 0, gen2._round_counter
            gen2.run(num_rounds=remaining, on_round=on_round("gen2"))
            roster = gen2.registry.status()
            return (records, model_fingerprint(gen2),
                    [a.trainer.round_idx for a in agents], roster,
                    join_addr, gens)
        finally:
            if backup is not None:
                backup.watchdog.stop()
                backup._stop_acting(wait=30.0)
            if backup_srv is not None:
                backup_srv.stop(0)
            stop_fleet(servers)

    note(f"control run ({rounds} rounds, no upgrade)")
    (c_records, c_model, c_counts, c_roster, _, _) = run_one(upgraded=False)
    note(f"upgrade run (drain at round {upgrade_round})")
    (u_records, u_model, u_counts, u_roster, u_join_addr, gens) = run_one(
        upgraded=True
    )

    lineage = [int(r["round"]) for r in u_records]
    result["lineage"] = {
        "committed": len(lineage),
        "strictly_monotone": lineage == sorted(set(lineage)),
        "exact_cover": lineage == list(range(rounds)),
    }
    result["generations"] = gens
    result["client_round_counts"] = {
        "control": c_counts, "upgraded": u_counts,
    }
    result["roster"] = {"control": c_roster, "upgraded": u_roster}
    result["bit_identical"] = bit_identical(c_model, u_model)
    result["wall_s"] = round(time.monotonic() - t_start, 2)

    assert result["lineage"]["exact_cover"], (
        f"lineage rounds not exactly 0..{rounds - 1}: {lineage}"
    )
    assert gens["gen1"] == upgrade_round and gens["acting"] >= 1, gens
    assert u_counts == c_counts == [rounds] * clients + [
        rounds - 1 - join_round
    ], (
        "client round counts diverged (a round was lost or retrained): "
        f"{c_counts} vs {u_counts}"
    )
    assert result["bit_identical"], (
        "post-upgrade global model differs from the unupgraded control"
    )
    # The mid-run join survived both handovers: gen 2's roster (addresses
    # are fleet-local, so compare shape + the joiner's presence).
    assert u_roster["size"] == c_roster["size"] == clients + 1, (
        c_roster, u_roster,
    )
    assert u_join_addr in u_roster["alive"], (
        "mid-run joiner missing from gen 2's roster after the upgrade"
    )
    result["ok"] = True
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", default=12, type=int)
    ap.add_argument("--upgrade-round", default=5, type=int)
    ap.add_argument("--clients", default=3, type=int)
    ap.add_argument("--watchdog", default=1.5, type=float)
    ap.add_argument("--seed", default=0, type=int)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        result = run_upgrade_drill(
            rounds=args.rounds, upgrade_round=args.upgrade_round,
            clients=args.clients, watchdog_s=args.watchdog, seed=args.seed,
        )
    except AssertionError as exc:
        print(json.dumps({"ok": False, "error": str(exc)}))
        return 1
    art = os.path.join(REPO, "artifacts")
    os.makedirs(art, exist_ok=True)
    with open(os.path.join(art, "ROLLING_UPGRADE.json"), "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
