#!/usr/bin/env python
"""Chaos soak: N federated rounds under a seeded fault schedule, including
a mid-round primary kill -> backup promotion -> primary recovery, driven
against the LIVE gRPC transport. ``--churn`` instead runs the long-haul
ELASTIC-MEMBERSHIP soak (:func:`run_churn_soak`): 1k rounds of continuous
seeded churn — dynamic joins over the Join RPC, silent leaves, stale
rejoins, graceful Leave/rejoin cycles — plus one mid-soak rolling
primary -> backup -> primary upgrade, verifying zero transient deaths, a
strictly monotone lineage round counter, a bit-identical final model vs an
unupgraded control run, and a FLAT memory profile from the ``/statusz``
RSS gauge. Writes ``artifacts/CHURN_SOAK.json``. ``--disaster`` runs the
TOTAL-PROCESS-LOSS drill (:func:`run_disaster_soak`): primary and backup
SIGKILLed mid-round under seeded disk faults, cold restart from the
hardened checkpoint store with generation fallback, bit-identical to a
no-crash control — ``artifacts/DISASTER_SOAK.json``. ``--partition`` runs
the PARTITION-HEAL soak (:func:`run_partition_soak`): symmetric,
asymmetric (split-brain fork) and gray-flap legs driven by ``partition``/
``flaky`` chaos rules, gated on epoch fencing leaving exactly one
surviving exact-cover lineage with zero transient client deaths and
bounded failover churn — ``artifacts/PARTITION_SOAK.json``. ``--tiered``
runs the HIERARCHICAL-AGGREGATION leg (:func:`run_tiered_soak`): a
2-tier real-gRPC topology (leaf aggregators as genuine subprocesses of
``fedtpu.cli.server --role aggregator``) under transient SubmitPartial
faults, with one leaf aggregator SIGKILLed mid-round — the root must
commit with the tier's rows masked, zero transient client deaths, and
an exact-cover lineage — ``artifacts/TIERED_SOAK.json``.

What it proves (the acceptance spine of the chaos/resilience PR;
docs/FAULT_TOLERANCE.md):

1. **Transient faults never kill clients.** The schedule injects transient
   RPC errors (and corrupt payloads) on >=30% of StartTrain calls;
   the retry policy absorbs them (``fedtpu_rpc_retries_total`` > 0,
   ``fedtpu_ft_client_deaths_total`` == 0).
2. **Sub-quorum rounds abort without mutating the global model.** A
   pre-flight in-process drill forces a below-quorum round and asserts the
   post-abort params/opt-state are BIT-IDENTICAL to the pre-round
   snapshot; the multi-process phase then schedules a full-round delay
   burst so a real abort (straggler-shaped, no deaths) appears in the
   round log and training still completes.
3. **Failover under fire.** A ``kill@StartTrain:rounds=K,max=1`` rule
   SIGKILLs the primary mid-round; the backup watchdog promotes, the
   acting primary commits rounds with the full client fleet, and a
   restarted primary demotes it, pulls the newer model, and finishes the
   run with a finite final eval on every client.

Topology: client agents + backup in THIS process (their state is
inspectable), the primary as a real subprocess of ``fedtpu.cli.server``
(so SIGKILL is a genuine process death over a genuine network edge).

Usage::

    python tools/chaos_soak.py                  # full soak, ~2-3 min
    python tools/chaos_soak.py --rounds 8 --kill-round 3   # quicker

Writes ``artifacts/CHAOS_SOAK.json`` and exits non-zero on any failed
assertion. The fast tier-1 chaos leg lives in ``tests/test_chaos.py``;
the full soak runs there too, marked ``slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrape_metrics(port: int) -> dict:
    """{metric_name: {labelstr: value}} from a live /metrics endpoint."""
    from fedtpu.obs import parse_prometheus_text

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as resp:
        return parse_prometheus_text(resp.read().decode())


def _read_records(path: str) -> list:
    from fedtpu.obs import read_round_records

    if not os.path.exists(path):
        return []
    return read_round_records(path)


def _committed(records: list) -> int:
    return sum(1 for r in records if not r.get("aborted"))


def _tiny_cfg(num_clients: int, rounds: int, **fed_kw):
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )

    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=rounds, **fed_kw),
        steps_per_round=2,
    )


def quorum_drill(seed: int = 7) -> dict:
    """In-process sub-quorum abort with the bit-identical restore assert:
    a chaos rule fails EVERY StartTrain of one round; with round_quorum=1.0
    the round must abort leaving params, server-opt state, and the round
    counter byte-for-byte untouched, and the next round (faults exhausted)
    must commit."""
    import numpy as np
    import jax

    from fedtpu.config import RetryPolicy
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.transport.federation import PrimaryServer, serve_client

    n, attempts = 2, 2
    cfg = _tiny_cfg(
        n, 4,
        round_quorum=1.0,
        server_optimizer="momentum",
        retry=RetryPolicy(max_attempts=attempts, backoff_s=0.01),
    )
    # Enough injections to exhaust every retry of every client for exactly
    # one round; afterwards the rule is spent and rounds commit.
    chaos = parse_spec(
        f"error@StartTrain:p=1.0,max={n * attempts},seed={seed}"
    )
    servers = []
    try:
        addrs = []
        for i in range(n):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        # p=1.0 on every StartTrain attempt: round 0 exhausts every
        # client's retry budget (the designed mark_failed path) and lands
        # below quorum -> abort.
        rec0 = primary.round()
        assert rec0.get("aborted"), f"expected round 0 abort, got {rec0}"

        def round_state(server):
            # The quorum contract covers the ROUND state (model, moments,
            # lineage counter) — the membership leaf is roster state and
            # legitimately changes as the abort marks clients dead.
            tree = server.state_tree()
            tree.pop("membership", None)
            return jax.tree.map(np.asarray, tree)

        state_after_abort = round_state(primary)
        fresh = PrimaryServer(cfg, [])  # same seed -> same init
        state_initial = round_state(fresh)
        mismatch = []
        jax.tree.map(
            lambda a, b: mismatch.append(True)
            if not np.array_equal(a, b) else None,
            state_after_abort, state_initial,
        )
        assert not mismatch, "aborted round mutated the global state"
        # Revive the exhausted clients (their servers are healthy — only
        # the schedule was hostile) and re-run: the rule is spent, so the
        # re-run commits with the full fleet.
        deadline = time.monotonic() + 30
        while primary.registry.dead_clients() and time.monotonic() < deadline:
            primary.monitor.tick()
        rec1 = primary.round()
        assert not rec1.get("aborted") and rec1["participants"] == n, rec1
        return {
            "aborted_round_bit_identical": True,
            "recommit_participants": rec1["participants"],
            "chaos_injected": chaos.injected_total(),
        }
    finally:
        for s in servers:
            s.stop(0)


def run_soak(
    rounds: int = 20,
    clients: int = 3,
    kill_round: int = 8,
    quorum: float = 0.5,
    seed: int = 7,
    error_p: float = 0.3,
    corrupt_p: float = 0.05,
    retries: int = 8,
    watchdog_s: float = 4.0,
    workdir: str = "/tmp/fedtpu_chaos_soak",
    verbose: bool = True,
) -> dict:
    """The full multi-process soak; returns the assertion/result dict."""
    from fedtpu.transport.federation import BackupServer, serve_client

    os.makedirs(workdir, exist_ok=True)
    # Round-record writers APPEND: stale files from a previous soak in the
    # same workdir would inflate the committed/aborted counts.
    for name in os.listdir(workdir):
        if name.startswith("primary_gen"):
            os.unlink(os.path.join(workdir, name))
    result: dict = {"config": {
        "rounds": rounds, "clients": clients, "kill_round": kill_round,
        "quorum": quorum, "seed": seed, "error_p": error_p,
        "corrupt_p": corrupt_p, "retries": retries,
    }}

    def note(msg):
        if verbose:
            print(f"[soak] {msg}", flush=True)

    note("phase 0: in-process quorum drill (bit-identical abort)")
    result["quorum_drill"] = quorum_drill(seed=seed)

    cfg = _tiny_cfg(clients, rounds)
    agents, servers, addrs = [], [], []
    backup_srv = None
    procs = []
    try:
        for i in range(clients):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        backup_addr_port = free_port()
        backup = BackupServer(cfg, addrs, watchdog_timeout=watchdog_s)
        backup_srv = backup.start(f"localhost:{backup_addr_port}")

        # The primary's schedule: transient errors + payload corruption on
        # the StartTrain fan-out throughout, one full-round delay burst
        # (straggler-shaped sub-quorum abort, nobody dies), and the
        # one-shot mid-round SIGKILL. The consec caps make the
        # error/corrupt rules transient BY CONSTRUCTION: the worst
        # interleaved failure run is 2*3+1 = 7 attempts, strictly under
        # the retry budget, so "zero transient deaths" holds for ANY seed
        # and any port draw.
        delay_round = max(2, kill_round // 2)
        assert retries > 7, "retry budget must exceed the worst chaos run"
        spec = (
            f"kill@StartTrain:p=1.0,rounds={kill_round}-{kill_round + 1},"
            f"max=1,seed={seed};"
            f"delay@StartTrain:p=1.0,rounds={delay_round}-{delay_round + 1},"
            f"max={clients},delay=6;"
            f"error@StartTrain:p={error_p},consec=3;"
            f"corrupt@StartTrain:p={corrupt_p},consec=1"
        )
        result["chaos_spec"] = spec

        def launch_primary(gen: int, num_rounds: int, obs_port: int):
            metrics = os.path.join(workdir, f"primary_gen{gen}.jsonl")
            prom = os.path.join(workdir, f"primary_gen{gen}.prom")
            cmd = [
                sys.executable, "-m", "fedtpu.cli.server",
                "--p", "y", "--platform", "cpu",
                "--model", "mlp", "--dataset", "synthetic",
                "--num-examples", "256", "--batch-size", "8",
                "--eval-batch-size", "8",
                "--rounds", str(num_rounds),
                "--clients", ",".join(addrs),
                "--backupAddress", "localhost",
                "--backupPort", str(backup_addr_port),
                "--metrics", metrics, "--prom-out", prom,
                "--obs-port", str(obs_port),
                "--chaos-spec", spec,
                "--round-quorum", str(quorum),
                "--round-deadline", "3",
                "--rpc-retries", str(retries),
                "--rpc-backoff", "0.02",
                "--seed", "0",
            ]
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen(
                cmd, cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            return proc, metrics, prom

        note(f"phase 1: primary gen 1 ({rounds} rounds, kill at "
             f"round {kill_round}, delay burst at round {delay_round})")
        obs1 = free_port()
        p1, metrics1, prom1 = launch_primary(1, rounds, obs1)
        procs.append(p1)
        last_scrape: dict = {}
        deadline = time.monotonic() + 600
        while p1.poll() is None and time.monotonic() < deadline:
            try:
                last_scrape = _scrape_metrics(obs1)
            except Exception:
                pass
            time.sleep(0.5)
        assert p1.poll() is not None, "primary gen 1 never exited (no kill?)"
        result["gen1_rc"] = p1.returncode
        assert p1.returncode != 0, (
            "primary gen 1 exited cleanly — the kill rule never fired"
        )
        recs1 = _read_records(metrics1)
        result["gen1_committed"] = _committed(recs1)
        result["gen1_aborted"] = len(recs1) - _committed(recs1)
        deaths = sum(
            last_scrape.get("fedtpu_ft_client_deaths_total", {}).values()
        )
        retried = sum(
            last_scrape.get("fedtpu_rpc_retries_total", {}).values()
        )
        injected = sum(
            last_scrape.get("fedtpu_chaos_injected_total", {}).values()
        )
        result["gen1_client_deaths"] = deaths
        result["gen1_retries"] = retried
        result["gen1_chaos_injected"] = injected
        assert deaths == 0, (
            f"{deaths} clients marked dead by transient faults (gen 1)"
        )
        assert retried > 0, "no RPC was ever retried under 30% fault load"

        note("phase 2: waiting for backup promotion + acting rounds")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (backup.machine.role.value == "acting_primary"
                    and backup.acting is not None
                    and _committed(backup.acting.history) >= 1):
                break
            time.sleep(0.25)
        result["promoted"] = backup.machine.role.value == "acting_primary"
        acting_committed = (
            _committed(backup.acting.history) if backup.acting else 0
        )
        result["acting_committed"] = acting_committed
        assert result["promoted"], "backup never promoted after the kill"
        assert acting_committed >= 1, "acting primary committed no rounds"

        remaining = max(1, rounds - result["gen1_committed"])
        note(f"phase 3: primary gen 2 ({remaining} rounds; demotes the "
             "acting primary and pulls its model)")
        obs2 = free_port()
        p2, metrics2, prom2 = launch_primary(2, remaining, obs2)
        procs.append(p2)
        try:
            p2.wait(timeout=600)
        except subprocess.TimeoutExpired:
            p2.kill()
            raise AssertionError("primary gen 2 hung")
        result["gen2_rc"] = p2.returncode
        assert p2.returncode == 0, f"gen 2 failed rc={p2.returncode}"
        recs2 = _read_records(metrics2)
        result["gen2_committed"] = _committed(recs2)
        with open(prom2) as fh:
            from fedtpu.obs import parse_prometheus_text

            prom2_metrics = parse_prometheus_text(fh.read())
        deaths2 = sum(
            prom2_metrics.get("fedtpu_ft_client_deaths_total", {}).values()
        )
        result["gen2_client_deaths"] = deaths2
        result["gen2_retries"] = sum(
            prom2_metrics.get("fedtpu_rpc_retries_total", {}).values()
        )
        assert deaths2 == 0, (
            f"{deaths2} clients marked dead by transient faults (gen 2)"
        )
        assert backup.machine.role.value == "backup", (
            "acting primary never demoted after gen 2's recovery ping"
        )

        total = (result["gen1_committed"] + acting_committed
                 + result["gen2_committed"])
        result["total_committed"] = total
        assert total >= rounds, (
            f"only {total} rounds committed across generations, "
            f"wanted >= {rounds}"
        )
        assert result["gen1_aborted"] >= 1, (
            "the full-round delay burst never produced a sub-quorum abort"
        )

        note("phase 4: final eval finiteness on every client")
        evals = []
        for agent in agents:
            assert agent.last_eval is not None, "client never evaluated"
            loss, acc = agent.last_eval
            assert loss == loss and abs(loss) != float("inf"), loss
            evals.append({"loss": loss, "acc": acc})
        result["final_evals"] = evals
        result["ok"] = True
        return result
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        if backup_srv is not None:
            backup.watchdog.stop()
            backup._stop_acting(wait=10.0)
            backup_srv.stop(0)
        for s in servers:
            s.stop(0)


# ------------------------------------------------------------ byzantine soak
def run_byzantine_soak(
    rounds: int = 100,
    clients: int = 7,
    malicious: int = 2,
    error_p: float = 0.10,
    retries: int = 6,
    quorum: float = 0.25,
    evict_after: int = 5,
    seed: int = 7,
    verbose: bool = True,
) -> dict:
    """The Byzantine soak (acceptance spine of the attack-harness PR):
    ``rounds`` federated rounds over the LIVE gRPC transport with ~30%
    seeded model-level attackers (sign_flip + boosted-scale, armed through
    the chaos DSL on the attacker agents) AND ~10% transient StartTrain
    faults (the PR 5 wire-chaos layer, primary-side), with fused screening
    + reputation + quarantine escalation armed. Gates:

    1. **zero honest-client deaths** — the transient faults retry away and
       the defense never kills an honest client
       (``fedtpu_ft_client_deaths_total == 0``);
    2. **every attacker quarantined-then-evicted** through the live
       MembershipTable (``fedtpu_membership_quarantine_total == malicious``,
       evictions ``reason=quarantine`` == malicious, attackers absent from
       the final roster);
    3. **monotone lineage** — committed round records cover exactly
       ``0..rounds-1``;
    4. the attack/chaos/screening layers all demonstrably fired.

    Writes ``artifacts/BYZANTINE_SOAK.json`` via ``--byzantine``.
    """
    from fedtpu.config import RetryPolicy, ScreenConfig
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import parse_prometheus_text, prometheus_text
    from fedtpu.transport.federation import PrimaryServer, serve_client

    t_start = time.monotonic()

    def note(msg):
        if verbose:
            print(f"[byz] {msg}", flush=True)

    assert 0 < malicious < clients
    cfg = _tiny_cfg(
        clients, rounds,
        weighted=False,
        round_quorum=quorum,
        # quarantine_at 0.8 with ewma 0.5 = three CONSECUTIVE flags to
        # quarantine: a persistent attacker escalates by round 3 while a
        # one-off honest false positive decays back to zero. Calibration
        # (measured on this workload, instrumented 40-round run): once
        # training converges the honest norm SPREAD reaches ~4x the median
        # (tiny noise-dominated gradients) while the boosted attacker sits
        # at 25x; under screen_rows' MAD floor z ~= 13.5*(norm/median - 1),
        # so zmax=60 cuts at ~5x the median — between the two populations,
        # with the cushion on the honest side (zmax=6 flagged honest
        # heterogeneity, and exclusion is self-reinforcing: a wrongly
        # screened client's data leaves the aggregate, inflating its next
        # delta). cos_min=-0.5 not 0 for the same reason: converged honest
        # cosines hover around 0; only a strong contrarian (sign-flip
        # scores ~-1) is evidence.
        screen=ScreenConfig(
            zmax=60.0, cos_min=-0.5, ewma=0.5,
            quarantine_at=0.8, release_at=0.2, evict_after=evict_after,
        ),
        retry=RetryPolicy(max_attempts=retries, backoff_s=0.01),
    )
    # Attacker i alternates the two delta-level kinds; every attacker fires
    # every round (persistent adversaries — the quarantine ladder's case).
    attack_specs = [
        f"sign_flip:p=1.0,seed={seed + i}" if i % 2 == 0
        else f"scale:factor=25,p=1.0,seed={seed + i}"
        for i in range(malicious)
    ]
    wire_spec = f"error@StartTrain:p={error_p},consec=2,seed={seed}"
    assert retries > 3, "retry budget must exceed the consec cap"

    servers, addrs, agents = [], [], []
    primary = None
    result: dict = {"config": {
        "rounds": rounds, "clients": clients, "malicious": malicious,
        "error_p": error_p, "retries": retries, "quorum": quorum,
        "evict_after": evict_after, "seed": seed,
        "attack_specs": attack_specs, "wire_spec": wire_spec,
    }}
    try:
        for i in range(clients):
            addr = f"localhost:{free_port()}"
            chaos = parse_spec(attack_specs[i]) if i < malicious else None
            srv, agent = serve_client(addr, cfg, seed=i, chaos=chaos)
            servers.append(srv)
            addrs.append(addr)
            agents.append(agent)
        attackers = set(addrs[:malicious])
        note(f"{clients} clients up, attackers: {sorted(attackers)}")
        primary = PrimaryServer(cfg, addrs, chaos=parse_spec(wire_spec))
        records = []
        primary.run(num_rounds=rounds,
                    on_round=lambda r, rec: records.append(rec))

        committed = [r for r in records if not r.get("aborted")]
        lineage = [int(r["round"]) for r in committed]
        parsed = parse_prometheus_text(
            prometheus_text(primary.telemetry.registry)
        )

        def metric_sum(name, label_filter=None):
            total = 0.0
            for labels, v in parsed.get(name, {}).items():
                if label_filter is None or label_filter in labels:
                    total += v
            return total

        attack_injected = sum(
            sum(parse_prometheus_text(
                prometheus_text(a.trainer.telemetry.registry)
            ).get("fedtpu_attack_injected_total", {}).values())
            for a in agents
        )
        result["lineage"] = {
            "committed": len(committed),
            "aborted": len(records) - len(committed),
            "exact_cover": lineage == list(range(rounds)),
        }
        result["observed"] = {
            "client_deaths": metric_sum("fedtpu_ft_client_deaths_total"),
            "rpc_retries": metric_sum("fedtpu_rpc_retries_total"),
            "chaos_injected": metric_sum("fedtpu_chaos_injected_total"),
            "attack_injected": attack_injected,
            "screening_rejected": metric_sum(
                "fedtpu_screening_rejected_total"),
            "quarantines": metric_sum("fedtpu_membership_quarantine_total"),
            "evictions_quarantine": metric_sum(
                "fedtpu_membership_evictions_total", "quarantine"),
        }
        result["final_roster"] = primary.registry.status()
        result["attackers_still_members"] = sorted(
            a for a in attackers if primary.registry.is_member(a)
        )
        honest = [a for a in addrs if a not in attackers]
        result["honest_evicted"] = sorted(
            a for a in honest if not primary.registry.is_member(a)
        )
        result["honest_quarantined_at_end"] = sorted(
            a for a in honest
            if primary.registry.is_quarantined(a)
        )

        # ------------------------------------------------------- the gates
        obs = result["observed"]
        assert result["lineage"]["exact_cover"], (
            f"lineage not exactly 0..{rounds - 1}: {result['lineage']}"
        )
        assert obs["client_deaths"] == 0, (
            f"{obs['client_deaths']} client deaths — transient faults or "
            "the defense killed an honest client"
        )
        assert not result["attackers_still_members"], (
            "attackers survived in the roster: "
            f"{result['attackers_still_members']}"
        )
        # Honest clients may suffer a TRANSIENT false-positive quarantine
        # over a long soak (the redemption path exists for exactly that),
        # but must never be evicted and must end the soak unquarantined.
        assert not result["honest_evicted"], (
            f"honest clients evicted: {result['honest_evicted']}"
        )
        assert not result["honest_quarantined_at_end"], (
            "honest clients still quarantined at soak end: "
            f"{result['honest_quarantined_at_end']}"
        )
        assert obs["quarantines"] >= malicious, (
            f"{obs['quarantines']} quarantines, wanted >= {malicious}"
        )
        assert obs["evictions_quarantine"] == malicious, (
            f"{obs['evictions_quarantine']} quarantine evictions, wanted "
            f"{malicious}"
        )
        assert obs["attack_injected"] > 0, "no attack ever executed"
        assert obs["screening_rejected"] >= malicious, (
            "screening never rejected the attackers"
        )
        assert obs["chaos_injected"] > 0 and obs["rpc_retries"] > 0, (
            "the transient-fault layer never exercised the retry path"
        )
        # Honest clients finished with finite evals (they kept being
        # served throughout the attack).
        evals = []
        for addr, agent in zip(addrs, agents):
            if addr in attackers:
                continue
            assert agent.last_eval is not None, f"{addr} never evaluated"
            loss, acc = agent.last_eval
            assert loss == loss and abs(loss) != float("inf"), loss
            evals.append({"loss": loss, "acc": acc})
        result["honest_final_evals"] = evals
        result["wall_s"] = round(time.monotonic() - t_start, 2)
        result["ok"] = True
        return result
    finally:
        for s in servers:
            s.stop(0)


# ------------------------------------------------------------- disaster soak
def _model_fingerprint_from_dir(ckpt_dir: str):
    """(latest_round, sha256-of-model) from a checkpoint directory, read
    WITHOUT a config template (wire.decode_raw): the fingerprint covers
    the params + batch_stats leaves in deterministic key order, so two
    runs with different ports/rosters still compare model-for-model."""
    import hashlib

    from fedtpu.checkpoint import latest_round
    from fedtpu.transport import wire

    r = latest_round(ckpt_dir)
    assert r is not None, f"no checkpoint generations in {ckpt_dir}"
    with open(os.path.join(ckpt_dir, f"round_{r}.fckpt"), "rb") as fh:
        tree = wire.decode_raw(fh.read())
    h = hashlib.sha256()

    def fold(node):
        if isinstance(node, dict):
            for key in sorted(node):
                h.update(str(key).encode())
                fold(node[key])
        else:
            import numpy as np

            arr = np.asarray(node)
            h.update(str(arr.dtype).encode() + str(arr.shape).encode())
            h.update(np.ascontiguousarray(arr).tobytes())

    fold({"params": tree["params"], "batch_stats": tree["batch_stats"]})
    return r, h.hexdigest()


def run_disaster_soak(
    rounds: int = 24,
    clients: int = 3,
    kill_round: int = 12,
    keep: int = 8,
    seed: int = 7,
    watchdog_s: float = 120.0,
    workdir: str = "/tmp/fedtpu_disaster_soak",
    verbose: bool = True,
) -> dict:
    """The total-process-loss drill (acceptance spine of the durability
    PR; docs/OPERATIONS.md §Disaster recovery): primary AND backup are
    SIGKILLed mid-round — every in-memory copy of the federation state is
    gone — under seeded DISK faults that silently corrupted the two newest
    checkpoint generations (``ckpt_torn`` on the save after round K-1,
    ``ckpt_rot`` on the save after round K-2). A cold-restarted primary
    (``--resume`` against the same ``--checkpoint-dir``) must then:

    1. fall back past both corrupt generations to the newest VERIFIED one
       (``fedtpu_checkpoint_fallback_total == 2``), resuming at round K-2
       with ZERO manual intervention (no files deleted, no flags beyond
       the ordinary restart command);
    2. resync the surviving clients through the ordinary pre-round
       broadcast — no re-registration (``fedtpu_membership_joins_total ==
       0`` post-restart), full participation from the first recovered
       round; the lineage round carried in StartTrain makes each client
       roll its local state back to its round-K-2 snapshot, so the
       replayed rounds retrain bit-for-bit;
    3. produce a lineage that is exact-cover monotone across the restart
       under SUPERSESSION semantics: the crash voided the never-durable
       rounds >= K-2, the restart re-commits them, and the durable history
       (pre-crash records below the resume point + the restart's records)
       covers exactly 0..N-1;
    4. end with a final model BIT-IDENTICAL to an uninterrupted control
       run — the whole recovery, rollback included, is trajectory-neutral.

    Topology: client agents in THIS process (they survive — the disaster
    is coordinator-total, not world-total; a restarted CLIENT is covered
    by --state-dir, tests/test_disaster.py), primary and backup as real
    subprocesses so SIGKILL is a genuine process death. Writes
    ``artifacts/DISASTER_SOAK.json`` via ``--disaster``.
    """
    from fedtpu.obs import parse_prometheus_text
    from fedtpu.transport.federation import serve_client

    assert 4 <= kill_round <= rounds - 2, (kill_round, rounds)
    assert keep >= 4, "need headroom: two corrupt generations + fallback"
    t_start = time.monotonic()

    def note(msg):
        if verbose:
            print(f"[disaster] {msg}", flush=True)

    os.makedirs(workdir, exist_ok=True)
    for name in os.listdir(workdir):
        path = os.path.join(workdir, name)
        if os.path.isdir(path):
            import shutil

            shutil.rmtree(path, ignore_errors=True)
        else:
            os.unlink(path)
    ckpt_dir = os.path.join(workdir, "ckpt")
    control_dir = os.path.join(workdir, "ckpt_control")

    cfg = _tiny_cfg(clients, rounds)
    # The save after round K-1 is TORN and the one after K-2 BIT-ROTTEN —
    # both silently (the writer verified before the fault landed, exactly
    # a disk that acked and then lost the bytes). The kill fires on the
    # first StartTrain of round K. Newest verified generation: K-3, so
    # recovery resumes at K-2 after two fallbacks.
    spec = (
        f"kill@StartTrain:p=1.0,rounds={kill_round}-{kill_round + 1},"
        f"max=1,seed={seed};"
        f"ckpt_torn@Disk:p=1.0,rounds={kill_round - 1}-{kill_round},max=1;"
        f"ckpt_rot@Disk:p=1.0,rounds={kill_round - 2}-{kill_round - 1},max=1"
    )
    expected_resume = kill_round - 2
    result: dict = {"config": {
        "rounds": rounds, "clients": clients, "kill_round": kill_round,
        "keep": keep, "seed": seed, "chaos_spec": spec,
        "expected_resume_round": expected_resume,
    }}

    def launch_backup(gen: int, addrs, port: int):
        cmd = [
            sys.executable, "-m", "fedtpu.cli.server",
            "--platform", "cpu",
            "--model", "mlp", "--dataset", "synthetic",
            "--num-examples", "256", "--batch-size", "8",
            "--eval-batch-size", "8",
            "--clients", ",".join(addrs),
            "--listen", f"localhost:{port}",
            "--watchdog-timeout", str(watchdog_s),
            "--seed", "0",
        ]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )

    def launch_primary(tag: str, addrs, backup_port, directory,
                       chaos_spec=None, resume=False, sync_writes=False):
        metrics = os.path.join(workdir, f"primary_{tag}.jsonl")
        prom = os.path.join(workdir, f"primary_{tag}.prom")
        cmd = [
            sys.executable, "-m", "fedtpu.cli.server",
            "--p", "y", "--platform", "cpu",
            "--model", "mlp", "--dataset", "synthetic",
            "--num-examples", "256", "--batch-size", "8",
            "--eval-batch-size", "8",
            "--rounds", str(rounds),
            "--clients", ",".join(addrs),
            "--checkpoint-dir", directory,
            "--checkpoint-keep", str(keep),
            "--metrics", metrics, "--prom-out", prom,
            "--seed", "0",
        ]
        if backup_port is not None:
            cmd += ["--backupAddress", "localhost",
                    "--backupPort", str(backup_port)]
        if chaos_spec:
            cmd += ["--chaos-spec", chaos_spec]
        if resume:
            cmd += ["--resume"]
        if sync_writes:
            # Deterministic disk-fault placement: synchronous saves pin
            # each save's chaos round window to the round it snapshots
            # (the background writer races the next round's set_round).
            cmd += ["--checkpoint-sync"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        return proc, metrics, prom

    # ------------------------------------------------------- disaster run
    servers, agents, addrs = [], [], []
    procs = []
    try:
        for i in range(clients):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        bport1 = free_port()
        backup1 = launch_backup(1, addrs, bport1)
        procs.append(backup1)
        note(f"gen 1: {rounds} rounds, kill at round {kill_round}, "
             f"torn ckpt at {kill_round - 1}, rot at {kill_round - 2}")
        p1, metrics1, _prom1 = launch_primary(
            "gen1", addrs, bport1, ckpt_dir, chaos_spec=spec,
            sync_writes=True,
        )
        procs.append(p1)
        deadline = time.monotonic() + 600
        while p1.poll() is None and time.monotonic() < deadline:
            time.sleep(0.5)
        assert p1.poll() is not None, "gen 1 never exited (kill never fired)"
        result["gen1_rc"] = p1.returncode
        assert p1.returncode != 0, (
            "gen 1 exited cleanly — the kill rule never fired"
        )
        # The disaster is TOTAL: the backup's in-memory replica dies too,
        # seconds after the primary (before its watchdog could promote).
        backup1.kill()
        backup1.wait(timeout=30)
        note("primary and backup SIGKILLed; every in-memory copy is gone")
        recs1 = _read_records(metrics1)
        committed1 = [r for r in recs1 if not r.get("aborted")]
        result["gen1_committed"] = len(committed1)
        assert len(committed1) == kill_round, (
            f"gen 1 committed {len(committed1)} rounds, wanted {kill_round}"
        )

        note("cold restart: fresh backup + primary --resume from the "
             "(partially corrupted) checkpoint dir — no manual cleanup")
        bport2 = free_port()
        backup2 = launch_backup(2, addrs, bport2)
        procs.append(backup2)
        p2, metrics2, prom2 = launch_primary(
            "gen2", addrs, bport2, ckpt_dir, resume=True,
        )
        procs.append(p2)
        try:
            p2.wait(timeout=600)
        except subprocess.TimeoutExpired:
            p2.kill()
            raise AssertionError("recovered primary hung")
        result["gen2_rc"] = p2.returncode
        assert p2.returncode == 0, f"recovery failed rc={p2.returncode}"
        backup2.kill()

        recs2 = _read_records(metrics2)
        committed2 = [r for r in recs2 if not r.get("aborted")]
        assert committed2, "recovered primary committed nothing"
        resume_round = int(committed2[0]["round"])
        result["resume_round"] = resume_round
        assert resume_round == expected_resume, (
            f"resumed at {resume_round}, expected {expected_resume} "
            "(two generation fallbacks)"
        )
        with open(prom2) as fh:
            prom2_metrics = parse_prometheus_text(fh.read())
        fallbacks = sum(
            prom2_metrics.get("fedtpu_checkpoint_fallback_total", {}).values()
        )
        rejoins = sum(
            prom2_metrics.get("fedtpu_membership_joins_total", {}).values()
        )
        result["checkpoint_fallbacks"] = fallbacks
        result["post_restart_joins"] = rejoins
        assert fallbacks == 2, (
            f"{fallbacks} restore fallbacks, expected 2 (torn + rot)"
        )
        assert rejoins == 0, (
            "surviving clients re-registered — roster was lost"
        )

        # Lineage under supersession: the crash voided the never-durable
        # tail (>= resume_round); what remains plus the restart's records
        # must cover exactly 0..N-1, strictly monotone.
        durable1 = [
            int(r["round"]) for r in committed1
            if int(r["round"]) < resume_round
        ]
        lineage = durable1 + [int(r["round"]) for r in committed2]
        result["lineage"] = {
            "committed": len(lineage),
            "superseded": len(committed1) - len(durable1),
            "strictly_monotone": all(
                b == a + 1 for a, b in zip(lineage, lineage[1:])
            ),
            "exact_cover": lineage == list(range(rounds)),
        }
        assert result["lineage"]["exact_cover"], result["lineage"]
        # Full participation from the first recovered round: the
        # survivors resynced through the ordinary broadcast.
        assert all(r["participants"] == clients for r in committed2), (
            "a surviving client missed a post-recovery round"
        )
        evals = []
        for agent in agents:
            assert agent.last_eval is not None, "client never evaluated"
            loss, acc = agent.last_eval
            assert loss == loss and abs(loss) != float("inf"), loss
            evals.append({"loss": loss, "acc": acc})
        result["final_evals"] = evals
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for s in servers:
            s.stop(0)

    # -------------------------------------------------------- control run
    note("control run: same config, fresh clients, no crash, no faults")
    servers2, addrs2 = [], []
    try:
        for i in range(clients):
            addr = f"localhost:{free_port()}"
            server, _agent = serve_client(addr, cfg, seed=i)
            servers2.append(server)
            addrs2.append(addr)
        pc, metrics_c, _prom_c = launch_primary(
            "control", addrs2, None, control_dir,
        )
        try:
            pc.wait(timeout=600)
        except subprocess.TimeoutExpired:
            pc.kill()
            raise AssertionError("control primary hung")
        assert pc.returncode == 0, f"control failed rc={pc.returncode}"
        recs_c = _read_records(metrics_c)
        assert _committed(recs_c) == rounds
    finally:
        for s in servers2:
            s.stop(0)

    r_d, fp_d = _model_fingerprint_from_dir(ckpt_dir)
    r_c, fp_c = _model_fingerprint_from_dir(control_dir)
    result["final_round"] = {"disaster": r_d, "control": r_c}
    result["model_fingerprint"] = {"disaster": fp_d, "control": fp_c}
    result["bit_identical_vs_control"] = fp_d == fp_c
    assert r_d == r_c == rounds - 1, (r_d, r_c)
    assert result["bit_identical_vs_control"], (
        "post-disaster final model differs from the uninterrupted "
        "control — recovery was not trajectory-neutral"
    )
    result["manual_interventions"] = 0  # scripted restart only, by design
    result["wall_s"] = round(time.monotonic() - t_start, 2)
    result["ok"] = True
    return result


# ---------------------------------------------------------------- churn soak
class GhostableAgent:
    """A ClientAgent whose reachability is a driver-controlled switch:
    ``down=True`` makes every RPC abort UNAVAILABLE — a silent departure —
    and ``down=False`` brings the SAME stateful agent back (a stale
    rejoin: its weights/optimizer/round counter are wherever it left
    them). Built lazily so jax imports stay inside the soak."""

    def __new__(cls, cfg, seed):
        import grpc

        from fedtpu.transport.federation import ClientAgent

        class _Ghost(ClientAgent):
            def __init__(self, cfg, seed):
                super().__init__(cfg, seed=seed)
                self.down = False

            def _gate(self, context):
                if self.down:
                    context.abort(grpc.StatusCode.UNAVAILABLE,
                                  "ghost: silently departed")

            def StartTrain(self, request, context):
                self._gate(context)
                return super().StartTrain(request, context)

            def SendModel(self, request, context):
                self._gate(context)
                return super().SendModel(request, context)

            def HeartBeat(self, request, context):
                self._gate(context)
                return super().HeartBeat(request, context)

        return _Ghost(cfg, seed)


class ChurnDriver:
    """Deterministic churn scheduler, driven from the round loop's
    ``on_round`` callback (so actions land at exact committed lineage
    rounds — identical in the upgrade run and its control run).

    Actions per committed round r (modular schedule seeded once):

    - **new join** at each round in ``join_rounds``: start a fresh serving
      agent and admit it through the REAL Join RPC against the current
      membership gate;
    - **silent leave** (r % 29 == 13, outside the final grace window):
      flip a live member's ghost switch — next round its StartTrain
      exhausts retries and the coordinator marks it dead (the ONLY
      expected deaths of the soak);
    - **stale rejoin** (r % 29 == 25): flip the switches back and tick the
      heartbeat monitor — the members revive through the probe + resync
      path with their stale local state;
    - **graceful leave** (r % 47 == 11): a previously-joined member sends
      Leave — evicted, seat freed;
    - **graceful rejoin** (r % 47 == 31): the departed members Join again
      (taking the freed seats back).

    The driver's OWN ledger (up/member flags) decides victim validity, so
    the schedule replays identically however coordinator bookkeeping lags.
    """

    def __init__(self, cfg, rounds, join_seeds, join_rounds, rss_every=10):
        self.cfg = cfg
        self.rounds = rounds
        self.join_seeds = list(join_seeds)
        self.join_rounds = list(join_rounds)
        self.rss_every = rss_every
        self.coord = None        # current coordinator (set by orchestrator)
        self.gate_stub = None    # current Join/Leave target
        self.obs_url = None      # /statusz endpoint for the RSS series
        self.servers = []        # grpc servers we own (for teardown)
        self.agents = {}         # addr -> agent (ghostables)
        self.up = {}             # addr -> driver's view of reachability
        self.member = {}         # addr -> driver's view of membership
        self.joined = []         # join-pool addrs in admission order
        self.order = []          # every agent ever created, creation order
        self.records = []        # committed round records, arrival order
        # Rounds where gate actions + revivals are suppressed (the drain
        # window before a promotion: see run_churn_soak's docstring).
        self.blackout = set()
        self.expected_deaths = 0
        self.scheduled = {"join": 0, "silent_leave": 0, "stale_rejoin": 0,
                          "leave": 0, "rejoin": 0}
        self.rss_series = []
        self.buffer_series = []

    def add_initial(self, addrs, agents):
        for addr, agent in zip(addrs, agents):
            self.agents[addr] = agent
            self.order.append(addr)
            self.up[addr] = True
            self.member[addr] = True

    def _join(self, addr) -> None:
        from fedtpu.transport import proto

        reply = self.gate_stub.Join(
            proto.JoinRequest(address=addr.encode()), timeout=10,
        )
        assert reply.admitted, f"gate refused join of {addr}"
        self.member[addr] = True
        self.scheduled["join" if addr not in self.joined else "rejoin"] += 1
        if addr not in self.joined:
            self.joined.append(addr)

    def _leave(self, addr) -> None:
        from fedtpu.transport import proto

        reply = self.gate_stub.Leave(
            proto.LeaveRequest(address=addr.encode()), timeout=10,
        )
        assert reply.left, f"gate refused leave of {addr}"
        self.member[addr] = False
        self.scheduled["leave"] += 1

    def on_round(self, r: int, rec: dict) -> None:
        if rec.get("aborted"):
            return
        r = int(rec.get("round", r))
        self.records.append(rec)
        if self.obs_url and (r % self.rss_every == 0 or r == self.rounds - 1):
            try:
                with urllib.request.urlopen(
                    f"{self.obs_url}/statusz", timeout=5
                ) as resp:
                    snap = json.loads(resp.read().decode())
                mem = snap.get("mem", {})
                self.rss_series.append([r, int(mem.get("rss_bytes", 0))])
                self.buffer_series.append(
                    [r, int(mem.get("buffer_bytes", 0))]
                )
            except Exception:
                pass
        if r in self.blackout:
            return  # drain window: no roster changes the replica would miss
        # New joiners enter through the gate at their scheduled rounds.
        if r in self.join_rounds:
            i = self.join_rounds.index(r)
            addr = f"localhost:{free_port()}"
            agent = GhostableAgent(self.cfg, seed=self.join_seeds[i])
            from fedtpu.transport.service import create_server

            server = create_server(addr, agent)
            server.start()
            self.servers.append(server)
            self.agents[addr] = agent
            self.order.append(addr)
            self.up[addr] = True
            self._join(addr)
        grace = r < self.rounds - 5  # deaths must land before the end
        # Victim/revival order is CREATION order, never address order:
        # ports differ between a run and its control, and an address sort
        # would churn different clients in each (breaking bit-parity).
        pool = [a for a in self.order if self.member[a]]
        if grace and r % 29 == 13 and pool:
            victim = pool[(r // 29) % len(pool)]
            if self.up[victim]:
                self.agents[victim].down = True
                self.up[victim] = False
                self.expected_deaths += 1
                self.scheduled["silent_leave"] += 1
        if r % 29 == 25:
            stale = [
                a for a in self.order if self.member[a] and not self.up[a]
            ]
            for addr in stale:
                self.agents[addr].down = False
                self.up[addr] = True
            if stale:
                self.scheduled["stale_rejoin"] += len(stale)
                self.coord.monitor.tick()
        if grace and r % 47 == 11 and self.joined:
            leaver = self.joined[(r // 47) % len(self.joined)]
            if self.member[leaver] and self.up[leaver]:
                self._leave(leaver)
        if r % 47 == 31:
            for addr in [a for a in self.joined if not self.member[a]]:
                if self.up[addr]:
                    self._join(addr)

    def teardown(self):
        for s in self.servers:
            s.stop(0)


def _flatness(series, rounds):
    """RSS growth between the settled first and final windows, in percent
    (warmup — jit caches for the joiner fleet — excluded)."""
    settled = [v for r, v in series if r >= 0.3 * rounds]
    if len(settled) < 8:
        return {"samples": len(settled), "growth_pct": 0.0}
    k = max(1, len(settled) // 4)
    first = sum(settled[:k]) / k
    last = sum(settled[-k:]) / k
    return {
        "samples": len(series),
        "settled_samples": len(settled),
        "first_window_bytes": int(first),
        "last_window_bytes": int(last),
        "growth_pct": round((last / max(first, 1.0) - 1.0) * 100.0, 3),
    }


def run_churn_soak(
    rounds: int = 1000,
    initial_clients: int = 4,
    joiners: int = 3,
    upgrade_round=None,
    quorum: float = 0.25,
    watchdog_s: float = 2.0,
    error_p: float = 0.12,
    retries: int = 6,
    acting_window: int = 20,
    seed: int = 7,
    rss_every: int = 10,
    rss_growth_limit_pct: float = 8.0,
    verbose: bool = True,
) -> dict:
    """The long-haul elastic-membership soak (module docstring, and the
    acceptance gate of the elastic-membership PR). Returns the result dict;
    raises AssertionError on any violated invariant.

    Determinism: every churn action keys on the committed LINEAGE round, so
    the unupgraded control run replays the identical membership history;
    the chaos errors are injected client-side pre-call and consec-capped
    under the retry budget, so they perturb timing and counters but never
    the training trajectory. The only intentional non-determinism is WHERE
    the two handover boundaries fall — which, by the zero-loss design,
    must not matter; the bit-identical gate is exactly that claim. Gate
    actions and revivals are blacked out for the 3 rounds before the
    drain (the last pre-promotion replica is pushed a round earlier, so a
    roster change there would be invisible to the acting primary but not
    to the control run); the acting -> gen2 handover needs no blackout
    because FetchModel serializes the CURRENT state at fetch time.
    """
    from fedtpu.config import RetryPolicy
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import ObsServer, parse_prometheus_text, prometheus_text
    from fedtpu.transport.federation import BackupServer, PrimaryServer
    from fedtpu.transport.service import TrainerStub, create_channel

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import rolling_upgrade as ru

    if upgrade_round is None:
        upgrade_round = rounds // 2
    assert 0 < upgrade_round < rounds
    t_start = time.monotonic()

    def note(msg):
        if verbose:
            print(f"[churn] {msg}", flush=True)

    base_cfg = ru.tiny_cfg(
        initial_clients, rounds,
        round_quorum=quorum,
        # flat layout -> streaming collect: the fedtpu_buffer_bytes gauge
        # then watches a real per-round allocation.
        delta_layout="flat",
        retry=RetryPolicy(max_attempts=retries, backoff_s=0.01),
    )
    join_rounds = []
    blackout = set(range(upgrade_round - 3, upgrade_round))
    for i in range(joiners):
        r = min(max(2, round(rounds * 0.06 * (i + 1))), rounds - 10)
        while r in blackout:
            r += 4
        join_rounds.append(r)
    join_seeds = [initial_clients + i for i in range(joiners)]

    def build_driver():
        from fedtpu.transport.service import create_server

        addrs, agents, servers = [], [], []
        for i in range(initial_clients):
            addr = f"localhost:{free_port()}"
            agent = GhostableAgent(base_cfg, seed=i)
            server = create_server(addr, agent)
            server.start()
            servers.append(server)
            addrs.append(addr)
            agents.append(agent)
        driver = ChurnDriver(
            base_cfg, rounds, join_seeds, join_rounds, rss_every=rss_every,
        )
        driver.blackout = blackout
        driver.servers.extend(servers)
        driver.add_initial(addrs, agents)
        return driver, addrs

    # The error schedule is PRE-CALL and consec-capped under the retry
    # budget: injected attempts never reach an agent and never exhaust, so
    # the chaos is bit-transparent to the training trajectory (the control
    # run need not replay the same port-keyed draws).
    chaos_spec = f"error@StartTrain:p={error_p},consec=2,seed={seed}"
    assert retries > 3, "retry budget must exceed the consec cap"

    def counters_sum(primaries, name):
        """Sum a counter (all label sets) across coordinator registries."""
        total = 0.0
        for p in primaries:
            if p is None:
                continue
            parsed = parse_prometheus_text(
                prometheus_text(p.telemetry.registry)
            )
            total += sum(parsed.get(name, {}).values())
        return total

    result: dict = {"config": {
        "rounds": rounds, "initial_clients": initial_clients,
        "joiners": joiners, "upgrade_round": upgrade_round,
        "quorum": quorum, "watchdog_s": watchdog_s, "error_p": error_p,
        "retries": retries, "seed": seed, "chaos_spec": chaos_spec,
        "join_rounds": join_rounds,
    }}

    # ------------------------------------------------------ upgraded run
    note(f"upgrade run: {rounds} rounds, {initial_clients}+{joiners} "
         f"clients, rolling upgrade at round {upgrade_round}")
    driver, addrs = build_driver()
    obs = ObsServer(port=0, status_fn=lambda: driver.coord.status_snapshot())
    obs.start()
    driver.obs_url = obs.url
    backup = backup_srv = None
    gen1 = gen2 = None
    try:
        backup_addr = f"localhost:{free_port()}"
        backup = BackupServer(
            base_cfg, addrs, watchdog_timeout=watchdog_s,
            on_acting_round=lambda r, rec: (
                setattr(driver, "coord", backup.acting),
                driver.on_round(r, rec),
            )[-1],
        )
        backup_srv = backup.start(backup_addr)
        gate1_addr = f"localhost:{free_port()}"
        gen1 = PrimaryServer(
            base_cfg, addrs, backup_address=backup_addr,
            chaos=parse_spec(chaos_spec),
        )
        gen1.start_gate(gate1_addr)
        driver.coord = gen1
        driver.gate_stub = TrainerStub(create_channel(gate1_addr))
        note(f"phase 1: gen 1 drives rounds 0..{upgrade_round - 1}, "
             "then drains for the upgrade")
        gen1.run(num_rounds=upgrade_round, on_round=driver.on_round)
        gen1.stop_gate()
        # While the "new binary rolls out", the backup bridges: joins and
        # leaves retarget the backup's stable address (it delegates to its
        # acting primary once promoted).
        driver.gate_stub = TrainerStub(create_channel(backup_addr))
        note("phase 2: watchdog promotes the backup; acting primary "
             f"bridges ~{acting_window} rounds")
        target = min(rounds, upgrade_round + acting_window)
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if driver.records and int(
                driver.records[-1]["round"]
            ) >= target - 1:
                break
            time.sleep(0.2)
        assert backup.acting is not None, "backup never promoted"
        acting = backup.acting
        note("phase 3: upgraded gen 2 announces itself, pulls state, "
             "finishes the soak")
        gen2 = PrimaryServer(
            base_cfg, addrs, backup_address=backup_addr,
            chaos=parse_spec(chaos_spec),
        )
        gen2.pinger.tick()  # demote + drain + FetchModel install
        gate2_addr = f"localhost:{free_port()}"
        gen2.start_gate(gate2_addr)
        driver.coord = gen2
        driver.gate_stub = TrainerStub(create_channel(gate2_addr))
        acting_committed = gen2._round_counter - upgrade_round
        assert acting_committed >= 1, "acting primary committed no rounds"
        remaining = rounds - gen2._round_counter
        gen2.run(num_rounds=remaining, on_round=driver.on_round)
        gen2.stop_gate()

        primaries = [gen1, acting, gen2]
        lineage = [int(r["round"]) for r in driver.records]
        u_model = ru.model_fingerprint(gen2)
        u_counts = [
            driver.agents[a].trainer.round_idx for a in driver.order
        ]
        result["generations"] = {
            "gen1": upgrade_round,
            "acting": int(acting_committed),
            "gen2": int(remaining),
        }
        result["lineage"] = {
            "committed": len(lineage),
            "strictly_monotone": all(
                b == a + 1 for a, b in zip(lineage, lineage[1:])
            ),
            "exact_cover": lineage == list(range(rounds)),
        }
        result["scheduled"] = dict(driver.scheduled)
        result["expected_silent_deaths"] = driver.expected_deaths
        result["observed"] = {
            "client_deaths": counters_sum(
                primaries, "fedtpu_ft_client_deaths_total"),
            "recoveries": counters_sum(
                primaries, "fedtpu_ft_client_recoveries_total"),
            "rpc_retries": counters_sum(
                primaries, "fedtpu_rpc_retries_total"),
            "chaos_injected": counters_sum(
                primaries, "fedtpu_chaos_injected_total"),
            "membership_joins": counters_sum(
                primaries, "fedtpu_membership_joins_total"),
            "membership_evictions": counters_sum(
                primaries, "fedtpu_membership_evictions_total"),
            "round_aborts": counters_sum(
                primaries, "fedtpu_round_aborts_total"),
        }
        result["final_roster"] = gen2.registry.status()
        result["memory"] = _flatness(driver.rss_series, rounds)
        result["memory"]["rss_series_sampled"] = driver.rss_series[::5]
        result["memory"]["buffer_bytes_last"] = (
            driver.buffer_series[-1][1] if driver.buffer_series else 0
        )
    finally:
        if backup is not None:
            backup.watchdog.stop()
            backup._stop_acting(wait=30.0)
        if backup_srv is not None:
            backup_srv.stop(0)
        if gen1 is not None:
            gen1.stop_gate()
        if gen2 is not None:
            gen2.stop_gate()
        obs.stop()
        driver.teardown()

    # ------------------------------------------------------- control run
    note("control run: identical churn schedule, no upgrade")
    driver2, addrs2 = build_driver()
    control = None
    try:
        control = PrimaryServer(
            base_cfg, addrs2, chaos=parse_spec(chaos_spec),
        )
        gate_c = f"localhost:{free_port()}"
        control.start_gate(gate_c)
        driver2.coord = control
        driver2.gate_stub = TrainerStub(create_channel(gate_c))
        control.run(num_rounds=rounds, on_round=driver2.on_round)
        control.stop_gate()
        c_model = ru.model_fingerprint(control)
        c_counts = [
            driver2.agents[a].trainer.round_idx for a in driver2.order
        ]
        c_deaths = counters_sum(
            [control], "fedtpu_ft_client_deaths_total")
    finally:
        if control is not None:
            control.stop_gate()
        driver2.teardown()

    result["bit_identical_vs_control"] = ru.bit_identical(c_model, u_model)
    result["client_round_counts"] = {
        "control": c_counts, "upgraded": u_counts,
    }
    result["wall_s"] = round(time.monotonic() - t_start, 2)

    # ------------------------------------------------------- the gates
    obs_d = result["observed"]
    assert result["lineage"]["exact_cover"], (
        "lineage round counter not exactly 0..N-1 "
        f"(committed {result['lineage']['committed']})"
    )
    assert obs_d["client_deaths"] == driver.expected_deaths, (
        f"{obs_d['client_deaths']} deaths observed, "
        f"{driver.expected_deaths} silent leaves scheduled — transient "
        "faults killed clients"
    )
    assert c_deaths == driver2.expected_deaths, (
        f"control run: {c_deaths} deaths vs "
        f"{driver2.expected_deaths} scheduled"
    )
    assert obs_d["rpc_retries"] > 0 and obs_d["chaos_injected"] > 0, (
        "the chaos schedule never exercised the retry path"
    )
    assert obs_d["membership_joins"] == (
        driver.scheduled["join"] + driver.scheduled["rejoin"]
    ), (result["scheduled"], obs_d)
    assert obs_d["membership_evictions"] == driver.scheduled["leave"], (
        result["scheduled"], obs_d,
    )
    assert obs_d["round_aborts"] == 0, (
        f"{obs_d['round_aborts']} unexpected sub-quorum aborts"
    )
    assert driver.scheduled["join"] == joiners
    assert min(driver.scheduled["silent_leave"],
               driver.scheduled["stale_rejoin"],
               driver.scheduled["leave"],
               driver.scheduled["rejoin"]) > 0, (
        "a churn mode never fired: " + json.dumps(driver.scheduled)
    )
    assert u_counts == c_counts, (
        "per-client round counts diverged (a round was lost or "
        f"retrained): control={c_counts} upgraded={u_counts}"
    )
    assert result["bit_identical_vs_control"], (
        "post-upgrade global model differs from the unupgraded control"
    )
    mem = result["memory"]
    if rounds >= 300:
        # The leak gate needs a LONG soak: below ~300 rounds the settled
        # window is all jit-cache warmup and the slope means nothing.
        assert mem.get("settled_samples", 0) >= 8, mem
        assert mem["growth_pct"] < rss_growth_limit_pct, (
            f"RSS grew {mem['growth_pct']}% across the soak "
            f"(limit {rss_growth_limit_pct}%) — leak"
        )
        mem["gate"] = f"growth < {rss_growth_limit_pct}% (enforced)"
    else:
        mem["gate"] = "skipped (short run; enforced from 300 rounds)"
    result["ok"] = True
    return result


# ------------------------------------------------- partition-heal soak
def _supersession_lineage(recs):
    """Fold arrival-ordered committed round records (from EVERY
    coordinator that ever ran) into the SURVIVING lineage under epoch
    supersession (docs/FAULT_TOLERANCE.md §Coordinator fencing): a
    higher-epoch commit at round ``r`` supersedes every previously-kept
    round ``>= r`` (the winner re-based past the fork), and a lower-epoch
    commit arriving after the winner's is a stale fork's and void.
    Returns ``(survivors, voided)``."""
    kept, voided, cur = [], [], -1
    for rec in recs:
        e, r = rec["epoch"], rec["round"]
        if e > cur:
            voided.extend(k for k in kept if k["round"] >= r)
            kept = [k for k in kept if k["round"] < r]
            kept.append(rec)
            cur = e
        elif e == cur:
            kept.append(rec)
        else:
            voided.append(rec)
    return kept, voided


def _partition_leg(mode: str, rounds: int, partition_round: int,
                   clients: int, seed: int, verbose: bool) -> dict:
    """One leg of the partition-heal soak, over the live gRPC transport:

    - ``symmetric``  — a ``partition`` group rule cuts the primary from
      backup AND clients; the watchdog promotes, the acting primary
      (epoch 2) commits rounds; on heal the stale primary is fenced via
      live STALE_COORDINATOR rejections, voids its in-flight round,
      re-bases (demote + FetchModel, epoch 3) and finishes. Gated
      bit-identical to a no-partition control.
    - ``asymmetric`` — only the primary->backup direction is cut: the
      backup hears silence and promotes while clients still obey the old
      primary, which keeps committing a STALE FORK. The acting primary's
      sync fences it mid-fork; it stays fenced (the backup link is still
      down, so the recovering handshake cannot land) until the heal.
      Gated on the supersession fold voiding >= 1 forked round while the
      survivors exact-cover the lineage.
    - ``gray``       — a ``flaky`` rule flaps ONLY the watchdog ping
      path for a bounded window (delays past the watchdog timeout, then
      fails): promote/fence/re-base cycles churn, but stay BOUNDED and
      the lineage converges once the window closes.

    Every leg gates zero transient client deaths and a final demoted
    backup + healthy (200) primary. Returns the leg's evidence dict;
    raises AssertionError on any gate."""
    import threading

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import rolling_upgrade as ru

    from fedtpu.config import RetryPolicy
    from fedtpu.ft import Role
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import parse_prometheus_text, prometheus_text
    from fedtpu.transport.federation import BackupServer, PrimaryServer

    def vlog(msg):
        if verbose:
            print(f"[partition:{mode}] {msg}", flush=True)

    def registry(coord):
        tel = coord.telemetry
        return tel.registry if tel.enabled else None

    def csum(regs, name):
        total = 0.0
        for reg in regs:
            if reg is None:
                continue
            total += sum(parse_prometheus_text(
                prometheus_text(reg)).get(name, {}).values())
        return total

    gray_window_s = 8.0
    if mode == "symmetric":
        # The cut includes the client links: only a LONG capped-backoff
        # retry budget keeps the collect workers retrying (partitioned
        # links fail instantly, so attempts are cheap) until the heal.
        retry = RetryPolicy(max_attempts=600, backoff_s=0.05,
                            backoff_multiplier=1.5, backoff_max_s=0.25)
        watchdog = 2.0
    else:
        # Client links stay clean; backup-link failures should resolve
        # FAST so the stale fork keeps committing (asymmetric) and flap
        # cycles stay short (gray).
        retry = RetryPolicy(max_attempts=4, backoff_s=0.05,
                            backoff_multiplier=1.5, backoff_max_s=0.1)
        watchdog = 2.5 if mode == "asymmetric" else 1.5
    cfg = _tiny_cfg(
        clients, rounds,
        round_quorum=1.0,
        server_optimizer="momentum",
        ft_heartbeat_period_s=0.5,
        retry=retry,
    )

    addrs, servers, agents = ru.build_fleet(cfg, clients, seed0=seed)
    backup_addr = f"localhost:{free_port()}"
    if mode == "symmetric":
        group = "|".join([backup_addr] + addrs)
        spec = f"partition@*:peer={group},p=1,window=3600-1000000"
    elif mode == "asymmetric":
        spec = f"partition@*:peer={backup_addr},p=1,window=3600-1000000"
    else:
        spec = (f"flaky@CheckIfPrimaryUp:p=0.8,delay=2.5,code=UNAVAILABLE,"
                f"seed={seed},window=3600-{3600 + gray_window_s:.0f}")
    sched = parse_spec(spec)

    lock = threading.Lock()
    timeline = []   # (source, round record) in arrival order
    actings = []    # every acting PrimaryServer ever observed

    def on_rec(src):
        def cb(r, rec):
            with lock:
                timeline.append((src, dict(rec)))
            if (src == "primary" and not rec.get("aborted")
                    and rec.get("epoch") == 1
                    and rec["round"] == partition_round - 1):
                # Open the fault window at this exact lineage boundary
                # (the callback runs synchronously inside the round loop).
                sched._t0 = time.monotonic() - 3601.0
                vlog(f"window OPEN after round {rec['round']}")
        return cb

    def committed(src=None):
        with lock:
            return [rec for s, rec in timeline
                    if not rec.get("aborted") and src in (None, s)]

    healed = threading.Event()
    bail = threading.Event()
    result = {"mode": mode, "rounds": rounds, "clients": clients,
              "partition_round": partition_round, "watchdog_s": watchdog,
              "spec": spec}
    backup = BackupServer(cfg, addrs, watchdog_timeout=watchdog,
                          on_acting_round=on_rec("acting"))
    backup_srv = backup.start(backup_addr)
    primary = PrimaryServer(cfg, addrs, backup_address=backup_addr,
                            chaos=sched)
    errs = []

    def drive():
        try:
            # healed gates the exit so a flap can never strand a live
            # acting primary after the stale side already finished.
            primary.run(
                num_rounds=10**9,
                stop=lambda: bail.is_set() or (
                    healed.is_set()
                    and primary._coord_epoch > 1
                    and not primary._fenced
                    and primary._round_counter >= rounds),
                on_round=on_rec("primary"),
            )
        except BaseException as exc:  # surfaced by the soak thread
            errs.append(exc)

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    try:
        def harvest():
            a = backup.acting
            if a is not None and all(a is not x for x in actings):
                actings.append(a)
                vlog(f"acting primary #{len(actings)} "
                     f"(epoch {a._coord_epoch})")

        def wait_for(cond, what, timeout=420.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                harvest()
                if errs:
                    raise AssertionError(
                        f"{mode}: primary loop died: {errs[0]!r}")
                if cond():
                    return
                time.sleep(0.05)
            raise AssertionError(f"{mode}: timed out waiting for {what}")

        wait_for(lambda: actings, "watchdog promotion")
        if mode == "symmetric":
            wait_for(lambda: len(committed("acting")) >= 2,
                     "acting-primary commits")
            sched._t0 = time.monotonic() - 10_000_000.0
            healed.set()
            vlog("window HEALED")
        elif mode == "asymmetric":
            # The fence arrives over the CLIENT links (the acting sync's
            # higher epoch) while the backup link is still down — the
            # primary must hold the fence rather than mint past a winner
            # it cannot reach.
            wait_for(lambda: primary._fenced,
                     "fence via client-side rejections")
            vlog("stale primary fenced mid-fork")
            wait_for(lambda: len(committed("acting")) >= 2,
                     "acting-primary commits")
            sched._t0 = time.monotonic() - 10_000_000.0
            healed.set()
            vlog("window HEALED")
        else:  # gray: the window expires on its own
            wait_for(
                lambda: time.monotonic() - sched._t0
                > 3600 + gray_window_s + 0.5,
                "flap-window expiry",
            )
            healed.set()
            vlog("window EXPIRED")
        t.join(timeout=420.0)
        assert not t.is_alive(), f"{mode}: round loop never finished"
        assert not errs, errs
        wait_for(lambda: backup.machine.role is Role.BACKUP,
                 "final demotion", timeout=60.0)
        harvest()

        # ---- exactly ONE surviving lineage, exact cover ----
        survivors, voided = _supersession_lineage(committed())
        lineage = [r["round"] for r in survivors]
        if mode == "gray":
            # The exit is gated on window expiry (so a flap can never
            # strand a live acting primary), and the lineage keeps
            # committing while the link flaps: gate a CONTIGUOUS exact
            # cover 0..K-1 of at least the configured length.
            assert (lineage == list(range(len(lineage)))
                    and len(lineage) >= rounds), (
                f"gray: surviving lineage is not a contiguous cover: "
                f"{lineage}")
        else:
            assert lineage == list(range(rounds)), (
                f"{mode}: surviving lineage is not an exact cover: "
                f"{lineage}")
        result["lineage_rounds"] = len(lineage)
        result["stale_fork_rounds"] = len(voided)
        result["epoch_chain"] = sorted({r["epoch"] for r in survivors})
        if mode == "symmetric":
            # The cut primary could never commit forked rounds: its
            # in-flight round died on unreachable clients and was voided.
            assert not voided, f"symmetric: unexpected fork: {voided}"
        if mode == "asymmetric":
            assert len(voided) >= 1, (
                "asymmetric: the stale primary committed no forked "
                "rounds before the fence — the leg proved nothing")
        result["acting_rounds"] = len(committed("acting"))
        assert result["acting_rounds"] >= 1

        # ---- post-heal protocol state ----
        assert primary._coord_epoch >= 3 and not primary._fenced, (
            mode, primary._coord_epoch, primary._fenced)
        assert primary.health() == (True, "ok")
        result["final_epoch"] = primary._coord_epoch

        # ---- bounded failover churn ----
        breg = backup.telemetry.registry
        promotions = int(breg.counter(
            "fedtpu_ft_failover_transitions_total",
            labels={"to": "acting_primary"}).value)
        demotions = int(breg.counter(
            "fedtpu_ft_failover_transitions_total",
            labels={"to": "backup"}).value)
        result["promotions"], result["demotions"] = promotions, demotions
        assert promotions >= 1
        if mode == "gray":
            # window / watchdog + slack: flapping must stay BOUNDED — a
            # promotion storm would mean fencing amplifies the gray link.
            assert promotions <= 8, f"promotion storm: {promotions}"
        else:
            assert promotions == 1, (mode, promotions)
        assert demotions == promotions, (promotions, demotions)

        # ---- zero transient deaths; the fence actually fired ----
        coord_regs = [registry(primary)] + [registry(a) for a in actings]
        deaths = csum(coord_regs, "fedtpu_ft_client_deaths_total")
        assert deaths == 0, f"{mode}: {deaths} transient client deaths"
        result["client_deaths"] = int(deaths)
        fences = csum(coord_regs, "fedtpu_ft_fenced_total")
        assert fences >= 1
        if mode != "gray":
            assert fences == 1, (mode, fences)
        result["fences"] = int(fences)
        stale = csum(
            [a_.trainer.telemetry.registry for a_ in agents]
            + [backup.telemetry.registry],
            "fedtpu_ft_stale_rejected_total")
        assert stale >= 1, f"{mode}: no live STALE_COORDINATOR rejection"
        result["stale_rejections"] = int(stale)

        if mode == "symmetric":
            # The stale lineage never reached a client: every committed
            # round trained every client exactly once.
            counts = [a_.trainer.round_idx for a_ in agents]
            assert counts == [rounds] * clients, counts
            u_model = ru.model_fingerprint(primary)
    finally:
        sched._t0 = time.monotonic() - 10_000_000.0  # heal for teardown
        bail.set()
        backup.watchdog.stop()
        backup._stop_acting(wait=30.0)
        backup_srv.stop(0)
        ru.stop_fleet(servers)

    if mode == "symmetric":
        addrs2, servers2, agents2 = ru.build_fleet(cfg, clients,
                                                   seed0=seed)
        try:
            control = PrimaryServer(cfg, addrs2)
            control.run(num_rounds=rounds)
            c_model = ru.model_fingerprint(control)
        finally:
            ru.stop_fleet(servers2)
        result["bit_identical_vs_control"] = ru.bit_identical(
            c_model, u_model)
        assert result["bit_identical_vs_control"], (
            "symmetric: post-heal global model differs from the "
            "no-partition control — the fork leaked into the surviving "
            "trajectory")
    vlog("leg complete: " + json.dumps(
        {k: v for k, v in result.items() if k != "spec"}))
    result["ok"] = True
    return result


def run_partition_soak(rounds: int = 20, clients: int = 3,
                       partition_round: int = 6, seed: int = 7,
                       verbose: bool = False) -> dict:
    """The partition-tolerance acceptance soak: three legs (symmetric
    cut, asymmetric cut, gray flap — see :func:`_partition_leg`) over the
    live gRPC transport. Writes ``artifacts/PARTITION_SOAK.json`` via
    ``main``; the fast in-process drill is tier-1 in
    ``tests/test_fencing.py``."""
    legs = {}
    for mode in ("symmetric", "asymmetric", "gray"):
        t0 = time.monotonic()
        legs[mode] = _partition_leg(
            mode, rounds, partition_round, clients, seed, verbose)
        legs[mode]["wall_s"] = round(time.monotonic() - t0, 2)
    return {
        "ok": all(leg["ok"] for leg in legs.values()),
        "soak": "partition",
        "rounds_per_leg": rounds,
        "clients": clients,
        "partition_round": partition_round,
        "seed": seed,
        "legs": legs,
    }


# --------------------------------------------------------------- tiered soak
def _scrape_statusz(port: int) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=5
    ) as resp:
        return json.loads(resp.read().decode())


def run_tiered_soak(
    rounds: int = 12,
    aggregators: int = 2,
    fanout: int = 2,
    kill_round: int = 5,
    error_p: float = 0.15,
    retries: int = 6,
    seed: int = 7,
    verbose: bool = True,
) -> dict:
    """The hierarchical-aggregation chaos leg (acceptance spine of the
    multi-tier PR; docs/ARCHITECTURE.md §Multi-tier, docs/OPERATIONS.md
    §Hierarchical aggregation): a 2-tier topology over the LIVE gRPC
    transport — leaf clients in THIS process, every leaf
    ``AggregatorServer`` a real subprocess of ``fedtpu.cli.server --role
    aggregator``, the root an in-process ``PrimaryServer`` in tier mode —
    with seeded transient faults on the root->aggregator ``SubmitPartial``
    link throughout and one leaf aggregator SIGKILLed MID-ROUND. Gates:

    1. **The root commits through the kill with the tier's rows masked.**
       The kill round (and every round after it) commits with
       ``participants == aggregators - 1`` and ``clients_aggregated ==
       (aggregators - 1) * fanout`` — the dead tier becomes one masked
       row, never an abort, never a hang (``round_quorum`` is per-tier).
    2. **Zero transient client deaths.** The tier-link faults retry away
       (``fedtpu_rpc_retries_total > 0``) and the only death anywhere is
       the SIGKILLed aggregator itself: root-side
       ``fedtpu_ft_client_deaths_total == 1`` (the aggregator peer), and
       every SURVIVING aggregator's roster shows zero dead cohort
       clients.
    3. **Exact-cover lineage.** Committed round records cover exactly
       ``0..rounds-1``, strictly monotone — the mid-round process death
       costs capacity, not lineage.

    Writes ``artifacts/TIERED_SOAK.json`` via ``--tiered``. The fast
    in-process masking drill is tier-1 in ``tests/test_aggregator.py``
    (``test_root_masks_failed_aggregator_row``).
    """
    import threading

    from fedtpu.config import RetryPolicy
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import parse_prometheus_text, prometheus_text
    from fedtpu.transport.federation import PrimaryServer, serve_client

    assert aggregators >= 2, "need a surviving tier to mask against"
    assert 2 <= kill_round <= rounds - 2, (kill_round, rounds)
    t_start = time.monotonic()

    def note(msg):
        if verbose:
            print(f"[tiered] {msg}", flush=True)

    # consec=2 keeps the worst failure run strictly under the retry
    # budget: the tier-link faults are transient BY CONSTRUCTION, so the
    # only mark_failed of the soak is the genuine process death.
    spec = f"error@SubmitPartial:p={error_p},consec=2,seed={seed}"
    assert retries > 3, "retry budget must exceed the consec cap"
    cfg = _tiny_cfg(
        aggregators, rounds,
        delta_layout="flat",
        tier_fanout=fanout,
        round_quorum=0.5,
        retry=RetryPolicy(max_attempts=retries, backoff_s=0.02),
    )
    result: dict = {"config": {
        "rounds": rounds, "aggregators": aggregators, "fanout": fanout,
        "kill_round": kill_round, "error_p": error_p, "retries": retries,
        "seed": seed, "chaos_spec": spec,
    }}

    servers, agents, client_addrs = [], [], []
    procs, agg_addrs, obs_ports = [], [], []
    try:
        for i in range(aggregators * fanout):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            client_addrs.append(addr)
        note(f"{len(client_addrs)} leaf clients up")

        for j in range(aggregators):
            cohort = client_addrs[j * fanout:(j + 1) * fanout]
            port, obs_port = free_port(), free_port()
            cmd = [
                sys.executable, "-m", "fedtpu.cli.server",
                "--role", "aggregator", "--platform", "cpu",
                "--model", "mlp", "--dataset", "synthetic",
                "--num-examples", "256", "--batch-size", "8",
                "--eval-batch-size", "8",
                "--clients", ",".join(cohort),
                "--listen", f"localhost:{port}",
                "--delta-layout", "flat",
                "--tier-fanout", str(fanout),
                "--obs-port", str(obs_port),
                "--seed", "0",
            ]
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            procs.append(subprocess.Popen(
                cmd, cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            ))
            agg_addrs.append(f"localhost:{port}")
            obs_ports.append(obs_port)
        # Wait for every aggregator's obs endpoint (jax import is the
        # long pole) before the root starts pulling.
        deadline = time.monotonic() + 120
        for j, obs_port in enumerate(obs_ports):
            while True:
                assert procs[j].poll() is None, (
                    f"aggregator {j} died during startup"
                )
                try:
                    snap = _scrape_statusz(obs_port)
                    assert snap["mem"]["tier"] == "leaf", snap
                    break
                except (OSError, KeyError):
                    assert time.monotonic() < deadline, (
                        f"aggregator {j} never served /statusz"
                    )
                    time.sleep(0.25)
        note(f"{aggregators} leaf aggregators up (subprocesses), "
             f"cohorts of {fanout}")

        victim = aggregators - 1
        killed_at = []
        armed = threading.Event()

        def killer():
            armed.wait()
            # The previous round just committed; the root is already
            # inside round `kill_round`'s broadcast/fan-out by the time
            # this fires (a leaf round walls hundreds of ms), so the
            # SIGKILL lands with the tier's SubmitPartial in flight.
            time.sleep(0.05)
            procs[victim].kill()
            killed_at.append(time.monotonic())
            note(f"aggregator {victim} ({agg_addrs[victim]}) SIGKILLed "
                 "mid-round")

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        records = []

        def on_round(r, rec):
            records.append(dict(rec))
            if not rec.get("aborted") and int(rec["round"]) == kill_round - 1:
                armed.set()

        primary = PrimaryServer(cfg, agg_addrs, chaos=parse_spec(spec))
        note(f"root: {rounds} rounds over {aggregators} tiers, kill at "
             f"round {kill_round}, tier-link chaos {spec!r}")
        primary.run(num_rounds=rounds, on_round=on_round)
        kt.join(timeout=10)
        assert killed_at, "the kill never fired"

        committed = [r for r in records if not r.get("aborted")]
        lineage = [int(r["round"]) for r in committed]
        result["lineage"] = {
            "committed": len(committed),
            "aborted": len(records) - len(committed),
            "exact_cover": lineage == list(range(rounds)),
        }
        assert result["lineage"]["exact_cover"], (
            f"lineage not exactly 0..{rounds - 1}: {lineage}"
        )

        # ---- the masked-tier gate ----
        masked = [int(r["round"]) for r in committed
                  if r["participants"] < aggregators]
        result["first_masked_round"] = masked[0] if masked else None
        assert masked and masked[0] == kill_round, (
            f"masking started at {masked[:1]}, expected round {kill_round}"
        )
        for rec in committed:
            r = int(rec["round"])
            want = aggregators - 1 if r >= kill_round else aggregators
            assert rec["participants"] == want, (r, rec)
            assert rec["aggregated"] == want, (r, rec)
            assert rec["clients_aggregated"] == want * fanout, (r, rec)
            # Seat capacity (and so the rank/world data partition) is
            # stable across the death: the tier is masked, not re-tiled.
            assert rec["world"] == aggregators * fanout, (r, rec)
            assert rec["tier_fanout"] == fanout, (r, rec)
        result["participants_by_round"] = [
            [int(r["round"]), int(r["participants"])] for r in committed
        ]
        result["clients_aggregated_by_round"] = [
            [int(r["round"]), int(r["clients_aggregated"])]
            for r in committed
        ]

        # ---- zero transient deaths; the tier-link chaos really fired ----
        parsed = parse_prometheus_text(
            prometheus_text(primary.telemetry.registry)
        )

        def msum(name):
            return sum(parsed.get(name, {}).values())

        result["observed"] = {
            "root_peer_deaths": msum("fedtpu_ft_client_deaths_total"),
            "rpc_retries": msum("fedtpu_rpc_retries_total"),
            "chaos_injected": msum("fedtpu_chaos_injected_total"),
        }
        obs = result["observed"]
        assert obs["root_peer_deaths"] == 1, (
            f"{obs['root_peer_deaths']} root-side deaths — transient "
            "tier-link faults killed a live aggregator (expected exactly "
            "the SIGKILLed one)"
        )
        assert obs["rpc_retries"] > 0 and obs["chaos_injected"] > 0, (
            "the tier-link chaos never exercised the SubmitPartial retry "
            "path"
        )
        survivors = []
        for j in range(aggregators):
            if j == victim:
                continue
            snap = _scrape_statusz(obs_ports[j])
            agg_metrics = _scrape_metrics(obs_ports[j])
            dead = snap["clients"]["dead"]
            cohort_deaths = sum(
                agg_metrics.get("fedtpu_ft_client_deaths_total", {}).values()
            )
            assert dead == 0 and cohort_deaths == 0, (
                f"aggregator {j}: {dead} dead cohort clients "
                f"({cohort_deaths} death events) — the tier kill cascaded"
            )
            survivors.append({
                "aggregator": agg_addrs[j],
                "tier": snap["mem"]["tier"],
                "round_seen": snap["round"],
                "cohort_active": snap["clients"]["active"],
                "cohort_dead": dead,
            })
        result["surviving_tiers"] = survivors

        # Surviving-cohort clients finished with finite evals (they were
        # served through the death without interruption).
        evals = []
        for i, agent in enumerate(agents):
            if i // fanout == victim:
                continue  # orphaned mid-soak by design
            assert agent.last_eval is not None, "client never evaluated"
            loss, acc = agent.last_eval
            assert loss == loss and abs(loss) != float("inf"), loss
            evals.append({"loss": loss, "acc": acc})
        result["surviving_final_evals"] = evals
        result["wall_s"] = round(time.monotonic() - t_start, 2)
        result["ok"] = True
        return result
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        for s in servers:
            s.stop(0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", default=20, type=int)
    ap.add_argument("--clients", default=3, type=int)
    ap.add_argument("--kill-round", default=8, type=int)
    ap.add_argument("--quorum", default=0.5, type=float)
    ap.add_argument("--seed", default=7, type=int)
    ap.add_argument("--error-p", default=0.3, type=float)
    ap.add_argument("--retries", default=8, type=int,
                    help="retry budget; must exceed the worst interleaved "
                    "chaos run (2*3+1 attempts under the default spec)")
    ap.add_argument("--workdir", default="/tmp/fedtpu_chaos_soak")
    ap.add_argument(
        "--byzantine", action="store_true",
        help="run the Byzantine soak instead: N rounds over real gRPC "
        "with ~30%% seeded model-level attackers + ~10%% transient wire "
        "faults, screening/quarantine armed; gates zero honest deaths, "
        "every attacker quarantined-and-evicted, monotone lineage; "
        "writes artifacts/BYZANTINE_SOAK.json",
    )
    ap.add_argument("--byz-rounds", default=100, type=int)
    ap.add_argument("--byz-clients", default=7, type=int)
    ap.add_argument("--byz-malicious", default=2, type=int)
    ap.add_argument("--byz-error-p", default=0.10, type=float)
    ap.add_argument(
        "--disaster", action="store_true",
        help="run the total-process-loss drill instead: primary AND "
        "backup SIGKILLed mid-round under seeded ckpt_torn/ckpt_rot disk "
        "faults -> cold restart from --checkpoint-dir falls back past the "
        "corrupt generations, survivors resync without re-registration, "
        "lineage exact-covers under supersession, final model bit-"
        "identical to a no-crash control; writes "
        "artifacts/DISASTER_SOAK.json",
    )
    ap.add_argument("--disaster-rounds", default=24, type=int)
    ap.add_argument("--disaster-kill-round", default=12, type=int)
    ap.add_argument("--disaster-keep", default=8, type=int)
    ap.add_argument(
        "--churn", action="store_true",
        help="run the long-haul elastic-membership churn soak instead "
        "(continuous join/leave/rejoin + one mid-soak rolling upgrade; "
        "writes artifacts/CHURN_SOAK.json)",
    )
    ap.add_argument("--churn-rounds", default=1000, type=int)
    ap.add_argument("--initial-clients", default=4, type=int)
    ap.add_argument("--joiners", default=3, type=int)
    ap.add_argument("--upgrade-round", default=None, type=int,
                    help="lineage round of the mid-soak rolling upgrade "
                    "(default: --churn-rounds / 2)")
    ap.add_argument(
        "--partition", action="store_true",
        help="run the partition-heal soak instead: three legs over live "
        "gRPC — symmetric cut (backup promotes; on heal the stale "
        "primary is fenced, voids its round, re-bases; bit-identical to "
        "a no-partition control), asymmetric cut (split-brain: the stale "
        "side commits a FORK that the epoch fold voids), gray flap "
        "(flaky watchdog pings; promote/demote churn stays bounded). "
        "Gates zero transient deaths + one surviving exact-cover "
        "lineage; writes artifacts/PARTITION_SOAK.json",
    )
    ap.add_argument("--partition-rounds", default=20, type=int)
    ap.add_argument("--partition-round", default=6, type=int,
                    help="lineage round after which each leg's fault "
                    "window opens")
    ap.add_argument(
        "--tiered", action="store_true",
        help="run the hierarchical-aggregation chaos leg instead: a "
        "2-tier real-gRPC topology (leaf aggregators as subprocesses of "
        "fedtpu.cli.server --role aggregator) under transient "
        "SubmitPartial faults, one leaf aggregator SIGKILLed mid-round; "
        "gates masked-tier commits at the root, zero transient client "
        "deaths, exact-cover lineage; writes artifacts/TIERED_SOAK.json",
    )
    ap.add_argument("--tiered-rounds", default=12, type=int)
    ap.add_argument("--tiered-kill-round", default=5, type=int)
    ap.add_argument("--aggregators", default=2, type=int)
    ap.add_argument("--fanout", default=2, type=int)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.tiered:
        try:
            result = run_tiered_soak(
                rounds=args.tiered_rounds,
                aggregators=args.aggregators,
                fanout=args.fanout,
                kill_round=args.tiered_kill_round,
                error_p=args.error_p if args.error_p != 0.3 else 0.15,
                retries=max(args.retries, 4),
                seed=args.seed,
            )
        except AssertionError as exc:
            print(json.dumps({"ok": False, "error": str(exc)}))
            return 1
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        with open(os.path.join(art, "TIERED_SOAK.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        print(json.dumps(result))
        return 0
    if args.partition:
        try:
            result = run_partition_soak(
                rounds=args.partition_rounds,
                clients=args.clients,
                partition_round=args.partition_round,
                seed=args.seed,
                verbose=args.verbose,
            )
        except AssertionError as exc:
            print(json.dumps({"ok": False, "error": str(exc)}))
            return 1
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        with open(os.path.join(art, "PARTITION_SOAK.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        print(json.dumps(result))
        return 0
    if args.disaster:
        try:
            result = run_disaster_soak(
                rounds=args.disaster_rounds,
                clients=args.clients,
                kill_round=args.disaster_kill_round,
                keep=args.disaster_keep,
                seed=args.seed,
            )
        except AssertionError as exc:
            print(json.dumps({"ok": False, "error": str(exc)}))
            return 1
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        with open(os.path.join(art, "DISASTER_SOAK.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        print(json.dumps(result))
        return 0
    if args.byzantine:
        try:
            result = run_byzantine_soak(
                rounds=args.byz_rounds,
                clients=args.byz_clients,
                malicious=args.byz_malicious,
                error_p=args.byz_error_p,
                retries=max(args.retries, 4),
                seed=args.seed,
            )
        except AssertionError as exc:
            print(json.dumps({"ok": False, "error": str(exc)}))
            return 1
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        with open(os.path.join(art, "BYZANTINE_SOAK.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        print(json.dumps(result))
        return 0
    if args.churn:
        try:
            result = run_churn_soak(
                rounds=args.churn_rounds,
                initial_clients=args.initial_clients,
                joiners=args.joiners,
                upgrade_round=args.upgrade_round,
                seed=args.seed,
                error_p=args.error_p,
                retries=max(args.retries, 4),
            )
        except AssertionError as exc:
            print(json.dumps({"ok": False, "error": str(exc)}))
            return 1
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        with open(os.path.join(art, "CHURN_SOAK.json"), "w") as fh:
            json.dump(result, fh, indent=2)
        print(json.dumps({k: v for k, v in result.items()
                          if k != "memory"} | {"memory": {
                              k: v for k, v in result["memory"].items()
                              if k != "rss_series_sampled"}}))
        return 0
    try:
        result = run_soak(
            rounds=args.rounds, clients=args.clients,
            kill_round=args.kill_round, quorum=args.quorum, seed=args.seed,
            error_p=args.error_p, retries=args.retries,
            workdir=args.workdir,
        )
    except AssertionError as exc:
        print(json.dumps({"ok": False, "error": str(exc)}))
        return 1
    art = os.path.join(REPO, "artifacts")
    os.makedirs(art, exist_ok=True)
    with open(os.path.join(art, "CHAOS_SOAK.json"), "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
