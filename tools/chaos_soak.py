#!/usr/bin/env python
"""Chaos soak: N federated rounds under a seeded fault schedule, including
a mid-round primary kill -> backup promotion -> primary recovery, driven
against the LIVE gRPC transport.

What it proves (the acceptance spine of the chaos/resilience PR;
docs/FAULT_TOLERANCE.md):

1. **Transient faults never kill clients.** The schedule injects transient
   RPC errors (and corrupt payloads) on >=30% of StartTrain calls;
   the retry policy absorbs them (``fedtpu_rpc_retries_total`` > 0,
   ``fedtpu_ft_client_deaths_total`` == 0).
2. **Sub-quorum rounds abort without mutating the global model.** A
   pre-flight in-process drill forces a below-quorum round and asserts the
   post-abort params/opt-state are BIT-IDENTICAL to the pre-round
   snapshot; the multi-process phase then schedules a full-round delay
   burst so a real abort (straggler-shaped, no deaths) appears in the
   round log and training still completes.
3. **Failover under fire.** A ``kill@StartTrain:rounds=K,max=1`` rule
   SIGKILLs the primary mid-round; the backup watchdog promotes, the
   acting primary commits rounds with the full client fleet, and a
   restarted primary demotes it, pulls the newer model, and finishes the
   run with a finite final eval on every client.

Topology: client agents + backup in THIS process (their state is
inspectable), the primary as a real subprocess of ``fedtpu.cli.server``
(so SIGKILL is a genuine process death over a genuine network edge).

Usage::

    python tools/chaos_soak.py                  # full soak, ~2-3 min
    python tools/chaos_soak.py --rounds 8 --kill-round 3   # quicker

Writes ``artifacts/CHAOS_SOAK.json`` and exits non-zero on any failed
assertion. The fast tier-1 chaos leg lives in ``tests/test_chaos.py``;
the full soak runs there too, marked ``slow``.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrape_metrics(port: int) -> dict:
    """{metric_name: {labelstr: value}} from a live /metrics endpoint."""
    from fedtpu.obs import parse_prometheus_text

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5
    ) as resp:
        return parse_prometheus_text(resp.read().decode())


def _read_records(path: str) -> list:
    from fedtpu.obs import read_round_records

    if not os.path.exists(path):
        return []
    return read_round_records(path)


def _committed(records: list) -> int:
    return sum(1 for r in records if not r.get("aborted"))


def _tiny_cfg(num_clients: int, rounds: int, **fed_kw):
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )

    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=rounds, **fed_kw),
        steps_per_round=2,
    )


def quorum_drill(seed: int = 7) -> dict:
    """In-process sub-quorum abort with the bit-identical restore assert:
    a chaos rule fails EVERY StartTrain of one round; with round_quorum=1.0
    the round must abort leaving params, server-opt state, and the round
    counter byte-for-byte untouched, and the next round (faults exhausted)
    must commit."""
    import numpy as np
    import jax

    from fedtpu.config import RetryPolicy
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.transport.federation import PrimaryServer, serve_client

    n, attempts = 2, 2
    cfg = _tiny_cfg(
        n, 4,
        round_quorum=1.0,
        server_optimizer="momentum",
        retry=RetryPolicy(max_attempts=attempts, backoff_s=0.01),
    )
    # Enough injections to exhaust every retry of every client for exactly
    # one round; afterwards the rule is spent and rounds commit.
    chaos = parse_spec(
        f"error@StartTrain:p=1.0,max={n * attempts},seed={seed}"
    )
    servers = []
    try:
        addrs = []
        for i in range(n):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        # p=1.0 on every StartTrain attempt: round 0 exhausts every
        # client's retry budget (the designed mark_failed path) and lands
        # below quorum -> abort.
        rec0 = primary.round()
        assert rec0.get("aborted"), f"expected round 0 abort, got {rec0}"
        state_after_abort = jax.tree.map(np.asarray, primary.state_tree())
        fresh = PrimaryServer(cfg, [])  # same seed -> same init
        state_initial = jax.tree.map(np.asarray, fresh.state_tree())
        mismatch = []
        jax.tree.map(
            lambda a, b: mismatch.append(True)
            if not np.array_equal(a, b) else None,
            state_after_abort, state_initial,
        )
        assert not mismatch, "aborted round mutated the global state"
        # Revive the exhausted clients (their servers are healthy — only
        # the schedule was hostile) and re-run: the rule is spent, so the
        # re-run commits with the full fleet.
        deadline = time.monotonic() + 30
        while primary.registry.dead_clients() and time.monotonic() < deadline:
            primary.monitor.tick()
        rec1 = primary.round()
        assert not rec1.get("aborted") and rec1["participants"] == n, rec1
        return {
            "aborted_round_bit_identical": True,
            "recommit_participants": rec1["participants"],
            "chaos_injected": chaos.injected_total(),
        }
    finally:
        for s in servers:
            s.stop(0)


def run_soak(
    rounds: int = 20,
    clients: int = 3,
    kill_round: int = 8,
    quorum: float = 0.5,
    seed: int = 7,
    error_p: float = 0.3,
    corrupt_p: float = 0.05,
    retries: int = 8,
    watchdog_s: float = 4.0,
    workdir: str = "/tmp/fedtpu_chaos_soak",
    verbose: bool = True,
) -> dict:
    """The full multi-process soak; returns the assertion/result dict."""
    from fedtpu.transport.federation import BackupServer, serve_client

    os.makedirs(workdir, exist_ok=True)
    # Round-record writers APPEND: stale files from a previous soak in the
    # same workdir would inflate the committed/aborted counts.
    for name in os.listdir(workdir):
        if name.startswith("primary_gen"):
            os.unlink(os.path.join(workdir, name))
    result: dict = {"config": {
        "rounds": rounds, "clients": clients, "kill_round": kill_round,
        "quorum": quorum, "seed": seed, "error_p": error_p,
        "corrupt_p": corrupt_p, "retries": retries,
    }}

    def note(msg):
        if verbose:
            print(f"[soak] {msg}", flush=True)

    note("phase 0: in-process quorum drill (bit-identical abort)")
    result["quorum_drill"] = quorum_drill(seed=seed)

    cfg = _tiny_cfg(clients, rounds)
    agents, servers, addrs = [], [], []
    backup_srv = None
    procs = []
    try:
        for i in range(clients):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        backup_addr_port = free_port()
        backup = BackupServer(cfg, addrs, watchdog_timeout=watchdog_s)
        backup_srv = backup.start(f"localhost:{backup_addr_port}")

        # The primary's schedule: transient errors + payload corruption on
        # the StartTrain fan-out throughout, one full-round delay burst
        # (straggler-shaped sub-quorum abort, nobody dies), and the
        # one-shot mid-round SIGKILL. The consec caps make the
        # error/corrupt rules transient BY CONSTRUCTION: the worst
        # interleaved failure run is 2*3+1 = 7 attempts, strictly under
        # the retry budget, so "zero transient deaths" holds for ANY seed
        # and any port draw.
        delay_round = max(2, kill_round // 2)
        assert retries > 7, "retry budget must exceed the worst chaos run"
        spec = (
            f"kill@StartTrain:p=1.0,rounds={kill_round}-{kill_round + 1},"
            f"max=1,seed={seed};"
            f"delay@StartTrain:p=1.0,rounds={delay_round}-{delay_round + 1},"
            f"max={clients},delay=6;"
            f"error@StartTrain:p={error_p},consec=3;"
            f"corrupt@StartTrain:p={corrupt_p},consec=1"
        )
        result["chaos_spec"] = spec

        def launch_primary(gen: int, num_rounds: int, obs_port: int):
            metrics = os.path.join(workdir, f"primary_gen{gen}.jsonl")
            prom = os.path.join(workdir, f"primary_gen{gen}.prom")
            cmd = [
                sys.executable, "-m", "fedtpu.cli.server",
                "--p", "y", "--platform", "cpu",
                "--model", "mlp", "--dataset", "synthetic",
                "--num-examples", "256", "--batch-size", "8",
                "--eval-batch-size", "8",
                "--rounds", str(num_rounds),
                "--clients", ",".join(addrs),
                "--backupAddress", "localhost",
                "--backupPort", str(backup_addr_port),
                "--metrics", metrics, "--prom-out", prom,
                "--obs-port", str(obs_port),
                "--chaos-spec", spec,
                "--round-quorum", str(quorum),
                "--round-deadline", "3",
                "--rpc-retries", str(retries),
                "--rpc-backoff", "0.02",
                "--seed", "0",
            ]
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            proc = subprocess.Popen(
                cmd, cwd=REPO, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            return proc, metrics, prom

        note(f"phase 1: primary gen 1 ({rounds} rounds, kill at "
             f"round {kill_round}, delay burst at round {delay_round})")
        obs1 = free_port()
        p1, metrics1, prom1 = launch_primary(1, rounds, obs1)
        procs.append(p1)
        last_scrape: dict = {}
        deadline = time.monotonic() + 600
        while p1.poll() is None and time.monotonic() < deadline:
            try:
                last_scrape = _scrape_metrics(obs1)
            except Exception:
                pass
            time.sleep(0.5)
        assert p1.poll() is not None, "primary gen 1 never exited (no kill?)"
        result["gen1_rc"] = p1.returncode
        assert p1.returncode != 0, (
            "primary gen 1 exited cleanly — the kill rule never fired"
        )
        recs1 = _read_records(metrics1)
        result["gen1_committed"] = _committed(recs1)
        result["gen1_aborted"] = len(recs1) - _committed(recs1)
        deaths = sum(
            last_scrape.get("fedtpu_ft_client_deaths_total", {}).values()
        )
        retried = sum(
            last_scrape.get("fedtpu_rpc_retries_total", {}).values()
        )
        injected = sum(
            last_scrape.get("fedtpu_chaos_injected_total", {}).values()
        )
        result["gen1_client_deaths"] = deaths
        result["gen1_retries"] = retried
        result["gen1_chaos_injected"] = injected
        assert deaths == 0, (
            f"{deaths} clients marked dead by transient faults (gen 1)"
        )
        assert retried > 0, "no RPC was ever retried under 30% fault load"

        note("phase 2: waiting for backup promotion + acting rounds")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (backup.machine.role.value == "acting_primary"
                    and backup.acting is not None
                    and _committed(backup.acting.history) >= 1):
                break
            time.sleep(0.25)
        result["promoted"] = backup.machine.role.value == "acting_primary"
        acting_committed = (
            _committed(backup.acting.history) if backup.acting else 0
        )
        result["acting_committed"] = acting_committed
        assert result["promoted"], "backup never promoted after the kill"
        assert acting_committed >= 1, "acting primary committed no rounds"

        remaining = max(1, rounds - result["gen1_committed"])
        note(f"phase 3: primary gen 2 ({remaining} rounds; demotes the "
             "acting primary and pulls its model)")
        obs2 = free_port()
        p2, metrics2, prom2 = launch_primary(2, remaining, obs2)
        procs.append(p2)
        try:
            p2.wait(timeout=600)
        except subprocess.TimeoutExpired:
            p2.kill()
            raise AssertionError("primary gen 2 hung")
        result["gen2_rc"] = p2.returncode
        assert p2.returncode == 0, f"gen 2 failed rc={p2.returncode}"
        recs2 = _read_records(metrics2)
        result["gen2_committed"] = _committed(recs2)
        with open(prom2) as fh:
            from fedtpu.obs import parse_prometheus_text

            prom2_metrics = parse_prometheus_text(fh.read())
        deaths2 = sum(
            prom2_metrics.get("fedtpu_ft_client_deaths_total", {}).values()
        )
        result["gen2_client_deaths"] = deaths2
        result["gen2_retries"] = sum(
            prom2_metrics.get("fedtpu_rpc_retries_total", {}).values()
        )
        assert deaths2 == 0, (
            f"{deaths2} clients marked dead by transient faults (gen 2)"
        )
        assert backup.machine.role.value == "backup", (
            "acting primary never demoted after gen 2's recovery ping"
        )

        total = (result["gen1_committed"] + acting_committed
                 + result["gen2_committed"])
        result["total_committed"] = total
        assert total >= rounds, (
            f"only {total} rounds committed across generations, "
            f"wanted >= {rounds}"
        )
        assert result["gen1_aborted"] >= 1, (
            "the full-round delay burst never produced a sub-quorum abort"
        )

        note("phase 4: final eval finiteness on every client")
        evals = []
        for agent in agents:
            assert agent.last_eval is not None, "client never evaluated"
            loss, acc = agent.last_eval
            assert loss == loss and abs(loss) != float("inf"), loss
            evals.append({"loss": loss, "acc": acc})
        result["final_evals"] = evals
        result["ok"] = True
        return result
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        if backup_srv is not None:
            backup.watchdog.stop()
            backup._stop_acting(wait=10.0)
            backup_srv.stop(0)
        for s in servers:
            s.stop(0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", default=20, type=int)
    ap.add_argument("--clients", default=3, type=int)
    ap.add_argument("--kill-round", default=8, type=int)
    ap.add_argument("--quorum", default=0.5, type=float)
    ap.add_argument("--seed", default=7, type=int)
    ap.add_argument("--error-p", default=0.3, type=float)
    ap.add_argument("--retries", default=8, type=int,
                    help="retry budget; must exceed the worst interleaved "
                    "chaos run (2*3+1 attempts under the default spec)")
    ap.add_argument("--workdir", default="/tmp/fedtpu_chaos_soak")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        result = run_soak(
            rounds=args.rounds, clients=args.clients,
            kill_round=args.kill_round, quorum=args.quorum, seed=args.seed,
            error_p=args.error_p, retries=args.retries,
            workdir=args.workdir,
        )
    except AssertionError as exc:
        print(json.dumps({"ok": False, "error": str(exc)}))
        return 1
    art = os.path.join(REPO, "artifacts")
    os.makedirs(art, exist_ok=True)
    with open(os.path.join(art, "CHAOS_SOAK.json"), "w") as fh:
        json.dump(result, fh, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
