#!/usr/bin/env python
"""Fused-round bench at the MXU-shaped config: resnet18/cifar100, 64 clients.

BASELINE.md's attribution of the smallcnn bench's 1.31% MFU ends with "the
right lever for MFU at fixed parity is a bigger model"; this measures that
claim on a real chip. Same engine program as ``bench.py`` (the fused
multi-round scan) at BASELINE config 4's model/dataset with
``RoundConfig(remat=True)`` (per-block remat + per-step streaming slices —
the single-chip-feasible form AOT-proven in ``PALLAS_TPU_COMPILE.json``).

Writes ``artifacts/BENCH_RESNET_TPU.json`` and prints one JSON line. The
whole measurement runs in a bounded subprocess (the tunnel can wedge
mid-compile — observed 2026-07-31: a >60 min hang with no output); on
timeout the artifact records the failure instead of hanging the watcher.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")
OUT = os.path.join(ART, "BENCH_RESNET_TPU.json")
TIMEOUT_S = 2700

_INNER = r"""
import json, time, sys
import jax, jax.numpy as jnp, numpy as np
sys.path.insert(0, %(repo)r)
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core.engine import Federation

NUM_CLIENTS=64; BATCH=128; STEPS=6; ROUNDS=2; TRIALS=3
cfg = RoundConfig(model="resnet18", num_classes=100, opt=OptimizerConfig(),
    data=DataConfig(dataset="cifar100", batch_size=BATCH, partition="iid",
                    num_examples=NUM_CLIENTS*STEPS*BATCH),
    fed=FedConfig(num_clients=NUM_CLIENTS), steps_per_round=STEPS,
    dtype="bfloat16", remat=True)
fed = Federation(cfg, seed=0)
d = fed._ensure_device_data()
alive = jnp.ones((ROUNDS, NUM_CLIENTS), bool)
multi = fed._multi_step(ROUNDS)
print("compiling...", flush=True)
t0=time.time()
step = multi.lower(fed.state, *d, fed.weights, alive, fed._data_key).compile()
print("compiled in %%.1fs" %% (time.time()-t0), flush=True)
flops = None
try:
    single = fed._data_step.lower(fed.state, *d, fed.weights,
        jnp.ones((NUM_CLIENTS,), bool), fed._data_key).compile()
    an = single.cost_analysis()
    if isinstance(an,(list,tuple)): an = an[0] if an else {}
    flops = float(an.get("flops",0.0)) or None
except Exception as e:
    print("cost analysis failed:", e, flush=True)
state = fed.state
state, m = step(state, *d, fed.weights, alive, fed._data_key)
np.asarray(m.loss)  # warmup + honest sync
rates=[]
for _ in range(TRIALS):
    t0=time.perf_counter()
    state, m = step(state, *d, fed.weights, alive, fed._data_key)
    np.asarray(m.loss)
    rates.append(ROUNDS/(time.perf_counter()-t0))
rps = sorted(rates)[len(rates)//2]
kind = jax.devices()[0].device_kind
out = {"metric":"fedavg_rounds_per_sec_cifar100_resnet18_64clients_1chip",
  "rounds_per_sec": round(rps,4),
  "client_epochs_per_sec_per_chip": round(rps*NUM_CLIENTS,2),
  "num_clients":NUM_CLIENTS,"batch":BATCH,"steps_per_round":STEPS,
  "remat":True,"dtype":"bfloat16","device_kind":kind,
  "backend":jax.default_backend()}
if flops:
    out["flops_per_round"]=flops
    import bench
    peak = bench._peak_for(kind)
    if peak:
        out["mfu"]=round(rps*flops/peak,4)
print(json.dumps(out), flush=True)
"""


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from jsontail import last_json_line

    inner = _INNER % {"repo": REPO}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", inner], capture_output=True, text=True,
            timeout=TIMEOUT_S, cwd=REPO,
        )
        out, err, note = proc.stdout, proc.stderr, None
    except subprocess.TimeoutExpired as exc:
        out = (exc.stdout or b"")
        out = out.decode() if isinstance(out, bytes) else out
        err, note = "", f"timeout after {TIMEOUT_S}s"
    line = last_json_line(out)
    if line is None:
        line = {"metric": "fedavg_rounds_per_sec_cifar100_resnet18_64clients_1chip",
                "value": 0.0,
                "error": note or f"no JSON (rc={proc.returncode}): {err.strip()[-400:]}",
                "progress": (out or "").strip().splitlines()[-3:]}
    line["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(line, f, indent=2)
    os.replace(tmp, OUT)
    print(json.dumps(line))
    return 0 if "error" not in line else 4


if __name__ == "__main__":
    raise SystemExit(main())
