#!/usr/bin/env python
"""Evidence that the steady-state round is compute-bound.

Times three ways of feeding the same federated round (same model, same
config, same data):

  compute_only   — a fixed pre-built RoundBatch reused every round: pure
                   device compute, the floor.
  device_gather  — the production path (Federation.step with batch=None):
                   HBM-resident dataset, per-round gather inside the jitted
                   program.
  host_rebuild   — the pre-round-3 path: numpy fancy-indexing rebuilds every
                   client's batch tensors on the host each round, then
                   transfers.

The claim "per-round host data preparation no longer gates throughput" holds
iff device_gather ~= compute_only while host_rebuild is materially slower.
Writes one JSON line (and --out file). CPU-safe; on TPU the same script
measures the real thing.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

ROUNDS = 20


def _time(fn, rounds=ROUNDS):
    fn()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(rounds):
        fn()
    return (time.perf_counter() - t0) / rounds


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None)
    p.add_argument("--clients", type=int, default=64)
    # Defaults mirror bench.py's shapes: 6 steps x 128 images per client per
    # round — the sizing at which the host rebuild moves ~600 MB per round.
    p.add_argument("--steps", type=int, default=6)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument(
        "--platform",
        default="cpu",
        choices=["cpu", "tpu", "cuda"],
        help="jax platform to measure on (default cpu: this container's "
        "env-default TPU backend can hang; pass 'tpu' explicitly to measure "
        "the real thing)",
    )
    args = p.parse_args()
    jax.config.update("jax_platforms", args.platform)

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05),
        data=DataConfig(dataset="synthetic", batch_size=args.batch,
                        partition="iid", num_examples=64 * args.clients),
        fed=FedConfig(num_clients=args.clients),
        steps_per_round=args.steps,
    )

    fed = Federation(cfg, seed=0)
    fixed = fed.round_batch(0)

    def compute_only():
        m = fed.step(fixed)
        float(m.loss)

    def device_gather():
        m = fed.step()
        float(m.loss)

    def host_rebuild():
        r = fed._round_number()
        m = fed.step(fed.round_batch(r))
        float(m.loss)

    result = {
        "metric": "seconds_per_round",
        "clients": args.clients,
        "compute_only": round(_time(compute_only), 5),
        "device_gather": round(_time(device_gather), 5),
        "host_rebuild": round(_time(host_rebuild), 5),
        "platform": jax.default_backend(),
    }
    result["gather_overhead_vs_compute"] = round(
        result["device_gather"] / result["compute_only"] - 1, 4
    )
    result["host_rebuild_slowdown"] = round(
        result["host_rebuild"] / result["device_gather"], 2
    )
    print(json.dumps(result))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(result, fh, indent=1)


if __name__ == "__main__":
    raise SystemExit(main())
