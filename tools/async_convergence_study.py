#!/usr/bin/env python
"""Sync-vs-FedBuff convergence comparison on the engine (VERDICT r3 #7).

Same task, same clients, same total LOCAL work per unit of wall-clock
(one tick == one synchronous round == every live client trains one local
epoch): the synchronous engine aggregates everyone at a barrier; the async
engine aggregates ``buffer_k`` staleness-discounted arrivals per tick under
heterogeneous client speeds (``speed_sigma``). Writes one JSONL row per
round/tick with the global model's test accuracy for each mode, plus a
summary row — the committed artifact is
``artifacts/ASYNC_SYNC_CONVERGENCE.jsonl``.

Run (CPU): ``python tools/async_convergence_study.py``
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # tunnel-safe; this is a CPU study

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import AsyncFederation, Federation
from fedtpu.data import load

ROUNDS = 25
ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "artifacts")


def cfg_for():
    return RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, schedule="constant"),
        data=DataConfig(
            dataset="cifar10_hard",
            batch_size=32,
            partition="dirichlet",
            dirichlet_alpha=0.5,
            num_examples=1024,
            augment=False,
        ),
        fed=FedConfig(num_clients=8),
        steps_per_round=4,
    )


def main():
    out_path = os.path.join(ART, "ASYNC_SYNC_CONVERGENCE.jsonl")
    test = load("cifar10_hard", "test", num=1024)
    rows = []
    cfg = cfg_for()

    sync = Federation(cfg, seed=0)
    for r in range(ROUNDS):
        sync.step()
        _, acc = sync.evaluate(*test)
        rows.append({"mode": "sync_barrier", "round": r,
                     "test_acc": round(acc, 4)})
        print(rows[-1], file=sys.stderr, flush=True)

    for sigma in (0.0, 1.0):
        # damping=False pinned: the fedbuff_k2_sigma* labels in the artifact
        # mean the round-4 weight-normalized semantics; the damped (now
        # engine-default) runs are fedbuff_stall_study.py --damped with
        # *_damped labels.
        asyn = AsyncFederation(cfg, seed=0, buffer_k=2, speed_sigma=sigma,
                               staleness_damping=False)
        stale_total = 0.0
        for r in range(ROUNDS):
            m = asyn.tick()
            stale_total += float(m.staleness_mean)
            _, acc = asyn.evaluate(*test)
            rows.append({"mode": f"fedbuff_k2_sigma{sigma:g}", "round": r,
                         "test_acc": round(acc, 4),
                         "staleness_mean": round(float(m.staleness_mean), 2)})
            print(rows[-1], file=sys.stderr, flush=True)
        rows.append({"mode": f"fedbuff_k2_sigma{sigma:g}",
                     "summary": True,
                     "mean_staleness": round(stale_total / ROUNDS, 2),
                     "final_test_acc": rows[-1]["test_acc"]})

    with open(out_path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")
    print(json.dumps({"written": out_path, "rows": len(rows)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
