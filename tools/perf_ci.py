#!/usr/bin/env python
"""Perf-regression CI harness for the observability hot path.

The observatory's cost claims ("accounting is ≤1% of a round",
"telemetry=basic is sub-ppm") are measured once by ``bench.py`` legs that
take minutes. This harness keeps them true CONTINUOUSLY with a seconds-
scale microbench of every per-round instrument the framework executes —
span enter/exit, counter/gauge/histogram updates, MFU accounting,
client-latency summarization, round-record serialization, Prometheus
rendering, trace merge and gap analysis — compared against a committed
baseline (``artifacts/PERF_BASELINE.json``).

Machine-speed normalization: raw microsecond medians are not portable
across hosts, so every run also times a fixed pure-Python *calibration
workload*; ``--check`` scales the baseline by
``measured_calibration / baseline_calibration`` (clamped) before
comparing. Drift tolerance per metric is
``max(75%, 4 x noise_floor_pct)`` over the scaled baseline — wide enough
that scheduler jitter never flakes tier-1, tight enough that an
accidental O(n) regression on a per-round instrument (the 2x injected
slowdown the tests pin) reliably fails.

Usage:
    python tools/perf_ci.py --baseline     # (re)write the committed baseline
    python tools/perf_ci.py --check        # compare vs baseline, exit 1 on drift
    python tools/perf_ci.py                # measure + print, no comparison

Env:
    FEDTPU_PERF_CI_REPS    measurement repetitions (default 5)
    FEDTPU_PERF_CI_INJECT  "name=factor[,name=factor]" or "all=2.0":
                           multiply measured medians after measurement —
                           the test hook proving --check actually fails
                           on a regression (recorded in the output).

Mode-rotation discipline per bench.py: the metric measurement order is
rotated every rep so machine-wide drift within a rep cannot land on the
same metrics every time and read as regression.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time
from typing import Callable, Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SCHEMA_VERSION = 1
BASELINE_PATH = os.path.join(REPO, "artifacts", "PERF_BASELINE.json")

# Relative drift always tolerated, on top of the calibration scaling.
MIN_BAND = 0.75
# ... widened by the larger of the two runs' own noise floors.
NOISE_BAND_MULT = 4.0
# Calibration scaling is a correction, not a free pass: a host claiming to
# be 10x slower is more likely a broken measurement than a real machine.
SCALE_CLAMP = (0.25, 4.0)


# --------------------------------------------------------------- workloads
def _calibration() -> None:
    """Fixed pure-Python workload: the machine-speed yardstick. Mixed
    arithmetic + hashing so neither interpreter dispatch nor memory
    bandwidth alone dominates."""
    acc = 0
    for i in range(2000):
        acc += i * i % 7
    hashlib.sha256(b"fedtpu-perf-ci" * 64).hexdigest()


def _synthetic_merged_doc(n_spans: int = 120, n_ops: int = 120) -> dict:
    """A small merged timeline (host lane + device lane) shaped like
    trace_merge.py output, for the merge/analyze workloads."""
    events = []
    for i in range(n_spans):
        events.append({
            "ph": "X", "pid": 1, "tid": 1, "name": f"phase_{i % 7}",
            "ts": i * 100.0, "dur": 60.0,
        })
    for i in range(n_ops):
        events.append({
            "ph": "X", "pid": 2, "tid": 1, "name": "fusion",
            "cat": "device", "ts": i * 100.0 + 30.0, "dur": 40.0,
        })
    return {"traceEvents": events, "metadata": {}}


def _build_workloads() -> List[Tuple[str, Callable[[], None], int, object]]:
    """[(metric name, one-iteration thunk, iterations per timing, optional
    post-batch reset)]. All
    imports are host-side fedtpu.obs + tools modules — no jax, so the
    harness runs in a couple of seconds and is safe for tier-1."""
    import gap_analyze
    import trace_merge
    import numpy as np
    import ml_dtypes
    from fedtpu.obs import (
        RoundRecordWriter,
        Telemetry,
        latency_summary,
        prometheus_text,
    )
    from fedtpu.obs.profile import CostModel, RoundProfiler

    tel = Telemetry("trace")
    counter = tel.counter("perf_ci_c")
    gauge = tel.gauge("perf_ci_g")
    hist = tel.histogram("perf_ci_h")

    profiler = RoundProfiler(tel, n_devices=1, device_kind="")
    profiler.set_cost_model(
        CostModel(xla_flops=1.0e12, xla_bytes=2.0e11, analytic=1.0e12)
    )
    profiler.peak_flops = 9.18e14  # fixed: no env / device dependence

    pairs = [(f"client_{i:03d}", 0.05 + (i % 13) * 0.01) for i in range(64)]

    rec_path = os.path.join(
        tempfile.mkdtemp(prefix="fedtpu_perf_ci_"), "records.jsonl"
    )
    writer = RoundRecordWriter(path=rec_path, echo=False)
    rec_fields = {
        "participants": 8, "loss": 1.234567, "t_round_s": 0.123456,
        "wire_bytes": 1 << 20, "mfu": 0.4321,
    }
    rec_step = [0]

    def record_one():
        writer.log(rec_step[0], **rec_fields)
        rec_step[0] += 1

    doc = _synthetic_merged_doc()
    host_doc = {
        "traceEvents": [e for e in doc["traceEvents"] if "cat" not in e],
        "metadata": {"wall_start": 1000.0, "role": "engine"},
    }
    dev_doc = {
        "traceEvents": [e for e in doc["traceEvents"] if "cat" in e],
        "metadata": {"wall_start": 1000.0, "role": "engine"},
    }

    # Mixed-precision host costs (PR: bf16 device residency + megabatch).
    # These are the ONLY host-side steps the compute_dtype/megabatch knobs
    # add outside the jitted round: the one-time f32 -> bf16 master-copy
    # cast at device upload, and the [clients] -> [groups, k*batch] static
    # regrouping reshape. Both must stay trivially cheap — a regression
    # here means someone moved the cast/regroup out of XLA into a per-round
    # host loop. numpy + ml_dtypes stand in for the jitted versions so the
    # harness stays jax-free and seconds-scale.
    cast_src = np.ones((64, 4096), dtype=np.float32)

    def cast_one():
        cast_src.astype(ml_dtypes.bfloat16)

    mega_src = np.ones((8, 32, 32, 32, 3), dtype=np.float32)  # [C,B,H,W,ch]

    def megabatch_reshape_one():
        # Group k=4 clients -> [G, k*B, H, W, ch]. The contiguous [clients]
        # axis makes this a VIEW (sub-microsecond) — exactly the claim in
        # validate_megabatch's error message; this metric pins that nobody
        # replaces it with a gather/copy regroup.
        np.ascontiguousarray(mega_src.reshape(2, 4 * 32, 32, 32, 3))

    # Hierarchical-aggregation host costs (PR: sub-aggregator tier). The
    # two per-round steps the tier adds OUTSIDE the jitted reduce: the
    # leaf's [cohort, P] -> one-row weighted fold (numpy stands in for the
    # jitted fedtpu.ops.flat.partial_reduce_rows so the harness stays
    # jax-free), and assembling the FSP1 partial_flat record — one O(P)
    # row copy + header/CRC framing, the wire cost of SubmitPartial's
    # reply. A regression here means the leaf started re-materializing
    # rows per client or the record grew a per-coordinate encode loop.
    fold_rows = np.ones((16, 4096), dtype=np.float32)
    fold_w = np.arange(1.0, 17.0, dtype=np.float32)

    def partial_reduce_fold_one():
        (fold_rows * fold_w[:, None]).sum(axis=0)
        fold_w.sum()

    import struct
    import zlib

    partial_row = np.arange(32768, dtype=np.float32)

    def submit_partial_frame_one():
        payload = partial_row.tobytes()
        struct.pack("<4sBBI", b"FSP1", 1, 0,
                    zlib.crc32(payload) & 0xFFFFFFFF) + payload

    # Sketch-codec host costs (PR: rotated-sketch + random-k wire codecs).
    # The two hot loops the codecs add on the HOST side of the edge: the
    # in-place FWHT butterfly over the padded row (the encoder/decoder both
    # run it once per record — numpy stands in for transport.sparse._fwht_np
    # which IS numpy, so this times the real algorithm), and the seeded
    # Philox index draw + gather that builds a randk record. A regression
    # here means someone replaced the O(h log h) butterfly with a dense
    # h x h matmul, or the sorted no-replacement draw with a per-coordinate
    # Python loop.
    had_row = np.arange(4096, dtype=np.float32)

    def hadamard_rotate_one():
        x = had_row.copy()
        h = x.size
        step = 1
        while step < h:
            y = x.reshape(h // (2 * step), 2, step)
            a, b = y[:, 0, :], y[:, 1, :]
            x = np.concatenate([a + b, a - b], axis=1).reshape(h)
            step *= 2

    randk_x = np.arange(32768, dtype=np.float32)

    def randk_gather_one():
        rng = np.random.Generator(np.random.Philox(7))
        idx = np.sort(rng.choice(randk_x.size, size=1638, replace=False))
        randk_x[idx]

    def span_one():
        with tel.span("perf_ci", round=0):
            pass

    def span_reset():
        # The tracer buffers every finished span; drain it between timed
        # batches so buffer growth/GC pressure doesn't drift later reps.
        tel.tracer._events.clear()

    return [
        ("calibration_us", _calibration, 200, None),
        ("span_trace_us", span_one, 5000, span_reset),
        ("counter_inc_us", counter.inc, 20000, None),
        ("gauge_set_us", lambda: gauge.set(0.5), 20000, None),
        ("histogram_observe_us", lambda: hist.observe(0.01), 20000, None),
        ("mfu_observe_us",
         lambda: (profiler.observe_round(0.5), profiler.record_fields()),
         5000, None),
        ("latency_summary_us", lambda: latency_summary(pairs), 2000, None),
        ("round_record_us", record_one, 2000, None),
        ("prometheus_render_us", lambda: prometheus_text(tel.registry), 500,
         None),
        ("trace_merge_us",
         lambda: trace_merge.merge_docs([host_doc], device_docs=[dev_doc]),
         50, None),
        ("gap_analyze_us", lambda: gap_analyze.analyze(doc), 20, None),
        ("mixed_precision_cast_us", cast_one, 200, None),
        ("megabatch_reshape_us", megabatch_reshape_one, 5000, None),
        ("partial_reduce_fold_us", partial_reduce_fold_one, 500, None),
        ("submit_partial_frame_us", submit_partial_frame_one, 500, None),
        ("hadamard_rotate_us", hadamard_rotate_one, 200, None),
        ("randk_gather_us", randk_gather_one, 200, None),
    ]


# -------------------------------------------------------------- measuring
def measure(reps: int = None) -> Dict[str, object]:
    reps = reps or int(os.environ.get("FEDTPU_PERF_CI_REPS", "5"))
    workloads = _build_workloads()
    trials: Dict[str, List[float]] = {
        name: [] for name, _f, _n, _r in workloads
    }
    # Warmup: allocators, lazy imports and span machinery all pay a first-
    # call cost that would otherwise land in rep 0's noise floor.
    for _name, fn, n, reset in workloads:
        for _ in range(min(n, 200)):
            fn()
        if reset is not None:
            reset()
    for rep in range(reps):
        # Rotate the measurement order per rep (bench.py discipline).
        order = workloads[rep % len(workloads):] + \
            workloads[: rep % len(workloads)]
        for name, fn, n, reset in order:
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            trials[name].append((time.perf_counter() - t0) / n * 1e6)
            if reset is not None:
                reset()
    metrics: Dict[str, Dict[str, float]] = {}
    for name, ts in trials.items():
        med = sorted(ts)[len(ts) // 2]
        noise = (max(ts) - min(ts)) / med * 100.0 if med else 0.0
        metrics[name] = {
            "median_us": round(med, 4),
            "noise_floor_pct": round(noise, 2),
        }
    _apply_injection(metrics)
    return {
        "schema_version": SCHEMA_VERSION,
        "reps": reps,
        "metrics": metrics,
        "python": ".".join(map(str, sys.version_info[:3])),
    }


def _apply_injection(metrics: Dict[str, Dict[str, float]]) -> None:
    """FEDTPU_PERF_CI_INJECT test hook: inflate measured medians so the
    tests can prove --check fails on a real slowdown without depending on
    an actual regression being present."""
    spec = os.environ.get("FEDTPU_PERF_CI_INJECT", "")
    if not spec:
        return
    for part in spec.split(","):
        if "=" not in part:
            continue
        name, _eq, factor = part.partition("=")
        name, factor = name.strip(), float(factor)
        for key, row in metrics.items():
            if name in ("all", key):
                row["median_us"] = round(row["median_us"] * factor, 4)
                row["injected_factor"] = factor


# -------------------------------------------------------------- comparing
def compare(measured: dict, baseline: dict) -> dict:
    """The --check verdict: measured vs (calibration-scaled) baseline."""
    base_m = baseline["metrics"]
    now_m = measured["metrics"]
    base_cal = base_m.get("calibration_us", {}).get("median_us") or 1.0
    now_cal = now_m.get("calibration_us", {}).get("median_us") or base_cal
    scale = max(SCALE_CLAMP[0], min(SCALE_CLAMP[1], now_cal / base_cal))
    rows = {}
    failures = []
    for name, base in sorted(base_m.items()):
        if name == "calibration_us":
            continue
        now = now_m.get(name)
        if now is None:
            failures.append({
                "metric": name,
                "problem": "metric disappeared from the harness — update "
                           "the baseline deliberately, don't drop coverage",
            })
            continue
        band = max(
            MIN_BAND,
            NOISE_BAND_MULT
            * max(base["noise_floor_pct"], now["noise_floor_pct"]) / 100.0,
        )
        limit = base["median_us"] * scale * (1.0 + band)
        row = {
            "measured_us": now["median_us"],
            "baseline_us": base["median_us"],
            "limit_us": round(limit, 4),
            "band_pct": round(band * 100.0, 1),
            "ratio_vs_scaled_baseline": round(
                now["median_us"] / (base["median_us"] * scale), 3
            ),
        }
        if now["median_us"] > limit:
            row["regression"] = True
            failures.append({"metric": name, **row})
        rows[name] = row
    return {
        "pass": not failures,
        "calibration_scale": round(scale, 3),
        "calibration_us": {"baseline": base_cal, "measured": now_cal},
        "failures": failures,
        "metrics": rows,
        "injected": os.environ.get("FEDTPU_PERF_CI_INJECT", "") or None,
    }


def write_baseline(measured: dict, path: str = None) -> str:
    path = path or BASELINE_PATH
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(measured, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("--baseline", action="store_true",
                   help="measure and (re)write artifacts/PERF_BASELINE.json")
    p.add_argument("--check", action="store_true",
                   help="measure and compare against the committed "
                        "baseline; exit 1 on drift")
    p.add_argument("--against", default=None, metavar="PATH",
                   help="baseline file for --check (default: committed)")
    p.add_argument("--reps", default=None, type=int)
    args = p.parse_args(argv)

    measured = measure(reps=args.reps)
    if args.baseline:
        path = write_baseline(measured)
        print(json.dumps(measured, indent=2))
        print(f"baseline written: {os.path.relpath(path, REPO)}",
              file=sys.stderr)
        return 0
    if args.check:
        path = args.against or BASELINE_PATH
        with open(path) as fh:
            baseline = json.load(fh)
        verdict = compare(measured, baseline)
        print(json.dumps(verdict, indent=2))
        if not verdict["pass"]:
            for f in verdict["failures"]:
                print(f"PERF REGRESSION: {json.dumps(f)}", file=sys.stderr)
            return 1
        print("perf check ok: "
              f"{len(verdict['metrics'])} metrics within "
              f"{int(MIN_BAND * 100)}%+ band of scaled baseline",
              file=sys.stderr)
        return 0
    print(json.dumps(measured, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
