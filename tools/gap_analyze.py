#!/usr/bin/env python
"""Attribute device-idle gaps in a merged fedtpu timeline to host phases.

Input is ``tools/trace_merge.py`` output that includes at least one device
lane (``--device-trace``, events tagged ``cat="device"``). The analyzer:

1. unions the device-op intervals across every device lane into "device
   busy" time, bounded to the capture window (first to last device op);
2. finds the idle gaps — maximal sub-intervals of the window where no
   device lane is executing — longer than ``--min-gap-us``;
3. attributes each gap to the host spans that overlap it, deepest
   (innermost) span first: a gap microsecond is charged to the most
   specific host phase covering it (``h2d`` inside ``round``, not
   ``round``), and whatever no host span covers is reported as
   ``unattributed`` (blocking Python between spans, GC, scheduler);
4. emits a structured JSON report: the top-k gaps with per-gap
   attribution plus an aggregate ``by_phase`` table over ALL gaps — the
   ranked "where does device idleness come from" answer the ROADMAP's
   raw-speed item wants instead of guessing.

Import-free of fedtpu (stdlib only), like the other ``tools/`` readers.

Usage:
    python tools/gap_analyze.py merged.json -o artifacts/GAP_REPORT.json \
        [--top 10] [--min-gap-us 100] [--check] \
        [--roofline artifacts/MFU_PROFILE_r04.json]

``--check`` exits non-zero when the timeline has no device lane (the
acceptance gate for a --profile-rounds capture that silently produced no
device ops). An EMPTY gap list is not a failure — a fully-busy device is
the goal state.

``--roofline PROFILE`` additionally stamps roofline placement onto the
report: for each config row in an ``--mfu-profile`` artifact (or a flat
dict carrying ``flops_per_round``/``bytes_per_round``) it recomputes
arithmetic intensity, ridge point, bound and utilization through
``fedtpu.obs.profile.roofline``, so one report answers both "where does
the idle time go" (gaps) and "what is the busy time limited by"
(roofline). This is the only path that imports fedtpu — it is loaded
lazily inside the flag handler so the default invocation stays stdlib
only.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

Interval = Tuple[float, float]


def load_doc(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    return doc


def _events(doc: dict, device: bool) -> List[dict]:
    return [
        e for e in doc.get("traceEvents", [])
        if e.get("ph") == "X"
        and (e.get("cat") == "device") == device
        and "ts" in e and "dur" in e
    ]


def union_intervals(intervals: List[Interval]) -> List[Interval]:
    """Merge overlapping/adjacent ``(start, end)`` intervals."""
    out: List[Interval] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def find_gaps(
    busy: List[Interval], window: Interval, min_gap_us: float
) -> List[Interval]:
    """Maximal idle sub-intervals of ``window`` not covered by the merged
    ``busy`` union, at least ``min_gap_us`` long."""
    gaps: List[Interval] = []
    cur = window[0]
    for s, e in busy:
        if s > cur:
            gaps.append((cur, min(s, window[1])))
        cur = max(cur, e)
        if cur >= window[1]:
            break
    if cur < window[1]:
        gaps.append((cur, window[1]))
    return [(s, e) for s, e in gaps if e - s >= min_gap_us]


def _depths(spans: List[dict]) -> List[int]:
    """Nesting depth per span: the number of spans on the same lane that
    properly contain it (O(n^2) — host span counts are small)."""
    depths = []
    for i, a in enumerate(spans):
        a0, a1 = a["ts"], a["ts"] + a["dur"]
        d = 0
        for j, b in enumerate(spans):
            if i == j or b.get("pid") != a.get("pid"):
                continue
            b0, b1 = b["ts"], b["ts"] + b["dur"]
            if b0 <= a0 and a1 <= b1 and (b0 < a0 or a1 < b1):
                d += 1
        depths.append(d)
    return depths


def _subtract(intervals: List[Interval], cut: Interval) -> List[Interval]:
    out: List[Interval] = []
    c0, c1 = cut
    for s, e in intervals:
        if e <= c0 or s >= c1:
            out.append((s, e))
            continue
        if s < c0:
            out.append((s, c0))
        if e > c1:
            out.append((c1, e))
    return out


def attribute_gap(
    gap: Interval, spans: List[dict], depths: List[int]
) -> Tuple[List[dict], float]:
    """Charge a gap to overlapping host spans, innermost first. Returns
    ``(attribution rows, unattributed_us)``; rows carry the span name,
    charged microseconds and fraction of the gap."""
    g0, g1 = gap
    total = g1 - g0
    overlapping = [
        (depths[i], s) for i, s in enumerate(spans)
        if s["ts"] < g1 and s["ts"] + s["dur"] > g0
    ]
    # Deepest (most specific) spans claim their part of the gap first;
    # an enclosing span only gets what its children left uncovered.
    overlapping.sort(key=lambda ds: -ds[0])
    remaining: List[Interval] = [gap]
    charged: Dict[str, float] = {}
    for _d, s in overlapping:
        s0, s1 = s["ts"], s["ts"] + s["dur"]
        got = sum(
            min(e, s1) - max(b, s0)
            for b, e in remaining
            if b < s1 and e > s0
        )
        if got > 0:
            charged[s["name"]] = charged.get(s["name"], 0.0) + got
            remaining = _subtract(remaining, (max(g0, s0), min(g1, s1)))
    unattributed = sum(e - b for b, e in remaining)
    rows = [
        {
            "span": name,
            "us": round(us, 3),
            "fraction": round(us / total, 4) if total else 0.0,
        }
        for name, us in sorted(charged.items(), key=lambda kv: -kv[1])
    ]
    return rows, unattributed


def analyze(
    doc: dict, top: int = 10, min_gap_us: float = 100.0
) -> dict:
    """The GAP_REPORT dict for one merged timeline (see module docstring).
    Tolerates an empty device side: the report then carries
    ``device_lanes: 0`` and no gaps rather than failing."""
    device = _events(doc, device=True)
    host = _events(doc, device=False)
    lanes = sorted({e.get("pid") for e in device})
    report = {
        "schema_version": SCHEMA_VERSION,
        "device_lanes": len(lanes),
        "device_ops": len(device),
        "min_gap_us": min_gap_us,
        "gaps": [],
        "by_phase": [],
    }
    if not device:
        report.update(
            window_us=None, device_busy_us=0.0, device_idle_us=0.0,
            idle_fraction=None, n_gaps=0,
        )
        return report
    busy = union_intervals(
        [(e["ts"], e["ts"] + e["dur"]) for e in device]
    )
    window = (busy[0][0], busy[-1][1])
    busy_us = sum(e - s for s, e in busy)
    gaps = find_gaps(busy, window, min_gap_us)
    gaps.sort(key=lambda g: g[0] - g[1])  # longest first
    depths = _depths(host)
    by_phase: Dict[str, float] = {}
    unattributed_total = 0.0
    gap_rows = []
    for g in gaps:
        rows, unattr = attribute_gap(g, host, depths)
        for r in rows:
            by_phase[r["span"]] = by_phase.get(r["span"], 0.0) + r["us"]
        unattributed_total += unattr
        gap_rows.append({
            "start_us": round(g[0], 3),
            "end_us": round(g[1], 3),
            "dur_us": round(g[1] - g[0], 3),
            "attribution": rows,
            "unattributed_us": round(unattr, 3),
        })
    window_us = window[1] - window[0]
    idle_us = window_us - busy_us
    report.update(
        window_us=round(window_us, 3),
        device_busy_us=round(busy_us, 3),
        device_idle_us=round(idle_us, 3),
        idle_fraction=round(idle_us / window_us, 4) if window_us else None,
        n_gaps=len(gaps),
    )
    report["gaps"] = gap_rows[:top]
    if unattributed_total > 0:
        by_phase["(unattributed)"] = unattributed_total
    report["by_phase"] = [
        {"span": name, "us": round(us, 3)}
        for name, us in sorted(by_phase.items(), key=lambda kv: -kv[1])
    ]
    return report


def roofline_stamp(profile_path: str) -> dict:
    """Roofline placement rows for every config in a profile artifact.

    Accepts the ``--mfu-profile`` schema (``{"configs": [...]}`` where each
    row has ``flops_per_round``/``bytes_per_round``/``device_kind`` and
    usually ``rounds_per_sec``) or a flat dict with the same per-row keys.
    Peaks resolve through ``fedtpu.obs.profile.device_peaks`` (honouring
    the ``FEDTPU_PEAK_*`` env overrides); utilization is filled when the
    row carries an achieved rate. Imports fedtpu lazily — see module
    docstring."""
    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from fedtpu.obs.profile import device_peaks, roofline

    doc = load_doc(profile_path)
    rows = doc.get("configs") if isinstance(doc.get("configs"), list) else [doc]
    out_rows = []
    for row in rows:
        if not isinstance(row, dict):
            continue
        flops = row.get("flops_per_round")
        nbytes = row.get("bytes_per_round")
        if flops is None and nbytes is None:
            continue
        peak_f, peak_b = device_peaks(row.get("device_kind") or "")
        achieved = None
        if flops and row.get("rounds_per_sec"):
            achieved = flops * row["rounds_per_sec"]
        placement = roofline(flops, nbytes, peak_f, peak_b, achieved)
        out_rows.append({
            "batch": row.get("batch"),
            "device_kind": row.get("device_kind"),
            "flops_per_round": flops,
            "bytes_per_round": nbytes,
            "mfu": row.get("mfu"),
            **placement,
        })
    return {
        "profile_artifact": profile_path,
        "rows": out_rows,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("merged", help="trace_merge.py output with device lanes")
    p.add_argument("-o", "--out", default=None,
                   help="write the JSON report here (default: stdout)")
    p.add_argument("--top", default=10, type=int,
                   help="how many gaps to detail, longest first")
    p.add_argument("--min-gap-us", default=100.0, type=float,
                   help="ignore device-idle gaps shorter than this")
    p.add_argument("--check", action="store_true",
                   help="fail when the timeline has no device lane at all")
    p.add_argument("--roofline", default=None, metavar="PROFILE",
                   help="stamp roofline placement (bound / intensity / "
                        "utilization) from this --mfu-profile artifact "
                        "onto the report (imports fedtpu lazily)")
    args = p.parse_args(argv)

    report = analyze(
        load_doc(args.merged), top=args.top, min_gap_us=args.min_gap_us
    )
    if args.roofline:
        report["roofline"] = roofline_stamp(args.roofline)
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text + "\n")
    else:
        print(text)
    top_gap = report["gaps"][0] if report["gaps"] else None
    print(
        f"device lanes {report['device_lanes']}, "
        f"idle {report['idle_fraction']} of window, "
        f"{report['n_gaps']} gaps >= {args.min_gap_us}us"
        + (
            f"; top gap {top_gap['dur_us']}us -> "
            + (top_gap["attribution"][0]["span"]
               if top_gap["attribution"] else "(unattributed)")
            if top_gap else ""
        ),
        file=sys.stderr,
    )
    rl = report.get("roofline", {}).get("rows") or []
    if rl:
        r0 = rl[0]
        print(
            f"roofline: {r0['roofline_bound']} bound, "
            f"AI {r0['arith_intensity_flops_per_byte']} vs ridge "
            f"{r0['ridge_point_flops_per_byte']} "
            f"({len(rl)} config rows stamped)",
            file=sys.stderr,
        )
    if args.check and report["device_lanes"] == 0:
        print("CHECK FAILED: no device lane in the merged timeline "
              "(merge with --device-trace)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
