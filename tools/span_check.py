#!/usr/bin/env python
"""Span- and metric-name drift check: everything emitted must be documented.

Scans ``fedtpu/`` for literal span names passed to ``*.span("name", ...)``
and literal metric names passed to ``.counter/.gauge/.histogram(...)``, and
verifies each appears as inline code (`` `name` ``) in
``docs/OBSERVABILITY.md``. Catches the silent failure mode where a new
subsystem adds spans or ``fedtpu_*`` metrics (or renames one) and the
operator-facing model drifts out of date — dashboards, alerts and trace
queries then filter on names that no longer exist.

Tier-1 runnable: ``tests/test_obs_propagation.py`` calls :func:`check`;
standalone: ``python tools/span_check.py`` (exit 1 + a list on drift).
Stdlib only.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Literal first argument of a .span( call. Variables/f-strings never match
# — fedtpu's span names are deliberately all literal (greppability is the
# point of a fixed span vocabulary).
_SPAN_CALL = re.compile(r"""\.span\(\s*(['"])([A-Za-z0-9_.:-]+)\1""")
# Literal first argument of a .counter(/.gauge(/.histogram( call on the
# telemetry facade or registry. Only the framework namespace is policed:
# ad-hoc test instruments don't start with fedtpu_.
_METRIC_CALL = re.compile(
    r"""\.(?:counter|gauge|histogram)\(\s*(['"])(fedtpu_[A-Za-z0-9_]+)\1"""
)
_INLINE_CODE = re.compile(r"`([^`]+)`")


def emitted_span_names(package_dir: str = None) -> Dict[str, List[str]]:
    """{span name: [relative file paths emitting it]} over fedtpu/."""
    package_dir = package_dir or os.path.join(REPO, "fedtpu")
    found: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            for m in _SPAN_CALL.finditer(text):
                rel = os.path.relpath(path, REPO)
                found.setdefault(m.group(2), []).append(rel)
    return found


def emitted_metric_names(package_dir: str = None) -> Dict[str, List[str]]:
    """{metric name: [relative file paths emitting it]} over fedtpu/."""
    package_dir = package_dir or os.path.join(REPO, "fedtpu")
    found: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            for m in _METRIC_CALL.finditer(text):
                rel = os.path.relpath(path, REPO)
                found.setdefault(m.group(2), []).append(rel)
    return found


def documented_names(doc_path: str = None) -> Set[str]:
    """Every inline-code token in OBSERVABILITY.md (the span table uses
    `` `name` `` markup; matching the whole doc keeps the check insensitive
    to table layout)."""
    doc_path = doc_path or os.path.join(REPO, "docs", "OBSERVABILITY.md")
    with open(doc_path, encoding="utf-8") as fh:
        text = fh.read()
    # Drop fenced code blocks first: their ``` markers desynchronize naive
    # single-backtick pairing over the rest of the document.
    text = re.sub(r"```.*?```", "", text, flags=re.S)
    names: Set[str] = set()
    for m in _INLINE_CODE.finditer(text):
        # A cell like `round` / `fused_rounds` documents both tokens.
        for tok in re.split(r"[\s/|,]+", m.group(1)):
            if tok:
                tok = tok.strip()
                names.add(tok)
                # `fedtpu_foo{label="x"}` documents the base metric name.
                names.add(tok.split("{")[0])
    return names


def check(package_dir: str = None, doc_path: str = None) -> List[str]:
    """Problem strings (empty = pass)."""
    emitted = emitted_span_names(package_dir)
    documented = documented_names(doc_path)
    problems = []
    if not emitted:
        problems.append("scanner found NO span calls in fedtpu/ — the "
                        "regex or layout drifted; fix tools/span_check.py")
    for name in sorted(emitted):
        if name not in documented:
            problems.append(
                f"span {name!r} (emitted in {', '.join(emitted[name])}) has "
                "no entry in docs/OBSERVABILITY.md"
            )
    problems.extend(check_metrics(package_dir, doc_path))
    if package_dir is None:
        problems.extend(check_chaos_kinds())
    return problems


def check_metrics(package_dir: str = None, doc_path: str = None) -> List[str]:
    """Metric-name drift problems (empty = pass)."""
    emitted = emitted_metric_names(package_dir)
    documented = documented_names(doc_path)
    problems = []
    # Scanner-drift guard only for the real tree: a synthetic package_dir
    # may legitimately emit spans but no metrics.
    if not emitted and package_dir is None:
        problems.append("scanner found NO fedtpu_* metric calls in fedtpu/ "
                        "— the regex or layout drifted; fix "
                        "tools/span_check.py")
    for name in sorted(emitted):
        if name not in documented:
            problems.append(
                f"metric {name!r} (emitted in {', '.join(emitted[name])}) "
                "has no entry in docs/OBSERVABILITY.md"
            )
    return problems


def check_chaos_kinds(doc_path: str = None) -> List[str]:
    """Chaos fault-kind drift problems (empty = pass): every kind name in
    ``fedtpu.ft.chaos.KINDS`` must appear as inline code in
    docs/FAULT_TOLERANCE.md's DSL grammar — a new fault class
    (``NET_KINDS`` and whatever follows) cannot ship undocumented.
    chaos.py is loaded standalone (importlib, stdlib-only module) so this
    check never drags jax into a docs-lint environment."""
    import importlib.util

    doc_path = doc_path or os.path.join(REPO, "docs", "FAULT_TOLERANCE.md")
    chaos_path = os.path.join(REPO, "fedtpu", "ft", "chaos.py")
    spec = importlib.util.spec_from_file_location("_span_check_chaos",
                                                  chaos_path)
    chaos = importlib.util.module_from_spec(spec)
    # Registered for the exec: dataclass processing resolves the module's
    # (string) annotations through sys.modules.
    sys.modules[spec.name] = chaos
    try:
        spec.loader.exec_module(chaos)
        kinds = tuple(chaos.KINDS)
    finally:
        sys.modules.pop(spec.name, None)
    documented = documented_names(doc_path)
    problems = []
    if not kinds:
        problems.append("fedtpu.ft.chaos.KINDS is empty — the kind registry "
                        "or loader drifted; fix tools/span_check.py")
    for kind in sorted(kinds):
        if kind not in documented:
            problems.append(
                f"chaos fault kind {kind!r} (fedtpu/ft/chaos.py KINDS) has "
                "no entry in docs/FAULT_TOLERANCE.md"
            )
    return problems


def main(argv=None) -> int:
    problems = check()
    if problems:
        for problem in problems:
            print(f"SPAN DRIFT: {problem}", file=sys.stderr)
        return 1
    n = len(emitted_span_names())
    m = len(emitted_metric_names())
    print(f"ok: {n} span names + {m} metric names emitted + chaos kinds, "
          "all documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
