#!/usr/bin/env python
"""Span-name drift check: every span the framework emits must be documented.

Scans ``fedtpu/`` for literal span names passed to ``*.span("name", ...)``
and verifies each appears as inline code (`` `name` ``) in
``docs/OBSERVABILITY.md``'s span table. Catches the silent failure mode
where a new subsystem adds spans (or renames one) and the operator-facing
span model drifts out of date — dashboards and trace queries then filter
on names that no longer exist.

Tier-1 runnable: ``tests/test_obs_propagation.py`` calls :func:`check`;
standalone: ``python tools/span_check.py`` (exit 1 + a list on drift).
Stdlib only.
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Set

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Literal first argument of a .span( call. Variables/f-strings never match
# — fedtpu's span names are deliberately all literal (greppability is the
# point of a fixed span vocabulary).
_SPAN_CALL = re.compile(r"""\.span\(\s*(['"])([A-Za-z0-9_.:-]+)\1""")
_INLINE_CODE = re.compile(r"`([^`]+)`")


def emitted_span_names(package_dir: str = None) -> Dict[str, List[str]]:
    """{span name: [relative file paths emitting it]} over fedtpu/."""
    package_dir = package_dir or os.path.join(REPO, "fedtpu")
    found: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(package_dir):
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
            for m in _SPAN_CALL.finditer(text):
                rel = os.path.relpath(path, REPO)
                found.setdefault(m.group(2), []).append(rel)
    return found


def documented_names(doc_path: str = None) -> Set[str]:
    """Every inline-code token in OBSERVABILITY.md (the span table uses
    `` `name` `` markup; matching the whole doc keeps the check insensitive
    to table layout)."""
    doc_path = doc_path or os.path.join(REPO, "docs", "OBSERVABILITY.md")
    with open(doc_path, encoding="utf-8") as fh:
        text = fh.read()
    # Drop fenced code blocks first: their ``` markers desynchronize naive
    # single-backtick pairing over the rest of the document.
    text = re.sub(r"```.*?```", "", text, flags=re.S)
    names: Set[str] = set()
    for m in _INLINE_CODE.finditer(text):
        # A cell like `round` / `fused_rounds` documents both tokens.
        for tok in re.split(r"[\s/|,]+", m.group(1)):
            if tok:
                names.add(tok.strip())
    return names


def check(package_dir: str = None, doc_path: str = None) -> List[str]:
    """Problem strings (empty = pass)."""
    emitted = emitted_span_names(package_dir)
    documented = documented_names(doc_path)
    problems = []
    if not emitted:
        problems.append("scanner found NO span calls in fedtpu/ — the "
                        "regex or layout drifted; fix tools/span_check.py")
    for name in sorted(emitted):
        if name not in documented:
            problems.append(
                f"span {name!r} (emitted in {', '.join(emitted[name])}) has "
                "no entry in docs/OBSERVABILITY.md"
            )
    return problems


def main(argv=None) -> int:
    problems = check()
    if problems:
        for problem in problems:
            print(f"SPAN DRIFT: {problem}", file=sys.stderr)
        return 1
    n = len(emitted_span_names())
    print(f"ok: {n} span names emitted, all documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
