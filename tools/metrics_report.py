#!/usr/bin/env python
"""Human-readable report from a round-record JSONL (+ optional trace) pair.

Turns the telemetry exporters' output back into the question operators
actually ask — *where did the round time and the wire bytes go?*:

    python tools/metrics_report.py metrics.jsonl
    python tools/metrics_report.py metrics.jsonl --trace trace.json

- The JSONL is the ``--metrics`` file a run/server CLI wrote
  (``fedtpu.obs.RoundRecordWriter``; legacy unversioned records are read
  as schema v0). Phase columns appear for whichever ``t_*_s`` fields the
  records carry (the distributed server's records carry
  collect/decode/h2d/aggregate/post_barrier).
- The trace is a ``--trace-out`` Chrome-trace dump; per-span-name and
  per-client aggregates come from it (span ``args.client`` labels the
  collect workers and broadcast sends).

Pure stdlib on purpose: this must run anywhere the JSONL landed, including
boxes with no jax install.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from jsontail import round_records  # noqa: E402

# Round-record phase fields, in pipeline order (server rounds carry all of
# these; engine-CLI records carry none and just get the scalar summary).
PHASES = ("t_collect_s", "t_decode_s", "t_h2d_s", "t_aggregate_s",
          "t_post_barrier_s")


def _stats(values):
    values = sorted(values)
    n = len(values)
    return {
        "n": n,
        "mean": sum(values) / n,
        "p50": values[n // 2],
        "max": values[-1],
    }


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0


def report_records(records, skipped, out=sys.stdout):
    w = out.write
    if not records:
        w("no round records found\n")
        return
    versions = sorted({r["schema_version"] for r in records})
    w(f"rounds: {len(records)}  (schema versions: "
      f"{', '.join(map(str, versions))}"
      + (f"; {skipped} lines skipped" if skipped else "") + ")\n")

    numeric = {}
    for key in ("participants", "stragglers", "loss", "acc", "test_acc"):
        vals = [r[key] for r in records if isinstance(r.get(key), (int, float))]
        if vals:
            numeric[key] = _stats(vals)
    if numeric:
        w("\n  field          n     mean       p50       max\n")
        for key, s in numeric.items():
            w(f"  {key:<13}{s['n']:>4}  {s['mean']:>8.4f}  {s['p50']:>8.4f}"
              f"  {s['max']:>8.4f}\n")

    up = sum(r.get("bytes_up", 0) for r in records)
    down = sum(r.get("bytes_down", 0) for r in records)
    if up or down:
        w(f"\nwire: {_fmt_bytes(up)} up, {_fmt_bytes(down)} down "
          f"({_fmt_bytes(up / len(records))}/round up, "
          f"{_fmt_bytes(down / len(records))}/round down)\n")

    phase_rows = [
        (key, _stats([r[key] for r in records if key in r]))
        for key in PHASES
        if any(key in r for r in records)
    ]
    if phase_rows:
        # Share of the round attributed against collect+aggregate wall
        # (decode/h2d overlap collect under the streaming pipeline, so
        # shares can exceed 100% — that overlap is the point).
        wall = sum(
            r.get("t_collect_s", 0) + r.get("t_aggregate_s", 0)
            for r in records
        )
        w("\n  phase             mean ms    p50 ms    max ms   % of wall\n")
        for key, s in phase_rows:
            total = s["mean"] * s["n"]
            share = 100.0 * total / wall if wall else 0.0
            name = key[2:-2]  # t_collect_s -> collect
            w(f"  {name:<15}{s['mean'] * 1e3:>10.2f}{s['p50'] * 1e3:>10.2f}"
              f"{s['max'] * 1e3:>10.2f}{share:>11.1f}\n")
        w("  (decode/h2d overlap collect under server_pipeline=stream;"
          " shares are of collect+aggregate wall)\n")


def report_trace(events, out=sys.stdout):
    w = out.write
    if not events:
        w("\nno trace events\n")
        return
    by_name, by_client = {}, {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e["dur"])
        client = e.get("args", {}).get("client")
        if client is not None:
            by_client.setdefault(client, {}).setdefault(
                e["name"], []
            ).append(e["dur"])
    w(f"\ntrace: {len(events)} spans\n")
    w("\n  span            count   total ms    mean ms     max ms\n")
    for name, durs in sorted(
        by_name.items(), key=lambda kv: -sum(kv[1])
    ):
        w(f"  {name:<15}{len(durs):>6}{sum(durs) / 1e3:>11.2f}"
          f"{sum(durs) / len(durs) / 1e3:>11.2f}{max(durs) / 1e3:>11.2f}\n")
    if by_client:
        w("\n  per-client (total ms by span):\n")
        names = sorted({n for spans in by_client.values() for n in spans})
        w("  client".ljust(24) + "".join(f"{n:>12}" for n in names) + "\n")
        for client in sorted(by_client):
            row = "  " + str(client).ljust(22)
            for n in names:
                durs = by_client[client].get(n)
                row += f"{sum(durs) / 1e3:>12.2f}" if durs else f"{'-':>12}"
            w(row + "\n")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("metrics", help="round-record JSONL path (--metrics file)")
    p.add_argument("--trace", default=None,
                   help="Chrome trace JSON path (--trace-out file)")
    args = p.parse_args(argv)

    with open(args.metrics) as fh:
        records, skipped = round_records(fh.read())
    report_records(records, skipped)
    if args.trace:
        with open(args.trace) as fh:
            doc = json.load(fh)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        report_trace(events)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
