#!/usr/bin/env python
"""Live one-line-per-round view of a federation's ``/statusz`` endpoint.

Point it at a process started with ``--obs-port`` (server/run/train CLIs):

    python tools/statusz.py http://127.0.0.1:8790            # one line now
    python tools/statusz.py http://127.0.0.1:8790 --watch    # line per round

``--watch`` polls every ``--interval`` seconds and prints a fresh line
whenever the round (or failover role) advances — the terminal-native
replacement for staring at a JSONL tail. Stdlib only, no fedtpu import
(usable against a remote host from a machine without the repo).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def fetch(url: str, timeout: float = 2.0) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/statusz",
                                timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def render_line(status: dict) -> str:
    """One compact line from a /statusz snapshot (any role's shape)."""
    # A promoted backup nests the acting primary's status; show that one,
    # prefixed with the outer role.
    prefix = ""
    if "acting" in status and isinstance(status["acting"], dict):
        prefix = f"[{status.get('role', '?')}] "
        status = status["acting"]
    parts = [f"{prefix}role={status.get('role', '?')}"]
    if "round" in status:
        parts.append(f"round={status['round']}")
    if "phase" in status:
        parts.append(f"phase={status['phase']}")
    clients = status.get("clients")
    if isinstance(clients, dict):
        if isinstance(clients.get("active"), int):
            # Aggregator snapshots carry roster COUNTS (active/dead/total),
            # not address lists — the cohort can be large.
            active, dead_n = clients["active"], int(clients.get("dead", 0))
            parts.append(f"alive={active}/{active + dead_n}")
        else:
            alive = clients.get("alive", [])
            dead = clients.get("dead", [])
            parts.append(f"alive={len(alive)}/{len(alive) + len(dead)}")
            if dead:
                parts.append(f"dead={','.join(dead)}")
    elif isinstance(status.get("alive"), list):
        mask = status["alive"]
        parts.append(f"alive={sum(1 for a in mask if a)}/{len(mask)}")
    mem = status.get("mem")
    if isinstance(mem, dict) and mem.get("tier"):
        # Hierarchical topology: which tier this process is (root/leaf,
        # flat when one-tier) and the rows currently buffered toward its
        # partial reduce — nonzero only mid-collect.
        parts.append(f"tier={mem['tier']}")
        if mem.get("partial_rows_buffered"):
            parts.append(f"partial_rows={int(mem['partial_rows_buffered'])}")
    if status.get("heartbeat_misses"):
        parts.append(f"hb_miss={int(status['heartbeat_misses'])}")
    if status.get("seconds_since_primary_ping") is not None:
        parts.append(f"ping_age={status['seconds_since_primary_ping']:.1f}s")
    last = status.get("last_round")
    if isinstance(last, dict):
        timing = " ".join(
            f"{k[2:-2]}={last[k]:.3f}s"
            for k in ("t_collect_s", "t_aggregate_s")
            if isinstance(last.get(k), (int, float))
        )
        extras = []
        if "participants" in last:
            extras.append(f"part={last['participants']}")
        if last.get("stragglers"):
            extras.append(f"strag={last['stragglers']}")
        parts.append(("last[" + " ".join(extras + [timing]).strip() + "]"))
    return " ".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("url", help="base obs URL, e.g. http://127.0.0.1:8790")
    p.add_argument("--watch", action="store_true",
                   help="poll until interrupted; print a line whenever the "
                   "round or role changes")
    p.add_argument("--interval", default=1.0, type=float,
                   help="--watch poll period in seconds")
    p.add_argument("--timeout", default=2.0, type=float)
    args = p.parse_args(argv)

    last_key = None
    while True:
        try:
            status = fetch(args.url, timeout=args.timeout)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            print(f"unreachable: {exc}", file=sys.stderr)
            if not args.watch:
                return 1
            time.sleep(args.interval)
            continue
        inner = status.get("acting") or status
        key = (inner.get("round"), status.get("role"), inner.get("role"))
        if not args.watch:
            print(render_line(status))
            return 0
        if key != last_key:
            print(render_line(status), flush=True)
            last_key = key
        time.sleep(args.interval)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except KeyboardInterrupt:
        raise SystemExit(130)
