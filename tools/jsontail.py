"""Shared helper: salvage the last JSON-object line from a child's stdout.

Child processes on the wedge-prone tunnel backend can die or hang AFTER
printing their measurement (interpreter teardown, profiler shutdown), so
every capture tool scans stdout backwards for the last parseable JSON line
instead of trusting the exit code. One implementation, used by
``tools/run_accfull_tpu.py``, ``tools/bench_resnet_tpu.py`` and
``tools/tpu_watch.py`` (and mirroring ``bench.py``'s internal `_salvage_json`).
"""

import json


def last_json_line(text):
    """Last line of ``text`` that parses as a JSON object, or ``None``."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None
