"""Shared helpers: JSON-line salvage + versioned round-record parsing.

Child processes on the wedge-prone tunnel backend can die or hang AFTER
printing their measurement (interpreter teardown, profiler shutdown), so
every capture tool scans stdout backwards for the last parseable JSON line
instead of trusting the exit code. One implementation, used by
``tools/run_accfull_tpu.py``, ``tools/bench_resnet_tpu.py`` and
``tools/tpu_watch.py`` (and mirroring ``bench.py``'s internal `_salvage_json`).

Round records (the ``--metrics`` JSONL the CLIs write through
``fedtpu.obs.RoundRecordWriter``) are schema-versioned since PR 3:
:func:`round_records` normalises a stream of them — legacy unversioned
lines get ``schema_version: 0``, lines from a NEWER schema than this
checkout understands are surfaced, not silently misread.
"""

import json

# The round-record schema this checkout's tools understand. Mirrors
# fedtpu.obs.exporters.SCHEMA_VERSION without importing fedtpu (the tools
# must run standalone); tests/test_obs_exporters.py pins the two equal.
ROUND_RECORD_SCHEMA_VERSION = 1


def last_json_line(text):
    """Last line of ``text`` that parses as a JSON object, or ``None``."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def round_records(text, max_schema=ROUND_RECORD_SCHEMA_VERSION):
    """Parse round records out of a JSONL blob.

    Returns ``(records, skipped)``: every parseable JSON-object line that
    looks like a round record (has a ``step``), with missing
    ``schema_version`` normalised to 0, in file order — plus the count of
    lines skipped for being unparseable OR carrying a schema newer than
    ``max_schema`` (a newer writer's keys cannot be trusted to mean what
    this checkout thinks they mean).
    """
    records, skipped = [], 0
    for line in (text or "").strip().splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if not isinstance(rec, dict) or "step" not in rec:
            continue
        rec.setdefault("schema_version", 0)
        if rec["schema_version"] > max_schema:
            skipped += 1
            continue
        records.append(rec)
    return records, skipped


def last_round_record(text, max_schema=ROUND_RECORD_SCHEMA_VERSION):
    """Newest understood round record in ``text``, or ``None``."""
    records, _ = round_records(text, max_schema=max_schema)
    return records[-1] if records else None
