#!/usr/bin/env python
"""Convergence delta of the bf16-momentum mode vs f32 parity (VERDICT r4 #4a).

The bf16 momentum buffer halves optimizer-state HBM traffic (the BASELINE.md
roofline names f32 param+momentum traffic a leading bandwidth consumer); its
cost is one bf16 round-trip of the buffer per step. Whether that rounding
hurts LEARNING is an empirical question — this runs BASELINE config 2's
shape (smallcnn / cifar10_hard / 8 clients / dirichlet — the non-saturating
task used for every accuracy-parity row) once per momentum dtype, same seed
and data, and appends both curves + finals to
``artifacts/MOMENTUM_DTYPE_CONVERGENCE.jsonl``.

Runs on the CPU platform (pinned in-process; the decision is about
convergence, not speed — the SPEED side is the watcher's bench_mom_bf16 leg
on the real chip).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "artifacts", "MOMENTUM_DTYPE_CONVERGENCE.jsonl")


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")  # env var ignored under axon
    import dataclasses

    from bench_parity import acc_configs
    from fedtpu.core.engine import Federation
    from fedtpu.data import load

    (name, cfg), = [c for c in acc_configs()
                    if c[0].startswith("2_acc_smallcnn")]
    rows = []
    with open(OUT, "a") as out:
        for dtype in ("float32", "bfloat16"):
            run_cfg = dataclasses.replace(
                cfg, opt=dataclasses.replace(cfg.opt, momentum_dtype=dtype))
            fed = Federation(run_cfg, seed=0)
            test = load(run_cfg.data.dataset, "test", seed=run_cfg.data.seed,
                        num=run_cfg.data.num_examples)
            t0 = time.time()
            curve = []
            for r in range(run_cfg.fed.num_rounds):
                m = fed.step()
                float(m.loss)
                _, ta = fed.evaluate(*test)
                curve.append(round(ta, 4))
            row = {
                "study": "momentum_dtype", "config": name,
                "momentum_dtype": dtype, "rounds": run_cfg.fed.num_rounds,
                "final_test_acc": curve[-1], "curve": curve,
                "data_source": fed.data_source,
                "wall_s": round(time.time() - t0, 1),
                "at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            }
            rows.append(row)
            out.write(json.dumps(row) + "\n")
            out.flush()
            print(json.dumps(row), flush=True)
    delta = rows[1]["final_test_acc"] - rows[0]["final_test_acc"]
    print(json.dumps({"study": "momentum_dtype", "final_acc_delta_bf16_minus_f32":
                      round(delta, 4)}))


if __name__ == "__main__":
    main()
