#!/usr/bin/env python
"""Fused-round bench for an arbitrary zoo model on the one real chip.

``bench.py`` measures the parity smallcnn headline; ``bench_resnet_tpu.py``
measures the MXU-shaped config-4 model. This tool covers everything else —
round 5's first target is the reference's DEFAULT model, MobileNet
(hardcoded at ``/root/reference/src/main.py:69`` and ``src/server.py:158``),
which until now had AOT-compile evidence only
(``PALLAS_TPU_COMPILE.json``: 2.54 TFLOP/round, 64 clients, single chip).

Same engine program as ``bench.py``: the fused multi-round scan at 64
clients / batch 128 / 6 steps, bf16 activations. Parameterised via env so
the watcher can queue several models without one file per model:

  FEDTPU_BM_MODEL    (default "mobilenet")
  FEDTPU_BM_DATASET  (default "cifar10")
  FEDTPU_BM_CLASSES  (default 10)
  FEDTPU_BM_REMAT    (default "0")
  FEDTPU_BM_ROUNDS   (fused rounds per dispatch, default 2)
  FEDTPU_BM_OUT      (artifact name, default "BENCH_<MODEL>_TPU.json")
  FEDTPU_BM_CLIENTS / FEDTPU_BM_BATCH / FEDTPU_BM_STEPS (64 / 128 / 6)
  FEDTPU_BM_PLATFORM (unset = default backend; "cpu" pins the virtual CPU
                      platform IN-PROCESS — the env var alone is ignored
                      under the axon plugin — so the wrapper can be smoked
                      end-to-end without burning a TPU window)

The whole measurement runs in a bounded subprocess (the tunnel can wedge
mid-compile); on timeout the artifact records the failure instead of
hanging the watcher.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts")
MODEL = os.environ.get("FEDTPU_BM_MODEL", "mobilenet")
DATASET = os.environ.get("FEDTPU_BM_DATASET", "cifar10")
CLASSES = int(os.environ.get("FEDTPU_BM_CLASSES", "10"))
REMAT = os.environ.get("FEDTPU_BM_REMAT", "0") == "1"
ROUNDS = int(os.environ.get("FEDTPU_BM_ROUNDS", "2"))
OUT = os.path.join(ART, os.environ.get(
    "FEDTPU_BM_OUT", f"BENCH_{MODEL.upper()}_TPU.json"))
TIMEOUT_S = 2700

_INNER = r"""
import json, time, sys
import jax, jax.numpy as jnp, numpy as np
if %(platform)r:
    jax.config.update("jax_platforms", %(platform)r)
sys.path.insert(0, %(repo)r)
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core.engine import Federation

NUM_CLIENTS=%(clients)d; BATCH=%(batch)d; STEPS=%(steps)d; ROUNDS=%(rounds)d; TRIALS=3
cfg = RoundConfig(model=%(model)r, num_classes=%(classes)d,
    opt=OptimizerConfig(),
    data=DataConfig(dataset=%(dataset)r, batch_size=BATCH, partition="iid",
                    num_examples=NUM_CLIENTS*STEPS*BATCH),
    fed=FedConfig(num_clients=NUM_CLIENTS), steps_per_round=STEPS,
    dtype="bfloat16", remat=%(remat)r)
fed = Federation(cfg, seed=0)
d = fed._ensure_device_data()
alive = jnp.ones((ROUNDS, NUM_CLIENTS), bool)
multi = fed._multi_step(ROUNDS)
print("compiling...", flush=True)
t0=time.time()
step = multi.lower(fed.state, *d, fed.weights, alive, fed._data_key).compile()
print("compiled in %%.1fs" %% (time.time()-t0), flush=True)
flops = None
try:
    single = fed._data_step.lower(fed.state, *d, fed.weights,
        jnp.ones((NUM_CLIENTS,), bool), fed._data_key).compile()
    an = single.cost_analysis()
    if isinstance(an,(list,tuple)): an = an[0] if an else {}
    flops = float(an.get("flops",0.0)) or None
except Exception as e:
    print("cost analysis failed:", e, flush=True)
state = fed.state
state, m = step(state, *d, fed.weights, alive, fed._data_key)
np.asarray(m.loss)  # warmup + honest sync
rates=[]
for _ in range(TRIALS):
    t0=time.perf_counter()
    state, m = step(state, *d, fed.weights, alive, fed._data_key)
    np.asarray(m.loss)
    rates.append(ROUNDS/(time.perf_counter()-t0))
rps = sorted(rates)[len(rates)//2]
kind = jax.devices()[0].device_kind
out = {"metric":"fedavg_rounds_per_sec_%(dataset)s_%(model)s_%%dclients_1chip" %% NUM_CLIENTS,
  "rounds_per_sec": round(rps,4),
  "client_epochs_per_sec_per_chip": round(rps*NUM_CLIENTS,2),
  "num_clients":NUM_CLIENTS,"batch":BATCH,"steps_per_round":STEPS,
  "remat":%(remat)r,"dtype":"bfloat16","device_kind":kind,
  "backend":jax.default_backend()}
if flops:
    out["flops_per_round"]=flops
    import bench
    peak = bench._peak_for(kind)
    if peak:
        out["mfu"]=round(rps*flops/peak,4)
print(json.dumps(out), flush=True)
"""


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from jsontail import last_json_line

    inner = _INNER % {
        "repo": REPO, "model": MODEL, "dataset": DATASET,
        "classes": CLASSES, "remat": REMAT, "rounds": ROUNDS,
        "clients": int(os.environ.get("FEDTPU_BM_CLIENTS", "64")),
        "batch": int(os.environ.get("FEDTPU_BM_BATCH", "128")),
        "steps": int(os.environ.get("FEDTPU_BM_STEPS", "6")),
        "platform": os.environ.get("FEDTPU_BM_PLATFORM", ""),
    }
    proc = None
    try:
        proc = subprocess.run(
            [sys.executable, "-c", inner], capture_output=True, text=True,
            timeout=TIMEOUT_S, cwd=REPO,
        )
        out, err, note = proc.stdout, proc.stderr, None
    except subprocess.TimeoutExpired as exc:
        out = (exc.stdout or b"")
        out = out.decode() if isinstance(out, bytes) else out
        err, note = "", f"timeout after {TIMEOUT_S}s"
    n_clients = int(os.environ.get("FEDTPU_BM_CLIENTS", "64"))
    line = last_json_line(out)
    if line is None:
        line = {"metric":
                f"fedavg_rounds_per_sec_{DATASET}_{MODEL}_{n_clients}clients_1chip",
                "value": 0.0,
                "error": note or f"no JSON (rc={proc.returncode}): {err.strip()[-400:]}",
                "progress": (out or "").strip().splitlines()[-3:]}
    line["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(line, f, indent=2)
    os.replace(tmp, OUT)
    print(json.dumps(line))
    return 0 if "error" not in line else 4


if __name__ == "__main__":
    raise SystemExit(main())
