"""Disaster recovery: cold-start restore, generation fallback, client
rollback/state persistence — the tier-1 leg of the durability PR.

The fast drill here is the in-process twin of ``tools/chaos_soak.py
--disaster`` (which runs real subprocess SIGKILLs as a ``slow`` soak): a
primary checkpoints every round through the hardened store while a seeded
``ckpt_rot`` disk fault silently corrupts the newest generation; the
primary object is then abandoned mid-lineage (total coordinator loss — no
graceful handoff, no replica), a FRESH primary cold-starts from the
directory, falls back a generation, resyncs the surviving stateful
clients, and — because the lineage round carried in StartTrain makes the
clients roll back to their matching round snapshots — finishes with a
final model BIT-IDENTICAL to an uninterrupted control run.
"""

import os
import socket

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.checkpoint import Checkpointer
from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
)
from fedtpu.ft.chaos import parse_spec
from fedtpu.obs import MetricsRegistry
from fedtpu.transport import wire
from fedtpu.transport.federation import LocalTrainer, PrimaryServer, serve_client


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(num_clients=2, rounds=6, **fed_kw) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=128,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=rounds, **fed_kw),
        steps_per_round=2,
    )


def _params_equal(a, b) -> bool:
    ok = []
    jax.tree.map(
        lambda x, y: ok.append(
            np.array_equal(np.asarray(x), np.asarray(y))
        ),
        a, b,
    )
    return all(ok)


# ------------------------------------------------------------ the fast drill
def test_cold_restart_with_generation_fallback_matches_control(tmp_path):
    """Total coordinator loss, corrupt newest generation, surviving
    stateful clients: the recovered lineage must re-run the voided round
    through client rollback and converge BIT-IDENTICALLY to a run that
    never crashed. Also pins: fallback counted, restored FedOpt moments,
    supersession-exact lineage, full participation post-recovery."""
    n, rounds, crash_after = 2, 6, 5  # crash after round 4 committed
    cfg = tiny_cfg(n, rounds, server_optimizer="momentum")
    ckpt_dir = str(tmp_path / "ckpt")

    def run_control():
        servers, addrs = [], []
        try:
            for i in range(n):
                addr = f"localhost:{free_port()}"
                server, _ = serve_client(addr, cfg, seed=i)
                servers.append(server)
                addrs.append(addr)
            primary = PrimaryServer(cfg, addrs)
            recs = [primary.round() for _ in range(rounds)]
            return (
                jax.tree.map(np.asarray, primary.params),
                [int(r["round"]) for r in recs],
            )
        finally:
            for s in servers:
                s.stop(0)

    control_params, control_lineage = run_control()
    assert control_lineage == list(range(rounds))

    servers, addrs = [], []
    try:
        for i in range(n):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        # Generation 4 (the newest at crash time) silently bit-rots after
        # its verified write — the same schedule drives the primary's wire
        # interceptors (where the disk rule is inert) so set_round flows.
        chaos = parse_spec(
            f"ckpt_rot:p=1.0,rounds={crash_after - 1},max=1"
        )
        reg1 = MetricsRegistry()
        ckpt1 = Checkpointer(
            ckpt_dir, keep=4, backend="wire", metrics=reg1, chaos=chaos,
        )
        primary1 = PrimaryServer(cfg, addrs, chaos=chaos)
        gen1_lineage = []
        for r in range(crash_after):
            rec = primary1.round()
            gen1_lineage.append(int(rec["round"]))
            ckpt1.save(r, primary1.state_tree())
        assert gen1_lineage == list(range(crash_after))
        # CRASH: the primary object is abandoned — no graceful handoff,
        # no replica; the disk is the only surviving copy.
        del primary1

        reg2 = MetricsRegistry()
        ckpt2 = Checkpointer(ckpt_dir, keep=4, backend="wire", metrics=reg2)
        primary2 = PrimaryServer(cfg, addrs)
        start = primary2.restore_from_checkpoint(ckpt2)
        # Newest (4) is rotten -> fallback to 3 -> resume at round 4.
        assert start == crash_after - 1
        assert reg2.counter(
            "fedtpu_checkpoint_fallback_total", ""
        ).value == 1
        assert primary2._round_counter == start
        gen2_lineage = []
        for _ in range(rounds - start):
            rec = primary2.round()
            gen2_lineage.append(int(rec["round"]))
            assert rec["participants"] == n  # survivors resynced, no loss
        # Supersession: the crash voided the never-durable round 4; the
        # durable history + the restart's records exact-cover 0..N-1.
        durable = [r for r in gen1_lineage if r < start]
        assert durable + gen2_lineage == list(range(rounds))
        recovered_params = jax.tree.map(np.asarray, primary2.params)
    finally:
        for s in servers:
            s.stop(0)

    assert _params_equal(recovered_params, control_params), (
        "recovered trajectory diverged from the uninterrupted control"
    )


def test_cold_restart_all_generations_corrupt_raises(tmp_path):
    """A directory where nothing verifies must fail the resume loudly —
    never silently restart the lineage from round 0."""
    cfg = tiny_cfg(2, 2)
    ckpt_dir = str(tmp_path / "ckpt")
    primary = PrimaryServer(cfg, [])
    ckpt = Checkpointer(ckpt_dir, keep=3, backend="wire")
    ckpt.save(0, primary.state_tree())
    path = os.path.join(ckpt_dir, "round_0.fckpt")
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0x55
    open(path, "wb").write(bytes(data))
    fresh = PrimaryServer(cfg, [])
    with pytest.raises(wire.WireError, match="checkpoint generations"):
        fresh.restore_from_checkpoint(Checkpointer(ckpt_dir, backend="wire"))


def test_membership_and_reputation_survive_cold_restart(tmp_path):
    """Roster state restored from disk: a member admitted at runtime (the
    Join path) and its suspicion score are both present after a cold
    restart WITHOUT re-registration — the "no re-registration data loss"
    half of the recovery protocol."""
    cfg = tiny_cfg(2, 4)
    ckpt_dir = str(tmp_path / "ckpt")
    servers, addrs = [], []
    try:
        for i in range(3):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        static, joiner = addrs[:2], addrs[2]
        primary1 = PrimaryServer(cfg, static)
        out = primary1.admit_client(joiner)
        assert out["admitted"] and out["resynced"]
        version1 = primary1.registry.version
        primary1.registry.observe_screening(joiner, True, ewma=0.5)
        suspicion1 = primary1.registry.suspicion(joiner)
        assert suspicion1 > 0
        primary1.round()
        ckpt = Checkpointer(ckpt_dir, keep=3, backend="wire")
        ckpt.save(0, primary1.state_tree())
        del primary1

        primary2 = PrimaryServer(cfg, static)  # startup roster: 2 members
        start = primary2.restore_from_checkpoint(
            Checkpointer(ckpt_dir, backend="wire")
        )
        assert start == 1
        assert primary2.registry.is_member(joiner)
        assert primary2.registry.version == version1
        assert primary2.registry.suspicion(joiner) == pytest.approx(
            suspicion1
        )
        # The adopted roster is dialable: the next round reaches all 3.
        rec = primary2.round()
        assert rec["participants"] == 3
    finally:
        for s in servers:
            s.stop(0)


# ----------------------------------------------------- client-side durability
def test_client_state_dir_restart_resumes_bit_identically(tmp_path):
    """A RESTARTED client (fresh process semantics: new LocalTrainer, same
    --state-dir) must produce the exact payload the uninterrupted client
    would have: round counter, optimizer moments, PRNG stream, and the
    error-feedback residual all restore from the per-round generational
    store. Without state_dir the restart silently diverges (pinned too —
    that is the failure the flag exists for)."""
    cfg = tiny_cfg(1, 8, compression="topk", topk_fraction=0.05)
    state_dir = str(tmp_path / "client_state")

    def fresh(seed=0, state_dir_=None):
        t = LocalTrainer(cfg, seed=seed, state_dir=state_dir_)
        return t

    # One fixed "global" install per round, standing in for the server's
    # per-round broadcast (identical for every trainer instance: same
    # seed -> same init).
    proto_trainer = fresh()
    global_payload = wire.encode(
        {"params": proto_trainer.params,
         "batch_stats": proto_trainer.batch_stats},
    )

    def run_rounds(trainer, k):
        out = None
        for _ in range(k):
            trainer.set_global(global_payload)
            out = trainer.train_round(0, 1)
        return out

    control = fresh()
    control_payload = run_rounds(control, 3)

    t1 = fresh(state_dir_=state_dir)
    run_rounds(t1, 2)
    assert t1.edge_residual is not None  # EF is live and persisted
    del t1  # process death

    t2 = fresh(state_dir_=state_dir)
    assert t2.round_idx == 2  # resumed, not reset
    assert t2.edge_residual is not None
    resumed_payload = run_rounds(t2, 1)
    assert resumed_payload == control_payload

    # Counter-example: a stateless restart diverges (different round seed
    # and a lost residual) — the hazard the flag closes.
    t3 = fresh()
    run_rounds(t3, 2)
    t4 = fresh()  # restart WITHOUT state_dir
    diverged_payload = run_rounds(t4, 1)
    assert diverged_payload != control_payload


def test_client_rollback_on_coordinator_replay():
    """A StartTrain carrying a lineage round BEHIND the client's local
    counter (coordinator recovered from an older generation) rolls the
    client back to its round snapshot: the replayed round's payload is
    byte-identical to the original. A request AHEAD of the counter keeps
    the ordinary drift semantics (no rollback)."""
    cfg = tiny_cfg(1, 8)
    t = LocalTrainer(cfg, seed=0)
    payloads = {}
    for r in range(4):
        payloads[r] = t.train_round(0, 1, coord_round=r)
    assert t.round_idx == 4
    # Replay round 2: rollback (snapshot ring holds rounds 0..3).
    replay = t.train_round(0, 1, coord_round=2)
    assert replay == payloads[2]
    assert t.round_idx == 3  # counter follows the replayed lineage
    # And the lineage continues forward identically.
    assert t.train_round(0, 1, coord_round=3) == payloads[3]
    # Ahead-of-counter (sampling skip): trains forward, no rollback.
    before = t.round_idx
    t.train_round(0, 1, coord_round=before + 5)
    assert t.round_idx == before + 1


def test_client_rollback_depth_is_ring_bounded():
    """A replay deeper than SNAPSHOT_KEEP has no snapshot: the client
    logs and trains forward (divergence is reported, not hidden)."""
    cfg = tiny_cfg(1, 16)
    t = LocalTrainer(cfg, seed=0)
    for r in range(8):
        t.train_round(0, 1, coord_round=r)
    target = 8 - LocalTrainer.SNAPSHOT_KEEP - 1
    assert not t._rollback(target)
    t.train_round(0, 1, coord_round=target)  # no raise; forward training
    assert t.round_idx == 9


# ------------------------------------------------------- the full soak (slow)
@pytest.mark.slow
def test_disaster_soak_total_process_loss(tmp_path):
    """The committed-artifact soak re-run: subprocess primary+backup
    SIGKILLed mid-round under seeded torn+rot disk faults, cold restart,
    supersession-exact lineage, bit-identical final model vs control.
    Several minutes; marked slow."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    ))
    import chaos_soak

    result = chaos_soak.run_disaster_soak(
        rounds=16, kill_round=8, workdir=str(tmp_path / "soak"),
        verbose=False,
    )
    assert result["ok"] is True
    assert result["checkpoint_fallbacks"] == 2
    assert result["bit_identical_vs_control"] is True
    assert result["lineage"]["exact_cover"] is True
