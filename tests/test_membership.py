"""Elastic membership: the MembershipTable, Join/Leave over real gRPC,
quorum-over-live-set semantics, membership replication through failover,
and the rolling-upgrade / churn drills.

Fast legs run in tier-1 (a few seconds of real gRPC on localhost); the
1k-round churn soak runs as ``slow``.
"""

import dataclasses
import os
import sys
import threading
import time

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from fedtpu.config import RetryPolicy
from fedtpu.ft import MembershipTable
from fedtpu.ft.heartbeat import HeartbeatMonitor
from fedtpu.transport import proto

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import chaos_soak  # noqa: E402
import rolling_upgrade  # noqa: E402


# ------------------------------------------------------- membership table
def test_admit_evict_seats_and_versions():
    t = MembershipTable(["a", "b"])
    assert t.clients == ["a", "b"]
    assert t.capacity() == 2 and t.version == 0  # startup roster: no churn
    # New members start DEAD (must be resynced before StartTrain) and take
    # fresh seats.
    assert t.admit("c") == 2
    assert not t.is_alive("c")
    assert t.capacity() == 3 and t.version == 1
    t.mark_alive("c")
    # Eviction frees the seat; the next joiner reuses it (lowest first),
    # so capacity — the `world` clients partition against — holds steady.
    assert t.evict("b", reason="leave")
    assert t.clients == ["a", "c"] and t.version == 2
    assert t.admit("d") == 1
    assert t.capacity() == 3 and t.version == 3
    assert t.seat_of("d") == 1 and t.seat_of("c") == 2
    # Idempotent admit keeps the seat and does not bump the epoch.
    assert t.admit("d") == 1 and t.version == 3
    # Masks/orderings are seat-ordered over CURRENT members.
    t.mark_alive("d")
    np.testing.assert_array_equal(t.alive_mask(), [True, True, True])
    assert t.clients == ["a", "d", "c"]


def test_unknown_ids_are_logged_and_ignored():
    """A late RPC completion from an evicted client lands in mark_failed /
    mark_alive on an unknown id — that must log-and-ignore, never raise
    (a bare KeyError here killed the collect worker thread)."""
    t = MembershipTable(["a"])
    t.admit("b")
    t.evict("b")
    t.mark_failed("b")   # no raise
    t.mark_alive("b")    # no raise
    assert t.is_alive("b") is False
    assert not t.evict("b")  # double-evict: reported, not raised
    assert t.is_member("b") is False


def test_snapshot_restore_roundtrip_preserves_alive_and_seats():
    t = MembershipTable(["a", "b", "c"])
    t.mark_failed("b")
    t.evict("c", reason="leave")
    t.admit("d")
    snap = t.snapshot()
    fresh = MembershipTable(["x", "y"])  # promoted backup's startup list
    fresh.restore(snap)
    assert fresh.clients == t.clients
    assert fresh.seat_map() == t.seat_map()
    assert not fresh.is_alive("b")      # dead flags replicate
    assert not fresh.is_member("c")
    assert fresh.capacity() == t.capacity()
    # Seat allocation continues correctly after the restore ("d" already
    # reused c's freed seat, so "e" must grow capacity, not collide).
    assert fresh.admit("e") == 3
    assert fresh.version >= snap["version"]


def test_concurrent_admit_evict_revive_races():
    """Hammer one table from many threads; invariants that must hold
    whatever the interleaving: unique seats, capacity >= live seats,
    monotone version, no exceptions."""
    t = MembershipTable([f"s{i}" for i in range(4)])
    stop = time.monotonic() + 1.5
    errors = []

    def worker(k):
        i = 0
        try:
            while time.monotonic() < stop:
                cid = f"w{k}-{i % 7}"
                t.admit(cid)
                t.mark_alive(cid)
                t.mark_failed(cid)
                if i % 3 == 0:
                    t.evict(cid)
                t.is_alive(f"w{(k + 1) % 6}-{i % 7}")
                t.alive_mask()
                i += 1
        except Exception as exc:  # pragma: no cover - the failure signal
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(6)]
    for th in threads:
        th.start()
    versions = []
    while time.monotonic() < stop:
        versions.append(t.version)
        snap = t.snapshot()
        seats = [row[1] for row in snap["members"]]
        assert len(set(seats)) == len(seats), "duplicate seats"
        assert max(seats, default=-1) < snap["capacity"]
    for th in threads:
        th.join()
    assert not errors, errors
    assert versions == sorted(versions), "membership version went backwards"
    # And the final state is internally consistent + restorable.
    fresh = MembershipTable([])
    fresh.restore(t.snapshot())
    assert fresh.clients == t.clients


def test_heartbeat_probes_run_concurrently_and_bounded():
    """One hung probe must not starve the other dead clients' recovery
    (the old sequential pass blocked on each in turn), and the tick is
    bounded by probe_deadline_s."""
    t = MembershipTable(["slow", "fast"])
    t.mark_failed("slow")
    t.mark_failed("fast")
    release = threading.Event()

    def probe(c):
        if c == "slow":
            release.wait(5.0)  # a blackholed peer
        return True

    monitor = HeartbeatMonitor(
        t, probe=probe, resync=lambda c: None, probe_deadline_s=1.0,
    )
    t0 = time.monotonic()
    recovered = monitor.tick()
    elapsed = time.monotonic() - t0
    assert recovered == ["fast"], recovered
    assert elapsed < 3.0, f"tick blocked on the hung probe ({elapsed:.1f}s)"
    assert t.is_alive("fast") and not t.is_alive("slow")
    release.set()
    deadline = time.monotonic() + 5
    while not t.is_alive("slow") and time.monotonic() < deadline:
        time.sleep(0.05)
    # The overrunning probe still completed its revival in the background.
    assert t.is_alive("slow")


# ------------------------------------------------------------ proto layer
def test_join_leave_proto_roundtrip():
    req = proto.JoinRequest(address=b"localhost:5051")
    assert proto.JoinRequest.decode(req.encode()) == req
    rep = proto.JoinReply(admitted=1, seat=3, world=7, version=42,
                          message=b"resynced")
    assert proto.JoinReply.decode(rep.encode()) == rep
    lreq = proto.LeaveRequest(address=b"localhost:5051")
    assert proto.LeaveRequest.decode(lreq.encode()) == lreq
    lrep = proto.LeaveReply(left=1, version=43)
    assert proto.LeaveReply.decode(lrep.encode()) == lrep
    # Proto3 defaults round-trip as empty bytes.
    assert proto.JoinReply.decode(proto.JoinReply().encode()) == proto.JoinReply()


# ------------------------------------------------- live-transport churn leg
def _cfg(n, rounds=4, **fed_kw):
    return chaos_soak._tiny_cfg(n, rounds, **fed_kw)


def _fleet(cfg, n, seed0=0, ghost=False):
    from fedtpu.transport.federation import serve_client
    from fedtpu.transport.service import create_server

    addrs, servers, agents = [], [], []
    for i in range(n):
        addr = f"localhost:{chaos_soak.free_port()}"
        if ghost:
            agent = chaos_soak.GhostableAgent(cfg, seed=seed0 + i)
            server = create_server(addr, agent)
            server.start()
        else:
            server, agent = serve_client(addr, cfg, seed=seed0 + i)
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    return addrs, servers, agents


def test_join_silent_leave_stale_rejoin_over_grpc():
    """The tier-1 churn leg: a third client enters through the REAL Join
    RPC mid-run and trains from the next round; a member leaves silently
    (marked dead after retry exhaustion, nobody else affected); it returns
    stale and is revived + resynced through the heartbeat path; a graceful
    Leave frees its seat for the next joiner."""
    from fedtpu.transport.federation import PrimaryServer
    from fedtpu.transport.service import TrainerStub, create_channel

    cfg = _cfg(2, rounds=8, retry=RetryPolicy(max_attempts=2, backoff_s=0.01),
               ft_heartbeat_period_s=1e6)
    addrs, servers, agents = _fleet(cfg, 3, ghost=True)
    primary = None
    try:
        primary = PrimaryServer(cfg, addrs[:2])
        gate_addr = f"localhost:{chaos_soak.free_port()}"
        primary.start_gate(gate_addr)
        stub = TrainerStub(create_channel(gate_addr))
        rec = primary.round()
        assert rec["participants"] == 2 and rec["world"] == 2
        # --- dynamic join over the wire
        reply = stub.Join(
            proto.JoinRequest(address=addrs[2].encode()), timeout=10
        )
        assert reply.admitted == 1 and reply.seat == 2 and reply.world == 3
        assert reply.message == b"resynced"
        assert agents[2].trainer.synced  # the joiner holds the global NOW
        rec = primary.round()
        assert rec["participants"] == 3 and rec["world"] == 3
        assert rec["membership_version"] == 1
        assert agents[2].trainer.round_idx == 1
        # --- silent leave: RPC failures exhaust retries -> dead, only it
        agents[1].down = True
        rec = primary.round()
        assert rec["participants"] == 2
        assert primary.registry.dead_clients() == [addrs[1]]
        # --- stale rejoin: heartbeat probe + resync + revive
        agents[1].down = False
        assert primary.monitor.tick() == [addrs[1]]
        rec = primary.round()
        assert rec["participants"] == 3
        # --- graceful leave frees the seat; the next joiner reuses it
        reply = stub.Leave(
            proto.LeaveRequest(address=addrs[1].encode()), timeout=10
        )
        assert reply.left == 1
        assert primary.registry.clients == [addrs[0], addrs[2]]
        rec = primary.round()
        assert rec["participants"] == 2 and rec["world"] == 3
        out = primary.admit_client(addrs[1])
        assert out["seat"] == 1  # the freed seat, not a new one
    finally:
        if primary is not None:
            primary.stop_gate()
        for s in servers:
            s.stop(0)


def test_quorum_counts_current_members_not_startup_roster():
    """round_quorum is a fraction of CURRENT members: dead-but-not-evicted
    members hold the denominator up (abort), and evicting them is what
    lets the survivors commit again."""
    from fedtpu.transport.federation import PrimaryServer

    cfg = _cfg(3, rounds=8, round_quorum=0.6,
               retry=RetryPolicy(max_attempts=2, backoff_s=0.01),
               ft_heartbeat_period_s=1e6)
    addrs, servers, agents = _fleet(cfg, 3, ghost=True)
    try:
        primary = PrimaryServer(cfg, addrs)
        rec = primary.round()
        assert not rec.get("aborted")
        # Two of three members leave silently: 1 reply < ceil(0.6*3)=2.
        agents[1].down = True
        agents[2].down = True
        rec = primary.round()
        assert rec.get("aborted") and rec["quorum_needed"] == 2
        # Evicting the departed shrinks the electorate: ceil(0.6*1)=1 —
        # the survivor commits.
        primary.remove_client(addrs[1], reason="operator")
        primary.remove_client(addrs[2], reason="operator")
        rec = primary.round()
        assert not rec.get("aborted") and rec["participants"] == 1
    finally:
        for s in servers:
            s.stop(0)


def test_membership_replicates_to_backup_and_survives_promotion():
    """The roster (joins, evictions, alive flags, seats) rides the replica
    payload: a promoted backup inherits the CURRENT membership, not the
    startup list it was constructed with."""
    from fedtpu.transport.federation import BackupServer, PrimaryServer

    cfg = _cfg(2, rounds=8, ft_heartbeat_period_s=1e6)
    addrs, servers, agents = _fleet(cfg, 3)
    backup_srv = None
    try:
        backup_addr = f"localhost:{chaos_soak.free_port()}"
        backup = BackupServer(cfg, addrs[:2], watchdog_timeout=3600.0)
        backup_srv = backup.start(backup_addr)
        primary = PrimaryServer(cfg, addrs[:2], backup_address=backup_addr)
        primary.round()
        primary.admit_client(addrs[2])          # join
        primary.remove_client(addrs[0])         # leave -> seat 0 freed
        primary.round()                          # replicates the new roster
        backup._promote()
        try:
            acting = backup.acting
            assert acting is not None
            assert acting.registry.clients == [addrs[1], addrs[2]]
            assert acting.registry.seat_of(addrs[2]) == 2
            assert acting.registry.capacity() == 3
            assert acting.registry.version >= 2
            # The acting primary can drive the inherited fleet.
            deadline = time.monotonic() + 30
            while not acting.history and time.monotonic() < deadline:
                time.sleep(0.1)
            assert acting.history and acting.history[-1]["participants"] == 2
        finally:
            backup._stop_acting(wait=30.0)
    finally:
        if backup_srv is not None:
            backup.watchdog.stop()
            backup_srv.stop(0)
        for s in servers:
            s.stop(0)


def test_statusz_membership_and_mem_blocks():
    """/statusz carries the membership block (version/size/capacity/roster)
    and the leak gauges; the prometheus registry exports
    fedtpu_process_rss_bytes and fedtpu_buffer_bytes after a round."""
    from fedtpu.obs import parse_prometheus_text, prometheus_text
    from fedtpu.transport.federation import PrimaryServer

    cfg = _cfg(2, rounds=4, delta_layout="flat")  # flat -> stream -> buffer
    addrs, servers, agents = _fleet(cfg, 2)
    try:
        primary = PrimaryServer(cfg, addrs)
        primary.round()
        snap = primary.status_snapshot()
        assert snap["membership"]["size"] == 2
        assert snap["membership"]["capacity"] == 2
        assert snap["membership"]["version"] == 0
        assert snap["mem"]["rss_bytes"] > 0
        assert snap["mem"]["buffer_bytes"] > 0  # streaming collect ran
        parsed = parse_prometheus_text(
            prometheus_text(primary.telemetry.registry)
        )
        assert sum(parsed["fedtpu_process_rss_bytes"].values()) > 0
        assert sum(parsed["fedtpu_buffer_bytes"].values()) > 0
    finally:
        for s in servers:
            s.stop(0)


# ----------------------------------------------------- upgrade/churn drills
def test_rolling_upgrade_zero_loss_bit_identical():
    """Tier-1 rolling-upgrade acceptance at reduced scale: the scripted
    primary -> backup -> primary handover loses zero rounds (lineage
    0..N-1 exactly), retrains none (client round counts match the
    control), and leaves the global model bit-identical to an unupgraded
    control run — with a mid-run Join surviving both handovers."""
    result = rolling_upgrade.run_upgrade_drill(
        rounds=6, upgrade_round=2, clients=2, join_round=0,
        acting_window=1, watchdog_s=1.0, verbose=False,
    )
    assert result["ok"]
    assert result["lineage"]["exact_cover"]
    assert result["bit_identical"]
    assert result["generations"]["acting"] >= 1


@pytest.mark.slow
def test_churn_soak_1k_rounds():
    """The full long-haul gate: 1000 rounds of continuous seeded churn +
    one mid-soak rolling upgrade (see tools/chaos_soak.py --churn)."""
    result = chaos_soak.run_churn_soak(rounds=1000, verbose=True)
    assert result["ok"]
    assert result["lineage"]["exact_cover"]
    assert result["bit_identical_vs_control"]
    assert result["memory"]["growth_pct"] < 8.0
