"""Hierarchical multi-tier aggregation (PR 14): the AggregatorServer role.

The exactness spine: 2-tier parity pins for every flat codec (dense /
int8 / top-k) with DYADIC-RATIONAL inputs, where the partial-reduce
associativity contract makes the tiered mean BYTE-FOR-BYTE identical to
the one-tier :func:`fedtpu.core.round.flat_weighted_mean` — plus the
fault face (parent-epoch fencing, per-tier quorum, the root masking a
failed aggregator's row) and the 3-role merged trace
(root -> aggregator -> client under one trace id,
``tools/trace_merge.py --check``).

Dyadic inputs are the point, not a convenience: all values are small
integers times powers of two, so every f32 add in either grouping is
EXACT and the single division at the root sees identical operands. Real
training deltas differ between the groupings by ~1 ulp (the adds round);
the pins hold the associativity contract, not a fluke of one input.
"""

import json
import os
import socket
import sys

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

import jax
import jax.numpy as jnp

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
    validate_tier_config,
)
from fedtpu.core.round import flat_weighted_mean
from fedtpu.ops import flat as flat_ops
from fedtpu.transport import proto, sparse, wire
from fedtpu.transport.aggregator import AggregatorServer, serve_aggregator
from fedtpu.transport.service import TrainerStub, create_channel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import trace_merge  # noqa: E402


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# A tiny two-leaf surface: total = 40 real coordinates, padded to 128.
TEMPLATE = {
    "params": {
        "bias": np.zeros((8,), np.float32),
        "dense": np.zeros((4, 8), np.float32),
    },
    "batch_stats": {},
}


def dyadic_deltas(rng, num_clients):
    """Client delta pytrees whose values are multiples of 1/4 with
    max|leaf| pinned to 127/4 — so the int8 codec's per-leaf scale is
    exactly 1/4 (a power of two) and quant/dequant round-trips exactly."""
    out = []
    for _ in range(num_clients):
        tree = {"params": {}, "batch_stats": {}}
        for name, leaf in TEMPLATE["params"].items():
            vals = rng.integers(-126, 127, size=leaf.shape).astype(
                np.float32
            ) * np.float32(0.25)
            vals.flat[0] = np.float32(31.75)  # 127 * 2^-2: pins the scale
            tree["params"][name] = vals
        out.append(tree)
    return out


def rows_from_payloads(layout, payloads, template=None, base=None):
    """Decode encoded client replies into a fresh [N, P] flat buffer via
    the aggregator's exact streaming paths; returns (rows, weights)."""
    rows = np.zeros((len(payloads), layout.padded), np.float32)
    weights = np.zeros((len(payloads),), np.float32)
    for i, data in enumerate(payloads):
        if sparse.is_sparse_payload(data):
            extra = sparse.decode_into_row(data, layout.sizes, rows[i])
        else:
            extra = wire.decode_into_row(data, template, base, rows[i])
        weights[i] = float(extra["num_examples"])
    return rows, weights


def tiered_mean(layout, rows, weights, groups):
    """The full 2-tier pipeline on already-decoded rows: per-group
    partial reduce -> FSP1 partial_flat record -> root decode into the
    [aggregators, P] surface -> single combine."""
    root_rows = np.zeros((len(groups), layout.padded), np.float32)
    weight_sums = np.zeros((len(groups),), np.float32)
    for g, idx in enumerate(groups):
        sum_row, wsum = flat_ops.partial_reduce_rows(
            jnp.asarray(rows[list(idx)]), jnp.asarray(weights[list(idx)])
        )
        record = sparse.encode_partial_flat(
            np.asarray(sum_row)[: layout.total], layout.sizes,
            extra={"weight_sum": np.float32(float(wsum)),
                   "clients": np.int64(len(idx))},
        )
        extra = sparse.decode_into_row(record, layout.sizes, root_rows[g])
        weight_sums[g] = float(extra["weight_sum"])
    return np.asarray(flat_ops.combine_partial_rows(
        jnp.asarray(root_rows), jnp.asarray(weight_sums)
    ))


def encode_clients(codec, deltas, weights, base=None):
    payloads = []
    for delta, w in zip(deltas, weights):
        extra = {"num_examples": np.float32(w)}
        if codec == "topk":
            payloads.append(
                sparse.encode_topk_flat(delta, 1.0, extra=extra)[0]
            )
        elif codec == "int8":
            payloads.append(sparse.encode_int8_flat(delta, extra=extra)[0])
        elif codec == "rotq":
            payloads.append(
                sparse.encode_rotq_flat(
                    delta, bits=8, extra=extra, collect_residual=False,
                    seed=5,
                )[0]
            )
        elif codec == "randk":
            # fraction 0.5 on the 40-coordinate surface: k=20, so the
            # no-EF unbiasedness rescale total/k == 2.0 is a power of two
            # and the dyadic values stay exact through the codec.
            payloads.append(
                sparse.encode_randk_flat(
                    delta, 0.5, extra=extra, collect_residual=False, seed=5
                )[0]
            )
        else:  # dense: full weights = base + delta, wire-framed
            tree = {
                "params": {
                    k: base["params"][k] + delta["params"][k]
                    for k in base["params"]
                },
                "batch_stats": {},
                "num_examples": np.float32(w),
            }
            payloads.append(wire.encode(tree))
    return payloads


# ------------------------------------------------ exactness / parity pins
@pytest.mark.parametrize("codec", ["dense", "int8", "topk", "randk"])
def test_two_tier_parity_bitwise(codec):
    """The acceptance pin: 6 clients through codec encode -> stream decode
    -> 2 leaf partial reduces -> partial_flat wire -> root combine equals
    the one-tier flat weighted mean BYTE FOR BYTE."""
    rng = np.random.default_rng(7)
    deltas = dyadic_deltas(rng, 6)
    weights = [1.0, 2.0, 4.0, 8.0, 1.0, 2.0]  # powers of two: exact w*x
    layout = flat_ops.make_layout(TEMPLATE)
    # Dyadic base (1.0 everywhere): base + delta and the decode-side
    # subtraction are both exact in f32.
    base = {
        "params": {
            k: np.ones_like(v) for k, v in TEMPLATE["params"].items()
        },
        "batch_stats": {},
    }
    payload_template = dict(TEMPLATE, num_examples=np.zeros((), np.float32))
    payloads = encode_clients(codec, deltas, weights, base=base)
    rows, got_w = rows_from_payloads(
        layout, payloads, template=payload_template, base=base
    )
    assert got_w.tolist() == weights

    flat = np.asarray(
        flat_weighted_mean(jnp.asarray(rows), jnp.asarray(got_w))
    )
    two_tier = tiered_mean(layout, rows, got_w, [(0, 1, 2), (3, 4, 5)])
    assert two_tier.tobytes() == flat.tobytes()
    # The mean is non-trivial (decode really reconstructed the values).
    assert np.abs(flat[: layout.total]).max() > 0


def test_partial_reduce_grouping_invariance():
    """Associativity directly: ANY grouping of exact-dyadic rows into
    tiers combines to the identical bytes — including the degenerate
    1-aggregator grouping, which IS flat_weighted_mean's program."""
    rng = np.random.default_rng(3)
    rows = (rng.integers(-512, 513, size=(8, 256)).astype(np.float32)
            * np.float32(0.125))
    weights = np.asarray([1, 2, 4, 2, 1, 8, 4, 2], np.float32)
    flat = np.asarray(
        flat_weighted_mean(jnp.asarray(rows), jnp.asarray(weights))
    ).tobytes()
    for groups in [
        [(0, 1, 2, 3, 4, 5, 6, 7)],
        [(0, 1, 2, 3), (4, 5, 6, 7)],
        [(0,), (1, 2), (3, 4, 5), (6, 7)],
    ]:
        root_rows = np.zeros((len(groups), 256), np.float32)
        wsums = np.zeros((len(groups),), np.float32)
        for g, idx in enumerate(groups):
            s, w = flat_ops.partial_reduce_rows(
                jnp.asarray(rows[list(idx)]),
                jnp.asarray(weights[list(idx)]),
            )
            root_rows[g] = np.asarray(s)
            wsums[g] = float(w)
        combined = np.asarray(flat_ops.combine_partial_rows(
            jnp.asarray(root_rows), jnp.asarray(wsums)
        )).tobytes()
        assert combined == flat, f"grouping {groups} diverged"


def test_partial_flat_record_roundtrip_and_validation():
    layout = flat_ops.make_layout(TEMPLATE)
    row = np.arange(layout.total, dtype=np.float32)
    rec = sparse.encode_partial_flat(
        row, layout.sizes, extra={"weight_sum": np.float32(5.0)}
    )
    assert sparse.is_sparse_payload(rec)
    out = np.zeros((layout.padded,), np.float32)
    extra = sparse.decode_into_row(rec, layout.sizes, out)
    assert float(extra["weight_sum"]) == 5.0
    np.testing.assert_array_equal(out[: layout.total], row)
    assert not out[layout.total:].any()  # pad stays clean
    with pytest.raises(ValueError):
        sparse.encode_partial_flat(row[:-1], layout.sizes)
    # A record for a DIFFERENT layout must be rejected, not scattered.
    other = sparse.encode_partial_flat(
        np.zeros((8,), np.float32), (8,), extra={}
    )
    with pytest.raises(wire.WireError):
        sparse.decode_into_row(other, layout.sizes, out)


def test_partial_row_sharding_divides_rows():
    from fedtpu.parallel.mesh import partial_row_sharding

    sharding = partial_row_sharding(4)
    # On any device count, the mesh size divides the row count (falls back
    # toward 1 device rather than failing on awkward aggregator counts).
    assert 4 % sharding.mesh.devices.size == 0
    arr = jax.device_put(np.zeros((4, 256), np.float32), sharding)
    assert arr.sharding.is_equivalent_to(sharding, ndim=2)


def test_validate_tier_config_rejects_incompatible_features():
    ok = FedConfig(num_clients=2, tier_fanout=2, delta_layout="flat")
    validate_tier_config(ok, "test")
    import dataclasses

    for bad in [
        dataclasses.replace(ok, tier_fanout=-1),
        dataclasses.replace(ok, aggregator="trimmed_mean"),
        dataclasses.replace(ok, dp_clip_norm=1.0),
        dataclasses.replace(ok, delta_layout="per_leaf"),
    ]:
        with pytest.raises(ValueError):
            validate_tier_config(bad, "test")


# --------------------------------------------------- fault face (real gRPC)
def sim_cfg(**fed_kw) -> RoundConfig:
    fed = FedConfig(num_clients=2, delta_layout="flat", **fed_kw)
    return RoundConfig(fed=fed)


@pytest.fixture()
def sim_aggregator():
    """One aggregator over real localhost gRPC whose cohort is a mutable
    payload list (the CohortSource seam)."""
    holder = {"payloads": []}
    server, agg = serve_aggregator(
        f"localhost:{free_port()}",
        sim_cfg(),
        cohort_source=lambda rnd, base, world: list(holder["payloads"]),
        template=TEMPLATE,
    )
    stub = TrainerStub(create_channel(agg.identity))
    yield holder, agg, stub
    server.stop(0)


def _fill(holder, n=3):
    rng = np.random.default_rng(11)
    holder["payloads"] = encode_clients(
        "topk", dyadic_deltas(rng, n), [8.0] * n
    )


def test_aggregator_partial_over_grpc(sim_aggregator):
    holder, agg, stub = sim_aggregator
    _fill(holder, n=3)
    reply = stub.SubmitPartial(
        proto.SubmitPartialRequest(rank_base=0, world=3, round=0, epoch=1),
        timeout=30,
    )
    assert reply.clients == 3
    layout = agg._flat_layout
    out = np.zeros((layout.padded,), np.float32)
    extra = sparse.decode_into_row(reply.record, layout.sizes, out)
    assert float(extra["weight_sum"]) == 24.0  # 3 clients x 8 examples
    assert int(extra["clients"]) == 3
    assert agg.status_snapshot()["last_partial"]["clients"] == 3
    assert agg.status_snapshot()["mem"]["tier"] == "leaf"


def test_two_tier_rotq_roundtrip_close():
    """rotq through the 2-tier pipeline: the 8-bit sketch's decoded rows
    are NOT dyadic (arbitrary lo/scale grid), so the pin is allclose
    rather than bitwise — grouping still changes nothing beyond f32
    summation order, and the decode really reconstructs the deltas."""
    rng = np.random.default_rng(7)
    deltas = dyadic_deltas(rng, 6)
    weights = [1.0, 2.0, 4.0, 8.0, 1.0, 2.0]
    layout = flat_ops.make_layout(TEMPLATE)
    payloads = encode_clients("rotq", deltas, weights)
    rows, got_w = rows_from_payloads(layout, payloads)
    assert got_w.tolist() == weights
    flat = np.asarray(
        flat_weighted_mean(jnp.asarray(rows), jnp.asarray(got_w))
    )
    two_tier = tiered_mean(layout, rows, got_w, [(0, 1, 2), (3, 4, 5)])
    np.testing.assert_allclose(two_tier, flat, rtol=1e-6, atol=1e-6)
    # 8-bit fidelity: each decoded row tracks its input delta closely.
    for i, d in enumerate(deltas):
        ref = np.concatenate(
            [np.ravel(l) for l in jax.tree_util.tree_leaves(d)]
        )
        got = rows[i, : layout.total]
        assert np.linalg.norm(got - ref) < 0.05 * np.linalg.norm(ref)


@pytest.mark.parametrize("codec", ["rotq", "randk"])
def test_aggregator_partial_over_grpc_sketch_codecs(sim_aggregator, codec):
    """Leaf aggregator ingests rotq/randk client records over live gRPC and
    its partial_flat reply reproduces the weighted sum of the decoded
    rows — the 2-tier compatibility pin for the new record kinds."""
    holder, agg, stub = sim_aggregator
    rng = np.random.default_rng(13)
    deltas = dyadic_deltas(rng, 3)
    holder["payloads"] = encode_clients(codec, deltas, [8.0] * 3)
    reply = stub.SubmitPartial(
        proto.SubmitPartialRequest(rank_base=0, world=3, round=0, epoch=1),
        timeout=30,
    )
    assert reply.clients == 3
    layout = agg._flat_layout
    out = np.zeros((layout.padded,), np.float32)
    extra = sparse.decode_into_row(reply.record, layout.sizes, out)
    assert float(extra["weight_sum"]) == 24.0
    rows, w = rows_from_payloads(layout, holder["payloads"])
    expect = (rows * w[:, None]).sum(axis=0)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)
    assert np.abs(out[: layout.total]).max() > 0


def test_aggregator_fences_stale_coordinator(sim_aggregator):
    holder, agg, stub = sim_aggregator
    _fill(holder)
    stub.SubmitPartial(
        proto.SubmitPartialRequest(rank_base=0, world=3, round=0, epoch=2),
        timeout=30,
    )
    with pytest.raises(grpc.RpcError) as err:
        stub.SubmitPartial(
            proto.SubmitPartialRequest(
                rank_base=0, world=3, round=1, epoch=1
            ),
            timeout=30,
        )
    assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "STALE_COORDINATOR" in err.value.details()
    assert agg._max_epoch == 2


def test_aggregator_aborts_sub_quorum_cohort(sim_aggregator):
    holder, agg, stub = sim_aggregator
    holder["payloads"] = []  # the whole cohort is gone this round
    with pytest.raises(grpc.RpcError) as err:
        stub.SubmitPartial(
            proto.SubmitPartialRequest(
                rank_base=0, world=3, round=0, epoch=1
            ),
            timeout=30,
        )
    assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    assert "SUB_QUORUM" in err.value.details()


# ------------------------------------------- root composition (real model)
def real_cfg(tier_fanout, num_clients=2, telemetry="off") -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(
            num_clients=num_clients, num_rounds=2,
            delta_layout="flat", tier_fanout=tier_fanout,
            telemetry=telemetry,
        ),
        steps_per_round=2,
    )


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_root_masks_failed_aggregator_row():
    """One leaf answers with a healthy partial; the other's whole cohort
    is dead, so its SubmitPartial aborts typed SUB_QUORUM. The root must
    commit the round from the surviving tier with the dead tier's row
    masked — exactly a failed client, one level up."""
    from fedtpu.transport.federation import PrimaryServer

    cfg = real_cfg(tier_fanout=3)
    holders = [{"payloads": []}, {"payloads": []}]
    servers, aggs, addrs = [], [], []
    try:
        for holder in holders:
            addr = f"localhost:{free_port()}"
            server, agg = serve_aggregator(
                addr, cfg,
                cohort_source=(
                    lambda rnd, base, world, h=holder: list(h["payloads"])
                ),
            )
            servers.append(server)
            aggs.append(agg)
            addrs.append(addr)
        layout = aggs[0]._flat_layout

        def leaf_payloads(n, seed):
            rng = np.random.default_rng(seed)
            out = []
            for i in range(n):
                flat = np.zeros((layout.total,), np.float32)
                delta = flat_ops.unpack(
                    layout, jnp.asarray(
                        np.pad(flat, (0, layout.pad))
                    )
                )
                out.append(sparse.encode_topk_flat(
                    delta, 1.0,
                    extra={"num_examples": np.float32(8.0)},
                )[0])
            return out

        holders[0]["payloads"] = leaf_payloads(3, seed=1)
        # holders[1] stays empty -> SUB_QUORUM abort on that leaf.
        primary = PrimaryServer(cfg, addrs)
        rec = primary.round()
        assert not rec.get("aborted")
        assert rec["tier_fanout"] == 3
        assert rec["world"] == 6  # 2 aggregator seats x fanout
        assert rec["participants"] == 1  # the SUB_QUORUM tier dropped out
        assert rec["aggregated"] == 1  # ...and its row stayed masked
        assert rec["clients_aggregated"] == 3  # the live cohort only
        assert primary.status_snapshot()["mem"]["tier"] == "root"
    finally:
        for s in servers:
            s.stop(0)


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_three_role_trace_merges_under_root_round(tmp_path):
    """Root -> aggregator -> client over real gRPC with telemetry=trace:
    the merged doc carries ONE trace id and every client_train span roots
    in the ROOT's round span across both process hops."""
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = real_cfg(tier_fanout=2, telemetry="trace")
    stops = []
    try:
        client_addrs, agents = [], []
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            stops.append(server)
            client_addrs.append(addr)
            agents.append(agent)
        agg_addr = f"localhost:{free_port()}"
        agg_server, agg = serve_aggregator(
            agg_addr, cfg, clients=client_addrs
        )
        stops.append(agg_server)
        primary = PrimaryServer(cfg, [agg_addr])
        for _ in range(2):
            rec = primary.round()
            assert not rec.get("aborted")
            assert rec["clients_aggregated"] == 2

        coord_id = primary.telemetry.tracer.trace_id
        assert agg.telemetry.tracer.trace_id == coord_id
        paths = [str(tmp_path / "primary.json")]
        primary.telemetry.export_trace(paths[0])
        paths.append(str(tmp_path / "aggregator.json"))
        agg.telemetry.export_trace(paths[1])
        for i, agent in enumerate(agents):
            tel = agent.trainer.telemetry
            assert tel.tracer.trace_id == coord_id
            paths.append(str(tmp_path / f"client{i}.json"))
            tel.export_trace(paths[-1])
    finally:
        for s in stops:
            s.stop(0)

    merged = str(tmp_path / "merged.json")
    assert trace_merge.main(paths + ["-o", merged, "--check"]) == 0
    with open(merged) as fh:
        doc = json.load(fh)
    assert doc["metadata"]["trace_ids"] == [coord_id]
    index = trace_merge.span_index(doc)
    names = {e.get("name") for e in doc["traceEvents"]}
    # The tier's own phases made it into the one timeline.
    assert {"submit_partial", "collect", "partial_reduce"} <= names
    trains = [
        e for e in doc["traceEvents"] if e.get("name") == "client_train"
    ]
    assert len(trains) >= 4  # 2 clients x 2 rounds
    for e in trains:
        root = trace_merge.root_of(index, e)
        assert root is not None and root["name"] == "round"
        assert root["args"]["span_id"].startswith("primary/")
        # Immediate remote parent: the AGGREGATOR's per-client rpc span.
        parent = index[e["args"]["parent_id"]]
        assert parent["name"] == "client_rpc"
        assert parent["args"]["span_id"].startswith("aggregator")
