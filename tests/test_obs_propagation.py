"""Federation-wide observability (PR 4): trace propagation over real gRPC,
the live introspection endpoints, the crash flight recorder, and the
crash-proofed exit exporters.

The acceptance spine: a 2-client federation over real gRPC produces
per-process traces whose client ``client_train`` spans carry the
coordinator's trace id and — after ``tools/trace_merge.py`` — parent
(via the propagated ``fedtpu-trace-bin`` context) under the coordinator's
``round`` span, while ``/statusz`` scraped DURING the run reports the live
round number and client liveness.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from fedtpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    ObsServer,
    StatusBoard,
    parse_prometheus_text,
)
from fedtpu.obs import propagate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import span_check  # noqa: E402
import statusz  # noqa: E402
import trace_merge  # noqa: E402


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, resp.read().decode()


# ----------------------------------------------------------- context codec
def test_trace_context_roundtrips_and_tolerates_garbage():
    ctx = propagate.TraceContext("a3f1", span_id=7, role="primary", round=12)
    blob = propagate.encode_context(ctx)
    assert propagate.decode_context(blob) == ctx
    assert propagate.from_metadata(
        [("other-key", b"x"), (propagate.METADATA_KEY, blob)]
    ) == ctx
    # Malformed payloads must never fail an RPC.
    assert propagate.decode_context(b"not json") is None
    assert propagate.decode_context(b'{"span_id": 1}') is None  # no trace_id
    assert propagate.from_metadata(None) is None
    assert propagate.from_metadata([]) is None
    # span_args: collision-proof keys, empty without a context.
    assert propagate.span_args(None) == {}
    args = propagate.span_args(ctx)
    assert args["trace_id"] == "a3f1" and args["remote_parent"] == 7
    assert "round" not in args  # receiver's own round= arg must win


# --------------------------------------- the acceptance spine (real gRPC)
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_propagation_endpoints_and_merge_over_real_grpc(tmp_path):
    """One 2-client federation run covering the tentpole end to end:
    propagated contexts on the wire, live /statusz + /metrics + /healthz
    scraped DURING rounds, per-process trace export, and the merged
    Perfetto timeline with cross-process parent chains."""
    pytest.importorskip("grpc")
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=2, num_rounds=3, telemetry="trace"),
        steps_per_round=2,
    )
    servers, agents, addrs = [], [], []
    obs = None
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs)
        obs = ObsServer(
            port=0,
            registry=primary.telemetry.registry,
            status_fn=primary.status_snapshot,
            flight=primary.flight,
        ).start()

        # Drive rounds on a background thread; scrape the live plane from
        # here while they run.
        runner = threading.Thread(target=lambda: primary.run(num_rounds=3))
        runner.start()
        statuses, prom_samples = [], []
        while runner.is_alive():
            code, body = _get(obs.url + "/healthz")
            assert code == 200 and body.strip() == "ok"
            code, body = _get(obs.url + "/statusz")
            assert code == 200
            statuses.append(json.loads(body))
            code, body = _get(obs.url + "/metrics")
            assert code == 200
            # Scrape-during-round consistency: every mid-run dump parses.
            prom_samples.append(parse_prometheus_text(body))
            time.sleep(0.05)
        runner.join()
        statuses.append(json.loads(_get(obs.url + "/statusz")[1]))

        # Live round number + client liveness showed up mid-run.
        assert any("round" in s and "phase" in s for s in statuses)
        final = statuses[-1]
        assert final["round"] >= 2
        assert final["clients"]["alive"] == addrs
        assert final["clients"]["dead"] == []
        assert final["last_round"]["participants"] == 2
        assert final["trace_id"] == primary.telemetry.tracer.trace_id
        # Counters in successive scrapes are monotone (consistent
        # snapshots, no torn reads).
        completed = [
            p["fedtpu_rounds_completed_total"][""]
            for p in prom_samples
            if "fedtpu_rounds_completed_total" in p
        ]
        assert completed == sorted(completed)
        assert json.loads(_get(obs.url + "/flightz")[1])  # ring non-empty

        # Per-process traces: clients adopted the coordinator's trace id
        # and stamped it (plus the remote parent) on their spans.
        coord_id = primary.telemetry.tracer.trace_id
        paths = []
        path = str(tmp_path / "primary.json")
        primary.telemetry.export_trace(path)
        paths.append(path)
        for i, agent in enumerate(agents):
            tel = agent.trainer.telemetry
            assert tel.tracer.trace_id == coord_id
            trains = [
                e for e in tel.tracer.events()
                if e["name"] == "client_train"
            ]
            assert trains
            for e in trains:
                assert e["args"]["trace_id"] == coord_id
                assert e["args"]["remote_role"] == "primary"
                assert e["args"]["remote_parent"] > 0
            path = str(tmp_path / f"client{i}.json")
            tel.export_trace(path)
            paths.append(path)
    finally:
        if obs is not None:
            obs.stop()
        for s in servers:
            s.stop(0)

    # Merge via the CLI surface (--check is the CI assertion) and then
    # re-verify the nesting by hand on the merged doc.
    merged_path = str(tmp_path / "merged.json")
    assert trace_merge.main(paths + ["-o", merged_path, "--check"]) == 0
    with open(merged_path) as fh:
        doc = json.load(fh)
    assert doc["metadata"]["trace_ids"] == [coord_id]
    assert doc["metadata"]["merged_roles"][0] == "primary"
    index = trace_merge.span_index(doc)
    trains = [
        e for e in doc["traceEvents"] if e.get("name") == "client_train"
    ]
    assert len(trains) >= 4  # 2 clients x >=2 traced rounds
    for e in trains:
        assert e["args"]["parent_is_remote"] is True
        root = trace_merge.root_of(index, e)
        assert root is not None and root["name"] == "round"
        # ...and the root lives in the coordinator's lane.
        assert root["args"]["span_id"].startswith("primary/")
        # The immediate remote parent is the collect worker's client_rpc.
        assert index[e["args"]["parent_id"]]["name"] == "client_rpc"


# ------------------------------------------------------------- endpoints
def test_obs_server_routes_and_404s():
    reg = MetricsRegistry()
    reg.counter("x_total").inc(2)
    board = StatusBoard(role="t")
    board.update(round=5, phase="collect")
    obs = ObsServer(port=0, registry=reg, status_fn=board.snapshot).start()
    try:
        assert _get(obs.url + "/healthz")[1] == "ok\n"
        parsed = parse_prometheus_text(_get(obs.url + "/metrics")[1])
        assert parsed["x_total"][""] == 2
        status = json.loads(_get(obs.url + "/statusz")[1])
        assert status["round"] == 5 and status["phase"] == "collect"
        assert status["updated_at"] > 0
        for path in ("/nope", "/flightz"):  # no flight attached either
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(obs.url + path)
            assert err.value.code == 404
    finally:
        obs.stop()


def test_statusz_tool_renders_live_and_offline():
    board = StatusBoard(role="primary")
    board.update(
        round=7, phase="aggregate",
        clients={"alive": ["a", "b"], "dead": ["c"]},
        heartbeat_misses=4.0,
        last_round={
            "participants": 2, "stragglers": 1,
            "t_collect_s": 1.25, "t_aggregate_s": 0.5,
        },
    )
    line = statusz.render_line(board.snapshot())
    for frag in ("role=primary", "round=7", "phase=aggregate", "alive=2/3",
                 "dead=c", "hb_miss=4", "part=2", "strag=1",
                 "collect=1.250s"):
        assert frag in line, line
    # Promoted backup: the nested acting status is what gets rendered.
    outer = {"role": "acting_primary", "acting": board.snapshot()}
    assert statusz.render_line(outer).startswith(
        "[acting_primary] role=primary"
    )
    obs = ObsServer(port=0, status_fn=board.snapshot).start()
    try:
        assert statusz.fetch(obs.url)["round"] == 7
        assert statusz.main([obs.url]) == 0
    finally:
        obs.stop()
    assert statusz.main([obs.url]) == 1  # server gone -> nonzero, no hang


# -------------------------------------------------------- flight recorder
def test_flight_recorder_ring_and_dump(tmp_path):
    fr = FlightRecorder(capacity=3, role="t", artifacts_dir=str(tmp_path))
    for i in range(5):
        fr.record("tick", i=i)
    snap = fr.snapshot()
    assert [e["i"] for e in snap] == [2, 3, 4]  # bounded, newest kept
    path = fr.dump(reason="manual")
    assert path == fr.dump_path() and os.path.exists(path)
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["reason"] == "manual" and doc["role"] == "t"
    assert doc["num_events"] == 3
    assert [e["kind"] for e in doc["events"]] == ["tick"] * 3


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_flight_recorder_dumps_on_injected_exception(tmp_path):
    fr = FlightRecorder(role="crash", artifacts_dir=str(tmp_path))
    fr.install(signum=None)
    try:
        fr.record("work", step=1)
        try:
            raise ValueError("injected boom")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        path = fr.dump_path()
        assert os.path.exists(path)
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["reason"] == "unhandled:ValueError"
        kinds = [e["kind"] for e in doc["events"]]
        assert kinds == ["work", "exception"]
        assert "injected boom" in doc["events"][-1]["message"]
        assert "traceback" in doc["events"][-1]

        # Worker-thread crashes dump too (threading.excepthook chain).
        os.remove(path)

        def boom():
            raise RuntimeError("thread boom")

        t = threading.Thread(target=boom, name="worker")
        t.start()
        t.join()
        with open(path) as fh:
            doc = json.load(fh)
        assert doc["reason"] == "thread-unhandled:RuntimeError"
        assert doc["events"][-1]["thread"] == "worker"
    finally:
        fr.uninstall()


def test_flight_recorder_dumps_on_sigusr1(tmp_path):
    fr = FlightRecorder(role="sig", artifacts_dir=str(tmp_path))
    fr.install()
    try:
        fr.record("before_signal")
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.monotonic() + 5.0
        while (not os.path.exists(fr.dump_path())
               and time.monotonic() < deadline):
            time.sleep(0.01)
        with open(fr.dump_path()) as fh:
            doc = json.load(fh)
        assert doc["reason"] == "signal:SIGUSR1"
        assert doc["events"][0]["kind"] == "before_signal"
    finally:
        fr.uninstall()


def test_failover_transitions_dump_the_flight_recorder(tmp_path):
    """A forced promote (watchdog expiry) and the demote both write the
    black box — the moments PR 3's exit-time exporters always lost."""
    from fedtpu.ft import FailoverStateMachine

    fr = FlightRecorder(role="backup", artifacts_dir=str(tmp_path))
    reg = MetricsRegistry()
    clock = [0.0]
    machine = FailoverStateMachine(
        timeout=10.0, clock=lambda: clock[0], metrics=reg, flight=fr,
    )
    machine.on_ping(False)  # arm the watchdog
    clock[0] = 11.0
    assert machine.check_watchdog() is True  # forced promote
    assert os.path.exists(fr.dump_path())
    with open(fr.dump_path()) as fh:
        doc = json.load(fh)
    assert doc["reason"] == "failover:acting_primary"
    ft_events = [e for e in doc["events"] if e["kind"] == "failover"]
    assert ft_events[-1]["dst"] == "acting_primary"

    assert machine.on_ping(True) == 1  # primary back -> demote
    with open(fr.dump_path()) as fh:
        doc = json.load(fh)
    assert doc["reason"] == "failover:backup"
    ft_events = [e for e in doc["events"] if e["kind"] == "failover"]
    assert [e["dst"] for e in ft_events] == ["acting_primary", "backup"]


# ------------------------------------------------- FT control-plane RTTs
def test_ft_rpc_latency_histograms():
    from fedtpu.ft import ClientRegistry, HeartbeatMonitor
    from fedtpu.ft.failover import PrimaryPinger

    reg = MetricsRegistry()
    cr = ClientRegistry(["a", "b"], metrics=reg)
    cr.mark_failed("a")
    monitor = HeartbeatMonitor(
        cr, probe=lambda c: False, resync=lambda c: None, metrics=reg,
    )
    monitor.tick()
    monitor.tick()
    hb = reg.histogram("fedtpu_ft_rpc_seconds", labels={"rpc": "HeartBeat"})
    assert hb.count == 2  # both probes timed, not just counted as misses

    pinger = PrimaryPinger(lambda recovering: 0, metrics=reg)
    pinger.tick()
    ping = reg.histogram(
        "fedtpu_ft_rpc_seconds", labels={"rpc": "CheckIfPrimaryUp"}
    )
    assert ping.count == 1
    # Probes that raise RpcError map to None in the production probe()
    # wrapper; a None-returning send still times the attempt.
    PrimaryPinger(lambda recovering: None, metrics=reg).tick()
    assert ping.count == 2


# ---------------------------------------------------- span-name drift CI
def test_every_emitted_span_name_is_documented():
    emitted = span_check.emitted_span_names()
    assert len(emitted) >= 10  # the scanner actually sees the span calls
    assert "client_train" in emitted and "round" in emitted
    assert span_check.check() == []


def test_span_check_catches_drift(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text('tel.span("brand_new_span")\n')
    doc = tmp_path / "OBS.md"
    doc.write_text("documented: `round` only\n")
    problems = span_check.check(str(pkg), str(doc))
    assert len(problems) == 1 and "brand_new_span" in problems[0]


# ----------------------------------------- crash-proofed exit exporters
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_sigterm_mid_run_keeps_complete_records_and_prom_dump(tmp_path):
    """Kill the run CLI mid-flight: every already-logged round record must
    be complete v1 JSONL (per-record flush) and the SIGTERM flush must
    still write the --prom-out registry dump that previously only a clean
    exit produced."""
    from fedtpu.obs import SCHEMA_VERSION, read_round_records

    metrics_path = str(tmp_path / "m.jsonl")
    prom_path = str(tmp_path / "m.prom")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "fedtpu.cli.run",
            "--platform", "cpu",
            "--model", "mlp", "--dataset", "synthetic",
            "--num-clients", "2", "--rounds", "100000",
            "--steps-per-round", "1", "--batch-size", "8",
            "--eval-batch-size", "8", "--num-examples", "64",
            "--eval-every", "0",
            "--metrics", metrics_path, "--prom-out", prom_path,
        ],
        cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if (os.path.exists(metrics_path)
                    and len(read_round_records(metrics_path)) >= 3):
                break
            if proc.poll() is not None:
                pytest.fail(f"run CLI exited early: rc={proc.returncode}")
            time.sleep(0.2)
        else:
            pytest.fail("no round records appeared within 180s")
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    recs = read_round_records(metrics_path)
    assert len(recs) >= 3
    for rec in recs:  # complete v1 records, no torn tail
        assert rec["schema_version"] == SCHEMA_VERSION
        assert "loss" in rec and "t" in rec
    # With every line parseable, the raw line count must match too (a
    # truncated final line would have been silently skipped).
    with open(metrics_path) as fh:
        assert len([l for l in fh if l.strip()]) == len(recs)
    assert os.path.exists(prom_path), "SIGTERM lost the --prom-out dump"
    with open(prom_path) as fh:
        parsed = parse_prometheus_text(fh.read())
    assert parsed["fedtpu_rounds_completed_total"][""] >= 3
