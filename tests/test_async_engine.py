"""Engine-side FedBuff (fedtpu.core.async_engine).

The simulated twin of ``PrimaryServer.run_async`` (VERDICT r3 #7): buffered
staleness-weighted aggregation as one jitted program. Anchor property: with
``buffer_k == num_clients`` and homogeneous speeds, every client arrives
every tick with staleness 0 — the async program must reproduce the
synchronous FedAvg trajectory.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import AsyncFederation, Federation
from fedtpu.data import load


def tiny_cfg(num_clients=4, dataset="synthetic", **fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset=dataset,
            batch_size=8,
            eval_batch_size=64,
            num_examples=256,
            augment=False,
        ),
        fed=FedConfig(num_clients=num_clients, **fed_kw),
        steps_per_round=2,
    )


def _flat(tree):
    import jax

    return np.concatenate(
        [np.ravel(np.asarray(x)) for x in jax.tree.leaves(tree)]
    )


def test_full_buffer_matches_synchronous():
    """buffer_k == N: all clients arrive every tick from the same base ->
    the async trajectory IS the synchronous one."""
    cfg = tiny_cfg(num_clients=4)
    sync = Federation(cfg, seed=0)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=4, speed_sigma=0.0)
    for _ in range(3):
        sync.step()
        asyn.tick()
    np.testing.assert_allclose(
        _flat(sync.state.params), _flat(asyn.state.params),
        rtol=2e-5, atol=2e-6,
    )


def test_fused_ticks_equal_sequential():
    """run_on_device(T) (one lax.scan program) must equal T tick() calls
    with the same arrival draws."""
    cfg = tiny_cfg(num_clients=4)
    a = AsyncFederation(cfg, seed=1, buffer_k=2, speed_sigma=0.7)
    b = AsyncFederation(cfg, seed=1, buffer_k=2, speed_sigma=0.7)
    for _ in range(4):
        a.tick()
    b.run_on_device(4)
    assert int(a.state.version) == int(b.state.version) == 4
    np.testing.assert_allclose(
        _flat(a.state.params), _flat(b.state.params), rtol=2e-5, atol=2e-6
    )


def test_staleness_accounting():
    """A client that last pulled at version v and arrives at version v+s is
    discounted by (1+s)^-power, and the metric reports s."""
    cfg = tiny_cfg(num_clients=2)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
    # Control arrivals directly: client 0 arrives at ticks 0 and 1; client 1
    # first arrives at tick 2 with base_version still 0 -> staleness 2.
    schedule = [np.array([True, False]), np.array([True, False]),
                np.array([False, True])]
    asyn._arrive_mask = lambda: schedule.pop(0)
    m0 = asyn.tick()
    m1 = asyn.tick()
    m2 = asyn.tick()
    assert float(m0.staleness_mean) == 0.0
    # Client 0 re-pulled after tick 0, so its tick-1 arrival is fresh again.
    assert float(m1.staleness_mean) == 0.0
    # Client 1 still holds version 0 when it arrives at version 2.
    assert float(m2.staleness_mean) == 2.0
    assert int(asyn.state.version) == 3
    assert asyn.state.base_version.tolist() == [2, 3]


def test_async_learns_under_heterogeneous_speeds():
    """Slow clients accumulate staleness (speed_sigma > 0) and the global
    model still learns the synthetic task."""
    cfg = tiny_cfg(num_clients=8)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=2, speed_sigma=1.0)
    stale = []
    for _ in range(20):
        m = asyn.tick()
        stale.append(float(m.staleness_mean))
    test = load("synthetic", "test", num=256)
    _, acc = asyn.evaluate(*test)
    assert acc > 0.5, acc
    # Heterogeneity produced genuinely stale contributions.
    assert max(stale) >= 1.0, stale


def test_dead_client_never_arrives_and_rejoins():
    cfg = tiny_cfg(num_clients=4)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=2, speed_sigma=0.0)
    asyn.set_alive(3, False)
    for _ in range(5):
        asyn.tick()
    # The dead client never pulled a newer version.
    assert int(asyn.state.base_version[3]) == 0
    assert int(asyn.state.version) == 5
    asyn.set_alive(3, True)
    for _ in range(8):
        asyn.tick()
    assert int(asyn.state.base_version[3]) > 0  # rejoined and re-pulled


def test_async_rejects_unsound_compositions():
    with pytest.raises(ValueError, match="compression"):
        AsyncFederation(tiny_cfg(compression="topk", topk_fraction=0.1))
    with pytest.raises(ValueError, match="aggregator"):
        AsyncFederation(tiny_cfg(aggregator="median"))
    with pytest.raises(ValueError, match="DP|accounting"):
        AsyncFederation(
            dataclasses.replace(
                tiny_cfg(),
                fed=FedConfig(num_clients=4, dp_clip_norm=1.0,
                              weighted=False),
            )
        )
    with pytest.raises(ValueError, match="buffer_k"):
        AsyncFederation(tiny_cfg(), buffer_k=9)


def test_fedprox_anchor_parameter_pulls_toward_anchor():
    """The local update's explicit FedProx anchor must be the proximal
    center: with a strong (stable) mu, one epoch started at params != anchor moves
    TOWARD the anchor (an anchor wrongly tied to the scan's init would add
    ~zero proximal force)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fedtpu import models
    from fedtpu.core import make_local_update, optim

    cfg = dataclasses.replace(
        tiny_cfg(num_clients=1),
        # lr*mu must stay < 1 for the prox step to be stable
        fed=FedConfig(num_clients=1, algorithm="fedprox", fedprox_mu=5.0),
    )
    model = models.create("mlp", num_classes=10)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False
    )
    init_params = variables["params"]
    anchor = jax.tree.map(lambda x: x + 0.5, init_params)
    lu = jax.jit(make_local_update(model.apply, cfg))
    x = jnp.zeros((2, 8, 32, 32, 3), jnp.float32)
    y = jnp.zeros((2, 8), jnp.int32)
    out = lu(
        init_params, {}, optim.init(init_params), x, y,
        jnp.ones((2,), bool), jax.random.PRNGKey(1),
        jnp.zeros((), jnp.int32), anchor,
    )

    def dist(a, b):
        return float(sum(
            np.linalg.norm(np.asarray(x - y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        ))

    assert dist(out.params, anchor) < dist(init_params, anchor)


def test_fedprox_damps_async_client_drift():
    """In the async engine the prox term (anchored at the pulled global)
    reduces per-cycle client drift."""
    import jax

    def drift(mu):
        fed_kw = dict(algorithm="fedprox", fedprox_mu=mu) if mu else {}
        cfg = tiny_cfg(num_clients=3, **fed_kw)
        a = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
        # Client 2 NEVER arrives: it trains one pending epoch and idles.
        schedule = [np.array([True, False, False]),
                    np.array([False, True, False])] * 4
        a._arrive_mask = lambda: schedule.pop(0)
        for _ in range(8):
            a.tick()
        gap = jax.tree.map(
            lambda c, b: np.linalg.norm(np.asarray(c[2] - b[2])),
            a.state.client_params, a.state.base_params,
        )
        return float(sum(jax.tree.leaves(gap)))

    d_plain = drift(0.0)
    d_prox = drift(10.0)
    assert d_prox < d_plain, (d_prox, d_plain)


def test_one_epoch_per_pull_cycle():
    """FedBuff client loop: after training its single pending epoch, a
    client that never arrives IDLES (no compounding local trajectory) —
    matching run_async's gRPC clients, which train once per pull."""
    import jax

    cfg = tiny_cfg(num_clients=2)
    a = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
    a._arrive_mask = lambda: np.array([True, False])  # client 1 never arrives

    def c1_params():
        return _flat(jax.tree.map(lambda x: x[1], a.state.client_params))

    a.tick()
    after_first = c1_params()
    for _ in range(4):
        a.tick()
    np.testing.assert_array_equal(after_first, c1_params())
    assert bool(a.state.pending[1])
    assert not bool(a.state.pending[0])  # arrived + re-pulled, trains anew
