"""Engine-side FedBuff (fedtpu.core.async_engine).

The simulated twin of ``PrimaryServer.run_async`` (VERDICT r3 #7): buffered
staleness-weighted aggregation as one jitted program. Anchor property: with
``buffer_k == num_clients`` and homogeneous speeds, every client arrives
every tick with staleness 0 — the async program must reproduce the
synchronous FedAvg trajectory.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import AsyncFederation, Federation
from fedtpu.data import load


def tiny_cfg(num_clients=4, dataset="synthetic", **fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset=dataset,
            batch_size=8,
            eval_batch_size=64,
            num_examples=256,
            augment=False,
        ),
        fed=FedConfig(num_clients=num_clients, **fed_kw),
        steps_per_round=2,
    )


def _flat(tree):
    import jax

    return np.concatenate(
        [np.ravel(np.asarray(x)) for x in jax.tree.leaves(tree)]
    )


def test_full_buffer_matches_synchronous():
    """buffer_k == N: all clients arrive every tick from the same base ->
    the async trajectory IS the synchronous one."""
    cfg = tiny_cfg(num_clients=4)
    sync = Federation(cfg, seed=0)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=4, speed_sigma=0.0)
    for _ in range(3):
        sync.step()
        asyn.tick()
    np.testing.assert_allclose(
        _flat(sync.state.params), _flat(asyn.state.params),
        rtol=2e-5, atol=2e-6,
    )


def test_fused_ticks_equal_sequential():
    """run_on_device(T) (one lax.scan program) must equal T tick() calls
    with the same arrival draws."""
    cfg = tiny_cfg(num_clients=4)
    a = AsyncFederation(cfg, seed=1, buffer_k=2, speed_sigma=0.7)
    b = AsyncFederation(cfg, seed=1, buffer_k=2, speed_sigma=0.7)
    for _ in range(4):
        a.tick()
    b.run_on_device(4)
    assert int(a.state.version) == int(b.state.version) == 4
    np.testing.assert_allclose(
        _flat(a.state.params), _flat(b.state.params), rtol=2e-5, atol=2e-6
    )


def test_staleness_accounting():
    """A client that last pulled at version v and arrives at version v+s is
    discounted by (1+s)^-power, and the metric reports s."""
    cfg = tiny_cfg(num_clients=2)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
    # Control arrivals directly: client 0 arrives at ticks 0 and 1; client 1
    # first arrives at tick 2 with base_version still 0 -> staleness 2.
    schedule = [np.array([True, False]), np.array([True, False]),
                np.array([False, True])]
    asyn._arrive_mask = lambda: schedule.pop(0)
    m0 = asyn.tick()
    m1 = asyn.tick()
    m2 = asyn.tick()
    assert float(m0.staleness_mean) == 0.0
    # Client 0 re-pulled after tick 0, so its tick-1 arrival is fresh again.
    assert float(m1.staleness_mean) == 0.0
    # Client 1 still holds version 0 when it arrives at version 2.
    assert float(m2.staleness_mean) == 2.0
    assert int(asyn.state.version) == 3
    assert asyn.state.base_version.tolist() == [2, 3]


def test_async_learns_under_heterogeneous_speeds():
    """Slow clients accumulate staleness (speed_sigma > 0) and the global
    model still learns the synthetic task."""
    cfg = tiny_cfg(num_clients=8)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=2, speed_sigma=1.0)
    stale = []
    for _ in range(20):
        m = asyn.tick()
        stale.append(float(m.staleness_mean))
    test = load("synthetic", "test", num=256)
    _, acc = asyn.evaluate(*test)
    assert acc > 0.5, acc
    # Heterogeneity produced genuinely stale contributions.
    assert max(stale) >= 1.0, stale


def test_dead_client_never_arrives_and_rejoins():
    cfg = tiny_cfg(num_clients=4)
    asyn = AsyncFederation(cfg, seed=0, buffer_k=2, speed_sigma=0.0)
    asyn.set_alive(3, False)
    for _ in range(5):
        asyn.tick()
    # The dead client never pulled a newer version.
    assert int(asyn.state.base_version[3]) == 0
    assert int(asyn.state.version) == 5
    asyn.set_alive(3, True)
    for _ in range(8):
        asyn.tick()
    assert int(asyn.state.base_version[3]) > 0  # rejoined and re-pulled


def test_async_rejects_unsound_compositions():
    with pytest.raises(ValueError, match="compression"):
        AsyncFederation(tiny_cfg(compression="topk", topk_fraction=0.1))
    with pytest.raises(ValueError, match="aggregator"):
        AsyncFederation(tiny_cfg(aggregator="median"))
    with pytest.raises(ValueError, match="DP|accounting"):
        AsyncFederation(
            dataclasses.replace(
                tiny_cfg(),
                fed=FedConfig(num_clients=4, dp_clip_norm=1.0,
                              weighted=False),
            )
        )
    with pytest.raises(ValueError, match="buffer_k"):
        AsyncFederation(tiny_cfg(), buffer_k=9)


def test_fedprox_anchor_parameter_pulls_toward_anchor():
    """The local update's explicit FedProx anchor must be the proximal
    center: with a strong (stable) mu, one epoch started at params != anchor moves
    TOWARD the anchor (an anchor wrongly tied to the scan's init would add
    ~zero proximal force)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fedtpu import models
    from fedtpu.core import make_local_update, optim

    cfg = dataclasses.replace(
        tiny_cfg(num_clients=1),
        # lr*mu must stay < 1 for the prox step to be stable
        fed=FedConfig(num_clients=1, algorithm="fedprox", fedprox_mu=5.0),
    )
    model = models.create("mlp", num_classes=10)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False
    )
    init_params = variables["params"]
    anchor = jax.tree.map(lambda x: x + 0.5, init_params)
    lu = jax.jit(make_local_update(model.apply, cfg))
    x = jnp.zeros((2, 8, 32, 32, 3), jnp.float32)
    y = jnp.zeros((2, 8), jnp.int32)
    out = lu(
        init_params, {}, optim.init(init_params), x, y,
        jnp.ones((2,), bool), jax.random.PRNGKey(1),
        jnp.zeros((), jnp.int32), anchor,
    )

    def dist(a, b):
        return float(sum(
            np.linalg.norm(np.asarray(x - y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        ))

    assert dist(out.params, anchor) < dist(init_params, anchor)


def test_fedprox_damps_async_client_drift():
    """In the async engine the prox term (anchored at the pulled global)
    reduces per-cycle client drift."""
    import jax

    def drift(mu):
        fed_kw = dict(algorithm="fedprox", fedprox_mu=mu) if mu else {}
        cfg = tiny_cfg(num_clients=3, **fed_kw)
        a = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
        # Client 2 NEVER arrives: it trains one pending epoch and idles.
        schedule = [np.array([True, False, False]),
                    np.array([False, True, False])] * 4
        a._arrive_mask = lambda: schedule.pop(0)
        for _ in range(8):
            a.tick()
        gap = jax.tree.map(
            lambda c, b: np.linalg.norm(np.asarray(c[2] - b[2])),
            a.state.client_params, a.state.base_params,
        )
        return float(sum(jax.tree.leaves(gap)))

    d_plain = drift(0.0)
    d_prox = drift(10.0)
    assert d_prox < d_plain, (d_prox, d_plain)


def test_one_epoch_per_pull_cycle():
    """FedBuff client loop: after training its single pending epoch, a
    client that never arrives IDLES (no compounding local trajectory) —
    matching run_async's gRPC clients, which train once per pull."""
    import jax

    cfg = tiny_cfg(num_clients=2)
    a = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0)
    a._arrive_mask = lambda: np.array([True, False])  # client 1 never arrives

    def c1_params():
        return _flat(jax.tree.map(lambda x: x[1], a.state.client_params))

    a.tick()
    after_first = c1_params()
    for _ in range(4):
        a.tick()
    np.testing.assert_array_equal(after_first, c1_params())
    assert bool(a.state.pending[1])
    assert not bool(a.state.pending[0])  # arrived + re-pulled, trains anew


def test_mesh_tick_matches_single_program():
    """Async x mesh (VERDICT r4 weak #2 / next #6): the shard_map tick over
    an 8-device client mesh must reproduce the single-program trajectory —
    per-client diverged models shard like data rows, aggregation is a psum."""
    import jax

    from fedtpu.parallel import client_mesh

    cfg = tiny_cfg(num_clients=8)
    plain = AsyncFederation(cfg, seed=3, buffer_k=2, speed_sigma=0.8)
    mesh = client_mesh(8, cfg.mesh_axis)
    sharded = AsyncFederation(cfg, seed=3, buffer_k=2, speed_sigma=0.8,
                              mesh=mesh)
    for _ in range(3):
        plain.tick()
        sharded.tick()
    assert int(sharded.state.version) == 3
    np.testing.assert_allclose(
        _flat(plain.state.params), _flat(sharded.state.params),
        rtol=2e-6, atol=1e-7,
    )
    np.testing.assert_allclose(
        _flat(plain.state.client_params), _flat(sharded.state.client_params),
        rtol=2e-6, atol=1e-7,
    )
    np.testing.assert_array_equal(
        np.asarray(plain.state.base_version),
        np.asarray(sharded.state.base_version),
    )
    # And the fused multi-tick scan under the mesh agrees with ticking.
    fused = AsyncFederation(cfg, seed=3, buffer_k=2, speed_sigma=0.8,
                            mesh=mesh)
    fused.run_on_device(3)
    np.testing.assert_allclose(
        _flat(sharded.state.params), _flat(fused.state.params),
        rtol=2e-6, atol=1e-7,
    )


def test_mesh_async_metrics_match_single_program():
    """Scalar metrics psum to the same totals the single program computes."""
    from fedtpu.parallel import client_mesh

    cfg = tiny_cfg(num_clients=8)
    plain = AsyncFederation(cfg, seed=5, buffer_k=3, speed_sigma=0.5)
    sharded = AsyncFederation(cfg, seed=5, buffer_k=3, speed_sigma=0.5,
                              mesh=client_mesh(8, cfg.mesh_axis))
    for _ in range(2):
        mp = plain.tick()
        ms = sharded.tick()
    assert float(ms.num_arrived) == float(mp.num_arrived) == 3.0
    np.testing.assert_allclose(float(ms.loss), float(mp.loss), rtol=2e-5)
    np.testing.assert_allclose(
        float(ms.staleness_mean), float(mp.staleness_mean), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ms.per_client_loss), np.asarray(mp.per_client_loss),
        rtol=2e-5, atol=1e-7,
    )


def test_mesh_gather_layout_ticks_and_learns():
    """Gather layout under the mesh: per-shard permutation keys are folded
    with the axis index (review finding r5: without the fold, clients in
    different shards shuffled in lockstep), so no bit-parity claim — just
    soundness: ticks run, the model learns, nothing NaNs."""
    import dataclasses

    from fedtpu.parallel import client_mesh

    cfg = tiny_cfg(num_clients=8)
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, device_layout="gather"))
    asyn = AsyncFederation(cfg, seed=0, buffer_k=4, speed_sigma=0.0,
                           mesh=client_mesh(8, cfg.mesh_axis))
    for _ in range(8):
        m = asyn.tick()
        assert np.isfinite(float(m.loss))
    test = load("synthetic", "test", num=256)
    _, acc = asyn.evaluate(*test)
    assert acc > 0.5, acc


def test_staleness_damping_scales_update_magnitude():
    """Round-5 stall fix: with a uniform-staleness buffer the discount must
    damp the APPLIED update by exactly (1+s)^-p (FedBuff-paper semantics);
    the weight-normalized form (damping off) cancels it entirely. Setup:
    client 0 arrives alone at tick 1 with staleness 1."""
    import jax

    def run(damping):
        cfg = tiny_cfg(num_clients=2)
        a = AsyncFederation(cfg, seed=0, buffer_k=1, speed_sigma=0.0,
                            staleness_power=1.0, staleness_damping=damping)
        schedule = [np.array([False, True]), np.array([True, False])]
        a._arrive_mask = lambda: schedule.pop(0)
        a.tick()                   # client 1 arrives fresh; 0 holds
        m = a.tick()               # client 0 arrives with staleness 1
        assert float(m.staleness_mean) == 1.0
        return float(m.update_norm)

    undamped = run(False)
    damped = run(True)
    # Same single-client buffer, same delta: damped norm = undamped / (1+1).
    np.testing.assert_allclose(damped, undamped / 2.0, rtol=1e-5)


def test_damping_is_identity_at_zero_staleness():
    """buffer_k == N keeps every arrival at staleness 0, so damping must be
    a no-op and the synchronous-parity anchor holds in BOTH modes."""
    cfg = tiny_cfg(num_clients=4)
    on = AsyncFederation(cfg, seed=0, buffer_k=4, staleness_damping=True)
    off = AsyncFederation(cfg, seed=0, buffer_k=4, staleness_damping=False)
    for _ in range(3):
        on.tick()
        off.tick()
    np.testing.assert_allclose(
        _flat(on.state.params), _flat(off.state.params), rtol=1e-6, atol=1e-7
    )


def test_async_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Async checkpoint/resume: save after 3 ticks, restore into a FRESH
    AsyncFederation, continue 2 ticks with the same arrival schedule — must
    match 5 uninterrupted ticks exactly (all learned state rides the
    checkpoint; only the host arrival RNG deliberately does not, so the
    schedule is pinned explicitly here)."""
    import jax
    import numpy as np_mod

    from fedtpu.checkpoint import Checkpointer

    sched = [np.array([i % 4 == j for j in range(4)]) for i in range(5)]

    def fresh():
        a = AsyncFederation(tiny_cfg(num_clients=4), seed=7, buffer_k=1)
        a._arrive_mask = lambda s=list(sched): s.pop(0)
        return a

    ref = fresh()
    for _ in range(5):
        ref.tick()

    a = fresh()
    for _ in range(3):
        a.tick()
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(3, jax.tree.map(np_mod.asarray, a.state))

    b = AsyncFederation(tiny_cfg(num_clients=4), seed=7, buffer_k=1)
    tick3, state = ckpt.restore_latest(like=b.state)
    assert tick3 == 3
    b.load_state(state)
    rest = list(sched)[3:]
    b._arrive_mask = lambda: rest.pop(0)
    for _ in range(2):
        b.tick()
    assert int(b.state.version) == 5
    np.testing.assert_array_equal(_flat(ref.state.params),
                                  _flat(b.state.params))
    np.testing.assert_array_equal(_flat(ref.state.client_params),
                                  _flat(b.state.client_params))
    np.testing.assert_array_equal(
        np.asarray(ref.state.base_version), np.asarray(b.state.base_version))


def test_async_checkpoint_restore_onto_mesh(tmp_path):
    """A single-program async checkpoint restores onto a MESH federation
    (load_state re-shards every per-client stack)."""
    import jax
    import numpy as np_mod

    from fedtpu.checkpoint import Checkpointer
    from fedtpu.parallel import client_mesh

    cfg = tiny_cfg(num_clients=8)
    a = AsyncFederation(cfg, seed=1, buffer_k=2)
    for _ in range(2):
        a.tick()
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(2, jax.tree.map(np_mod.asarray, a.state))

    mesh = client_mesh(8, cfg.mesh_axis)
    b = AsyncFederation(cfg, seed=1, buffer_k=2, mesh=mesh)
    _, state = ckpt.restore_latest(like=b.state)
    b.load_state(state)
    assert int(b.state.version) == 2
    np.testing.assert_array_equal(_flat(a.state.params),
                                  _flat(b.state.params))
    m = b.tick()  # and it still runs under the mesh
    assert int(b.state.version) == 3
    assert np.isfinite(float(m.loss))
