"""Streaming server aggregation (``FedConfig.server_pipeline="stream"``).

The stream pipeline decodes each StartTrain reply into its row of one flat
``[clients, P]`` buffer and ships it to the device as it arrives; the only
post-barrier work is a single fused mean/unpack/server-opt finalize. These
tests pin the tentpole invariants over REAL gRPC on localhost:

- stream == barrier BIT-PARITY for the mean aggregator, across delta
  layouts (flat + per_leaf) and compressions (none / int8 / topk). The
  tests run on the 8-virtual-device CPU platform (tests/conftest.py), so
  the server-side jits execute on a multi-device backend — the "mesh
  present" case; the gRPC server itself is single-program by construction.
- a failed client's row never enters the aggregate (the gather path that
  keeps parity when the buffer holds rows the barrier path would not
  stack);
- config validation rejects stream + robust aggregation / DP with a
  reason string, and "auto" streams exactly for the flat layout;
- the round record carries the collect/decode/H2D/aggregate phase timing.
"""

import dataclasses
import socket

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

import jax

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
    resolve_server_pipeline,
)
from fedtpu.transport.federation import PrimaryServer, serve_client


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pipeline_cfg(
    layout="flat", compression="none", pipeline="auto", num_clients=2,
    **fed_kwargs,
) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(
            num_clients=num_clients,
            num_rounds=2,
            compression=compression,
            topk_fraction=0.25,
            delta_layout=layout,
            server_pipeline=pipeline,
            **fed_kwargs,
        ),
        steps_per_round=2,
    )


def run_federation(cfg, rounds=3, dead_tail=0):
    """Fresh clients + a fresh primary, ``rounds`` rounds; returns
    (flat params vector, round records, primary). ``dead_tail`` appends
    that many never-listening client addresses to the registry."""
    addrs, servers = [], []
    try:
        for i in range(cfg.fed.num_clients - dead_tail):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
        for _ in range(dead_tail):
            addrs.append(f"localhost:{free_port()}")  # nothing listening
        primary = PrimaryServer(cfg, addrs)
        if cfg.fed.compression != "none":
            primary.sync_clients()  # run() does this; round() alone needs it
        recs = [primary.round() for _ in range(rounds)]
        flat = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree.leaves(primary.params)]
        )
        return flat, recs, primary
    finally:
        for s in servers:
            s.stop(0)


# ----------------------------------------------------------- bit parity
@pytest.mark.parametrize("layout", ["flat", "per_leaf"])
@pytest.mark.parametrize("compression", ["none", "int8", "topk"])
def test_stream_barrier_bit_parity(layout, compression):
    """Identical client trajectories -> the streamed aggregate must be
    BIT-IDENTICAL to the barrier path's, for every layout x compression.
    This holds because the stream finalize runs the same order-stable
    stacked axis-0 reduce as the barrier mean over the same rows (a running
    per-arrival fold would NOT be bit-stable — fedtpu.core.round.
    flat_weighted_mean's docstring records the measurement)."""
    a, recs_a, pa = run_federation(
        pipeline_cfg(layout, compression, "stream")
    )
    b, recs_b, pb = run_federation(
        pipeline_cfg(layout, compression, "barrier")
    )
    assert pa.server_pipeline == "stream"
    assert pb.server_pipeline == "barrier"
    assert recs_a[-1]["participants"] == 2
    np.testing.assert_array_equal(a, b)


def test_stream_parity_with_round_deadline():
    """The deadline knob composes with streaming: with no straggler the
    deadline path must aggregate the same rows -> bitwise-equal params."""
    a, _, _ = run_federation(pipeline_cfg(pipeline="stream"), rounds=2)
    cfg = pipeline_cfg(pipeline="stream")
    addrs, servers = [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
        primary = PrimaryServer(cfg, addrs, round_deadline_s=120.0)
        for _ in range(2):
            primary.round()
        b = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree.leaves(primary.params)]
        )
    finally:
        for s in servers:
            s.stop(0)
    np.testing.assert_array_equal(a, b)


# ------------------------------------------------- failure mid-stream
def test_failed_client_row_never_enters_accumulator():
    """A client that RpcErrors never contributes a row: its (zero) buffer
    row is gathered OUT before the reduce, so the streamed aggregate is
    bit-identical to the barrier aggregate over the same survivors."""
    a, recs_a, pa = run_federation(
        pipeline_cfg(pipeline="stream", num_clients=3), dead_tail=1
    )
    b, recs_b, _ = run_federation(
        pipeline_cfg(pipeline="barrier", num_clients=3), dead_tail=1
    )
    assert recs_a[0]["participants"] == 2
    assert recs_a[0]["alive"] == [True, True, False]
    assert recs_b[0]["participants"] == 2
    np.testing.assert_array_equal(a, b)


# ----------------------------------------------------- config validation
def test_stream_rejects_robust_aggregators_with_reason():
    for agg in ("median", "trimmed_mean", "krum"):
        fed = FedConfig(
            aggregator=agg, server_pipeline="stream", compression="none"
        )
        with pytest.raises(ValueError, match="per-coordinate sums"):
            resolve_server_pipeline(fed)
        # PrimaryServer construction enforces it too.
        cfg = pipeline_cfg(pipeline="stream", aggregator=agg, weighted=False)
        with pytest.raises(ValueError, match="server_pipeline='stream'"):
            PrimaryServer(cfg, [])


def test_stream_rejects_dp_with_reason():
    fed = FedConfig(
        server_pipeline="stream", dp_clip_norm=1.0, weighted=False
    )
    with pytest.raises(ValueError, match="DP"):
        resolve_server_pipeline(fed)


def test_auto_streams_for_flat_layout_only():
    assert resolve_server_pipeline(FedConfig(delta_layout="flat")) == "stream"
    assert (
        resolve_server_pipeline(FedConfig(delta_layout="per_leaf"))
        == "barrier"
    )
    # Auto silently falls back to barrier for non-streamable combines —
    # only an EXPLICIT stream request errors.
    assert (
        resolve_server_pipeline(
            FedConfig(delta_layout="flat", aggregator="median",
                      compression="none")
        )
        == "barrier"
    )
    assert (
        resolve_server_pipeline(
            FedConfig(delta_layout="flat", dp_clip_norm=1.0, weighted=False)
        )
        == "barrier"
    )
    with pytest.raises(ValueError, match="unknown server_pipeline"):
        resolve_server_pipeline(FedConfig(server_pipeline="eager"))


# --------------------------------------------------------- phase timing
@pytest.mark.parametrize("pipeline", ["stream", "barrier"])
def test_round_record_carries_phase_timing(pipeline):
    _, recs, primary = run_federation(
        pipeline_cfg(pipeline=pipeline), rounds=1
    )
    rec = recs[0]
    assert rec["pipeline"] == pipeline
    for key in (
        "t_collect_s", "t_decode_s", "t_h2d_s", "t_aggregate_s",
        "t_post_barrier_s",
    ):
        assert key in rec and rec[key] >= 0.0, (key, rec)
    # Decode work happened and the collect phase wall-clock is sane.
    assert rec["t_decode_s"] > 0.0
    assert rec["t_collect_s"] > 0.0
    if pipeline == "stream":
        assert rec["t_h2d_s"] > 0.0  # rows were shipped during collect
    else:
        assert rec["t_h2d_s"] == 0.0  # transfer rides the aggregate dispatch


def test_stream_replies_decode_without_template_trees():
    """The stream path must not build per-leaf delta templates: the
    per-round template cache stays empty for sparse replies (flat layout),
    which is the decode-into-row claim in one observable bit."""
    cfg = pipeline_cfg(layout="flat", compression="int8", pipeline="stream")
    addrs, servers = [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
        primary = PrimaryServer(cfg, addrs)
        primary.sync_clients()
        import fedtpu.transport.federation as fed_mod

        calls = []
        real = fed_mod.sparse.decode

        def spy(data, like):
            calls.append(1)
            return real(data, like)

        fed_mod.sparse.decode = spy
        try:
            rec = primary.round()
        finally:
            fed_mod.sparse.decode = real
        assert rec["participants"] == 2
        assert not calls, "stream path fell back to template tree decode"
    finally:
        for s in servers:
            s.stop(0)
