"""Checkpoint/resume: save-restore fidelity, retention, resume semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu import models
from fedtpu.checkpoint import Checkpointer, latest_round, restore, save
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import round as round_lib


def small_state():
    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=4),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    model = models.create(cfg.model, num_classes=10)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.float32)
    )
    return cfg, model, state


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("backend", ["wire", "orbax"])
def test_roundtrip_full_federated_state(tmp_path, backend):
    if backend == "orbax":
        pytest.importorskip("orbax.checkpoint")
    _, _, state = small_state()
    d = str(tmp_path / "ckpt")
    save(d, 7, state, backend=backend)
    restored = restore(d, 7, like=state, backend=backend)
    _assert_tree_equal(state, restored)
    assert latest_round(d) == 7


def test_wire_checkpoint_is_crc_protected(tmp_path):
    _, _, state = small_state()
    d = str(tmp_path / "ckpt")
    path = save(d, 0, state, backend="wire")
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0x55
    open(path, "wb").write(bytes(data))
    from fedtpu.transport.wire import WireError

    with pytest.raises(WireError):
        restore(d, 0, like=state, backend="wire")


def test_retention_keeps_newest(tmp_path):
    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=2, backend="wire")
    for r in range(5):
        ckpt.save(r, state)
    files = os.listdir(tmp_path)
    kept = sorted(
        int(f.split("_")[1].split(".")[0])
        for f in files if f.endswith(".fckpt")
    )
    assert kept == [3, 4]
    # Each surviving generation carries its digest manifest; pruned
    # generations lose theirs too.
    manifests = sorted(f for f in files if f.endswith(".manifest.json"))
    assert manifests == [
        "round_3.fckpt.manifest.json", "round_4.fckpt.manifest.json"
    ]
    assert latest_round(str(tmp_path)) == 4


def test_restore_latest_resumes_trajectory(tmp_path):
    """Saving mid-run and restoring reproduces the exact same subsequent
    rounds (full FederatedState: params + momentum + rng + round_idx)."""
    cfg, model, state = small_state()
    step = jax.jit(round_lib.make_round_step(model, cfg))
    rng = np.random.default_rng(0)
    n, s, b = 3, 2, 4
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 8)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 10, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool),
    )
    state1, _ = step(state, batch)
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(1, state1)

    # Continue directly...
    direct, _ = step(state1, batch)
    # ...and continue from the restored checkpoint.
    r, restored = ckpt.restore_latest(like=state1)
    assert r == 1
    restored = jax.tree.map(jnp.asarray, restored)
    resumed, _ = step(restored, batch)
    _assert_tree_equal(direct, resumed)


def test_restore_latest_empty_dir(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "nope"))
    assert ckpt.restore_latest(like={}) is None


# ----------------------------------------------------- durability hardening
def _corrupt_file(path, offset_from_end=3):
    data = bytearray(open(path, "rb").read())
    data[-offset_from_end] ^= 0x55
    open(path, "wb").write(bytes(data))


def test_restore_latest_falls_back_past_corrupt_newest(tmp_path):
    """Regression for the pre-hardening crash: a CRC-bad newest generation
    raised straight through --resume instead of falling back. Now it is a
    counted fallback event and the previous generation restores."""
    from fedtpu.obs import MetricsRegistry

    _, _, state = small_state()
    reg = MetricsRegistry()
    ckpt = Checkpointer(str(tmp_path), keep=3, backend="wire", metrics=reg)
    for r in range(3):
        ckpt.save(r, state)
    _corrupt_file(str(tmp_path / "round_2.fckpt"))
    r, restored = ckpt.restore_latest(like=state)
    assert r == 1
    _assert_tree_equal(state, restored)
    assert reg.counter(
        "fedtpu_checkpoint_fallback_total", ""
    ).value == 1


def test_restore_latest_falls_back_past_torn_write(tmp_path):
    """A truncated (torn) newest generation — the manifest still claims
    the full byte count — falls back the same way."""
    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=3, backend="wire")
    ckpt.save(0, state)
    ckpt.save(1, state)
    path = str(tmp_path / "round_1.fckpt")
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(size // 2)
    r, restored = ckpt.restore_latest(like=state)
    assert r == 0
    _assert_tree_equal(state, restored)


def test_restore_latest_all_corrupt_raises_loudly(tmp_path):
    """When generations exist but NONE verifies, resume must fail loudly —
    silently restarting from round 0 would erase the run's history."""
    from fedtpu.transport.wire import WireError

    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=3, backend="wire")
    ckpt.save(0, state)
    ckpt.save(1, state)
    for r in range(2):
        _corrupt_file(str(tmp_path / f"round_{r}.fckpt"))
    with pytest.raises(WireError, match="all 2 checkpoint generations"):
        ckpt.restore_latest(like=state)


def test_resume_requires_two_generations_retained(tmp_path):
    """keep=1 cannot support generation fallback; resuming under it is a
    config error, not a latent single-point-of-failure."""
    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=1, backend="wire")
    ckpt.save(0, state)
    with pytest.raises(ValueError, match="keep >= 2"):
        ckpt.restore_latest(like=state)
    # Unbounded retention (keep <= 0) is fine — there is always history.
    assert Checkpointer(
        str(tmp_path), keep=0, backend="wire"
    ).restore_latest(like=state)[0] == 0


def test_template_mismatch_still_raises_not_falls_back(tmp_path):
    """Corruption falls back; a CONFIG mismatch (intact bytes, wrong
    structure) must raise — restoring an older generation would mask it."""
    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=3, backend="wire")
    ckpt.save(0, state)
    ckpt.save(1, state)
    with pytest.raises(ValueError):
        ckpt.restore_latest(like={"different": np.zeros((3,), np.float32)})


def test_save_failure_is_nonfatal_and_counted(tmp_path):
    """An injected ENOSPC (chaos ckpt_fail) is a counted warning, not a
    crash: save returns None, training would continue, and the NEXT save
    (fault budget spent) succeeds. Old generations survive a failed save
    (prune-only-after-verified-save)."""
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import MetricsRegistry

    _, _, state = small_state()
    reg = MetricsRegistry()
    chaos = parse_spec("ckpt_fail:p=1.0,rounds=1,max=1")
    ckpt = Checkpointer(
        str(tmp_path), keep=2, backend="wire", metrics=reg, chaos=chaos,
    )
    chaos.set_round(0)
    assert ckpt.save(0, state) is not None
    chaos.set_round(1)
    assert ckpt.save(1, state) is None  # injected ENOSPC
    assert reg.counter(
        "fedtpu_checkpoint_save_failures_total", ""
    ).value == 1
    assert latest_round(str(tmp_path)) == 0  # generation 0 untouched
    chaos.set_round(2)
    assert ckpt.save(2, state) is not None  # out of window; back to normal
    assert ckpt.restore_latest(like=state)[0] == 2
    # Strict mode keeps the old raise-on-failure contract.
    strict = Checkpointer(
        str(tmp_path), keep=2, backend="wire", strict=True,
        chaos=parse_spec("ckpt_fail:p=1.0,max=1"),
    )
    with pytest.raises(OSError):
        strict.save(3, state)


def test_disk_chaos_rot_and_torn_are_silent_until_restore(tmp_path):
    """ckpt_rot / ckpt_torn model a disk that ACKED the write and lost
    bits later: the save reports success (metrics count it as a save, not
    a failure), and only restore-time verification notices."""
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.obs import MetricsRegistry

    _, _, state = small_state()
    reg = MetricsRegistry()
    chaos = parse_spec("ckpt_rot:p=1.0,rounds=1,max=1")
    ckpt = Checkpointer(
        str(tmp_path), keep=3, backend="wire", metrics=reg, chaos=chaos,
    )
    chaos.set_round(0)
    ckpt.save(0, state)
    chaos.set_round(1)
    assert ckpt.save(1, state) is not None  # "successful" — then rotted
    assert reg.counter(
        "fedtpu_checkpoint_save_failures_total", ""
    ).value == 0
    r, _restored = ckpt.restore_latest(like=state)
    assert r == 0
    assert reg.counter("fedtpu_checkpoint_fallback_total", "").value == 1


def test_legacy_decode_suffix_drop_ladder(tmp_path):
    """Each partial-generation blob restores with fresh-init backfill:
    (a) missing only ``last_client_loss`` (mid-generation writer), and
    (b) missing both ``server_opt_state`` and ``last_client_loss`` (first
    release). Decoded fields keep the blob's values; dropped fields come
    from ``like`` — its freshly initialised values."""
    from fedtpu.checkpoint.checkpoint import _wire_path
    from fedtpu.transport import wire as wire_mod

    _, _, state = small_state()
    # A recognisably different "saved" state: params/momenta bumped, so we
    # can tell decoded fields from backfilled ones.
    saved = state._replace(
        params=jax.tree.map(lambda l: l + 1.0, state.params),
        round_idx=state.round_idx + 7,
    )
    full = dict(saved._asdict())

    def write_blob(round_idx, drop):
        d = {k: v for k, v in full.items() if k not in drop}
        os.makedirs(tmp_path, exist_ok=True)
        with open(_wire_path(str(tmp_path), round_idx), "wb") as fh:
            fh.write(wire_mod.encode(d, compress=True))

    write_blob(0, drop=("last_client_loss",))
    write_blob(1, drop=("server_opt_state", "last_client_loss"))

    mid = restore(str(tmp_path), 0, like=state, backend="wire")
    _assert_tree_equal(mid.params, saved.params)           # decoded
    assert int(mid.round_idx) == int(saved.round_idx)      # decoded
    _assert_tree_equal(mid.server_opt_state, saved.server_opt_state)
    _assert_tree_equal(mid.last_client_loss, state.last_client_loss)  # backfilled

    oldest = restore(str(tmp_path), 1, like=state, backend="wire")
    _assert_tree_equal(oldest.params, saved.params)        # decoded
    _assert_tree_equal(oldest.server_opt_state, state.server_opt_state)
    _assert_tree_equal(oldest.last_client_loss, state.last_client_loss)


def test_background_writer_orders_flushes_and_survives_errors(tmp_path):
    """BackgroundCheckpointer: saves land in submission order, flush()
    drains, a failing save never kills the writer thread, and the handed-
    off trees are HOST arrays (the round loop's device buffers are
    released at snapshot time)."""
    from fedtpu.checkpoint import BackgroundCheckpointer
    from fedtpu.ft.chaos import parse_spec

    _, _, state = small_state()
    dev_state = jax.tree.map(jnp.asarray, state)
    # First save hits an injected ENOSPC (no rounds window: the writer
    # thread decides asynchronously, so windows keyed on set_round would
    # race); the remaining three land.
    chaos = parse_spec("ckpt_fail:p=1.0,max=1")
    inner = Checkpointer(
        str(tmp_path), keep=10, backend="wire", chaos=chaos,
    )
    bg = BackgroundCheckpointer(inner, queue_depth=2)
    seen = []
    real_save = inner.save

    def spy(round_idx, tree):
        seen.append((round_idx,
                     all(isinstance(l, np.ndarray)
                         for l in jax.tree.leaves(tree))))
        return real_save(round_idx, tree)

    inner.save = spy
    for r in range(4):
        bg.save(r, dev_state)
    assert bg.flush(timeout=30)
    assert [r for r, _ in seen] == [0, 1, 2, 3]  # submission order
    assert all(hosted for _, hosted in seen)     # host arrays only
    # Save 0 failed non-fatally; the writer survived and the others are
    # all durable and restorable.
    from fedtpu.checkpoint.checkpoint import _scan_rounds

    assert _scan_rounds(str(tmp_path)) == [1, 2, 3]
    r, restored = bg.restore_latest(like=state)
    assert r == 3
    _assert_tree_equal(state, restored)
    bg.close()
    bg.close()  # idempotent


def test_background_writer_snapshot_survives_buffer_donation(tmp_path):
    """The writer's snapshot must be a forced COPY: the engines' round
    steps donate their state buffers, and a zero-copy np view of a CPU
    jax array would observe the next round's bytes by write time. Donate
    the saved arrays immediately after save(); the written generation
    must still hold the pre-donation values."""
    from fedtpu.checkpoint import BackgroundCheckpointer

    state = {
        "a": jnp.arange(4096, dtype=jnp.float32),
        "b": jnp.ones((128,), jnp.float32),
    }
    expected = jax.tree.map(np.array, state)
    bump = jax.jit(
        lambda t: jax.tree.map(lambda l: l + 1.0, t), donate_argnums=0
    )
    bg = BackgroundCheckpointer(
        Checkpointer(str(tmp_path), keep=3, backend="wire")
    )
    bg.save(0, state)
    state = bump(state)  # donates the saved buffers
    jax.block_until_ready(state)
    assert bg.flush(timeout=30)
    restored = bg.restore(0, like=expected)
    _assert_tree_equal(expected, restored)
    bg.close()


def test_mesh_checkpoint_resume_matches_uninterrupted(tmp_path, eight_devices):
    """Save a mesh Federation mid-run, restore into a FRESH mesh Federation,
    and continue: the resumed trajectory must match the uninterrupted one.
    The state setter places the restored host tree back onto the mesh."""
    from fedtpu.core import Federation
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=4,
                        partition="round_robin", num_examples=128),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )
    mesh = client_mesh(8)
    straight = Federation(cfg, seed=0, mesh=mesh)
    straight.step()
    straight.step()

    interrupted = Federation(cfg, seed=0, mesh=mesh)
    interrupted.step()
    d = str(tmp_path / "ckpt")
    save(d, 1, interrupted.state, backend="wire")

    resumed = Federation(cfg, seed=0, mesh=mesh)
    resumed.state = restore(d, 1, like=resumed.state, backend="wire")
    m = resumed.step()
    assert int(m.num_active) == 8
    assert int(resumed.state.round_idx) == 2
    _assert_tree_equal(straight.state.params, resumed.state.params)
