"""Checkpoint/resume: save-restore fidelity, retention, resume semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu import models
from fedtpu.checkpoint import Checkpointer, latest_round, restore, save
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import round as round_lib


def small_state():
    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=4),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    model = models.create(cfg.model, num_classes=10)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.float32)
    )
    return cfg, model, state


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("backend", ["wire", "orbax"])
def test_roundtrip_full_federated_state(tmp_path, backend):
    if backend == "orbax":
        pytest.importorskip("orbax.checkpoint")
    _, _, state = small_state()
    d = str(tmp_path / "ckpt")
    save(d, 7, state, backend=backend)
    restored = restore(d, 7, like=state, backend=backend)
    _assert_tree_equal(state, restored)
    assert latest_round(d) == 7


def test_wire_checkpoint_is_crc_protected(tmp_path):
    _, _, state = small_state()
    d = str(tmp_path / "ckpt")
    path = save(d, 0, state, backend="wire")
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0x55
    open(path, "wb").write(bytes(data))
    from fedtpu.transport.wire import WireError

    with pytest.raises(WireError):
        restore(d, 0, like=state, backend="wire")


def test_retention_keeps_newest(tmp_path):
    _, _, state = small_state()
    ckpt = Checkpointer(str(tmp_path), keep=2, backend="wire")
    for r in range(5):
        ckpt.save(r, state)
    kept = sorted(
        int(f.split("_")[1].split(".")[0]) for f in os.listdir(tmp_path)
    )
    assert kept == [3, 4]
    assert latest_round(str(tmp_path)) == 4


def test_restore_latest_resumes_trajectory(tmp_path):
    """Saving mid-run and restoring reproduces the exact same subsequent
    rounds (full FederatedState: params + momentum + rng + round_idx)."""
    cfg, model, state = small_state()
    step = jax.jit(round_lib.make_round_step(model, cfg))
    rng = np.random.default_rng(0)
    n, s, b = 3, 2, 4
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 8)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 10, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool),
    )
    state1, _ = step(state, batch)
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(1, state1)

    # Continue directly...
    direct, _ = step(state1, batch)
    # ...and continue from the restored checkpoint.
    r, restored = ckpt.restore_latest(like=state1)
    assert r == 1
    restored = jax.tree.map(jnp.asarray, restored)
    resumed, _ = step(restored, batch)
    _assert_tree_equal(direct, resumed)


def test_restore_latest_empty_dir(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "nope"))
    assert ckpt.restore_latest(like={}) is None


def test_mesh_checkpoint_resume_matches_uninterrupted(tmp_path, eight_devices):
    """Save a mesh Federation mid-run, restore into a FRESH mesh Federation,
    and continue: the resumed trajectory must match the uninterrupted one.
    The state setter places the restored host tree back onto the mesh."""
    from fedtpu.core import Federation
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=4,
                        partition="round_robin", num_examples=128),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )
    mesh = client_mesh(8)
    straight = Federation(cfg, seed=0, mesh=mesh)
    straight.step()
    straight.step()

    interrupted = Federation(cfg, seed=0, mesh=mesh)
    interrupted.step()
    d = str(tmp_path / "ckpt")
    save(d, 1, interrupted.state, backend="wire")

    resumed = Federation(cfg, seed=0, mesh=mesh)
    resumed.state = restore(d, 1, like=resumed.state, backend="wire")
    m = resumed.step()
    assert int(m.num_active) == 8
    assert int(resumed.state.round_idx) == 2
    _assert_tree_equal(straight.state.params, resumed.state.params)
