"""Update-compression codecs (fedtpu.ops) — the ``-c Y`` parity path.

Covers: top-k sparsity level, int8 quantization error bound, the
mass-conservation property of error feedback (compressed + residual ==
input + previous residual), the Pallas kernels vs a plain-jnp oracle, and a
full round step running with compression enabled (residuals carried in
FederatedState.comp_state).
"""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu import models
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import round as round_lib
from fedtpu.ops import compression, pallas_kernels as pk


def tree_of_deltas(rng, n=4):
    return {
        "w": jnp.asarray(rng.normal(size=(n, 16, 32)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(n, 32)).astype(np.float32)),
    }


# --------------------------------------------------------------- pallas units
def test_threshold_kernel_matches_oracle(rng):
    y = jnp.asarray(rng.normal(size=(3, 1000)).astype(np.float32))
    t = jnp.asarray([0.5, 1.0, 2.0], jnp.float32)
    # interpret=True forces the actual pallas_call body (the off-TPU default
    # is the plain-jnp equivalent); both paths are checked against the oracle.
    for kw in ({"interpret": True}, {}):
        out, new_e = pk.threshold_with_feedback(y, t, **kw)
        yn = np.asarray(y)
        keep = np.abs(yn) >= np.asarray(t)[:, None]
        np.testing.assert_allclose(np.asarray(out), yn * keep, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_e), yn * ~keep, atol=1e-6)


def test_quantdequant_kernel_matches_oracle(rng):
    x = jnp.asarray(rng.normal(size=(2, 513)).astype(np.float32))
    scale = jnp.max(jnp.abs(x), axis=1) / 127.0
    for kw in ({"interpret": True}, {}):
        out = pk.quantdequant_int8(x, scale, **kw)
        s = np.asarray(scale)[:, None]
        expected = np.clip(np.round(np.asarray(x) / s), -127, 127) * s
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-6)


def test_quantdequant_zero_leaf_is_safe():
    x = jnp.zeros((2, 64), jnp.float32)
    for kw in ({"interpret": True}, {}):
        out = pk.quantdequant_int8(x, jnp.zeros((2,), jnp.float32), **kw)
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(np.asarray(out), 0.0)


# -------------------------------------------------------------------- codecs
def test_topk_sparsity_level(rng):
    deltas = tree_of_deltas(rng)
    comp = compression.make_topk(fraction=0.1, error_feedback=False)
    out, _ = comp.apply(deltas, {})
    frac = float(compression.nnz_fraction(out))
    # >= because ties keep extras; <= 2x because random gaussians rarely tie.
    assert 0.05 <= frac <= 0.2
    # Every kept entry must be at least as large as every dropped entry, per
    # client per leaf.
    for name in ("w", "b"):
        o = np.asarray(out[name]).reshape(4, -1)
        d = np.asarray(deltas[name]).reshape(4, -1)
        for c in range(4):
            kept = np.abs(d[c][o[c] != 0])
            dropped = np.abs(d[c][o[c] == 0])
            if len(kept) and len(dropped):
                assert kept.min() >= dropped.max() - 1e-6


def test_error_feedback_mass_conservation(rng):
    """compressed + new_residual == delta + old_residual, exactly."""
    deltas = tree_of_deltas(rng)
    comp = compression.make_topk(fraction=0.05, error_feedback=True)
    state = comp.init({k: v[0] for k, v in deltas.items()}, 4)
    # Seed nonzero residuals to exercise the carry.
    state = jax.tree.map(lambda e: e + 0.01, state)
    out, new_state = comp.apply(deltas, state)
    for k in deltas:
        lhs = np.asarray(out[k]) + np.asarray(new_state[k]).reshape(out[k].shape)
        rhs = np.asarray(deltas[k]) + 0.01
        np.testing.assert_allclose(lhs, rhs, atol=1e-5)


def test_error_feedback_recovers_dropped_mass(rng):
    """A constant delta stream through an aggressive top-k: with error
    feedback the cumulative compressed output tracks the cumulative input
    (residual stays bounded), so nothing is permanently lost."""
    comp = compression.make_topk(fraction=0.25, error_feedback=True)
    delta = {"w": jnp.asarray(rng.normal(size=(2, 64)).astype(np.float32))}
    state = comp.init({"w": delta["w"][0]}, 2)
    total_out = jax.tree.map(jnp.zeros_like, delta)
    rounds = 12
    for _ in range(rounds):
        out, state = comp.apply(delta, state)
        total_out = jax.tree.map(jnp.add, total_out, out)
    # total_in - total_out == final residual -> relative gap shrinks with T.
    gap = np.abs(
        rounds * np.asarray(delta["w"]) - np.asarray(total_out["w"])
    ).max()
    per_round = np.abs(np.asarray(delta["w"])).max()
    assert gap <= 4 * per_round  # residual bounded, not growing with rounds


def test_int8_error_bound(rng):
    deltas = tree_of_deltas(rng)
    comp = compression.make_int8(error_feedback=False)
    out, _ = comp.apply(deltas, {})
    for k in deltas:
        d = np.asarray(deltas[k]).reshape(4, -1)
        o = np.asarray(out[k]).reshape(4, -1)
        scale = np.abs(d).max(axis=1, keepdims=True) / 127.0
        assert np.all(np.abs(d - o) <= scale / 2 + 1e-7)


def test_make_compressor_dispatch():
    assert compression.make_compressor(FedConfig(compression="none")) is None
    assert compression.make_compressor(FedConfig(compression="topk")) is not None
    assert compression.make_compressor(FedConfig(compression="int8")) is not None
    with pytest.raises(ValueError):
        compression.make_compressor(FedConfig(compression="huffman"))
    # Sketch codecs are flat-layout only.
    for kind in ("rotq", "randk"):
        comp = compression.make_compressor(
            FedConfig(compression=kind, delta_layout="flat")
        )
        assert comp is not None and comp.layout == "flat"
        with pytest.raises(ValueError):
            compression.make_compressor(FedConfig(compression=kind))
    assert compression.make_compressor(
        FedConfig(compression="rotq", delta_layout="flat")
    ).pad_pow2
    with pytest.raises(ValueError):
        compression.make_rotq(bits=3)  # not a supported width


# -------------------------------------------------- end-to-end in round_step
def _round_setup(compression_kind, delta_layout="per_leaf"):
    cfg = RoundConfig(
        model="mlp",
        num_classes=4,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=8),
        fed=FedConfig(num_clients=4, compression=compression_kind,
                      topk_fraction=0.1, delta_layout=delta_layout),
        steps_per_round=3,
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    comp = compression.make_compressor(cfg.fed)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32), comp
    )
    step = jax.jit(round_lib.make_round_step(model, cfg, compressor=comp))
    rng = np.random.default_rng(0)
    n, s, b = 4, 3, 8
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 6)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 4, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool),
    )
    return cfg, state, step, batch


@pytest.mark.parametrize(
    "kind,layout",
    [
        ("topk", "per_leaf"),
        ("int8", "per_leaf"),
        # rotq exercises the pow2-padded flat path end-to-end through the
        # engine round step (tier-1); randk shares the plain flat wiring
        # already covered by the engine-codec units, so its full round step
        # rides the slow tier.
        ("rotq", "flat"),
        pytest.param("randk", "flat", marks=pytest.mark.slow),
    ],
)
def test_round_step_with_compression(kind, layout):
    cfg, state, step, batch = _round_setup(kind, delta_layout=layout)
    assert jax.tree_util.tree_leaves(state.comp_state)  # residuals allocated
    s1, m1 = step(state, batch)
    s2, m2 = step(s1, batch)
    # Model actually moves, and residuals become nonzero (lossy codec).
    moved = max(
        float(jnp.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(s2.params))
    )
    assert moved > 0
    res = max(float(jnp.abs(r).max()) for r in jax.tree.leaves(s2.comp_state))
    assert res > 0
    assert np.isfinite(float(m2.loss))


def test_dead_client_residual_preserved():
    """A dead client's error-feedback residual must be carried untouched —
    its (zeroed) delta contributes nothing, so draining the residual would
    permanently lose its correction mass."""
    cfg, state, step, batch = _round_setup("topk")
    s1, _ = step(state, batch)  # round 0: everyone alive, residuals fill
    dead = round_lib.RoundBatch(
        x=batch.x, y=batch.y, step_mask=batch.step_mask,
        weights=batch.weights,
        alive=jnp.asarray([True, True, True, False]),
    )
    s2, _ = step(s1, dead)
    for r1, r2 in zip(jax.tree.leaves(s1.comp_state), jax.tree.leaves(s2.comp_state)):
        # Client 3's residual row unchanged; a living client's moved.
        np.testing.assert_allclose(np.asarray(r1)[3], np.asarray(r2)[3], atol=0)
    moved = max(
        float(jnp.abs(np.asarray(r1)[0] - np.asarray(r2)[0]).max())
        for r1, r2 in zip(jax.tree.leaves(s1.comp_state), jax.tree.leaves(s2.comp_state))
    )
    assert moved > 0


def test_compressed_training_still_converges():
    """Short synthetic run: loss under top-k+EF decreases from round 0."""
    cfg, state, step, batch = _round_setup("topk")
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m.loss))
    assert losses[-1] < losses[0]


def test_pallas_blocks_are_mosaic_legal():
    """Block shapes must satisfy Mosaic's tiling rule: last two block dims
    divisible by (8, 128) or equal to the whole array dim (the constraint
    that rejected the original (1, N) row-tiling — see
    tools/compile_pallas_tpu.py for the deviceless TPU compile proof)."""
    from fedtpu.ops.pallas_kernels import _blocks

    for rows, cols in [(1, 7), (2, 100), (8, 128), (64, 3_217_226),
                       (12, 50_000), (64, 32 * 1024), (3, 129)]:
        rb, cb = _blocks(rows, cols)
        assert rb == rows or rb % 8 == 0, (rows, cols, rb)
        assert cb == cols or cb % 128 == 0, (rows, cols, cb)
        assert rb <= rows and cb <= cols


# ----------------------------------------------------- sketch codecs (flat)
def test_hadamard_rotate_interpret_matches_lax(rng):
    """Interpreted pallas butterfly vs the plain-lax branch: identical up
    to float-associativity, for a forward and an inverse rotation. This is
    the parity pin the docstring promises — the Mosaic-compiled body runs
    the same program on TPU."""
    for rows, h in [(1, 8), (4, 64), (9, 256)]:
        y = jnp.asarray(rng.normal(size=(rows, h)).astype(np.float32))
        signs = jnp.asarray(
            (rng.integers(0, 2, size=h).astype(np.float32)) * 2 - 1
        )
        for inverse in (False, True):
            ref = pk.hadamard_rotate(y, signs, inverse=inverse)
            got = pk.hadamard_rotate(y, signs, inverse=inverse,
                                     interpret=True)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
            )


def test_hadamard_rotation_pair_is_identity(rng):
    """inverse(forward(y)) == y exactly in math (fwht(fwht(x)) == h*x);
    f32 gives it back to ~1e-5."""
    y = jnp.asarray(rng.normal(size=(3, 128)).astype(np.float32))
    signs = jnp.asarray((rng.integers(0, 2, size=128) * 2 - 1).astype(np.float32))
    back = pk.hadamard_rotate(pk.hadamard_rotate(y, signs), signs, inverse=True)
    np.testing.assert_allclose(np.asarray(back), np.asarray(y),
                               rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        pk.hadamard_rotate(y[:, :100], signs[:100])  # not a power of two


def _flat_codec_setup(make, pow2, rng, n=3):
    from fedtpu.ops import flat as flat_ops

    template = {
        "w": np.zeros((16, 32), np.float32),
        "b": np.zeros((32,), np.float32),
    }
    lay = flat_ops.make_layout(template, pow2=pow2)
    y = jnp.asarray(
        rng.normal(size=(n, lay.padded)).astype(np.float32)
    ).at[:, lay.total:].set(0.0)
    comp = make()
    state = comp.init(template, n)
    return comp, lay, y, state


def test_rotq_engine_replay_is_deterministic(rng):
    """Same round_idx -> bit-identical compressed rows (the PRNG is keyed
    only on the round); a different round rotates differently."""
    comp, lay, y, state = _flat_codec_setup(
        lambda: compression.make_rotq(bits=4), True, rng
    )
    a1, _ = comp.apply_flat(y, state, lay, round_idx=3)
    a2, _ = comp.apply_flat(y, state, lay, round_idx=3)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    b, _ = comp.apply_flat(y, state, lay, round_idx=4)
    assert float(jnp.abs(a1 - b).max()) > 0


def test_rotq_engine_pad_stays_zero_and_ef_closes(rng):
    """The codec's output keeps the pad region exactly zero (the flat
    buffer invariant) and out + residual == input to f32 tolerance."""
    comp, lay, y, state = _flat_codec_setup(
        lambda: compression.make_rotq(bits=8), True, rng
    )
    out, res = comp.apply_flat(y, state, lay, round_idx=0)
    assert float(jnp.abs(out[:, lay.total:]).max()) == 0.0
    np.testing.assert_allclose(
        np.asarray(out + res), np.asarray(y), rtol=1e-4, atol=1e-4
    )


def test_rotq_engine_requires_pow2_row(rng):
    # error_feedback off so the check under test (the codec's own pow2
    # guard) fires rather than a residual-buffer shape mismatch.
    comp, lay, y, state = _flat_codec_setup(
        lambda: compression.make_rotq(bits=4, error_feedback=False), False, rng
    )
    if lay.padded & (lay.padded - 1):  # lane padding landed off a power of 2
        with pytest.raises(ValueError):
            comp.apply_flat(y, state, lay, round_idx=0)


def test_randk_engine_ef_keeps_exact_mass(rng):
    """EF on: kept coordinates ship unscaled and out + residual == y
    EXACTLY (disjoint supports — no rounding in the split)."""
    comp, lay, y, state = _flat_codec_setup(
        lambda: compression.make_randk(0.1), False, rng
    )
    out, res = comp.apply_flat(y, state, lay, round_idx=1)
    np.testing.assert_array_equal(np.asarray(out + res), np.asarray(y))
    # The kept support is shared across clients (one seeded draw per round).
    nz = np.asarray(out) != 0
    assert (nz.any(axis=0) == nz.all(axis=0))[np.asarray(y != 0).all(axis=0)].all()


def test_randk_engine_no_ef_is_rescaled(rng):
    """EF off: the kept values carry the total/k unbiasedness rescale."""
    frac = 0.1
    comp, lay, y, state = _flat_codec_setup(
        lambda: compression.make_randk(frac, error_feedback=False), False, rng
    )
    out, _ = comp.apply_flat(y, state, lay, round_idx=1)
    kept = np.asarray(out)
    mask = kept != 0
    import math as _math

    k = max(1, int(_math.ceil(frac * lay.total)))
    expect = np.asarray(y) * (lay.total / k)
    np.testing.assert_allclose(kept[mask], expect[mask], rtol=1e-5)
