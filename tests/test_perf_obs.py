"""Performance observatory (fedtpu.obs.profile + tools): MFU/roofline
accounting, compile observability, device-trace fusion, idle-gap
attribution, and the perf-regression CI harness.

Everything here is tier-1 cheap: pure-python math on synthetic inputs,
two tiny jit compiles, one tiny-engine round, and the seconds-scale
perf_ci harness against the committed baseline. The full bench legs
(``--mfu-profile``, ``--mfu-microbench``) re-run as ``slow`` in
tests/test_bench.py; their committed artifacts are contract-checked here.
"""

import json
import os
import sys

import pytest

from fedtpu.obs import Telemetry, parse_prometheus_text, prometheus_text
from fedtpu.obs.profile import (
    CompileWatcher,
    CostModel,
    RoundProfiler,
    analytic_flops,
    device_peaks,
    latency_summary,
    parse_round_window,
    roofline,
    write_profile_meta,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import gap_analyze  # noqa: E402
import perf_ci  # noqa: E402
import span_check  # noqa: E402
import trace_merge  # noqa: E402


# ------------------------------------------------------------ peaks/roofline
def test_device_peaks_table_and_env_override(monkeypatch):
    monkeypatch.delenv("FEDTPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("FEDTPU_PEAK_HBM_BYTES", raising=False)
    assert device_peaks("TPU v5 lite") == (197e12, 819e9)
    assert device_peaks("TPU v4") == (275e12, 1228e9)
    assert device_peaks("TPU v6e")[0] == 918e12
    assert device_peaks("cpu") == (None, None)
    assert device_peaks("") == (None, None)
    # Env overrides are the only path to MFU on uncovered hardware.
    monkeypatch.setenv("FEDTPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("FEDTPU_PEAK_HBM_BYTES", "5e10")
    assert device_peaks("cpu") == (1e12, 5e10)
    # ... and win over the table.
    assert device_peaks("TPU v4") == (1e12, 5e10)
    monkeypatch.setenv("FEDTPU_PEAK_FLOPS", "not-a-number")
    assert device_peaks("TPU v4")[0] == 275e12


def test_roofline_classification():
    # High arithmetic intensity -> compute-bound; utilization vs peak flops.
    r = roofline(1e12, 1e9, 2e14, 1e12, achieved_flops_per_s=1e14)
    assert r["roofline_bound"] == "compute"
    assert r["arith_intensity_flops_per_byte"] == 1000.0
    assert r["ridge_point_flops_per_byte"] == 200.0
    assert r["roofline_utilization"] == pytest.approx(0.5)
    # Low intensity -> bandwidth-bound; ceiling = peak_bw * intensity.
    r = roofline(1e9, 1e9, 2e14, 1e12, achieved_flops_per_s=5e11)
    assert r["roofline_bound"] == "bandwidth"
    assert r["roofline_utilization"] == pytest.approx(0.5)
    # Schema-stable on missing inputs: keys present, values None.
    r = roofline(None, None, None, None)
    assert set(r) == {
        "arith_intensity_flops_per_byte", "ridge_point_flops_per_byte",
        "roofline_bound", "roofline_utilization",
    }
    assert all(v is None for v in r.values())


def test_analytic_flops_agrees_with_xla_on_matmul():
    import jax
    import jax.numpy as jnp

    def f(a, b):
        return a @ b

    a = jnp.ones((32, 48), jnp.float32)
    b = jnp.ones((48, 16), jnp.float32)
    expect = 2 * 32 * 48 * 16
    got = analytic_flops(f, a, b)
    assert got == expect
    an = jax.jit(f).lower(a, b).compile().cost_analysis()
    if isinstance(an, (list, tuple)):
        an = an[0] if an else {}
    xla = float(an.get("flops", 0.0))
    if xla:  # cost analysis availability varies by backend
        assert got == pytest.approx(xla, rel=0.05)


def test_analytic_bytes_sees_dtype_and_skips_layout_ops():
    """analytic_bytes is the backend-independent byte model behind the
    mixed-precision microbench: fusion-group boundary bytes at the STATED
    aval dtypes (so bf16 halves traffic even where a CPU backend would
    emulate in f32), with pure layout ops (reshape/broadcast/transpose)
    free."""
    import jax.numpy as jnp

    from fedtpu.obs.profile import analytic_bytes

    def f(a, b):
        return a @ b

    a32 = jnp.ones((64, 128), jnp.float32)
    b32 = jnp.ones((128, 32), jnp.float32)
    got = analytic_bytes(f, a32, b32)
    # in (64*128 + 128*32) + out (64*32), 4 bytes each.
    assert got == (64 * 128 + 128 * 32 + 64 * 32) * 4
    a16, b16 = a32.astype(jnp.bfloat16), b32.astype(jnp.bfloat16)
    assert analytic_bytes(f, a16, b16) == got / 2

    def g(a, b):
        # The reshape/broadcast shuffle must add NOTHING over f.
        return a.reshape(64, 128) @ jnp.broadcast_to(b, b.shape)

    assert analytic_bytes(g, a32.reshape(128, 64), b32) == got


def test_analytic_bytes_fuses_elementwise_chains():
    """The model charges fusion-GROUP boundaries, not per-eqn I/O: a chain
    of elementwise ops is one pass over the data (intermediates are
    register traffic), and a reduction fuses with its producers but its
    output materializes. Without this, the f32 intermediates of e.g. a
    BatchNorm statistics chain would be charged at 5x activation size —
    biasing the model against the bf16 residency lever it exists to
    measure (tests the rationale in fedtpu/obs/profile.py)."""
    import jax.numpy as jnp

    from fedtpu.obs.profile import analytic_bytes

    a = jnp.ones((256, 128), jnp.float32)
    b = jnp.ones((256, 128), jnp.float32)

    def chain(a, b):
        return jnp.exp(a) * b + a

    # ONE group: reads {a, b}, writes {out} — the exp/mul intermediates
    # never count, and a's two uses inside the group charge once.
    n = 256 * 128 * 4
    assert analytic_bytes(chain, a, b) == 3 * n

    def stat(a):
        # square-then-reduce (the BN statistics shape): the reduce fuses
        # with its producers, so the whole chain is reads {a} + the tiny
        # reduced write.
        return jnp.square(a).sum(axis=0)

    assert analytic_bytes(stat, a) == n + 128 * 4

    def reduce_then_use(a):
        # A reduction OUTPUT materializes: its consumer starts a new pass,
        # re-reading both the reduced row and the full input.
        s = a.sum(axis=0)
        return a * s

    # group1 {sum}: read a, write s; group2 {mul}: read a + s, write out.
    assert analytic_bytes(reduce_then_use, a) == 2 * n + 2 * (128 * 4) + n


def test_cost_model_carries_analytic_bytes():
    cm = CostModel(xla_flops=1e10, xla_bytes=1e9, analytic=1.0e10,
                   analytic_bytes=8e8)
    assert cm.analytic_bytes == 8e8
    assert cm.as_dict()["analytic_bytes_per_round"] == 8e8
    # Optional: absent stays schema-stable None.
    cm = CostModel(xla_flops=None, xla_bytes=None, analytic=5e9)
    assert cm.analytic_bytes is None
    assert cm.as_dict()["analytic_bytes_per_round"] is None


# ----------------------------------------------------------- round profiler
def test_round_profiler_gauges_and_record_fields(monkeypatch):
    monkeypatch.setenv("FEDTPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("FEDTPU_PEAK_HBM_BYTES", "5e10")
    tel = Telemetry("basic")
    prof = RoundProfiler(tel, n_devices=2, device_kind="cpu")
    # Before a cost model: step-time only; no MFU stamps on records.
    out = prof.observe_round(0.5)
    assert out["step_time_s"] == 0.5
    assert out["achieved_flops_per_s"] is None and out["mfu"] is None
    assert prof.record_fields() == {}
    prof.set_cost_model(
        CostModel(xla_flops=1e10, xla_bytes=1e9, analytic=1.01e10)
    )
    out = prof.observe_round(0.5, rounds=5)
    assert out["step_time_s"] == pytest.approx(0.1)
    assert out["achieved_flops_per_s"] == pytest.approx(1e11)
    # MFU normalizes by ALL devices: 1e11 / (2 * 1e12).
    assert out["mfu"] == pytest.approx(0.05)
    fields = prof.record_fields()
    assert fields["mfu"] == pytest.approx(0.05)
    assert fields["achieved_flops_per_s"] == pytest.approx(1e11)
    parsed = parse_prometheus_text(prometheus_text(tel.registry))
    assert parsed["fedtpu_mfu_ratio"][""] == pytest.approx(0.05)
    assert parsed["fedtpu_step_time_seconds"][""] == pytest.approx(0.1)
    assert parsed["fedtpu_achieved_flops_per_sec"][""] == pytest.approx(1e11)
    snap = prof.snapshot()
    assert snap["mfu"] == pytest.approx(0.05)
    assert snap["flops_source"] == "xla"
    # Roofline keys merge flat into the /statusz perf block: intensity
    # 10 FLOP/B vs ridge 20 -> bandwidth-bound; per-chip achieved 5e10
    # against a 5e11 ceiling at that intensity.
    assert snap["roofline_bound"] == "bandwidth"
    assert snap["roofline_utilization"] == pytest.approx(0.1)


def test_cost_model_prefers_xla_and_reports_agreement():
    cm = CostModel(xla_flops=1e10, xla_bytes=1e9, analytic=1.02e10)
    assert cm.flops == 1e10 and cm.source == "xla"
    assert cm.agreement == pytest.approx(1.02)
    d = cm.as_dict()
    assert d["flops_source"] == "xla"
    assert d["analytic_vs_xla"] == pytest.approx(1.02)
    cm = CostModel(xla_flops=None, xla_bytes=None, analytic=5e9)
    assert cm.flops == 5e9 and cm.source == "analytic"
    assert cm.agreement is None


def test_engine_round_records_and_statusz_carry_mfu(monkeypatch):
    """Acceptance: per-round MFU lands on v1 round records and /statusz
    when accounting is enabled — at a seconds-scale engine config."""
    monkeypatch.setenv("FEDTPU_PEAK_FLOPS", "1e12")
    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation

    cfg = RoundConfig(
        model="mlp", num_classes=10,
        data=DataConfig(dataset="synthetic", batch_size=8, num_examples=64),
        fed=FedConfig(num_clients=2, num_rounds=2, telemetry="basic"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)
    fed.enable_mfu_accounting(xla_check=False)
    assert fed.profiler is not None and fed.profiler.cost is not None

    recs = []

    class _Recorder:
        def log(self, r, **rec):
            recs.append(rec)

    fed.run(num_rounds=2, logger=_Recorder())
    assert len(recs) == 2
    for rec in recs:
        assert rec["mfu"] > 0
        assert rec["achieved_flops_per_s"] > 0
    snap = fed.status_snapshot()
    assert snap["perf"]["mfu"] > 0
    assert snap["perf"]["flops_per_round"] > 0


# -------------------------------------------------------- latency summary
def test_latency_summary_percentiles_and_slowest():
    assert latency_summary([]) == {}
    pairs = [(f"c{i}", (i + 1) / 100.0) for i in range(100)]
    lat = latency_summary(pairs)
    assert lat["n"] == 100
    assert lat["p50_s"] == pytest.approx(0.50)
    assert lat["p95_s"] == pytest.approx(0.95)
    assert lat["p99_s"] == pytest.approx(0.99)
    assert lat["max_s"] == pytest.approx(1.00)
    assert [c for c, _s in lat["slowest"]] == ["c99", "c98", "c97"]
    # Fewer clients than top-k: everyone listed, worst first.
    lat = latency_summary([("a", 0.2), ("b", 0.7)])
    assert lat["p50_s"] == pytest.approx(0.2)
    assert [c for c, _s in lat["slowest"]] == ["b", "a"]


# ------------------------------------------------------- compile watcher
def test_compile_watcher_counts_and_flags_steady_recompiles():
    import jax
    import jax.numpy as jnp

    tel = Telemetry("basic")
    watcher = CompileWatcher(telemetry=tel)
    watcher.install()
    try:
        # Second concurrent watcher is a bug, not a silent double-count.
        with pytest.raises(RuntimeError):
            CompileWatcher().install()
        jax.jit(lambda x: x * 2 + 1)(jnp.ones((7, 3))).block_until_ready()
        snap = watcher.snapshot()
        assert snap["compiles"] >= 1
        assert snap["compile_seconds"] > 0
        assert snap["steady"] is False
        assert snap["recompiles_after_steady"] == 0
        watcher.mark_steady()
        before = watcher.snapshot()["compiles"]
        # A fresh shape after steady state = the recompile failure mode.
        jax.jit(lambda x: x * 2 + 1)(jnp.ones((3, 7))).block_until_ready()
        snap = watcher.snapshot()
        assert snap["steady"] is True
        assert snap["compiles"] > before
        assert snap["recompiles_after_steady"] >= 1
        parsed = parse_prometheus_text(prometheus_text(tel.registry))
        assert parsed["fedtpu_xla_compiles_total"][""] == snap["compiles"]
        assert (parsed["fedtpu_xla_recompiles_steady_total"][""]
                == snap["recompiles_after_steady"])
    finally:
        watcher.uninstall()
    # Uninstalled: a new watcher can install again.
    w2 = CompileWatcher()
    w2.install()
    w2.uninstall()


# ------------------------------------------------------- capture windows
def test_parse_round_window():
    assert parse_round_window("3:7") == (3, 7)
    assert parse_round_window("5") == (5, 6)
    assert parse_round_window(" 0:2 ") == (0, 2)
    for bad in ("", "a:b", "4:", "7:3", "-1:2"):
        with pytest.raises(ValueError):
            parse_round_window(bad)


def test_profile_meta_sidecar_roundtrip(tmp_path):
    d = str(tmp_path / "trace")
    write_profile_meta(d, role="engine", trace_id="abc123",
                       extra={"round_window": [1, 3]})
    with open(os.path.join(d, "profile_meta.json")) as fh:
        meta = json.load(fh)
    assert meta["role"] == "engine"
    assert meta["trace_id"] == "abc123"
    assert meta["round_window"] == [1, 3]
    assert meta["wall_start"] > 0
    assert meta["format"] == "jax.profiler"


# ------------------------------------------- trace_merge device ingestion
def _tpu_device_doc(wall_start=None):
    """Synthetic jax.profiler-shaped Chrome doc: TPU lanes are processes
    whose name carries '/device:TPU:N'."""
    events = [
        {"ph": "M", "name": "process_name", "pid": 10,
         "args": {"name": "/device:TPU:0 (fake)"}},
        {"ph": "M", "name": "process_name", "pid": 11,
         "args": {"name": "host threads"}},
        {"ph": "X", "pid": 10, "tid": 1, "name": "fusion.1",
         "ts": 100.0, "dur": 50.0},
        {"ph": "X", "pid": 10, "tid": 1, "name": "fusion.2",
         "ts": 200.0, "dur": 25.0},
        {"ph": "X", "pid": 11, "tid": 5, "name": "py_thing",
         "ts": 100.0, "dur": 10.0},
    ]
    doc = {"traceEvents": events, "metadata": {"role": "engine"}}
    if wall_start is not None:
        doc["metadata"]["wall_start"] = wall_start
    return doc


def _cpu_device_doc():
    """CPU-backend shape: no /device: process, XLA ops live on threads
    named tf_XLA..."""
    events = [
        {"ph": "M", "name": "thread_name", "pid": 20, "tid": 7,
         "args": {"name": "tf_XLA_CPU_worker"}},
        {"ph": "M", "name": "thread_name", "pid": 20, "tid": 8,
         "args": {"name": "main"}},
        {"ph": "X", "pid": 20, "tid": 7, "name": "convolution",
         "ts": 10.0, "dur": 5.0},
        {"ph": "X", "pid": 20, "tid": 8, "name": "python", "ts": 0.0,
         "dur": 100.0},
    ]
    return {"traceEvents": events, "metadata": {"role": "engine"}}


def _host_doc(wall_start=1000.0):
    return {
        "traceEvents": [
            {"ph": "X", "pid": 1, "tid": 1, "name": "round",
             "ts": 0.0, "dur": 500.0, "args": {"span_id": 1}},
        ],
        "metadata": {"role": "engine", "wall_start": wall_start,
                     "trace_id": "t1", "pid": 123},
    }


def test_extract_device_lanes_tpu_and_cpu_shapes():
    lanes = trace_merge.extract_device_lanes(_tpu_device_doc())
    assert len(lanes) == 1
    name, evs = lanes[0]
    assert "/device:TPU:0" in name
    assert [e["name"] for e in evs] == ["fusion.1", "fusion.2"]
    lanes = trace_merge.extract_device_lanes(_cpu_device_doc())
    assert len(lanes) == 1
    name, evs = lanes[0]
    assert name == "XLA:CPU"
    assert [e["name"] for e in evs] == ["convolution"]
    # No device-looking content at all -> no lanes, no crash.
    assert trace_merge.extract_device_lanes(
        {"traceEvents": [{"ph": "X", "pid": 1, "name": "x", "ts": 0,
                          "dur": 1}]}
    ) == []


def test_merge_docs_fuses_device_lane_with_wall_alignment():
    host = _host_doc(wall_start=1000.0)
    dev = _tpu_device_doc(wall_start=1000.25)  # device session opens 250ms in
    merged = trace_merge.merge_docs([host], device_docs=[dev])
    evs = merged["traceEvents"]
    device_evs = [e for e in evs if e.get("cat") == "device"]
    host_evs = [e for e in evs if e.get("ph") == "X"
                and e.get("cat") != "device"]
    assert len(device_evs) == 2 and len(host_evs) == 1
    # Wall alignment: device ts are shifted onto the host clock.
    f1 = next(e for e in device_evs if e["name"] == "fusion.1")
    assert f1["ts"] == pytest.approx(250000.0 + 100.0)
    # The device lane is its own pid with a named process, after host lanes.
    assert {e["pid"] for e in device_evs} != {e["pid"] for e in host_evs}
    lanes = merged["metadata"]["device_lanes"]
    assert len(lanes) == 1 and "/device:TPU:0" in lanes[0]
    names = [
        e["args"]["name"] for e in evs
        if e.get("ph") == "M" and e.get("name") == "process_name"
    ]
    assert any("/device:TPU:0" in n for n in names)


def test_merge_docs_tolerates_empty_device_trace():
    merged = trace_merge.merge_docs(
        [_host_doc()],
        device_docs=[{"traceEvents": [], "metadata": {}}],
    )
    assert merged["metadata"]["device_lanes"] == []
    assert all(e.get("cat") != "device" for e in merged["traceEvents"])


# --------------------------------------------------------- gap analysis
def _merged_doc_with_gaps():
    """One device lane busy [0,100] and [1100,1200] and [1250,1300] (us):
    a 1000us gap and a 50us gap. Host spans: 'round' covers everything;
    'h2d' (nested) covers [100, 700] — the deepest span over most of the
    big gap."""
    evs = [
        {"ph": "X", "pid": 1, "tid": 1, "name": "round", "ts": 0.0,
         "dur": 1300.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "h2d", "ts": 100.0,
         "dur": 600.0},
        {"ph": "X", "pid": 9, "tid": 1, "name": "fusion", "cat": "device",
         "ts": 0.0, "dur": 100.0},
        {"ph": "X", "pid": 9, "tid": 1, "name": "fusion", "cat": "device",
         "ts": 1100.0, "dur": 100.0},
        {"ph": "X", "pid": 9, "tid": 1, "name": "fusion", "cat": "device",
         "ts": 1250.0, "dur": 50.0},
    ]
    return {"traceEvents": evs, "metadata": {}}


def test_gap_analyze_ranks_gaps_and_attributes_to_deepest_span():
    report = gap_analyze.analyze(_merged_doc_with_gaps(), min_gap_us=10.0)
    assert report["device_lanes"] == 1
    assert report["n_gaps"] == 2
    assert report["window_us"] == pytest.approx(1300.0)
    assert report["device_busy_us"] == pytest.approx(250.0)
    assert report["idle_fraction"] == pytest.approx(1050.0 / 1300.0, abs=1e-3)
    # Longest gap first.
    top = report["gaps"][0]
    assert top["dur_us"] == pytest.approx(1000.0)
    assert (top["start_us"], top["end_us"]) == (100.0, 1100.0)
    assert report["gaps"][1]["dur_us"] == pytest.approx(50.0)
    # Attribution: the DEEPEST host phase over the gap wins its share —
    # h2d claims [100,700], the enclosing round only the uncovered rest.
    rows = {r["span"]: r["us"] for r in top["attribution"]}
    assert rows["h2d"] == pytest.approx(600.0)
    assert rows["round"] == pytest.approx(400.0)
    assert top["attribution"][0]["span"] == "h2d"  # charged-most first
    assert top["unattributed_us"] == pytest.approx(0.0)
    # Aggregate table mirrors the per-gap charges (small gap -> round too).
    by_phase = {r["span"]: r["us"] for r in report["by_phase"]}
    assert by_phase["h2d"] == pytest.approx(600.0)
    assert by_phase["round"] == pytest.approx(450.0)


def test_gap_analyze_reports_unattributed_idle():
    doc = _merged_doc_with_gaps()
    # Shrink the round span so [900, 1100) of the big gap is uncovered.
    doc["traceEvents"][0]["dur"] = 900.0
    report = gap_analyze.analyze(doc, min_gap_us=10.0)
    top = report["gaps"][0]
    assert top["unattributed_us"] == pytest.approx(200.0)
    by_phase = {r["span"]: r["us"] for r in report["by_phase"]}
    assert by_phase["(unattributed)"] == pytest.approx(250.0)


def test_gap_analyze_tolerates_timeline_without_device_ops():
    report = gap_analyze.analyze(_host_doc())
    assert report["device_lanes"] == 0
    assert report["n_gaps"] == 0
    assert report["window_us"] is None
    assert report["device_busy_us"] == 0.0


def test_gap_analyze_roofline_stamp(tmp_path):
    """--roofline: recomputes placement from a profile artifact's
    flops/bytes rows through obs.profile.roofline — the gap report then
    answers both idle attribution AND what the busy time is limited by."""
    profile = {
        "configs": [{
            "batch": 128, "device_kind": "TPU v5 lite",
            "flops_per_round": 276329529344.0,
            "bytes_per_round": 14553602048.0,
            "rounds_per_sec": 9.333, "mfu": 0.0131,
        }]
    }
    path = tmp_path / "profile.json"
    path.write_text(json.dumps(profile))
    stamp = gap_analyze.roofline_stamp(str(path))
    assert stamp["profile_artifact"] == str(path)
    (row,) = stamp["rows"]
    assert row["roofline_bound"] == "bandwidth"
    assert row["arith_intensity_flops_per_byte"] == pytest.approx(
        18.99, abs=0.01)
    assert row["ridge_point_flops_per_byte"] == pytest.approx(
        240.54, abs=0.01)
    # Achieved rate present -> utilization filled (the r04 hbm_util ~0.166).
    assert row["roofline_utilization"] == pytest.approx(0.166, abs=0.01)
    # Flat dict (microbench analytic row) also accepted; no achieved rate
    # -> utilization stays None.
    flat = tmp_path / "flat.json"
    flat.write_text(json.dumps({
        "flops_per_round": 1e9, "bytes_per_round": 1e9,
        "device_kind": "TPU v5 lite",
    }))
    (frow,) = gap_analyze.roofline_stamp(str(flat))["rows"]
    assert frow["roofline_bound"] == "bandwidth"
    assert frow["roofline_utilization"] is None


def test_gap_report_committed_artifact_contract():
    """The committed GAP_REPORT.json came from a real --profile-rounds
    densenet CPU capture piped through trace_merge --device-trace."""
    path = os.path.join(REPO, "artifacts", "GAP_REPORT.json")
    assert os.path.exists(path), "artifacts/GAP_REPORT.json missing"
    with open(path) as fh:
        report = json.load(fh)
    assert report["schema_version"] == gap_analyze.SCHEMA_VERSION
    assert report["device_lanes"] >= 1
    assert report["device_ops"] > 0
    assert 0.0 <= report["idle_fraction"] <= 1.0
    for gap in report["gaps"]:
        assert gap["dur_us"] >= report["min_gap_us"]


# ------------------------------------------------------- metric-name drift
def test_span_check_polices_metric_names(tmp_path):
    # Tier-1 enforcement for the real tree: every emitted fedtpu_* metric
    # is documented (the span half is asserted in test_obs_propagation).
    assert span_check.check_metrics() == []
    # Drift detection: an undocumented metric in a synthetic package.
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'tel.gauge("fedtpu_fake_metric", "help").set(1)\n'
        'tel.counter("fedtpu_documented_total").inc()\n'
    )
    doc = tmp_path / "OBS.md"
    doc.write_text("| `fedtpu_documented_total` | fine |\n")
    problems = span_check.check_metrics(str(pkg), str(doc))
    assert len(problems) == 1
    assert "fedtpu_fake_metric" in problems[0]
    # Labeled doc mentions document the base name.
    doc.write_text("`fedtpu_documented_total` `fedtpu_fake_metric{x=\"y\"}`")
    assert span_check.check_metrics(str(pkg), str(doc)) == []


# ------------------------------------------------------------ perf CI
def test_perf_ci_check_passes_on_committed_baseline(monkeypatch):
    """The tier-1 perf gate itself: measure the live tree and compare
    against the committed baseline — a real regression in any per-round
    instrument fails this test."""
    monkeypatch.delenv("FEDTPU_PERF_CI_INJECT", raising=False)
    monkeypatch.setenv("FEDTPU_PERF_CI_REPS", "3")
    with open(os.path.join(REPO, "artifacts", "PERF_BASELINE.json")) as fh:
        baseline = json.load(fh)
    assert baseline["schema_version"] == perf_ci.SCHEMA_VERSION
    measured = perf_ci.measure()
    assert set(measured["metrics"]) == set(baseline["metrics"])
    verdict = perf_ci.compare(measured, baseline)
    assert verdict["pass"] is True, verdict["failures"]
    assert 0.25 <= verdict["calibration_scale"] <= 4.0


def test_perf_ci_detects_2x_slowdown():
    """Acceptance: --check demonstrably fails on a 2x slowdown. Pinned at
    the compare layer with controlled noise floors so the verdict is
    deterministic, not a race against scheduler jitter."""
    base = {
        "schema_version": perf_ci.SCHEMA_VERSION,
        "metrics": {
            "calibration_us": {"median_us": 100.0, "noise_floor_pct": 5.0},
            "mfu_observe_us": {"median_us": 5.0, "noise_floor_pct": 5.0},
            "span_trace_us": {"median_us": 6.0, "noise_floor_pct": 5.0},
        },
    }
    good = json.loads(json.dumps(base))
    verdict = perf_ci.compare(good, base)
    assert verdict["pass"] is True
    slow = json.loads(json.dumps(base))
    slow["metrics"]["mfu_observe_us"]["median_us"] = 10.0  # the 2x
    verdict = perf_ci.compare(slow, base)
    assert verdict["pass"] is False
    assert [f["metric"] for f in verdict["failures"]] == ["mfu_observe_us"]
    f = verdict["failures"][0]
    assert f["measured_us"] == 10.0 and f["measured_us"] > f["limit_us"]
    # Dropping a metric from the harness is drift too, not a free pass.
    gone = json.loads(json.dumps(base))
    del gone["metrics"]["span_trace_us"]
    verdict = perf_ci.compare(gone, base)
    assert verdict["pass"] is False
    assert "disappeared" in verdict["failures"][0]["problem"]


def test_perf_ci_injection_hook_inflates_measurements(monkeypatch):
    metrics = {
        "mfu_observe_us": {"median_us": 5.0, "noise_floor_pct": 5.0},
        "span_trace_us": {"median_us": 6.0, "noise_floor_pct": 5.0},
    }
    monkeypatch.setenv("FEDTPU_PERF_CI_INJECT", "mfu_observe_us=2.0")
    perf_ci._apply_injection(metrics)
    assert metrics["mfu_observe_us"]["median_us"] == 10.0
    assert metrics["mfu_observe_us"]["injected_factor"] == 2.0
    assert metrics["span_trace_us"]["median_us"] == 6.0
    monkeypatch.setenv("FEDTPU_PERF_CI_INJECT", "all=2.0")
    perf_ci._apply_injection(metrics)
    assert metrics["span_trace_us"]["median_us"] == 12.0


def test_perf_ci_check_cli_fails_on_injected_slowdown(tmp_path, monkeypatch):
    """End-to-end --check exit codes: pass against a just-measured
    baseline, fail when the injection hook doubles a low-noise metric."""
    monkeypatch.delenv("FEDTPU_PERF_CI_INJECT", raising=False)
    monkeypatch.setenv("FEDTPU_PERF_CI_REPS", "2")
    measured = perf_ci.measure()
    # Pin noise floors so the band is exactly the 75% minimum: this keeps
    # the CLI-level assertion deterministic while the measurement itself
    # stays real.
    for row in measured["metrics"].values():
        row["noise_floor_pct"] = 5.0
    path = str(tmp_path / "baseline.json")
    perf_ci.write_baseline(measured, path)
    verdict = perf_ci.compare(measured, json.loads(open(path).read()))
    assert verdict["pass"] is True
    # Inject on specific metrics, NOT "all=": all= also doubles the
    # calibration yardstick and partially neutralizes the check.
    injected = json.loads(json.dumps(measured))
    monkeypatch.setenv(
        "FEDTPU_PERF_CI_INJECT",
        "mfu_observe_us=2.0,counter_inc_us=2.0",
    )
    perf_ci._apply_injection(injected["metrics"])
    verdict = perf_ci.compare(injected, json.loads(open(path).read()))
    assert verdict["pass"] is False
    assert {f["metric"] for f in verdict["failures"]} == {
        "mfu_observe_us", "counter_inc_us",
    }


def test_perf_baseline_committed_artifact_contract():
    path = os.path.join(REPO, "artifacts", "PERF_BASELINE.json")
    assert os.path.exists(path), "artifacts/PERF_BASELINE.json missing"
    with open(path) as fh:
        baseline = json.load(fh)
    assert baseline["schema_version"] == perf_ci.SCHEMA_VERSION
    expected = {
        "calibration_us", "span_trace_us", "counter_inc_us", "gauge_set_us",
        "histogram_observe_us", "mfu_observe_us", "latency_summary_us",
        "round_record_us", "prometheus_render_us", "trace_merge_us",
        "gap_analyze_us", "mixed_precision_cast_us", "megabatch_reshape_us",
        "partial_reduce_fold_us", "submit_partial_frame_us",
        "hadamard_rotate_us", "randk_gather_us",
    }
    assert set(baseline["metrics"]) == expected
    for row in baseline["metrics"].values():
        assert row["median_us"] > 0
        assert row["noise_floor_pct"] >= 0


def test_mfu_microbench_committed_gate():
    """The committed densenet-scale artifact must actually pass the <=1%
    gate: per-round MFU accounting cost over the bare round wall."""
    path = os.path.join(REPO, "artifacts", "MFU_ACCOUNTING_MICROBENCH.json")
    assert os.path.exists(path), "MFU_ACCOUNTING_MICROBENCH.json missing"
    with open(path) as fh:
        result = json.load(fh)
    assert result["metric"] == "mfu_accounting_overhead"
    assert result["model"] == "densenet_cifar"
    assert result["passes_gate"] is True
    assert result["value"] <= 1.0
    assert result["flops_per_round"] > 0
