"""Bit-parity pins for the TPU-shaped reformulations of round 4.

The round-4 on-chip traces (``artifacts/MFU_PROFILE_r04*.json``) drove three
rewrites of ops whose naive forms lower to serial per-example loops on
XLA:TPU. Each rewrite claims BIT-IDENTITY with the naive formulation; these
tests pin that claim on CPU (the claim is dtype/arithmetic-level, not
backend-level — every term is an exact 1.0/0.0 selection):

* shift-accumulate random crop vs the ``vmap(dynamic_slice)`` original
  (``fedtpu/data/augment.py``),
* dense-label CE vs ``optax.softmax_cross_entropy_with_integer_labels``
  (``fedtpu/ops/losses.py``; forward and gradients within tight float
  tolerance — softmax accumulation order differs, <= 5e-10 observed),
* the opt-in tiled max-pool vs ``nn.max_pool`` incl. first-max tie routing
  (``fedtpu/models/common.py``; kept as a measured negative, so its
  correctness must not rot).

Reference behaviors pinned: torchvision's RandomCrop(32, padding=4) +
RandomHorizontalFlip (``/root/reference/src/main.py:37-42``), torch
``nn.CrossEntropyLoss`` (``src/main.py:77``), torch ``MaxPool2d`` first-max
tie gradients.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
import flax.linen as nn

from fedtpu.data.augment import augment_batch
from fedtpu.models.common import _tiled_max_pool
from fedtpu.ops.losses import softmax_ce_int_labels


def _augment_oracle(rng, x, pad=4):
    """The original per-example dynamic-slice formulation (serial on TPU)."""
    n, h, w, c = x.shape
    crop_rng, flip_rng = jax.random.split(rng)
    padded = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    offs = jax.random.randint(crop_rng, (n, 2), 0, 2 * pad + 1)
    crop = jax.vmap(
        lambda img, off: jax.lax.dynamic_slice(
            img, (off[0], off[1], 0), (h, w, c)
        )
    )(padded, offs)
    flip = jax.random.bernoulli(flip_rng, 0.5, (n,))
    return jnp.where(flip[:, None, None, None], crop[:, :, ::-1, :], crop)


def test_shift_accumulate_crop_bitwise_matches_dynamic_slice():
    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(jax.random.PRNGKey(1), (33, 32, 32, 3), jnp.float32)
    a = _augment_oracle(rng, x)
    b = augment_batch(rng, x)
    assert a.shape == b.shape == x.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_shift_accumulate_crop_bitwise_in_bf16():
    # The pre-augment cast relies on the selection being exact in ANY dtype.
    rng = jax.random.PRNGKey(11)
    x = jax.random.normal(jax.random.PRNGKey(2), (9, 32, 32, 3))
    a = _augment_oracle(rng, x.astype(jnp.bfloat16))
    b = augment_batch(rng, x.astype(jnp.bfloat16))
    np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)
    )


def test_dense_label_ce_matches_optax_integer_labels():
    logits = 5.0 * jax.random.normal(jax.random.PRNGKey(3), (128, 100))
    y = jax.random.randint(jax.random.PRNGKey(4), (128,), 0, 100)
    ours = softmax_ce_int_labels(logits, y)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    np.testing.assert_allclose(
        np.asarray(ours), np.asarray(ref), rtol=0, atol=1e-5
    )
    g_ours = jax.grad(lambda l: softmax_ce_int_labels(l, y).mean())(logits)
    g_ref = jax.grad(
        lambda l: optax.softmax_cross_entropy_with_integer_labels(l, y).mean()
    )(logits)
    # Not bit-equal at every shape (softmax accumulation order differs);
    # observed max deviation 5e-10 on one element in 12.8k.
    np.testing.assert_allclose(
        np.asarray(g_ours), np.asarray(g_ref), rtol=0, atol=1e-8
    )


@pytest.mark.parametrize("k,shape", [(2, (3, 8, 8, 5)), (4, (2, 8, 8, 3))])
def test_tiled_max_pool_matches_reduce_window(k, shape):
    x = jax.random.normal(jax.random.PRNGKey(5), shape)
    ref_pool = lambda x: nn.max_pool(
        x, (k, k), strides=(k, k), padding="VALID"
    )
    np.testing.assert_array_equal(
        np.asarray(_tiled_max_pool(x, k)), np.asarray(ref_pool(x))
    )
    g1 = jax.grad(lambda x: (_tiled_max_pool(x, k) ** 2).sum())(x)
    g2 = jax.grad(lambda x: (ref_pool(x) ** 2).sum())(x)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_tiled_max_pool_tie_routing_exhaustive():
    # Every 0/1 pattern of a 2x2 window: cotangent must go to the FIRST max
    # in row-major order, exactly like select_and_scatter / torch MaxPool2d.
    ref_pool = lambda x: nn.max_pool(
        x, (2, 2), strides=(2, 2), padding="VALID"
    )
    for bits in itertools.product([0.0, 1.0], repeat=4):
        x = jnp.array(bits).reshape(1, 2, 2, 1)
        a = jax.grad(lambda x: _tiled_max_pool(x, 2).sum())(x)
        b = jax.grad(lambda x: ref_pool(x).sum())(x)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=f"tie pattern {bits}"
        )


def test_tiled_max_pool_vmap():
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 3, 8, 8, 4))
    out = jax.vmap(lambda x: _tiled_max_pool(x, 2))(x)
    ref = jax.vmap(
        lambda x: nn.max_pool(x, (2, 2), strides=(2, 2), padding="VALID")
    )(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
