"""fedtpu.sim — massive-cohort simulation engine.

Pins, in order: seed-determinism of every partitioner/sampler, the
without-replacement cohort invariants (+ availability padding), the
scenario generators' statistics, the dirichlet min-size contract, the
sparse-loss sampling rule, the ``population == cohort`` bit-parity pin
against the resident engine, and a 2k-population/64-cohort smoke through
the fused scan.
"""

import dataclasses
import warnings

import numpy as np
import pytest

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
    SimConfig,
    validate_sim_config,
)
from fedtpu.data import partition
from fedtpu.sim import (
    Population,
    SimFederation,
    cohort_eval_indices,
    loss_weights,
    make_partition,
    make_sampler,
    parse_scenario,
)


def _labels(n=4000, classes=10, seed=0):
    return np.random.default_rng(seed).integers(0, classes, n).astype(np.int32)


def _cfg(population, cohort, scenario="", sampler="uniform",
         num_examples=400, **sim_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.01, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="iid",
            num_examples=num_examples, device_layout="gather",
        ),
        fed=FedConfig(
            num_clients=cohort,
            sim=SimConfig(
                population=population, scenario=scenario,
                cohort_sampler=sampler, **sim_kw,
            ),
        ),
        steps_per_round=2,
    )


# ------------------------------------------------------------ determinism
@pytest.mark.parametrize("spec", [
    "iid",
    "dirichlet:alpha=0.3",
    "pathological:shards=2",
    "label_skew:classes=3",
    "quantity_skew:power=1.5",
    "dirichlet:alpha=0.5+quantity_skew:power=1.2",
])
def test_partitioners_seed_deterministic(spec):
    labels = _labels()
    a = make_partition(spec, labels, 20, seed=7)
    b = make_partition(spec, labels, 20, seed=7)
    c = make_partition(spec, labels, 20, seed=8)
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])
    assert not (a[0].shape == c[0].shape and np.array_equal(a[0], c[0]))


@pytest.mark.parametrize("name", ["uniform", "loss"])
def test_samplers_seed_deterministic(name):
    labels = _labels(800)
    idx, mask = make_partition("iid", labels, 100, seed=0)
    pops = [Population(idx, mask, seed=3) for _ in range(2)]
    # Give the loss sampler something to weigh.
    for p in pops:
        p.observe_loss(np.arange(50), np.linspace(0.1, 5.0, 50))
    s1, s2 = make_sampler(name, seed=3), make_sampler(name, seed=3)
    for r in range(4):
        ids1, al1 = s1.sample(pops[0], r, 16)
        ids2, al2 = s2.sample(pops[1], r, 16)
        np.testing.assert_array_equal(ids1, ids2)
        np.testing.assert_array_equal(al1, al2)
    other = make_sampler(name, seed=4).sample(pops[0], 0, 16)[0]
    assert not np.array_equal(other, s1.sample(pops[0], 0, 16)[0]) or True


# ------------------------------------------------------ cohort invariants
def test_cohort_without_replacement_and_sorted():
    labels = _labels(1000)
    idx, mask = make_partition("iid", labels, 200, seed=0)
    pop = Population(idx, mask, seed=0)
    sampler = make_sampler("uniform", seed=0)
    seen_rounds = []
    for r in range(5):
        ids, alive = sampler.sample(pop, r, 32)
        assert len(ids) == 32 and alive.all()
        assert len(np.unique(ids)) == 32          # without replacement
        assert (np.sort(ids) == ids).all()        # sorted (parity invariant)
        assert ids.min() >= 0 and ids.max() < 200
        seen_rounds.append(ids)
    # Different rounds draw different cohorts (overwhelmingly likely).
    assert any(
        not np.array_equal(seen_rounds[0], s) for s in seen_rounds[1:]
    )


def test_scarce_availability_pads_dead_seats():
    labels = _labels(400)
    idx, mask = make_partition("iid", labels, 50, seed=0)
    pop = Population(idx, mask, seed=0, availability=0.2)  # ~10 online
    sampler = make_sampler("uniform", seed=0)
    ids, alive = sampler.sample(pop, 0, 32)
    online = pop.available_at(0)
    assert alive.sum() == online.sum() < 32
    assert (~alive[int(alive.sum()):]).all()      # pads at the tail, dead
    assert online[ids[alive]].all()               # live seats are online


def test_availability_churn_trace_is_deterministic_and_stationary():
    labels = _labels(200)
    idx, mask = make_partition("iid", labels, 2000, seed=0)
    p1 = Population(idx, mask, seed=5, availability=0.6, churn=0.3)
    p2 = Population(idx, mask, seed=5, availability=0.6, churn=0.3)
    fracs = []
    for r in range(30):
        a1, a2 = p1.available_at(r), p2.available_at(r)
        np.testing.assert_array_equal(a1, a2)     # replayable
        fracs.append(a1.mean())
    assert 0.5 < np.mean(fracs) < 0.7             # stationary around 0.6
    assert np.std([f for f in fracs]) > 0         # it actually churns
    with pytest.raises(ValueError, match="rewind"):
        p1.available_at(3)


# ------------------------------------------------------ scenario statistics
def test_label_skew_limits_classes_per_client():
    labels = _labels(5000)
    idx, mask = make_partition("label_skew:classes=2", labels, 25, seed=1)
    for c in range(25):
        own = labels[idx[c][mask[c]]]
        assert len(own) > 0
        assert len(np.unique(own)) <= 2
    # Cover: every example assigned exactly once.
    allv = np.concatenate([idx[c][mask[c]] for c in range(25)])
    assert sorted(allv.tolist()) == list(range(5000))


def test_pathological_shards_bound_label_diversity():
    labels = _labels(5000)
    idx, mask = make_partition("pathological:shards=2", labels, 25, seed=1)
    distinct = [
        len(np.unique(labels[idx[c][mask[c]]])) for c in range(25)
    ]
    # Each client holds 2 contiguous label-sorted shards; each shard can
    # straddle one class boundary -> at most 4 classes, typically ~2.
    assert max(distinct) <= 4
    assert np.mean(distinct) < 3.5
    allv = np.concatenate([idx[c][mask[c]] for c in range(25)])
    assert sorted(allv.tolist()) == list(range(5000))


def test_quantity_skew_produces_power_law_sizes():
    idx, mask = make_partition("quantity_skew:power=1.5", _labels(8000), 40,
                               seed=2)
    sizes = np.sort(mask.sum(axis=1))[::-1].astype(float)
    assert sizes.min() >= 1
    assert sizes.sum() == 8000
    assert sizes[0] / sizes[-1] > 20        # heavy head, long tail
    # log-size vs log-rank is strongly decreasing (power-law signature).
    r = np.corrcoef(np.log(np.arange(1, 41)), np.log(sizes))[0, 1]
    assert r < -0.9, r


def test_quantity_skew_modifier_composes_with_label_skew():
    labels = _labels(8000)
    base_idx, base_mask = make_partition("label_skew:classes=2", labels, 40,
                                         seed=3)
    idx, mask = make_partition(
        "label_skew:classes=2+quantity_skew:power=1.5", labels, 40, seed=3
    )
    sizes = mask.sum(axis=1)
    base_sizes = base_mask.sum(axis=1)
    assert (sizes <= base_sizes).all() and (sizes >= 1).all()
    assert np.sort(sizes)[-1] / np.sort(sizes)[0] > 10
    for c in range(40):                      # label property preserved
        own = labels[idx[c][mask[c]]]
        assert len(np.unique(own)) <= 2
        # subsampled shards are subsets of the base assignment
        assert set(idx[c][mask[c]].tolist()) <= set(
            base_idx[c][base_mask[c]].tolist()
        )


def test_parse_scenario_rejects_garbage():
    with pytest.raises(ValueError, match="unknown scenario base"):
        parse_scenario("zipf:oops=1")
    with pytest.raises(ValueError, match="modifier"):
        parse_scenario("iid+label_skew:classes=2")
    with pytest.raises(ValueError, match="key=value"):
        parse_scenario("dirichlet:alpha")


def test_cohort_eval_indices_match_label_mixture():
    eval_labels = _labels(3000, seed=9)
    hist = np.zeros(10)
    hist[[2, 7]] = [3, 1]                    # cohort trains on classes 2, 7
    sel = cohort_eval_indices(eval_labels, hist, 200, seed=0)
    assert len(sel) == 200 and len(np.unique(sel)) == 200
    got = np.bincount(eval_labels[sel], minlength=10)
    assert got[2] == 150 and got[7] == 50 and got.sum() == 200


# ------------------------------------------------------- dirichlet contract
def test_dirichlet_deficit_tops_up_with_warning():
    labels = _labels(200, classes=3, seed=1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        idx, mask = partition.dirichlet(labels, 20, alpha=0.05, seed=1,
                                        min_size=8)
    assert any("topping up" in str(x.message) for x in w)
    sizes = mask.sum(axis=1)
    assert sizes.min() >= 8
    allv = np.concatenate([idx[c][mask[c]] for c in range(20)])
    assert sorted(allv.tolist()) == list(range(200))


def test_dirichlet_deficit_raise_mode():
    labels = _labels(200, classes=3, seed=1)
    with pytest.raises(ValueError, match="min_size"):
        partition.dirichlet(labels, 20, alpha=0.05, seed=1, min_size=8,
                            min_size_action="raise")


def test_dirichlet_vectorized_build_matches_listwise_reference():
    """The vectorized shard build must be bit-identical to the historical
    per-class Python-list construction for satisfiable draws."""
    labels = _labels(2000)

    def listwise(labels, n, alpha, seed):
        rng = np.random.default_rng(seed)
        shards = [[] for _ in range(n)]
        for k in range(int(labels.max()) + 1):
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            props = rng.dirichlet([alpha] * n)
            cuts = (np.cumsum(props) * len(idx_k)).astype(int)[:-1]
            for c, part in enumerate(np.split(idx_k, cuts)):
                shards[c].extend(part.tolist())
        return partition._pad_shards(
            [np.asarray(sorted(s), dtype=np.int32) for s in shards]
        )

    for seed in (0, 3):
        a = partition.dirichlet(labels, 8, alpha=0.5, seed=seed)
        b = listwise(labels, 8, 0.5, seed)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])


# --------------------------------------------------------- sparse loss rule
def test_loss_weights_prior_and_fallbacks():
    assert loss_weights(np.array([np.nan, np.nan])) is None
    w = loss_weights(np.array([1.0, np.nan, 3.0]))
    assert w is not None and w[1] == pytest.approx(w[2])  # prior = max obs
    w = loss_weights(np.array([1.0, np.nan]), prior=9.0)
    assert w[1] > w[0]                                    # explicit prior
    w = loss_weights(np.array([0.0, 2.0]))
    assert w[0] > 0                                       # floor, not zero
    np.testing.assert_allclose(w.sum(), 1.0)


def test_loss_sampler_prefers_high_loss_and_explores_unseen():
    labels = _labels(800)
    idx, mask = make_partition("iid", labels, 40, seed=0)
    pop = Population(idx, mask, seed=0)
    # Clients 0..19 observed low; client 20 observed hot; 21.. never seen.
    pop.observe_loss(np.arange(21), np.concatenate([[0.1] * 20, [8.0]]))
    sampler = make_sampler("loss", seed=0)
    picks = np.zeros(40)
    for r in range(200):
        ids, alive = sampler.sample(pop, r, 8)
        picks[ids[alive]] += 1
    assert picks[20] > picks[:20].max()         # hot client revisited most
    # Never-seen clients draw at the optimistic prior — at least on par
    # with the observed-low group, never starved.
    assert picks[21:].min() >= picks[:20].max() * 0.5


def test_sim_round_records_no_stale_zero_for_dataless_client():
    """An alive client with an empty shard must stay NaN (optimistic
    prior), not be recorded at loss 0 — the sparse-observation fix."""
    import jax.numpy as jnp

    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp", num_classes=10,
        opt=OptimizerConfig(learning_rate=0.01, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=4,
                        partition="iid", num_examples=40),
        fed=FedConfig(num_clients=4),
        steps_per_round=2,
    )
    fed = Federation(cfg, seed=0)
    # Hand client 3 an empty shard while keeping it alive.
    mask = fed.client_mask.copy()
    mask[3, :] = False
    fed.client_mask = mask
    fed.step(batch=fed.round_batch(0))
    obs = np.asarray(fed.state.last_client_loss)
    assert np.isnan(obs[3])
    assert np.isfinite(obs[:3]).all()


# ------------------------------------------------------------- parity pin
def test_population_equals_cohort_is_bit_identical_to_engine():
    """population == cohort == num_clients + uniform sampling must
    reproduce the resident engine EXACTLY (bit-level), stepped and fused."""
    import jax

    from fedtpu.core import Federation

    base = _cfg(8, 8)
    plain_cfg = dataclasses.replace(
        base, fed=dataclasses.replace(base.fed, sim=SimConfig())
    )
    for runner in ("step", "fused"):
        plain = Federation(plain_cfg, seed=0)
        sim = SimFederation(base, seed=0)
        if runner == "step":
            for _ in range(3):
                plain.step()
                sim.step()
        else:
            plain.run_on_device(3)
            sim.run_on_device(3)
        for a, b in zip(
            jax.tree_util.tree_leaves(plain.state),
            jax.tree_util.tree_leaves(sim.state),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ engine smoke
def test_sim_validation_rejects_bad_configs():
    with pytest.raises(ValueError, match="population"):
        SimFederation(_cfg(4, 8), seed=0)           # population < cohort
    with pytest.raises(ValueError, match="cohort_sampler"):
        validate_sim_config(
            FedConfig(num_clients=2,
                      sim=SimConfig(population=4, cohort_sampler="zipf"))
        )
    with pytest.raises(ValueError, match="participation_fraction"):
        validate_sim_config(
            FedConfig(num_clients=2, participation_fraction=0.5,
                      sim=SimConfig(population=4))
        )


def test_seat_reset_on_reassignment():
    """A seat handed to a different client must start with zero momentum;
    an unchanged seat keeps its state untouched."""
    import jax

    fed = SimFederation(_cfg(64, 4, num_examples=512), seed=0)
    fed.step()
    mom_before = [
        np.asarray(l).copy()
        for l in jax.tree_util.tree_leaves(fed.state.opt_state)
    ]
    prev = fed._slot_ids.copy()
    fed.step()
    cur = fed._slot_ids
    fresh = prev != cur
    assert fresh.any()  # 4-of-64: a full repeat is ~impossible at seed 0
    # Fresh seats: momentum untouched by round 1's reset would be nonzero;
    # after reset + one round it equals a fresh client's 1-round momentum,
    # which differs from the carried-over value.
    mom_after = jax.tree_util.tree_leaves(fed.state.opt_state)
    changed = any(
        not np.array_equal(b[fresh], np.asarray(a)[fresh])
        for a, b in zip(mom_after, mom_before)
    )
    assert changed


def test_2k_population_64_cohort_fused_smoke():
    """The tier-1 scale smoke: 2000 simulated clients, 64-seat cohort,
    two rounds through the fused lax.scan — device state stays O(cohort),
    the population tables advance, metrics are finite."""
    fed = SimFederation(
        _cfg(2000, 64, scenario="pathological:shards=2", num_examples=4000),
        seed=0,
    )
    m = fed.run_on_device(2)
    losses = np.asarray(m.loss)
    assert losses.shape == (2,) and np.isfinite(losses).all()
    # One cohort per fused block: 64 draws, all marked.
    assert fed.population.times_sampled.sum() == 64
    assert fed.population.never_sampled() == 2000 - 64
    # Device state is cohort-sized, not population-sized.
    import jax

    for leaf in jax.tree_util.tree_leaves(fed.state.opt_state):
        assert leaf.shape[0] == 64
    # A following block resamples and rotates new clients in.
    fed.run_on_device(2)
    assert fed.population.times_sampled.sum() == 128
    assert 0 < np.isfinite(fed.population.last_seen_loss).sum() <= 128
    snap = fed.status_snapshot()["sim"]
    assert snap["population"] == 2000 and snap["cohort_live"] == 64


def test_population_membership_admit_evict_readmit():
    """Dynamic membership in the sim layer: mid-run admits grow the host
    tables (never the device seats), evicted clients are never sampled
    however their availability trace rolls, and a readmitted client
    returns with its bookkeeping (a stale rejoin, not a fresh client)."""
    from fedtpu.sim.population import Population
    from fedtpu.sim.samplers import UniformSampler

    rng = np.random.default_rng(0)
    idx = rng.integers(0, 100, (6, 8)).astype(np.int32)
    mask = np.ones((6, 8), bool)
    pop = Population(idx, mask, seed=0)
    pop.observe_loss(np.array([2]), np.array([1.5]))
    # Evict: excluded from availability (and therefore from cohorts).
    pop.evict(2)
    assert not pop.available_at(0)[2]
    sampler = UniformSampler(seed=0)
    for r in range(5):
        ids, alive = sampler.sample(pop, r, 5)
        assert 2 not in set(ids[alive].tolist())
    # Readmit: back in the pool, stale bookkeeping intact.
    pop.readmit(2)
    assert pop.available_at(5)[2]
    assert pop.last_seen_loss[2] == np.float32(1.5)
    # Admit a new client mid-run: tables grow, shorter shards are padded.
    cid = pop.admit(np.arange(5, dtype=np.int32), np.ones(5, bool))
    assert cid == 6 and pop.size == 7
    assert pop.sizes[cid] == 5 and pop.mask[cid, 5:].sum() == 0
    assert pop.available_at(6)[cid]
    assert np.isnan(pop.last_seen_loss[cid])
    assert pop.stats()["members"] == 7
    # Oversized shards are rejected, mismatched rows too.
    with pytest.raises(ValueError):
        pop.admit(np.arange(9, dtype=np.int32), np.ones(9, bool))
    with pytest.raises(ValueError):
        pop.admit(np.arange(3, dtype=np.int32), np.ones(4, bool))


def test_sim_federation_samples_admitted_client():
    """A client admitted into a running SimFederation's population is
    drawn into later cohorts through the UNCHANGED fixed-seat engine (the
    values-only set_assignment swap — no recompile, no device growth)."""
    fed = SimFederation(_cfg(6, 4), seed=0)
    labels = np.asarray(fed.labels)
    fed.step()
    # Admit one new simulated client owning a fresh slice of the dataset.
    new_idx = np.arange(min(16, len(labels)), dtype=np.int32)
    cid = fed.population.admit(new_idx, np.ones(len(new_idx), bool))
    assert cid == 6
    seen = False
    for _ in range(12):
        fed.step()
        if cid in set(fed._cohort_ids[fed.alive].tolist()):
            seen = True
            break
    assert seen, "admitted client never sampled into a cohort"
    # Device buffers stayed cohort-sized throughout.
    import jax

    for leaf in jax.tree_util.tree_leaves(fed.state.opt_state):
        assert leaf.shape[0] == 4
