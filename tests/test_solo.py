"""Standalone single-node trainer (parity: reference ``src/main.py``
train/test/resume path) + the stats/init utils."""

import os

import jax
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core.solo import SoloTrainer, run_solo
from fedtpu.utils import get_mean_and_std, kaiming_init_params


def solo_cfg():
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=32,
                        eval_batch_size=32, num_examples=512),
        fed=FedConfig(num_clients=1),
    )


def test_solo_trains_and_checkpoints_best(tmp_path):
    path = str(tmp_path / "solo.fckpt")
    t = run_solo(solo_cfg(), epochs=3, checkpoint_path=path)
    assert t.epoch == 3
    assert t.best_acc > 0.5  # synthetic is easy
    assert os.path.exists(path)


def test_solo_resume_restores_everything(tmp_path):
    path = str(tmp_path / "solo.fckpt")
    t1 = SoloTrainer(solo_cfg(), checkpoint_path=path)
    t1.train_epoch()
    t1.test_epoch()  # saves (first eval is always the best so far)
    assert os.path.exists(path)

    t2 = SoloTrainer(solo_cfg(), checkpoint_path=path, resume=True)
    assert t2.epoch == t1.epoch
    assert t2.best_acc == pytest.approx(t1.best_acc)
    for a, b in zip(jax.tree.leaves(t1.params), jax.tree.leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree.leaves(t1.opt_state.momentum),
        jax.tree.leaves(t2.opt_state.momentum),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_solo_only_checkpoints_improvements(tmp_path):
    path = str(tmp_path / "solo.fckpt")
    t = SoloTrainer(solo_cfg(), checkpoint_path=path)
    t.best_acc = 2.0  # unbeatable
    t.train_epoch()
    t.test_epoch()
    assert not os.path.exists(path)


def test_get_mean_and_std():
    rng = np.random.default_rng(0)
    x = rng.normal(loc=[1.0, 2.0, 3.0], scale=[0.5, 1.0, 2.0],
                   size=(64, 8, 8, 3)).astype(np.float32)
    mean, std = get_mean_and_std(x)
    np.testing.assert_allclose(mean, [1, 2, 3], atol=0.1)
    np.testing.assert_allclose(std, [0.5, 1, 2], atol=0.1)


def test_kaiming_init_params():
    params = {
        "conv": {"kernel": np.ones((3, 3, 8, 16), np.float32),
                 "bias": np.ones((16,), np.float32)},
    }
    out = kaiming_init_params(params, jax.random.PRNGKey(0))
    k = np.asarray(out["conv"]["kernel"])
    assert k.std() == pytest.approx(np.sqrt(2.0 / (16 * 9)), rel=0.2)
    np.testing.assert_array_equal(np.asarray(out["conv"]["bias"]), 0.0)

def test_batch_parallel_solo_matches_single_device(tmp_path, eight_devices):
    """Batch data parallelism (the reference's DataParallel, SURVEY §2d):
    batch sharded over the mesh, grads/stats pmean'd — the trajectory must
    match single-device training exactly (augment off)."""
    import dataclasses

    import numpy as np
    import jax

    from fedtpu.config import DataConfig, OptimizerConfig, RoundConfig
    from fedtpu.core.solo import SoloTrainer
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=16, eval_batch_size=16,
            num_examples=128, augment=False,
        ),
        fed=dataclasses.replace(RoundConfig().fed, num_clients=1),
        steps_per_round=2,
    )
    single = SoloTrainer(cfg, seed=0)
    meshed = SoloTrainer(cfg, seed=0, mesh=client_mesh(8, axis_name="batch"))
    l1, a1 = single.train_epoch()
    l2, a2 = meshed.train_epoch()
    np.testing.assert_allclose(l1, l2, atol=1e-5)
    np.testing.assert_allclose(a1, a2, atol=1e-6)
    for x, y in zip(
        jax.tree_util.tree_leaves(single.params),
        jax.tree_util.tree_leaves(meshed.params),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-5)


def test_batch_parallel_requires_divisible_batch(eight_devices):
    import dataclasses

    from fedtpu.config import DataConfig, OptimizerConfig, RoundConfig
    from fedtpu.core.solo import SoloTrainer
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp", num_classes=10, opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=12, num_examples=64),
        fed=dataclasses.replace(RoundConfig().fed, num_clients=1),
    )
    with pytest.raises(ValueError, match="not divisible"):
        SoloTrainer(cfg, seed=0, mesh=client_mesh(8, axis_name="batch"))
