"""LR-schedule parity semantics.

The reference constructs ``CosineAnnealingLR(T_max=200)`` (``src/main.py:101``)
but never steps it: the driver loop containing ``scheduler.step()`` is
commented out (``src/main.py:231-242``) and the federated
``train(epoch, rank, world)`` path (``src/main.py:128-165``) doesn't step it
either, so the reference's effective learning rate is a constant 0.1. fedtpu
therefore defaults ``OptimizerConfig.schedule`` to ``'constant'`` for parity
and offers ``'cosine'`` as the schedule the reference *intended*. These tests
pin that divergence so it can never silently flip.
"""

import numpy as np
import pytest

from fedtpu.config import OptimizerConfig


def test_default_schedule_is_constant_reference_parity():
    opt = OptimizerConfig()
    assert opt.schedule == "constant"
    for r in (0, 1, 100, 200, 1000):
        assert float(opt.lr_at(r)) == pytest.approx(opt.learning_rate)


def test_cosine_schedule_anneals():
    opt = OptimizerConfig(learning_rate=0.1, schedule="cosine", cosine_t_max=200)
    assert float(opt.lr_at(0)) == pytest.approx(0.1)
    assert float(opt.lr_at(100)) == pytest.approx(0.05, abs=1e-6)
    assert float(opt.lr_at(200)) == pytest.approx(0.0, abs=1e-6)
    # Clamped past the horizon, like torch CosineAnnealingLR's floor.
    assert float(opt.lr_at(500)) == pytest.approx(0.0, abs=1e-6)


def test_cosine_diverges_from_reference_effective_lr():
    constant = OptimizerConfig(schedule="constant")
    cosine = OptimizerConfig(schedule="cosine")
    # Identical at round 0, diverging after — the reason parity configs must
    # pin schedule='constant'.
    assert float(cosine.lr_at(0)) == pytest.approx(float(constant.lr_at(0)))
    diffs = [
        abs(float(cosine.lr_at(r)) - float(constant.lr_at(r))) for r in (10, 50, 150)
    ]
    assert np.all(np.asarray(diffs) > 1e-4)


def test_unknown_schedule_raises():
    with pytest.raises(ValueError, match="unknown schedule"):
        OptimizerConfig(schedule="linear").lr_at(0)


def test_server_pipeline_default_resolves_to_parity_path():
    """The default config (per_leaf layout, auto pipeline) must keep the
    barrier parity path; the flat layout streams by default."""
    from fedtpu.config import FedConfig, resolve_server_pipeline

    fed = FedConfig()
    assert fed.server_pipeline == "auto"
    assert resolve_server_pipeline(fed) == "barrier"
    assert (
        resolve_server_pipeline(FedConfig(delta_layout="flat")) == "stream"
    )
