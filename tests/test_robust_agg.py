"""Byzantine-robust aggregation (fedtpu.core.round._robust_over_clients).

The reference can only average (``src/server.py:163-171``) — one adversarial
client owns the global model. These tests pin the robust combiners against a
NumPy oracle, their resistance to an adversarial client, dead-client
masking, and mesh parity (all_gather path).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.core.round import _robust_over_clients


def _cfg(**fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=5, **fed_kw),
        steps_per_round=2,
    )


def test_median_matches_numpy_oracle():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 7, 3)).astype(np.float32)
    w = np.asarray([1.0, 2.0, 1.0, 3.0, 1.0], np.float32)
    out = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None, "median", 0.1
    )["a"]
    np.testing.assert_allclose(np.asarray(out), np.median(x, axis=0), atol=1e-6)


def test_median_excludes_dead_clients():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(5, 4)).astype(np.float32)
    w = np.asarray([1.0, 0.0, 1.0, 1.0, 0.0], np.float32)  # 1 and 4 dead
    out = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None, "median", 0.1
    )["a"]
    np.testing.assert_allclose(
        np.asarray(out), np.median(x[[0, 2, 3]], axis=0), atol=1e-6
    )


def test_all_dead_round_is_a_no_op():
    x = jnp.ones((4, 3))
    out = _robust_over_clients(
        {"a": x}, jnp.zeros((4,)), None, "median", 0.1
    )["a"]
    np.testing.assert_array_equal(np.asarray(out), 0.0)


def test_trimmed_mean_discards_tails():
    # 1 huge outlier among 10 values per coordinate; trim 0.15 removes it.
    x = np.ones((10, 4), np.float32)
    x[3] = 1000.0
    out = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.ones((10,)), None, "trimmed_mean", 0.15
    )["a"]
    np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)


@pytest.mark.parametrize("agg", ["median", "trimmed_mean"])
def test_robust_round_resists_adversarial_client(agg):
    """Inject a poisoned client via a huge local LR surrogate: corrupt one
    client's delta by training on wildly mislabeled data. The mean round
    moves the global model far more than the robust round."""
    norms = {}
    for aggregator in ("mean", agg):
        cfg = _cfg(aggregator=aggregator, trim_fraction=0.25)
        fed = Federation(cfg, seed=0)
        # Poison: client 0's labels are shifted — its delta systematically
        # disagrees; amplify by corrupting its images too.
        imgs = np.asarray(fed.images).copy()
        labels = np.asarray(fed.labels).copy()
        own = fed.client_idx[0][fed.client_mask[0]]
        imgs[own] *= 50.0
        labels[own] = (labels[own] + 5) % 10
        fed2 = Federation(cfg, seed=0, data=(imgs, labels))
        before = [np.asarray(x).copy() for x in
                  jax.tree_util.tree_leaves(fed2.state.params)]
        fed2.step()
        after = jax.tree_util.tree_leaves(fed2.state.params)
        norms[aggregator] = float(
            sum(np.abs(a - np.asarray(b)).sum() for a, b in zip(before, after))
        )
    assert norms[agg] < norms["mean"] * 0.5, norms


def test_robust_mesh_matches_single_program(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=8, aggregator="median"),
        steps_per_round=2,
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    single.step()
    meshed.step()
    for a, b in zip(
        jax.tree_util.tree_leaves(single.state.params),
        jax.tree_util.tree_leaves(meshed.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_unknown_aggregator_raises():
    cfg = _cfg(aggregator="bulyan")
    with pytest.raises(ValueError, match="unknown aggregator"):
        Federation(cfg, seed=0).step()


def test_krum_selects_the_cluster_member():
    """5 clients: 4 clustered near delta=1, one far outlier — Krum must
    return one of the clustered deltas verbatim."""
    from fedtpu.core.round import _krum_over_clients

    rng = np.random.default_rng(0)
    base = np.ones((4, 6), np.float32) + 0.01 * rng.normal(size=(4, 6)).astype(
        np.float32
    )
    outlier = np.full((1, 6), 500.0, np.float32)
    x = np.concatenate([base[:2], outlier, base[2:]])
    out = _krum_over_clients(
        {"a": jnp.asarray(x)}, jnp.ones((5,)), None, 0.2
    )["a"]
    matches = [np.allclose(np.asarray(out), row, atol=1e-6) for row in base]
    assert any(matches), np.asarray(out)


def test_krum_excludes_dead_clients_and_all_dead_is_noop():
    from fedtpu.core.round import _krum_over_clients

    x = np.stack([
        np.full((4,), 1.0, np.float32),
        np.full((4,), 1.01, np.float32),
        np.full((4,), 900.0, np.float32),  # would win if dead rows counted
        np.full((4,), 0.99, np.float32),
    ])
    w = np.asarray([1.0, 1.0, 0.0, 1.0], np.float32)
    out = _krum_over_clients({"a": jnp.asarray(x)}, jnp.asarray(w), None, 0.0)["a"]
    assert float(np.abs(np.asarray(out)).max()) < 2.0
    zero = _krum_over_clients(
        {"a": jnp.asarray(x)}, jnp.zeros((4,)), None, 0.0
    )["a"]
    np.testing.assert_array_equal(np.asarray(zero), 0.0)


def test_krum_with_many_dead_clients_still_discriminates():
    """Regression: with dead > f+1, a k computed from the TOTAL row count
    pulls _KRUM_BIG distances into every live score, flattening them all to
    ~k*1e30 in f32 and degrading argmin to 'first live index'. k must come
    from the live count: here the first live row is the outlier and must
    NOT be selected."""
    from fedtpu.core.round import _krum_over_clients

    x = np.stack([
        np.full((4,), 700.0, np.float32),   # live outlier, lowest index
        np.full((4,), 1.0, np.float32),
        np.full((4,), 1.01, np.float32),
        np.full((4,), 0.99, np.float32),
        np.full((4,), 5000.0, np.float32),  # dead
        np.full((4,), 6000.0, np.float32),  # dead
        np.full((4,), 7000.0, np.float32),  # dead
        np.full((4,), 8000.0, np.float32),  # dead
    ])
    w = np.asarray([1, 1, 1, 1, 0, 0, 0, 0], np.float32)
    out = _krum_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None, 0.1
    )["a"]
    assert float(np.abs(np.asarray(out)).max()) < 2.0, np.asarray(out)


def test_krum_composes_with_nothing_unsound():
    """DP's mean-only guard covers krum; compression guard covers krum."""
    with pytest.raises(ValueError, match="mean aggregator"):
        Federation(
            _cfg(aggregator="krum", weighted=False, dp_clip_norm=0.1), seed=0
        )
    with pytest.raises(ValueError, match="cannot compose with"):
        Federation(
            _cfg(aggregator="krum", compression="topk"), seed=0
        )


def test_krum_selection_is_joint_across_trees():
    """Krum must pick ONE client for all trees — mixing client A's params
    with client B's stats would be incoherent."""
    from fedtpu.core.round import _krum_over_clients

    p = np.asarray([[1.0, 1.0], [1.02, 1.0], [50.0, 50.0]], np.float32)
    s = np.asarray([[10.0], [20.0], [30.0]], np.float32)
    out = _krum_over_clients(
        {"p": jnp.asarray(p), "s": jnp.asarray(s)}, jnp.ones((3,)), None, 0.34
    )
    sel = int(np.argmin([np.abs(p[i] - np.asarray(out["p"])).max()
                         for i in range(3)]))
    np.testing.assert_allclose(np.asarray(out["s"]), s[sel])


def test_krum_round_resists_adversarial_client():
    norms = {}
    for aggregator in ("mean", "krum"):
        cfg = _cfg(aggregator=aggregator, trim_fraction=0.25)
        probe = Federation(cfg, seed=0)
        imgs = np.asarray(probe.images).copy()
        labels = np.asarray(probe.labels).copy()
        own = probe.client_idx[0][probe.client_mask[0]]
        imgs[own] *= 50.0
        labels[own] = (labels[own] + 5) % 10
        fed = Federation(cfg, seed=0, data=(imgs, labels))
        before = [np.asarray(x).copy() for x in
                  jax.tree_util.tree_leaves(fed.state.params)]
        fed.step()
        after = jax.tree_util.tree_leaves(fed.state.params)
        norms[aggregator] = float(
            sum(np.abs(a - np.asarray(b)).sum() for a, b in zip(before, after))
        )
    assert norms["krum"] < norms["mean"] * 0.5, norms


def test_krum_mesh_matches_single_program(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=8, aggregator="krum"),
        steps_per_round=2,
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    single.step()
    meshed.step()
    for a, b in zip(
        jax.tree_util.tree_leaves(single.state.params),
        jax.tree_util.tree_leaves(meshed.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_krum_distributed_edge():
    from fedtpu.transport.federation import PrimaryServer

    srv = PrimaryServer(_cfg(aggregator="krum"), clients=[], seed=0)
    deltas = jax.tree.map(
        lambda p: jnp.stack(
            [jnp.ones_like(p) * 0.01, jnp.ones_like(p) * 0.0101,
             jnp.ones_like(p) * 1000.0, jnp.ones_like(p) * 0.0099]
        ),
        {"params": srv.params, "batch_stats": srv.batch_stats},
    )
    g = {"params": srv.params, "batch_stats": srv.batch_stats}
    out, _ = srv._aggregate(
        g, deltas, jnp.ones((4,)), srv._server_opt_state,
        jnp.asarray(0, jnp.int32),
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(out["params"]),
        jax.tree_util.tree_leaves(srv.params),
    ):
        move = np.abs(np.asarray(a) - np.asarray(b)).max()
        assert move < 0.02, move


def test_trimmed_mean_trim_zero_is_bit_identical_to_mean():
    """trim_fraction=0 trims nothing, so it must equal the uniform mean
    BIT-FOR-BIT (same ops, not just same math) — engine combiner level and
    full round level, with dead-client masking."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(6, 9, 4)).astype(np.float32)
    w = np.asarray([1.0, 1.0, 0.0, 1.0, 1.0, 1.0], np.float32)
    from fedtpu.core.round import _mean_over_clients

    robust = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None, "trimmed_mean", 0.0
    )["a"]
    mean = _mean_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None
    )[0]["a"]
    np.testing.assert_array_equal(np.asarray(robust), np.asarray(mean))

    # Full engine round: weighted=False mean vs trimmed_mean trim=0.
    params = {}
    for aggregator in ("mean", "trimmed_mean"):
        cfg = _cfg(aggregator=aggregator, trim_fraction=0.0, weighted=False)
        fed = Federation(cfg, seed=0)
        fed.step()
        params[aggregator] = jax.tree_util.tree_leaves(fed.state.params)
    for a, b in zip(params["mean"], params["trimmed_mean"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trim_zero_bit_identical_on_distributed_edge():
    """Same pin for PrimaryServer._aggregate (the barrier combine)."""
    from fedtpu.transport.federation import PrimaryServer

    outs = {}
    for aggregator in ("mean", "trimmed_mean"):
        srv = PrimaryServer(
            _cfg(aggregator=aggregator, trim_fraction=0.0, weighted=False),
            clients=[], seed=0,
        )
        rng = np.random.default_rng(0)
        deltas = jax.tree.map(
            lambda p: jnp.asarray(
                rng.normal(size=(3,) + np.shape(p)).astype(np.float32)
            ),
            {"params": srv.params, "batch_stats": srv.batch_stats},
        )
        g = {"params": srv.params, "batch_stats": srv.batch_stats}
        out, _ = srv._aggregate(
            g, deltas, jnp.ones((3,)), srv._server_opt_state,
            jnp.asarray(0, jnp.int32),
        )
        outs[aggregator] = jax.tree_util.tree_leaves(out)
    for a, b in zip(outs["mean"], outs["trimmed_mean"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_weighted_robust_warns_once_and_flags_round_record():
    """weighted=True + a robust aggregator silently ignores example-count
    weights; that must warn (once) and stamp the round record."""
    from fedtpu.core import round as round_lib
    from fedtpu.transport.federation import PrimaryServer

    round_lib._WEIGHTED_ROBUST_WARNED.discard("median")
    with _capture_warnings() as records:
        Federation(_cfg(aggregator="median", weighted=True), seed=0)
        Federation(_cfg(aggregator="median", weighted=True), seed=0)
    assert sum("ignores example-count weights" in r for r in records) == 1
    # The distributed server stamps every committed round record.
    srv = PrimaryServer(
        _cfg(aggregator="median", weighted=True), clients=[], seed=0
    )
    assert srv._weights_ignored is True
    plain = PrimaryServer(
        _cfg(aggregator="mean", weighted=True), clients=[], seed=0
    )
    assert plain._weights_ignored is False


class _capture_warnings:
    """Capture fedtpu.round warning messages."""

    def __enter__(self):
        import logging

        self.records = []
        self.handler = logging.Handler()
        self.handler.emit = lambda rec: self.records.append(rec.getMessage())
        logging.getLogger("fedtpu.round").addHandler(self.handler)
        return self.records

    def __exit__(self, *exc):
        import logging

        logging.getLogger("fedtpu.round").removeHandler(self.handler)
        return False


def test_trimmed_mean_never_empties_the_band_at_small_n():
    """Interpolated quantile bounds can exclude BOTH values at n=2 (verified
    failure mode); data-point bounds must keep the band non-empty."""
    x = np.asarray([[1.0, 2.0], [3.0, 5.0]], np.float32)
    out = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.ones((2,)), None, "trimmed_mean", 0.1
    )["a"]
    np.testing.assert_allclose(np.asarray(out), [2.0, 3.5], atol=1e-6)


def test_robust_rejects_compression_and_bad_trim():
    with pytest.raises(ValueError, match="cannot compose with"):
        Federation(
            _cfg(aggregator="median", compression="topk"), seed=0
        )
    with pytest.raises(ValueError, match="trim_fraction"):
        Federation(
            _cfg(aggregator="trimmed_mean", trim_fraction=0.5), seed=0
        )


def test_distributed_edge_robust_aggregate_and_guards():
    """PrimaryServer honors --aggregator median (one outlier client cannot
    own the model) and rejects robust+compression configs."""
    from fedtpu.transport.federation import PrimaryServer

    srv = PrimaryServer(_cfg(aggregator="median"), clients=[], seed=0)
    deltas = jax.tree.map(
        lambda p: jnp.stack(
            [jnp.ones_like(p) * 0.01, jnp.ones_like(p) * 0.01,
             jnp.ones_like(p) * 1000.0]
        ),
        {"params": srv.params, "batch_stats": srv.batch_stats},
    )
    g = {"params": srv.params, "batch_stats": srv.batch_stats}
    out, _ = srv._aggregate(
        g, deltas, jnp.ones((3,)), srv._server_opt_state,
        jnp.asarray(0, jnp.int32),
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(out["params"]),
        jax.tree_util.tree_leaves(srv.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b) + 0.01, atol=1e-5
        )
    with pytest.raises(ValueError, match="cannot compose with"):
        PrimaryServer(
            _cfg(aggregator="median", compression="topk"), clients=[], seed=0
        )


def test_distributed_edge_participation_sampling():
    """participation_fraction subsamples the StartTrain fan-out per round."""
    from fedtpu.transport import federation as fmod
    from fedtpu.transport.federation import PrimaryServer

    srv = PrimaryServer(
        _cfg(participation_fraction=0.5),
        clients=["a:1", "b:2", "c:3", "d:4"],
        seed=0,
        rpc_timeout=2.0,
    )
    srv._did_initial_sync = True
    seen = []
    orig = fmod.threading.Thread

    class SpyThread(orig):
        def __init__(self, *a, **kw):
            if kw.get("target") is not None and kw["target"].__name__ == "train_one":
                seen.append(kw["args"][1])
            super().__init__(*a, **kw)

    fmod.threading.Thread = SpyThread
    try:
        srv.round()
    finally:
        fmod.threading.Thread = orig
    assert len(seen) == 2, seen  # 0.5 of 4 live clients
    assert set(seen) <= {"a:1", "b:2", "c:3", "d:4"}


def test_legacy_checkpoint_without_server_opt_state_restores(tmp_path):
    """A checkpoint written before server_opt_state existed (simulated by
    encoding the old field set) must restore, refilling the new field from
    the template."""
    from fedtpu.checkpoint import Checkpointer, checkpoint
    from fedtpu.transport import wire

    fed = Federation(_cfg(), seed=0)
    fed.step()
    legacy = {
        k: v for k, v in fed.state._asdict().items()
        if k != "server_opt_state"
    }
    path = checkpoint._wire_path(str(tmp_path), 1)
    with open(path, "wb") as fh:
        fh.write(wire.encode(legacy, compress=True))

    fresh = Federation(_cfg(), seed=1)
    rnd, restored = Checkpointer(str(tmp_path), backend="wire").restore_latest(
        like=fresh.state
    )
    assert rnd == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(fed.state.params),
        jax.tree_util.tree_leaves(restored.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert restored.server_opt_state == ()
