"""Model zoo smoke tests: forward shapes + param realisability.

Replaces the reference's commented-out per-file ``test()`` functions
(e.g. ``src/models/resnet.py:127-132``) with executed checks.
"""

import jax
import jax.numpy as jnp
import pytest

from fedtpu import models


SMALL_MODELS = ["mlp", "smallcnn", "lenet", "mobilenet", "resnet18"]


@pytest.mark.parametrize("name", SMALL_MODELS)
def test_forward_shape(name):
    m = models.create(name, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3) if name != "mlp" else (2, 28, 28, 1))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("name", ["mobilenet", "resnet18"])
def test_train_mode_updates_batch_stats(name):
    m = models.create(name, num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables
    out, updated = m.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    # Running stats must actually move.
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(updated["batch_stats"])
    moved = any(
        float(jnp.abs(a - b).max()) > 0 for a, b in zip(after, before)
    )
    assert moved


def test_num_classes_plumbs_through():
    m = models.create("resnet18", num_classes=100)
    x = jnp.zeros((1, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    assert m.apply(variables, x, train=False).shape == (1, 100)


def test_registry_unknown_model():
    with pytest.raises(KeyError):
        models.create("nope")
