"""Model zoo smoke + parity tests.

Replaces the reference's commented-out per-file ``test()`` functions
(e.g. ``src/models/resnet.py:127-132``) with executed checks, and adds exact
parameter-count parity against the reference torch zoo (counts computed once
from ``/root/reference/src/models`` and baked in — counting only the
trainable ``params`` collection, which corresponds to torch
``Module.parameters()``; BN running stats live in ``batch_stats``/buffers on
both sides and are excluded).
"""

import jax
import jax.numpy as jnp
import pytest

from fedtpu import models


SMALL_MODELS = ["mlp", "smallcnn", "lenet", "mobilenet", "resnet18"]

# Exact parameter-count parity with the reference zoo (CIFAR-10 heads).
# Two deliberate divergences, both smaller than the reference:
#  - efficientnetb0: the reference instantiates an expansion conv even in
#    expand_ratio==1 blocks and never uses it (src/models/efficientnet.py:
#    63-70 vs the forward at :97) — 1088 dead params we don't replicate.
#  - shufflenetg2/g3 have no reference count at all: the reference crashes at
#    construction on modern torch (float mid_planes, src/models/shufflenet.py:28).
PARAM_PARITY = {
    "lenet": 62006,
    "mobilenet": 3217226,
    "mobilenetv2": 2296922,
    "vgg11": 9231114,
    "vgg19": 20040522,
    "resnet18": 11173962,
    "resnet50": 23520842,
    "preactresnet18": 11171146,
    "googlenet": 6166250,
    "densenet_cifar": 1000618,
    "densenet121": 6956298,
    "resnext29_2x64d": 9128778,
    "resnext29_32x4d": 4774218,
    "senet18": 11260354,
    "dpn26": 11574842,
    "shufflenetv2": 1263854,
    "efficientnetb0": 3598598,  # reference: 3599686 incl. 1088 dead params
    "regnetx_200mf": 2321946,
    "regnetx_400mf": 4779338,
    "regnety_400mf": 5714362,
    "pnasneta": 130646,
    "pnasnetb": 451626,
    "dla": 16291386,
    "simpledla": 15142970,
}

# Constructors with no baked reference count (reference-crashing or huge);
# still shape-checked abstractly.
SHAPE_ONLY = [
    "shufflenetg2",
    "shufflenetg3",
    "resnet34",
    "resnet101",
    "resnet152",
    "preactresnet34",
    "preactresnet50",
    "preactresnet101",
    "preactresnet152",
    "vgg13",
    "vgg16",
    "densenet161",
    "densenet169",
    "densenet201",
    "resnext29_4x64d",
    "resnext29_8x64d",
    "dpn92",
]


def _abstract_init(name):
    m = models.create(name, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3))
    shapes = jax.eval_shape(
        lambda r: m.init(r, x, train=False), jax.random.PRNGKey(0)
    )
    out = jax.eval_shape(
        lambda v: m.apply(v, x, train=False), shapes
    )
    return shapes, out


@pytest.mark.parametrize("name", sorted(PARAM_PARITY))
def test_param_count_parity(name):
    shapes, out = _abstract_init(name)
    n_params = sum(p.size for p in jax.tree.leaves(shapes["params"]))
    assert n_params == PARAM_PARITY[name]
    assert out.shape == (2, 10)


@pytest.mark.parametrize("name", SHAPE_ONLY)
def test_forward_shape_abstract(name):
    _, out = _abstract_init(name)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("name", SMALL_MODELS)
def test_forward_shape(name):
    m = models.create(name, num_classes=10)
    x = jnp.zeros((2, 32, 32, 3) if name != "mlp" else (2, 28, 28, 1))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("name", ["mobilenet", "resnet18"])
def test_train_mode_updates_batch_stats(name):
    m = models.create(name, num_classes=10)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables
    out, updated = m.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    # Running stats must actually move.
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(updated["batch_stats"])
    moved = any(
        float(jnp.abs(a - b).max()) > 0 for a, b in zip(after, before)
    )
    assert moved


def test_constructor_surface_matches_reference():
    """Every constructor the reference exports (src/models/__init__.py:1-18)
    exists here under the same name."""
    for ctor in [
        "MobileNet",
        "MobileNetV2",
        "ResNet18",
        "ResNet34",
        "ResNet50",
        "ResNet101",
        "ResNet152",
        "PreActResNet18",
        "VGG",
        "GoogLeNet",
        "DenseNet121",
        "densenet_cifar",
        "ResNeXt29_2x64d",
        "SENet18",
        "DPN26",
        "DPN92",
        "ShuffleNetG2",
        "ShuffleNetG3",
        "ShuffleNetV2",
        "EfficientNetB0",
        "RegNetX_200MF",
        "RegNetY_400MF",
        "PNASNetA",
        "PNASNetB",
        "DLA",
        "SimpleDLA",
        "LeNet",
    ]:
        assert hasattr(models, ctor), ctor


def test_shufflenetv2_sizes():
    for size in (0.5, 1, 1.5, 2):
        m = models.ShuffleNetV2(size)
        x = jnp.zeros((1, 32, 32, 3))
        out = jax.eval_shape(
            lambda r: m.apply(
                m.init(r, x, train=False), x, train=False
            ),
            jax.random.PRNGKey(0),
        )
        assert out.shape == (1, 10)


def test_num_classes_plumbs_through():
    m = models.create("resnet18", num_classes=100)
    x = jnp.zeros((1, 32, 32, 3))
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    assert m.apply(variables, x, train=False).shape == (1, 100)


def test_registry_unknown_model():
    with pytest.raises(KeyError):
        models.create("nope")
