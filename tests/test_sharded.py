"""Mesh-parallel round == single-program round, on an 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu import models
from fedtpu.core import round as round_lib
from fedtpu.parallel import (
    client_mesh,
    make_sharded_round_step,
    shard_batch,
    shard_state,
)


def cfg8():
    return RoundConfig(
        model="mlp",
        num_classes=4,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=8),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )


def make_batch(cfg, seed=0, alive=None, dim=6):
    rng = np.random.default_rng(seed)
    n, s, b = cfg.fed.num_clients, cfg.steps_per_round, cfg.data.batch_size
    return round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, dim)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 4, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool) if alive is None else jnp.asarray(alive),
    )


@pytest.fixture(scope="module")
def shared(request):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = cfg8()
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32)
    )
    mesh = client_mesh(8, cfg.mesh_axis)
    return cfg, model, state, mesh


def test_sharded_matches_single_program(shared):
    cfg, model, state, mesh = shared
    batch = make_batch(cfg, seed=0)

    single = jax.jit(round_lib.make_round_step(model, cfg))
    s_single, m_single = single(state, batch)

    sharded_step = make_sharded_round_step(model, cfg, mesh, donate=False)
    s_sh, m_sh = sharded_step(
        shard_state(state, mesh, cfg.mesh_axis),
        shard_batch(batch, mesh, cfg.mesh_axis),
    )

    for a, b in zip(jax.tree.leaves(s_single.params), jax.tree.leaves(s_sh.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(
        float(m_single.loss), float(m_sh.loss), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(m_single.accuracy), float(m_sh.accuracy), rtol=1e-5
    )


def test_sharded_dead_client_mask(shared):
    cfg, model, state, mesh = shared
    alive = np.ones(8, bool)
    alive[5] = False
    batch = make_batch(cfg, seed=1, alive=alive)

    single = jax.jit(round_lib.make_round_step(model, cfg))
    s_single, m_single = single(state, batch)

    sharded_step = make_sharded_round_step(model, cfg, mesh, donate=False)
    s_sh, m_sh = sharded_step(
        shard_state(state, mesh, cfg.mesh_axis),
        shard_batch(batch, mesh, cfg.mesh_axis),
    )
    assert float(m_sh.num_active) == 7.0
    for a, b in zip(jax.tree.leaves(s_single.params), jax.tree.leaves(s_sh.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)


def test_multiple_clients_per_device(shared):
    """16 clients on 8 devices — 2 clients per shard."""
    cfg, model, _, mesh = shared
    import dataclasses

    cfg16 = dataclasses.replace(cfg, fed=dataclasses.replace(cfg.fed, num_clients=16))
    state = round_lib.init_state(
        models.create(cfg16.model, num_classes=cfg16.num_classes),
        cfg16,
        jax.random.PRNGKey(0),
        jnp.zeros((1, 6), jnp.float32),
    )
    batch = make_batch(cfg16, seed=2)
    single = jax.jit(round_lib.make_round_step(model, cfg16))
    s_single, _ = single(state, batch)
    sharded_step = make_sharded_round_step(model, cfg16, mesh, donate=False)
    s_sh, _ = sharded_step(
        shard_state(state, mesh, cfg16.mesh_axis),
        shard_batch(batch, mesh, cfg16.mesh_axis),
    )
    for a, b in zip(jax.tree.leaves(s_single.params), jax.tree.leaves(s_sh.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)


def test_indivisible_clients_raises(shared):
    cfg, model, _, mesh = shared
    import dataclasses

    bad = dataclasses.replace(cfg, fed=dataclasses.replace(cfg.fed, num_clients=9))
    with pytest.raises(ValueError):
        make_sharded_round_step(model, bad, mesh)
