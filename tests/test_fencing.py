"""Partition-tolerant coordination: epoch fencing + split-brain elimination.

A network partition (unlike a crash) leaves TWO live coordinators: the
watchdog promotes the backup while the old primary keeps serving its
side. Coordinator epochs fence the stale side when the partition heals
(docs/FAULT_TOLERANCE.md §Coordinator fencing): every promotion mints a
higher epoch, receivers reject lower-epoch senders with the typed
``STALE_COORDINATOR`` status, and the fenced ex-primary voids its forked
round and re-bases through the recovering handshake.

Tier-1 here: the stale-epoch rejection contract against a LIVE client
agent, the stay-fenced-while-winner-unreachable rule, and the in-process
symmetric partition-heal drill (promote -> heal -> fence -> re-base ->
single exact-cover lineage, bit-identical to a no-partition control).
The three-leg soak (``tools/chaos_soak.py --partition``) re-runs as
``slow``.
"""

import os
import sys
import threading
import time

import pytest

grpc = pytest.importorskip("grpc")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import chaos_soak  # noqa: E402
import rolling_upgrade as ru  # noqa: E402

from fedtpu.config import RetryPolicy  # noqa: E402
from fedtpu.transport import proto  # noqa: E402
from fedtpu.transport.retry import is_stale_coordinator  # noqa: E402


def _csum(regs, name) -> float:
    """Sum a counter (all label sets) across metrics registries."""
    from fedtpu.obs import parse_prometheus_text, prometheus_text

    total = 0.0
    for reg in regs:
        if reg is None:
            continue
        total += sum(parse_prometheus_text(prometheus_text(reg)).get(
            name, {}).values())
    return total


def _registry(coord):
    tel = coord.telemetry
    return tel.registry if tel.enabled else None


# ------------------------------------------------- stale-epoch unit pins
def test_stale_epoch_rpcs_rejected_by_live_client():
    """The receiver-side fencing contract, pinned over real gRPC: a live
    ClientAgent tracks the max coordinator epoch and rejects lower-epoch
    StartTrain/SendModel with FAILED_PRECONDITION + STALE_COORDINATOR —
    without touching trainer state — while legacy (epoch-less) traffic
    keeps working."""
    from fedtpu.transport.federation import serve_client
    from fedtpu.transport.service import TrainerStub, create_channel

    cfg = chaos_soak._tiny_cfg(1, 4)
    addr = f"localhost:{chaos_soak.free_port()}"
    server, agent = serve_client(addr, cfg, seed=0)
    try:
        stub = TrainerStub(create_channel(addr))
        # Epoch 5 is the newest seen -> accepted, trains a round.
        reply = stub.StartTrain(
            proto.TrainRequest(rank=0, world=1, round=0, epoch=5),
            timeout=180,
        )
        assert reply.message
        assert agent._max_epoch == 5
        before = agent.trainer.round_idx

        # A stale coordinator (epoch 3 < 5) is rejected with the TYPED
        # status, and the rejection names the newest epoch so the fenced
        # sender can mint past it.
        with pytest.raises(grpc.RpcError) as ei:
            stub.StartTrain(
                proto.TrainRequest(rank=0, world=1, round=1, epoch=3),
                timeout=30,
            )
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert "STALE_COORDINATOR" in (ei.value.details() or "")
        assert (ei.value.details() or "").rstrip().endswith("5")
        assert is_stale_coordinator(ei.value)
        assert agent.trainer.round_idx == before  # no training happened

        # SendModel is fenced BEFORE the payload decode: garbage bytes
        # from a stale sender never reach the installer.
        with pytest.raises(grpc.RpcError) as ei2:
            stub.SendModel(
                proto.SendModelRequest(model=b"junk", epoch=4, role=1),
                timeout=30,
            )
        assert ei2.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert "STALE_COORDINATOR" in (ei2.value.details() or "")

        # Pre-fencing peers advertise no epoch (-1) and are never fenced.
        reply = stub.StartTrain(
            proto.TrainRequest(rank=0, world=1, round=1), timeout=180,
        )
        assert reply.message
        assert agent.trainer.round_idx == before + 1

        reg = agent.trainer.telemetry.registry
        assert reg.counter(
            "fedtpu_ft_stale_rejected_total", labels={"rpc": "StartTrain"},
        ).value == 1
        assert reg.counter(
            "fedtpu_ft_stale_rejected_total", labels={"rpc": "SendModel"},
        ).value == 1
    finally:
        server.stop(0)


def test_fenced_coordinator_stays_fenced_until_winner_reachable():
    """A fenced coordinator must NOT resume by minting past the winner
    while the winner is unreachable — adopting the winning state first is
    what eliminates the split-brain. With the backup link down (or no
    backup at all) handle_fence holds the fence and /healthz stays 503."""
    from fedtpu.transport.federation import PrimaryServer

    cfg = chaos_soak._tiny_cfg(1, 2)
    # Backup address bound to nothing: the recovering handshake cannot land.
    dead = f"localhost:{chaos_soak.free_port()}"
    primary = PrimaryServer(cfg, ["localhost:1"], backup_address=dead)
    primary._fence_retry_s = 0.01
    primary._fenced = True
    primary._epoch_seen = 5
    primary.handle_fence()
    assert primary._fenced, "re-based without reaching the winner"
    assert primary._coord_epoch == 1, "minted past an unadopted lineage"
    ok, reason = primary.health()
    assert not ok and "fenced" in reason

    # No backup channel at all (an acting primary awaiting demotion, or a
    # standalone primary): same rule — hold the fence.
    lone = PrimaryServer(cfg, ["localhost:1"])
    assert lone.pinger is None
    lone._fence_retry_s = 0.01
    lone._fenced = True
    lone._epoch_seen = 7
    lone.handle_fence()
    assert lone._fenced and lone._coord_epoch == 1


# ------------------------------------------------ partition-heal drill
def test_symmetric_partition_heal_single_lineage_bit_identical():
    """The tier-1 acceptance drill: a symmetric partition (primary cut
    from backup AND clients) promotes the backup, which mints epoch 2 and
    commits rounds; on heal the old primary is fenced by live
    STALE_COORDINATOR rejections, voids its in-flight round, re-bases
    through the recovering handshake (demote + FetchModel), mints epoch 3
    and finishes the run. Exactly one lineage exact-covers 0..N-1, no
    client ever dies, and the final model is BIT-IDENTICAL to a run that
    never partitioned."""
    from fedtpu.ft import Role
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.transport.federation import BackupServer, PrimaryServer

    rounds, pre, clients = 8, 3, 2
    # The retry budget must outlast the partition window: a partitioned
    # link fails FAST (no sleep), so capped backoff keeps the StartTrain
    # collect workers retrying (~0.25 s apart, ~150 s of coverage) until
    # the heal — transient faults never kill clients.
    cfg = chaos_soak._tiny_cfg(
        clients, rounds,
        round_quorum=1.0,
        server_optimizer="momentum",
        ft_heartbeat_period_s=0.5,
        retry=RetryPolicy(max_attempts=600, backoff_s=0.05,
                          backoff_multiplier=1.5, backoff_max_s=0.25),
    )

    addrs, servers, agents = ru.build_fleet(cfg, clients, seed0=0)
    backup_addr = f"localhost:{chaos_soak.free_port()}"
    group = "|".join([backup_addr] + addrs)
    # Wall-clock window, manually steered via the schedule's epoch base:
    # closed at start, opened at the exact committed-round boundary (the
    # on_round callback below), healed once the acting primary has
    # committed rounds.
    sched = parse_spec(f"partition@*:peer={group},p=1,window=3600-1000000")

    lock = threading.Lock()
    timeline = []  # (source, record) in arrival order

    def collect(src):
        def cb(r, rec):
            with lock:
                timeline.append((src, dict(rec)))
            if (src == "primary" and not rec.get("aborted")
                    and rec["round"] == pre - 1):
                # Open the partition at this exact lineage boundary.
                sched._t0 = time.monotonic() - 3601.0
        return cb

    def committed(src=None):
        with lock:
            return [
                rec for s, rec in timeline
                if not rec.get("aborted") and (src is None or s == src)
            ]

    backup = backup_srv = primary = None
    bail = threading.Event()
    try:
        backup = BackupServer(
            cfg, addrs, watchdog_timeout=2.0,
            on_acting_round=collect("acting"),
        )
        backup_srv = backup.start(backup_addr)
        primary = PrimaryServer(
            cfg, addrs, backup_address=backup_addr, chaos=sched,
        )
        errs = []

        def drive():
            try:
                primary.run(
                    num_rounds=10**9,
                    stop=lambda: bail.is_set()
                    or (primary._coord_epoch > 1
                        and not primary._fenced
                        and primary._round_counter >= rounds),
                    on_round=collect("primary"),
                )
            except BaseException as exc:  # surfaced by the main thread
                errs.append(exc)

        t = threading.Thread(target=drive, daemon=True)
        t.start()

        deadline = time.monotonic() + 240
        while backup.acting is None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert backup.acting is not None, "backup never promoted"
        acting = backup.acting
        while len(committed("acting")) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(committed("acting")) >= 2, "acting committed no rounds"

        # Heal: the window closes; the primary's in-flight retries now
        # reach peers that saw epoch 2 and fence it.
        sched._t0 = time.monotonic() - 10_000_000.0
        t.join(timeout=240)
        assert not t.is_alive(), "primary round loop never finished"
        assert not errs, errs

        # ---- exactly one lineage, exact cover, correct epoch chain ----
        recs = committed()
        lineage = [r["round"] for r in recs]
        assert lineage == list(range(rounds)), lineage
        srcs = [s for s, rec in timeline if not rec.get("aborted")]
        k = len(committed("acting"))
        assert srcs == ["primary"] * pre + ["acting"] * k + \
            ["primary"] * (rounds - pre - k), srcs
        epochs = [r["epoch"] for r in recs]
        assert epochs == [1] * pre + [2] * k + [3] * (rounds - pre - k), \
            epochs

        # The fenced void: the stale primary's in-flight round aborted
        # with the fence marker on its superseded epoch, and the global
        # model was untouched (the bit-identity gate below proves it).
        voided = [
            rec for s, rec in timeline
            if s == "primary" and rec.get("fenced")
        ]
        assert voided and voided[0]["epoch"] == 1, timeline

        # ---- protocol state after the heal ----
        assert primary._coord_epoch == 3 and not primary._fenced
        assert acting._coord_epoch == 2 and acting._role == 2
        assert backup.machine.role is Role.BACKUP
        assert backup._epoch_seen >= 3  # post-heal pings/replication
        assert primary.health() == (True, "ok")

        # ---- zero deaths, one fence, live stale rejections ----
        coords = [_registry(primary), _registry(acting)]
        assert _csum(coords, "fedtpu_ft_client_deaths_total") == 0
        assert _csum([_registry(primary)], "fedtpu_ft_fenced_total") == 1
        client_regs = [a.trainer.telemetry.registry for a in agents]
        assert _csum(client_regs, "fedtpu_ft_stale_rejected_total") >= 1
        transitions = _csum([_registry(backup)],
                            "fedtpu_ft_failover_transitions_total")
        assert transitions == 2  # one promote + one demote, no storm

        # Every committed round trained every client exactly once (the
        # stale lineage never reached them).
        assert [a.trainer.round_idx for a in agents] == [rounds] * clients
        u_model = ru.model_fingerprint(primary)
    finally:
        sched._t0 = time.monotonic() - 10_000_000.0  # heal for teardown
        bail.set()
        if backup is not None:
            backup.watchdog.stop()
            backup._stop_acting(wait=30.0)
        if backup_srv is not None:
            backup_srv.stop(0)
        ru.stop_fleet(servers)

    # ------------------------- control: same run, no partition, no backup
    addrs2, servers2, agents2 = ru.build_fleet(cfg, clients, seed0=0)
    try:
        control = PrimaryServer(cfg, addrs2)
        control.run(num_rounds=rounds)
        assert [a.trainer.round_idx for a in agents2] == [rounds] * clients
        c_model = ru.model_fingerprint(control)
    finally:
        ru.stop_fleet(servers2)

    assert ru.bit_identical(c_model, u_model), (
        "post-heal global model differs from the no-partition control — "
        "the forked lineage leaked into the surviving trajectory"
    )


# ------------------------------------------------------------- slow soak
@pytest.mark.slow
def test_partition_soak_three_legs():
    """The full acceptance soak: symmetric, asymmetric and gray-flap legs
    (see tools/chaos_soak.py --partition)."""
    result = chaos_soak.run_partition_soak(verbose=True)
    assert result["ok"]
    assert result["legs"]["symmetric"]["bit_identical_vs_control"]
    assert result["legs"]["asymmetric"]["stale_fork_rounds"] >= 1
    assert result["legs"]["gray"]["promotions"] >= 1
