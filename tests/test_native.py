"""Native host codec (native/codec.cpp via ctypes) vs numpy oracles.

Every binding is exercised against its pure-numpy fallback on the same
inputs; if the toolchain is unavailable the fallback is what runs and the
oracle comparison is still meaningful (self-consistency).
"""

import numpy as np
import pytest

from fedtpu import native


@pytest.fixture(scope="module", autouse=True)
def built():
    native.ensure_built()


def test_kth_magnitude_matches_partition(rng):
    x = rng.normal(size=5001).astype(np.float32)
    for k in (1, 7, 500, 5001):
        got = native.kth_magnitude(x, k)
        want = float(np.sort(np.abs(x))[::-1][k - 1])
        assert got == pytest.approx(want, rel=1e-6)


def test_kth_magnitude_edge_cases():
    assert native.kth_magnitude(np.zeros(0, np.float32), 3) == 0.0
    x = np.array([1.0, -2.0], np.float32)
    assert native.kth_magnitude(x, 0) == 2.0  # clamped to k=1
    assert native.kth_magnitude(x, 99) == 1.0  # clamped to k=n


def test_pack_unpack_sparse_roundtrip(rng):
    x = rng.normal(size=4096).astype(np.float32)
    t = native.kth_magnitude(x, 41)
    idx, vals = native.pack_sparse(x, t)
    assert len(idx) >= 41  # ties may keep extras
    dense = native.unpack_sparse(idx, vals, x.size)
    keep = np.abs(x) >= t
    np.testing.assert_array_equal(dense, np.where(keep, x, 0.0))


def test_pack_sparse_with_residual_conserves_mass(rng):
    x = rng.normal(size=2048).astype(np.float32)
    t = native.kth_magnitude(x, 20)
    idx, vals, residual = native.pack_sparse_with_residual(x, t)
    dense = native.unpack_sparse(idx, vals, x.size)
    np.testing.assert_allclose(dense + residual, x, atol=1e-7)
    # Kept entries have zero residual; dropped have zero dense.
    assert np.all(residual[idx] == 0.0)
    assert np.all(dense[np.abs(x) < t] == 0.0)


def test_quant_int8_error_bound(rng):
    x = rng.normal(size=3000).astype(np.float32)
    codes, scale = native.quant_int8(x)
    back = native.dequant_int8(codes, scale, x.size)
    assert np.abs(back - x).max() <= scale / 2 + 1e-7
    assert codes.dtype == np.int8


def test_quant_int8_zero_input():
    codes, scale = native.quant_int8(np.zeros(64, np.float32))
    assert scale == 0.0
    assert not codes.any()
    np.testing.assert_array_equal(
        native.dequant_int8(codes, scale, 64), np.zeros(64, np.float32)
    )


def test_native_and_fallback_agree(rng):
    """When the shared library is built, its outputs must match the numpy
    fallback path bit-for-bit (modulo float rounding in quant)."""
    if not native.available():
        pytest.skip("native library not built")
    x = rng.normal(size=1111).astype(np.float32)
    t = native.kth_magnitude(x, 30)

    # Force the fallback by temporarily hiding the lib.
    lib = native._lib
    try:
        native._lib = None
        f_idx, f_vals = native.pack_sparse(x, t)
        f_codes, f_scale = native.quant_int8(x)
    finally:
        native._lib = lib

    n_idx, n_vals = native.pack_sparse(x, t)
    np.testing.assert_array_equal(f_idx, n_idx)
    np.testing.assert_array_equal(f_vals, n_vals)
    n_codes, n_scale = native.quant_int8(x)
    assert f_scale == pytest.approx(n_scale, rel=1e-7)
    # round-half cases may differ by 1 code between rint and nearbyint only
    # if the tie-breaking modes differed; both are banker's rounding.
    np.testing.assert_array_equal(f_codes, n_codes)
