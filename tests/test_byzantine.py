"""Byzantine attack harness, fused screening, reputation & quarantine.

Covers the attack DSL (chaos ATTACK_KINDS + SimConfig.malicious_fraction),
the fused screening stats (:func:`fedtpu.ops.flat.screen_rows`), the
adversarial convergence pin (30% sign-flip/scaled attackers: unscreened
mean degrades while screening+krum tracks the clean run, replaying
bit-identically from seed), and the end-to-end quarantine -> evict drill
over real gRPC including roster survival through a backup promotion.

Fast legs run in tier-1; the 100-round Byzantine soak
(``tools/chaos_soak.py --byzantine``) re-runs as ``slow``.
"""

import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RetryPolicy,
    RoundConfig,
    ScreenConfig,
    SimConfig,
    screening_enabled,
    validate_screen_config,
)
from fedtpu.core import Federation
from fedtpu.ops import flat as flat_ops
from fedtpu.sim import adversary

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import chaos_soak  # noqa: E402


def _cfg(n=6, rounds=8, steps=2, **fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            partition="iid", num_examples=384,
        ),
        fed=FedConfig(num_clients=n, num_rounds=rounds, weighted=False,
                      **fed_kw),
        steps_per_round=steps,
    )


# ------------------------------------------------------------ spec parsing
def test_attack_spec_parse_and_validation():
    p = adversary.parse_attack("sign_flip")
    assert p.kind == "sign_flip" and p.coef == -1.0 and p.p == 1.0
    p = adversary.parse_attack("scale:factor=-20,p=0.5,rounds=3-9,seed=4")
    assert p.coef == -20.0 and p.p == 0.5 and p.rounds == (3, 9)
    p = adversary.parse_attack("noise:std=2.5,collude=1")
    assert p.kind == "noise" and p.std == 2.5 and p.collude
    p = adversary.parse_attack("label_flip:offset=3")
    assert p.label_offset == 3
    for bad in ("", "bulyan", "scale:factor=0", "sign_flip:p=0",
                "noise:wat=1", "label_flip:offset=0"):
        with pytest.raises(ValueError):
            adversary.parse_attack(bad)
    # A malformed spec fails at config-validation time, before any build.
    from fedtpu.config import validate_sim_config

    with pytest.raises(ValueError):
        validate_sim_config(FedConfig(
            sim=SimConfig(population=0, malicious_fraction=0.3,
                          attack="bulyan")
        ))


def test_chaos_dsl_attack_rules():
    """ATTACK_KINDS ride the chaos mini-DSL: keyed on the pseudo-RPC
    'Attack', never firing on wire consults (and wildcard wire rules never
    firing on the attack consult)."""
    from fedtpu.ft.chaos import parse_spec

    sched = parse_spec(
        "sign_flip:p=1.0,peer=c1;scale:factor=30,peer=c2;"
        "noise:std=0.5,collude=1;error@StartTrain:p=1.0,max=1"
    )
    rules = sched.rules
    assert [r.kind for r in rules[:3]] == ["sign_flip", "scale", "noise"]
    assert all(r.rpc == "Attack" for r in rules[:3])
    assert rules[1].factor == 30.0 and rules[2].collude
    # Wire consult never hits an attack rule; the error rule does fire.
    fired = sched.decide("StartTrain", "c1")
    assert fired is not None and fired.kind == "error"
    assert sched.decide("StartTrain", "c1") is None  # error rule capped
    # Attack consult picks the peer-matched attack rule, not wire rules.
    atk = sched.decide_attack("c1", round_idx=0)
    assert atk is not None and atk.kind == "sign_flip"
    atk2 = sched.decide_attack("c2", round_idx=0)
    assert atk2 is not None and atk2.kind == "scale"
    with pytest.raises(ValueError):
        parse_spec("sign_flip@StartTrain:p=1.0")  # attacks are not RPCs
    with pytest.raises(ValueError):
        parse_spec("scale:factor=0")


def test_attack_delta_application_and_collusion():
    from fedtpu.ft.chaos import parse_spec

    sched = parse_spec("noise:std=1.0,collude=1,seed=9")
    rule = sched.rules[0]
    tree = {"a": np.ones((3, 4), np.float32)}
    out1 = sched.apply_attack_delta(rule, tree, "c1", round_idx=5)
    out2 = sched.apply_attack_delta(rule, tree, "c2", round_idx=5)
    # Colluding: DIFFERENT peers, IDENTICAL noise vector.
    np.testing.assert_array_equal(out1["a"], out2["a"])
    assert not np.array_equal(out1["a"], tree["a"])
    # Non-colluding: per-peer draws differ.
    sched2 = parse_spec("noise:std=1.0,seed=9")
    rule2 = sched2.rules[0]
    i1 = sched2.apply_attack_delta(rule2, tree, "c1", round_idx=5)
    i2 = sched2.apply_attack_delta(rule2, tree, "c2", round_idx=5)
    assert not np.array_equal(i1["a"], i2["a"])
    # sign_flip / scale are exact multiplies.
    flip = parse_spec("sign_flip").rules[0]
    np.testing.assert_array_equal(
        sched.apply_attack_delta(flip, tree, "c", 0)["a"], -tree["a"]
    )


# -------------------------------------------------------------- screen_rows
def test_screen_rows_rejects_outliers_and_flips():
    # Honest FL updates share a direction (the true gradient) plus client
    # noise — unlike pure random vectors, whose pairwise cosines vanish.
    rng = np.random.default_rng(0)
    base = rng.normal(0.0, 1.0, size=(256,)).astype(np.float32)
    honest = base[None, :] + rng.normal(
        0.0, 0.3, size=(7, 256)
    ).astype(np.float32)
    rows = np.concatenate([
        honest,
        honest[:1] * 40.0,   # boosted
        -honest[1:2],        # sign-flipped
    ])
    alive = np.ones((9,), np.float32)
    keep, stats = flat_ops.screen_rows(
        jnp.asarray(rows), jnp.asarray(alive), norm_max=0.0, zmax=6.0,
        cos_min=0.0,
    )
    keep = np.asarray(keep)
    assert keep[:7].all(), np.asarray(stats["z"])
    assert not keep[7], "boosted row survived the z check"
    assert not keep[8], "sign-flipped row survived the cosine check"
    # Absolute norm bound alone.
    keep2, _ = flat_ops.screen_rows(
        jnp.asarray(rows), jnp.asarray(alive),
        norm_max=float(np.linalg.norm(honest, axis=1).max() * 1.5),
        zmax=0.0, cos_min=-1.0,
    )
    keep2 = np.asarray(keep2)
    assert keep2[:7].all() and not keep2[7] and keep2[8]


def test_screen_rows_degenerate_population_keeps():
    """With < 3 live rows the relative statistics are meaningless — only
    the absolute norm bound may reject."""
    rows = jnp.asarray(np.asarray([[1.0, 0.0], [100.0, 0.0]], np.float32))
    keep, _ = flat_ops.screen_rows(
        rows, jnp.ones((2,)), norm_max=0.0, zmax=1.0, cos_min=0.9
    )
    assert np.asarray(keep).all()
    keep2, _ = flat_ops.screen_rows(
        rows, jnp.ones((2,)), norm_max=5.0, zmax=1.0, cos_min=0.9
    )
    np.testing.assert_array_equal(np.asarray(keep2), [True, False])


def test_screen_config_validation():
    assert not screening_enabled(ScreenConfig())
    assert screening_enabled(ScreenConfig(zmax=3.0))
    with pytest.raises(ValueError):
        validate_screen_config(ScreenConfig(cos_min=2.0))
    with pytest.raises(ValueError):
        validate_screen_config(ScreenConfig(ewma=0.0))
    with pytest.raises(ValueError):
        validate_screen_config(
            ScreenConfig(quarantine_at=0.2, release_at=0.5)
        )


# ------------------------------------------------- convergence (acceptance)
def _final_train_loss(fed, rounds):
    fed.run(num_rounds=rounds)
    loss, _acc = fed.evaluate(fed.images, fed.labels)
    return loss


def test_adversarial_convergence_pin():
    """THE acceptance pin: under 30% boosted sign-flip attackers the plain
    mean degrades measurably while screening+krum tracks the clean run."""
    rounds = 8
    clean = Federation(_cfg(), seed=0)
    l_clean = _final_train_loss(clean, rounds)

    attack = SimConfig(malicious_fraction=0.34, attack="scale:factor=-8")
    mean_att = Federation(_cfg(sim=attack), seed=0)
    l_mean = _final_train_loss(mean_att, rounds)

    defended = Federation(
        _cfg(sim=attack, aggregator="krum", trim_fraction=0.34,
             screen=ScreenConfig(zmax=6.0, cos_min=0.0)),
        seed=0,
    )
    l_def = _final_train_loss(defended, rounds)

    # Unscreened mean measurably degrades...
    assert l_mean > l_clean * 1.5 + 0.1, (l_clean, l_mean, l_def)
    # ...while the defended run tracks the clean one (documented tolerance:
    # krum applies ONE client's delta per round, so it trains slower than
    # the mean of all honest clients but must stay the same order).
    assert l_def < l_clean * 3.0 + 0.2, (l_clean, l_mean, l_def)
    assert l_def < l_mean * 0.5, (l_clean, l_mean, l_def)


def test_attack_replays_bit_identically_from_seed():
    """Same config -> byte-identical attacked trajectory (the determinism
    contract PR 5 chaos set, extended to model-level attacks)."""
    attack = SimConfig(malicious_fraction=0.34,
                       attack="noise:std=0.5,p=0.7,seed=3")
    a = Federation(_cfg(sim=attack), seed=0)
    b = Federation(_cfg(sim=attack), seed=0)
    a.run(num_rounds=3)
    b.run(num_rounds=3)
    for x, y in zip(jax.tree_util.tree_leaves(a.state.params),
                    jax.tree_util.tree_leaves(b.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # A different attack seed perturbs the trajectory (the noise draw is
    # keyed on it), so the pin above is not vacuously comparing no-ops.
    c = Federation(
        _cfg(sim=SimConfig(malicious_fraction=0.34,
                           attack="noise:std=0.5,p=0.7,seed=4")),
        seed=0,
    )
    c.run(num_rounds=3)
    same = all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a.state.params),
                        jax.tree_util.tree_leaves(c.state.params))
    )
    assert not same


def test_label_flip_poisons_only_attacker_shards():
    cfg = _cfg(sim=SimConfig(malicious_fraction=0.34,
                             attack="label_flip:offset=3"))
    probe = Federation(_cfg(), seed=0)
    fed = Federation(cfg, seed=0)
    attackers = np.flatnonzero(fed.attacker_clients)
    assert len(attackers) == 2
    base = np.asarray(probe.labels)
    poisoned = np.asarray(fed.labels)
    for c in range(cfg.fed.num_clients):
        own = fed.client_idx[c][fed.client_mask[c]]
        if c in attackers:
            np.testing.assert_array_equal(
                poisoned[own], (base[own] + 3) % 10
            )
        else:
            np.testing.assert_array_equal(poisoned[own], base[own])


def test_sim_population_malicious_axis():
    """SimFederation carries the attacker set at population scope; the
    per-seat mask follows the cohort."""
    from fedtpu.sim.engine import SimFederation

    cfg = _cfg(
        n=6,
        sim=SimConfig(population=24, malicious_fraction=0.25,
                      attack="sign_flip"),
        # cos_min -0.5: only strong contrarians (sign-flip scores ~-1) —
        # honest cosines on a 6-seat cohort are noisy (see the soak
        # calibration notes in tools/chaos_soak.py).
        screen=ScreenConfig(zmax=6.0, cos_min=-0.5),
    )
    sf = SimFederation(cfg, seed=0)
    assert sf._pop_attackers.sum() == 6  # floor(0.25 * 24)
    caught = set()
    for r in range(3):
        m = sf.step()
        expected = (
            sf._pop_attackers[sf._cohort_ids] & sf.alive
        ).astype(np.float32)
        # The per-SEAT mask tracks the cohort exactly — the plumbing the
        # sim axis exists for.
        np.testing.assert_array_equal(sf._attack_seats, expected)
        screened = np.asarray(m.screened)
        caught |= {
            int(sf._cohort_ids[i]) for i in np.flatnonzero(screened)
            if expected[i]
        }
    # While training still carries signal (early rounds), screening
    # catches sign-flipped attackers. Detection is NOT expected to be
    # per-round exhaustive: a sign-flip of a converged, noise-level
    # update is both undetectable and harmless (bounded influence), and
    # the convergence pin above is the accuracy-level acceptance.
    assert caught, "no attacker was ever screened in the signal phase"


# ------------------------------------------- quarantine drill (acceptance)
def test_quarantine_evict_drill_over_grpc():
    """End-to-end over real gRPC: a persistent attacker is flagged,
    quarantined, then evicted through the live MembershipTable; the
    roster + reputation change survives a backup promotion; no honest
    client dies."""
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.transport.federation import (
        BackupServer, PrimaryServer, serve_client,
    )

    cfg = _cfg(
        n=4, rounds=10,
        screen=ScreenConfig(zmax=6.0, cos_min=0.0, ewma=0.5,
                            quarantine_at=0.6, release_at=0.2,
                            evict_after=3),
        retry=RetryPolicy(max_attempts=2, backoff_s=0.01),
        ft_heartbeat_period_s=1e6,
    )
    servers, addrs, agents = [], [], []
    backup_srv = None
    try:
        for i in range(4):
            addr = f"localhost:{chaos_soak.free_port()}"
            chaos = (
                parse_spec("sign_flip:p=1.0,seed=11") if i == 0 else None
            )
            srv, agent = serve_client(addr, cfg, seed=i, chaos=chaos)
            servers.append(srv)
            addrs.append(addr)
            agents.append(agent)
        attacker = addrs[0]
        backup_addr = f"localhost:{chaos_soak.free_port()}"
        backup = BackupServer(cfg, addrs, watchdog_timeout=3600.0)
        backup_srv = backup.start(backup_addr)
        primary = PrimaryServer(cfg, addrs, backup_address=backup_addr)

        saw_quarantine = False
        for _ in range(8):
            rec = primary.round()
            assert not rec.get("aborted")
            if attacker in rec.get("quarantined", []):
                saw_quarantine = True
                # Quarantined = still served, updates ignored: the
                # attacker keeps its membership while ignored.
                assert primary.registry.is_member(attacker)
            if not primary.registry.is_member(attacker):
                break
        assert saw_quarantine, "attacker was never quarantined"
        assert not primary.registry.is_member(attacker), (
            "attacker never escalated to eviction"
        )
        # No honest client died — screening is surgical.
        assert primary.registry.dead_clients() == []
        assert set(primary.registry.clients) == set(addrs[1:])
        # A late RPC outcome for the evicted attacker log-and-ignores.
        primary.registry.mark_failed(attacker)
        assert not primary.registry.is_member(attacker)

        # One more round replicates the post-eviction roster; the promoted
        # backup must inherit it (and the clean reputation table).
        primary.round()
        backup._promote()
        try:
            acting = backup.acting
            assert acting is not None
            assert set(acting.registry.clients) == set(addrs[1:])
            assert not acting.registry.is_member(attacker)
            assert acting.registry.quarantined_clients() == []
        finally:
            backup._stop_acting(wait=30.0)
    finally:
        if backup_srv is not None:
            backup.watchdog.stop()
            backup_srv.stop(0)
        for s in servers:
            s.stop(0)


def test_quarantined_client_can_redeem_itself():
    """A FALSELY flagged client must exit quarantine once its verdicts go
    clean (the release threshold) — quarantine is containment, not a
    death sentence."""
    from fedtpu.ft.chaos import parse_spec
    from fedtpu.transport.federation import PrimaryServer, serve_client

    # The attack stops after round 2 (rounds window), so the client turns
    # honest while quarantined and its suspicion decays.
    cfg = _cfg(
        n=4, rounds=12,
        screen=ScreenConfig(zmax=6.0, cos_min=0.0, ewma=0.5,
                            quarantine_at=0.6, release_at=0.2,
                            evict_after=0),  # never auto-evict
        ft_heartbeat_period_s=1e6,
    )
    servers, addrs = [], []
    try:
        for i in range(4):
            addr = f"localhost:{chaos_soak.free_port()}"
            chaos = (
                parse_spec("sign_flip:p=1.0,rounds=0-3,seed=5")
                if i == 0 else None
            )
            srv, _ = serve_client(addr, cfg, seed=i, chaos=chaos)
            servers.append(srv)
            addrs.append(addr)
        reformed = addrs[0]
        primary = PrimaryServer(cfg, addrs)
        quarantined_seen = released = False
        for _ in range(10):
            primary.round()
            if primary.registry.is_quarantined(reformed):
                quarantined_seen = True
            elif quarantined_seen:
                released = True
                break
        assert quarantined_seen, "attack window never triggered quarantine"
        assert released, "clean verdicts never released the client"
        assert primary.registry.is_member(reformed)
        # Released client's rows aggregate again.
        rec = primary.round()
        assert rec["aggregated"] == 4, rec
    finally:
        for s in servers:
            s.stop(0)


@pytest.mark.slow
def test_byzantine_soak_full():
    """The full 100-round Byzantine soak (also committed as
    artifacts/BYZANTINE_SOAK.json)."""
    result = chaos_soak.run_byzantine_soak(verbose=False)
    assert result["ok"] is True
