"""Real two-process ``jax.distributed`` smoke.

Spawns two subprocesses running ``examples/multihost_cpu.py`` — each pins a
4-virtual-device CPU platform, joins the cluster through
``fedtpu.parallel.multihost.initialize`` (the true multi-controller init
path, not a mock), builds one global 8-device mesh, and executes a full
sharded federated round whose FedAvg psum crosses the process boundary.
CPU stand-in for the reference's manual multi-machine launch
(``README.md:6-17``).
"""

import os
import socket
import subprocess
import sys

import pytest

# Capability marker, not a bug marker: some jaxlib CPU builds (observed:
# 0.4.37 in this container) implement jax.distributed bring-up but NOT
# cross-process computations on the CPU backend — every program spanning the
# two processes dies with this exact runtime error regardless of what fedtpu
# does. Skipping on it keeps the tier-1 dots honest where the capability is
# absent while the test still runs in full wherever multiprocess CPU
# collectives exist.
_NO_MULTIPROC_CPU = "Multiprocess computations aren't implemented on the CPU"

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "examples", "multihost_cpu.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(port: int, extra=()):
    env = dict(os.environ)
    # The child pins its own platform/device count; scrub ours so the
    # conftest's 8-device flag doesn't leak in.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _SCRIPT, "--process-id", str(i), "--port", str(port)]
            + list(extra),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            try:
                # 480 s: the --all spawn runs jax import + gloo bring-up +
                # THREE legs, and this 1-core host runs ~2x slower when a
                # heavy job shares it. A timeout feeds the rc!=0 retry path
                # instead of escaping as a raw TimeoutExpired.
                out, err = p.communicate(timeout=480)
                outs.append((p.returncode, out, err))
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                outs.append((124, out or "", (err or "") + "\n[timeout 480s]"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _run_and_check(markers, agree_keys, extra=()):
    """Launch both controllers, assert success + every ``markers`` entry
    (a list) in each output, and assert both agree on every ``agree_keys``
    (a list) tagged value (same psum result / same sampling masks). The
    free-port probe is inherently racy (the socket closes before the
    coordinator binds it), so a failed attempt retries once on a new
    port."""
    for attempt in range(2):
        outs = _launch(_free_port(), extra=extra)
        if all(rc == 0 for rc, _, _ in outs) or attempt == 1:
            break
    if any(_NO_MULTIPROC_CPU in err for _, _, err in outs):
        pytest.skip(
            "jaxlib CPU backend in this environment cannot run cross-process "
            "computations (XlaRuntimeError: Multiprocess computations aren't "
            "implemented on the CPU backend)"
        )
    for rc, out, err in outs:
        assert rc == 0, f"child failed (rc={rc}):\n{out}\n{err}"
        for marker in markers:
            assert marker in out, out
    for key in agree_keys:
        agreed = {line.split(key)[1] for rc, out, _ in outs
                  for line in out.splitlines() if key in line}
        assert len(agreed) == 1, (key, agreed)
    return outs


def test_two_process_all_legs():
    """ONE two-process jax.distributed spawn covering the three legs (each
    spawn costs ~20 s of jax import + gloo bring-up per process on this
    1-core host, so they share one cluster):

    1. Raw sharded round: mesh spanning both processes, cross-process psum
       FedAvg; both controllers agree on the aggregate loss.
    2. The high-level Federation engine: sharded per-client state,
       on-device gather, converging loss, then the fused multi-round scan
       (run_on_device) — controllers agree on every round's aggregate and
       the fused stack ("losses=" covers both lists).
    3. Loss-proportional participation sampling (round-5: previously
       rejected as single-controller-only): each process allgathers the
       sharded per-client loss vector, so the round-seeded draw yields the
       SAME mask on both hosts ("masks=" lists four consecutive rounds).
    """
    outs = _run_and_check(
        ["multihost ok", "multihost engine ok", "multihost loss-sampling ok"],
        ["loss=", "losses=", "masks="],
        extra=["--all"],
    )
    for _, out, _ in outs:
        assert "8 global devices" in out, out
