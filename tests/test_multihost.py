"""Real two-process ``jax.distributed`` smoke.

Spawns two subprocesses running ``examples/multihost_cpu.py`` — each pins a
4-virtual-device CPU platform, joins the cluster through
``fedtpu.parallel.multihost.initialize`` (the true multi-controller init
path, not a mock), builds one global 8-device mesh, and executes a full
sharded federated round whose FedAvg psum crosses the process boundary.
CPU stand-in for the reference's manual multi-machine launch
(``README.md:6-17``).
"""

import os
import socket
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(_REPO, "examples", "multihost_cpu.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(port: int, extra=()):
    env = dict(os.environ)
    # The child pins its own platform/device count; scrub ours so the
    # conftest's 8-device flag doesn't leak in.
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _SCRIPT, "--process-id", str(i), "--port", str(port)]
            + list(extra),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _run_and_check(marker: str, agree_key: str, extra=()):
    """Launch both controllers, assert success + ``marker`` in each output,
    and assert both agree on the ``agree_key``-tagged value (same psum
    result). The free-port probe is inherently racy (the socket closes
    before the coordinator binds it), so a failed attempt retries once on a
    new port."""
    for attempt in range(2):
        outs = _launch(_free_port(), extra=extra)
        if all(rc == 0 for rc, _, _ in outs) or attempt == 1:
            break
    for rc, out, err in outs:
        assert rc == 0, f"child failed (rc={rc}):\n{out}\n{err}"
        assert marker in out, out
    agreed = {line.split(agree_key)[1] for rc, out, _ in outs
              for line in out.splitlines() if agree_key in line}
    assert len(agreed) == 1, agreed
    return outs


def test_two_process_distributed_round():
    outs = _run_and_check("multihost ok", "loss=")
    for _, out, _ in outs:
        assert "8 global devices" in out, out


def test_two_process_federation_engine():
    """The high-level Federation engine itself over two controllers: mesh
    spanning both processes, sharded per-client state, on-device gather,
    cross-process psum FedAvg, converging loss — and both controllers agree
    on every round's aggregate. The run ends with the fused multi-round
    scan (run_on_device) over the same multi-controller mesh; both
    controllers must agree on its stacked losses too."""
    # The agree check on "losses=" covers the whole suffix of the status
    # line, which includes the fused list — one assertion, both values.
    _run_and_check("multihost engine ok", "losses=", extra=["--engine"])


def test_two_process_loss_sampling_masks_agree():
    """Loss-proportional participation sampling over two controllers
    (round-5: previously rejected as single-controller-only): each process
    allgathers the sharded per-client loss vector, so the round-seeded draw
    yields the SAME participation mask on both hosts — asserted via the
    masks= suffix, which lists four consecutive rounds' masks."""
    _run_and_check("multihost loss-sampling ok", "masks=",
                   extra=["--loss-sampling"])
