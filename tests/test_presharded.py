"""Presharded device-data layout (fedtpu.data.device, DataConfig.device_layout).

Round-4 finding (artifacts/MFU_PROFILE_r04.json): the gather layout's
computed-index row-gather lowers on TPU to a serial ~2 us dynamic-slice loop
per example (~250k ops/dispatch at the 64-client CIFAR bench) and dominates
the fused round. The presharded layout reorganises the dataset once at upload
into [clients, 2*shard_len, features] so each round's batches are one
contiguous rotated slice. These tests pin its semantics:

* bit-parity with the gather layout and the host oracle when unshuffled
  (round_robin — the reference's own unshuffled-loader semantics,
  src/main.py:140);
* rotation shuffling draws only from each client's own shard, varies across
  rounds, and is deterministic;
* stream (per-step slicing) == non-stream (materialised window) bit-for-bit;
* fused scan == sequential stepping, mesh == single-program;
* multi-local-epoch windows (need > shard length) cycle like `pos % length`.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.data import partition
from fedtpu.data.device import (
    make_data_round_step,
    preshard_arrays,
    presharded_window,
)


def _cfg(layout="presharded", part="round_robin", clients=3, **kw):
    base = dict(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition=part,
            num_examples=96,
            augment=False,
            device_layout=layout,
        ),
        fed=FedConfig(num_clients=clients),
        steps_per_round=2,
    )
    base.update(kw)
    return RoundConfig(**base)


def _leaves(state):
    return jax.tree_util.tree_leaves(state.params)


def test_preshard_arrays_layout_and_cycling():
    rng = np.random.default_rng(0)
    images = rng.normal(size=(20, 2, 2, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=20)
    idx, mask = partition.dirichlet(labels, 3, alpha=0.5, seed=0)
    xs, ys = preshard_arrays(images, labels, idx, mask)
    n, L = idx.shape
    assert xs.shape == (n, 2 * L, 4) and ys.shape == (n, 2 * L)
    flat = images.reshape(20, -1)
    for c in range(n):
        own = idx[c][mask[c]]
        if not len(own):
            assert not xs[c].any()
            continue
        expect = own[np.arange(L) % len(own)]
        np.testing.assert_array_equal(ys[c][:L], labels[expect])
        np.testing.assert_array_equal(ys[c][L:], ys[c][:L])  # doubled
        np.testing.assert_array_equal(xs[c][:L], flat[expect])


def test_window_rotates_and_wraps():
    n, L, F = 2, 5, 3
    base = np.arange(n * L * F, dtype=np.float32).reshape(n, L, F)
    xs = jnp.asarray(np.concatenate([base, base], axis=1))
    ys_b = np.arange(n * L, dtype=np.int32).reshape(n, L)
    ys = jnp.asarray(np.concatenate([ys_b, ys_b], axis=1))
    # need (4) <= L: one contiguous slice at the offset.
    x, y = presharded_window(xs, ys, jnp.int32(3), steps=2, batch_size=2,
                             shape=(3,))
    np.testing.assert_array_equal(
        np.asarray(y).reshape(n, -1),
        [[3, 4, 0, 1], [8, 9, 5, 6]],
    )
    assert x.shape == (n, 2, 2, 3)
    # need (8) > L: the rotated epoch cycles, pos % L semantics.
    x, y = presharded_window(xs, ys, jnp.int32(3), steps=4, batch_size=2,
                             shape=(3,))
    np.testing.assert_array_equal(
        np.asarray(y)[0].reshape(-1),
        [3, 4, 0, 1, 2, 3, 4, 0],
    )


def test_round_robin_presharded_equals_gather_and_host():
    """Unshuffled semantics are bit-identical across all three paths."""
    fp = Federation(_cfg("presharded"), seed=0)
    fg = Federation(_cfg("gather"), seed=0)
    fh = Federation(_cfg("presharded"), seed=0)
    fp.step()
    fg.step()
    fh.step(fh.round_batch(0))
    for a, b, c in zip(_leaves(fp.state), _leaves(fg.state), _leaves(fh.state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), atol=1e-6)


def test_rotation_shuffle_stays_in_shard_and_varies():
    """Every example a client trains on in rotate mode belongs to its own
    shard, the window changes across rounds, and reruns are deterministic."""
    labels = np.random.default_rng(0).integers(0, 10, size=60)
    images = np.zeros((60, 2, 2, 1), np.float32)
    idx, mask = partition.dirichlet(labels, 3, alpha=0.5, seed=0)
    xs, ys = preshard_arrays(images, labels, idx, mask)
    key = jax.random.PRNGKey(7)
    wins = []
    for r in range(3):
        rng = jax.random.fold_in(key, r)
        off = jax.random.randint(rng, (), 0, idx.shape[1])
        _, y = presharded_window(jnp.asarray(xs), jnp.asarray(ys), off,
                                 steps=2, batch_size=2, shape=(4,))
        wins.append(np.asarray(y))
    for c in range(3):
        own = set(labels[idx[c][mask[c]]].tolist())
        for w in wins:
            assert set(w[c].reshape(-1).tolist()) <= own
    assert any(not np.array_equal(wins[0], w) for w in wins[1:])
    rng = jax.random.fold_in(key, 0)
    off = jax.random.randint(rng, (), 0, idx.shape[1])
    _, again = presharded_window(jnp.asarray(xs), jnp.asarray(ys), off,
                                 steps=2, batch_size=2, shape=(4,))
    np.testing.assert_array_equal(wins[0], np.asarray(again))


def test_stream_equals_materialised_window():
    cfg = _cfg(part="iid")
    fed = Federation(cfg, seed=0)
    xs, ys = preshard_arrays(fed.images, fed.labels, fed.client_idx,
                             fed.client_mask)
    args = (
        jnp.asarray(xs), jnp.asarray(ys),
        jnp.asarray(fed.client_idx), jnp.asarray(fed.client_mask),
        fed.weights, jnp.ones((3,), bool), jax.random.PRNGKey(0),
    )
    outs = []
    for stream in (False, True):
        step = jax.jit(make_data_round_step(
            fed.model, cfg, 2, shuffle=True, layout="presharded",
            stream=stream,
        ))
        st, _ = step(Federation(cfg, seed=0).state, *args)
        outs.append(st)
    for a, b in zip(_leaves(outs[0]), _leaves(outs[1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_fused_scan_equals_sequential_presharded():
    cfg = _cfg(part="iid")
    fa, fb = Federation(cfg, seed=0), Federation(cfg, seed=0)
    fa.run_on_device(3)
    for _ in range(3):
        fb.step()
    for a, b in zip(_leaves(fa.state), _leaves(fb.state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_mesh_equals_single_program_presharded(eight_devices):
    from jax.sharding import Mesh

    cfg = _cfg(part="dirichlet", clients=8,
               data=DataConfig(dataset="synthetic", batch_size=4,
                               partition="dirichlet", num_examples=256,
                               augment=False))
    mesh = Mesh(np.array(eight_devices).reshape(8,), ("clients",))
    fm = Federation(cfg, seed=0, mesh=mesh)
    fs = Federation(cfg, seed=0)
    fm.step()
    fs.step()
    for a, b in zip(_leaves(fm.state), _leaves(fs.state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_async_engine_presharded_matches_gather_unshuffled():
    """The async tick's presharded path: round_robin (unshuffled) keeps both
    layouts bit-identical through a buffered-aggregation tick."""
    from fedtpu.core.async_engine import AsyncFederation

    outs = []
    for layout in ("presharded", "gather"):
        af = AsyncFederation(_cfg(layout, clients=4,
                                  fed=FedConfig(num_clients=4)), seed=0,
                             buffer_k=2)
        af.tick()
        outs.append(af.state)
    for a, b in zip(jax.tree_util.tree_leaves(outs[0].params),
                    jax.tree_util.tree_leaves(outs[1].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_empty_shard_client_is_masked():
    """A client with no data trains zero steps and contributes nothing —
    same invariant the gather layout pins."""
    labels = np.array([0, 1] * 12)
    images = np.random.default_rng(0).normal(size=(24, 2, 2, 1)).astype(
        np.float32
    )
    idx = np.zeros((3, 8), np.int64)
    mask = np.zeros((3, 8), bool)
    idx[0], mask[0] = np.arange(8), True
    idx[1], mask[1] = np.arange(8, 16), True
    # client 2: empty shard
    xs, ys = preshard_arrays(images, labels, idx, mask)
    assert not xs[2].any()
    cfg = _cfg(clients=3,
               data=DataConfig(dataset="synthetic", batch_size=4,
                               partition="iid", num_examples=24,
                               augment=False))
    fed = Federation(cfg, seed=0, data=(images, labels))
    fed.client_idx, fed.client_mask = idx, mask
    fed.weights = jnp.asarray(partition.shard_sizes(mask))
    m = fed.step()
    per_client = np.asarray(m.per_client_loss)
    assert np.isnan(per_client[2]) or per_client[2] == 0.0


def test_unknown_layout_raises():
    with pytest.raises(ValueError, match="device_layout"):
        Federation(_cfg("bogus"), seed=0)
    with pytest.raises(ValueError, match="device_layout"):
        make_data_round_step(None, _cfg(), 2, layout="bogus")
