"""Transport layer: proto codec, wire format, gRPC service loopback.

Wire parity is checked two ways: round-trips through our hand-rolled codec,
and — when the reference's generated ``federated_pb2`` is importable —
byte-for-byte cross-validation against protoc's output for every message
type (``federated.proto:24-63``).
"""

import os
import socket
import sys

import numpy as np
import pytest

from fedtpu.transport import proto, wire


# ------------------------------------------------------------------ proto
def test_train_request_roundtrip():
    for rank, world in [(0, 0), (1, 2), (63, 64), (2**31 - 1, 1), (-1, -5)]:
        msg = proto.TrainRequest(rank=rank, world=world)
        assert proto.TrainRequest.decode(msg.encode()) == msg


def test_train_request_round_field():
    """The additive lineage-round field (disaster recovery): encodes as
    round+1 so "unknown" (-1) is the proto3 omitted default — bytes from
    peers that predate the field decode as round=-1, and a request with
    round unset is byte-identical to a pre-field encoder's output."""
    for rnd in (-1, 0, 1, 17, 2**20):
        msg = proto.TrainRequest(rank=1, world=4, round=rnd)
        assert proto.TrainRequest.decode(msg.encode()) == msg
    # Unset round adds zero bytes: old decoders see exactly the old wire.
    legacy = proto.TrainRequest(rank=3, world=8)
    assert legacy.encode() == b"\x08\x03\x10\x08"  # no field-3 tag at all
    assert proto.TrainRequest.decode(legacy.encode()).round == -1
    # round=0 must survive (it is a real round, not the absent default).
    assert proto.TrainRequest.decode(
        proto.TrainRequest(round=0).encode()
    ).round == 0


def test_epoch_fields_roundtrip_and_stay_wire_compatible():
    """The additive fencing-epoch fields (split-brain elimination): same
    round+1 omit-zero pattern as TrainRequest.round — epoch unset (-1)
    adds ZERO bytes, so a fencing-aware peer's legacy traffic is
    byte-identical to a pre-fencing encoder's, and old bytes decode as
    epoch=-1 ("absent"), never colliding with a real epoch 0."""
    # TrainRequest.epoch (field 4).
    for ep in (-1, 0, 1, 42, 2**20):
        msg = proto.TrainRequest(rank=1, world=4, round=2, epoch=ep)
        assert proto.TrainRequest.decode(msg.encode()) == msg
    legacy = proto.TrainRequest(rank=3, world=8)
    assert legacy.encode() == b"\x08\x03\x10\x08"  # no field-3/4 tags at all
    assert proto.TrainRequest.decode(legacy.encode()).epoch == -1
    assert proto.TrainRequest.decode(
        proto.TrainRequest(epoch=0).encode()
    ).epoch == 0
    # SendModelRequest.epoch (field 2, +1) and .role (field 3, plain: 0 is
    # the legacy/unset default and stays off the wire).
    for ep, role in [(-1, 0), (0, 1), (7, 2)]:
        msg = proto.SendModelRequest(model=b"m", epoch=ep, role=role)
        assert proto.SendModelRequest.decode(msg.encode()) == msg
    legacy_sm = proto.SendModelRequest(model=b"payload")
    assert legacy_sm.encode() == b"\x0a\x07payload"  # field 1 only
    got = proto.SendModelRequest.decode(legacy_sm.encode())
    assert (got.epoch, got.role) == (-1, 0)
    # PingRequest.epoch (field 2, +1).
    for ep in (-1, 0, 9):
        msg = proto.PingRequest(req=b"r", epoch=ep)
        assert proto.PingRequest.decode(msg.encode()) == msg
    assert proto.PingRequest(req=b"x").encode() == b"\x0a\x01x"
    assert proto.PingRequest.decode(b"\x0a\x01x").epoch == -1


def test_submit_partial_messages_stay_wire_compatible():
    """The hierarchical-aggregation RPC (PR 14) is ADDITIVE: a brand-new
    method with its own messages, proto3 omit-zero throughout — so tiered
    builds put zero new bytes on any legacy RPC, a default-valued request
    encodes to b"" exactly, and a legacy peer that never registered
    SubmitPartial answers UNIMPLEMENTED (the root treats that as a dead
    aggregator, not a protocol error)."""
    for msg in [
        proto.SubmitPartialRequest(),
        proto.SubmitPartialRequest(rank_base=0, world=4, round=0, epoch=0),
        proto.SubmitPartialRequest(
            rank_base=2**20, world=2**24, round=17, epoch=3
        ),
    ]:
        assert proto.SubmitPartialRequest.decode(msg.encode()) == msg
    # The unset request IS the empty message (all four fields omit-zero:
    # rank_base/world plain zeros, round/epoch the +1 pattern).
    assert proto.SubmitPartialRequest().encode() == b""
    got = proto.SubmitPartialRequest.decode(b"")
    assert (got.rank_base, got.world, got.round, got.epoch) == (0, 0, -1, -1)
    # Byte pin: the exact varint layout is frozen — field 1/2 plain,
    # field 3/4 shifted by one so epoch 0 survives omit-zero.
    pinned = proto.SubmitPartialRequest(
        rank_base=4, world=16, round=3, epoch=2
    )
    assert pinned.encode().hex() == "0804101018042003"
    # round=0 / epoch=0 are real values, distinct from absent.
    z = proto.SubmitPartialRequest.decode(
        proto.SubmitPartialRequest(round=0, epoch=0).encode()
    )
    assert (z.round, z.epoch) == (0, 0)

    for reply in [
        proto.SubmitPartialReply(),
        proto.SubmitPartialReply(record=bytes(range(256)), clients=12),
    ]:
        assert proto.SubmitPartialReply.decode(reply.encode()) == reply
    assert proto.SubmitPartialReply().encode() == b""
    assert proto.SubmitPartialReply(
        record=b"r", clients=3
    ).encode() == b"\x0a\x01r\x10\x03"


def test_legacy_peer_without_submit_partial_answers_unimplemented():
    """Dial a server whose servicer predates the tier (no SubmitPartial
    handler): the call must fail UNIMPLEMENTED — the typed signal the
    root's retry policy treats as a dead peer, never a crash."""
    grpc = pytest.importorskip("grpc")
    from fedtpu.transport.service import (
        TrainerServicer, TrainerStub, create_channel, create_server,
    )

    class LegacyServicer(TrainerServicer):
        def SendModel(self, request, context):
            return proto.SendModelReply(reply=b"ok")

        def StartTrain(self, request, context):
            return proto.TrainReply(message=b"m")

        def HeartBeat(self, request, context):
            return proto.HeartBeatResponse(status=1)

    s = socket.socket()
    s.bind(("localhost", 0))
    addr = f"localhost:{s.getsockname()[1]}"
    s.close()
    server = create_server(addr, LegacyServicer())
    server.start()
    try:
        stub = TrainerStub(create_channel(addr))
        # The legacy surface still answers.
        assert stub.HeartBeat(proto.Request(), timeout=10).status == 1
        with pytest.raises(grpc.RpcError) as err:
            stub.SubmitPartial(proto.SubmitPartialRequest(), timeout=10)
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
    finally:
        server.stop(0)


def test_bytes_messages_roundtrip():
    payload = bytes(range(256)) * 100  # non-UTF8 on purpose
    for cls, field in [
        (proto.TrainReply, "message"),
        (proto.SendModelRequest, "model"),
        (proto.SendModelReply, "reply"),
        (proto.PingRequest, "req"),
    ]:
        msg = cls(**{field: payload})
        assert getattr(cls.decode(msg.encode()), field) == payload
        assert cls.decode(b"") == cls()  # proto3 default


def test_scalar_messages_roundtrip():
    assert proto.HeartBeatResponse.decode(
        proto.HeartBeatResponse(status=1).encode()
    ).status == 1
    assert proto.PingResponse.decode(
        proto.PingResponse(value=7).encode()
    ).value == 7
    assert proto.Request.decode(proto.Request().encode()) == proto.Request()


def test_proto_truncated_raises():
    with pytest.raises(proto.ProtoError):
        proto._decode_fields(b"\x0a\xff")  # length 255, no bytes follow


_REFERENCE_SRC = "/root/reference/src"


@pytest.mark.skipif(
    not os.path.isdir(_REFERENCE_SRC), reason="reference checkout not mounted"
)
def test_wire_parity_with_reference_pb2():
    """Our bytes must parse in protoc-generated code and vice versa."""
    pytest.importorskip("google.protobuf")
    sys.path.insert(0, _REFERENCE_SRC)
    try:
        import federated_pb2 as pb2
    except Exception as e:  # pragma: no cover - descriptor version skew
        pytest.skip(f"reference pb2 unimportable: {e}")
    finally:
        sys.path.remove(_REFERENCE_SRC)

    # ours -> protoc
    theirs = pb2.TrainRequest()
    theirs.ParseFromString(proto.TrainRequest(rank=3, world=64).encode())
    assert (theirs.rank, theirs.world) == (3, 64)

    # protoc -> ours
    msg = pb2.TrainRequest(rank=5, world=8)
    ours = proto.TrainRequest.decode(msg.SerializeToString())
    assert (ours.rank, ours.world) == (5, 8)

    assert pb2.HeartBeatResponse.FromString(
        proto.HeartBeatResponse(status=1).encode()
    ).status == 1
    assert proto.PingResponse.decode(
        pb2.PingResponse(value=2).SerializeToString()
    ).value == 2

    reply = pb2.TrainReply(message="hello")
    assert proto.TrainReply.decode(reply.SerializeToString()).message == b"hello"
    back = pb2.TrainReply()
    back.ParseFromString(proto.TrainReply(message=b"hello").encode())
    assert back.message == "hello"


# ------------------------------------------------------------------- wire
def _tree(rng):
    return {
        "w": rng.normal(size=(8, 16)).astype(np.float32),
        "b": rng.normal(size=(16,)).astype(np.float32),
        "nested": {"s": np.float32(3.0)},
    }


def test_wire_roundtrip(rng):
    tree = _tree(rng)
    like = {k: np.zeros_like(v) if isinstance(v, np.ndarray) else np.float32(0)
            for k, v in tree.items() if k != "nested"}
    like["nested"] = {"s": np.float32(0)}
    for compress in (False, True):
        data = wire.encode(tree, compress=compress)
        out = wire.decode(data, like)
        np.testing.assert_array_equal(out["w"], tree["w"])
        np.testing.assert_array_equal(out["b"], tree["b"])
        assert float(out["nested"]["s"]) == 3.0


def test_wire_compression_shrinks():
    tree = {"w": np.zeros((1000, 100), np.float32)}  # highly compressible
    raw = wire.encode(tree, compress=False)
    packed = wire.encode(tree, compress=True)
    assert len(packed) < len(raw) / 10


def test_wire_rejects_corruption(rng):
    tree = _tree(rng)
    data = bytearray(wire.encode(tree))
    data[-1] ^= 0xFF
    with pytest.raises(wire.WireError):
        wire.decode(bytes(data), tree)
    with pytest.raises(wire.WireError):
        wire.decode(b"nope" + bytes(20), tree)


def test_wire_no_base64_inflation(rng):
    """The whole point vs the reference (src/client.py:21): payload size is
    ~= raw array bytes, not 4/3 of them."""
    tree = {"w": rng.normal(size=(256, 256)).astype(np.float32)}
    raw_bytes = tree["w"].nbytes
    assert len(wire.encode(tree)) < raw_bytes * 1.01 + 256


# ------------------------------------------------------- gRPC service loop
def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_grpc_service_loopback():
    """Stub <-> servicer over real gRPC on localhost, with an echo servicer —
    validates method paths, serializers, and the 4-RPC surface without any
    training."""
    grpc = pytest.importorskip("grpc")
    from fedtpu.transport.service import (
        TrainerServicer,
        TrainerStub,
        create_channel,
        create_server,
        probe,
    )

    class Echo(TrainerServicer):
        def StartTrain(self, request, context):
            return proto.TrainReply(
                message=f"{request.rank}/{request.world}".encode()
            )

        def SendModel(self, request, context):
            return proto.SendModelReply(reply=request.model[::-1])

        def HeartBeat(self, request, context):
            return proto.HeartBeatResponse(status=1)

        def CheckIfPrimaryUp(self, request, context):
            return proto.PingResponse(value=1 if request.req == b"1" else 0)

    addr = f"localhost:{free_port()}"
    server = create_server(addr, Echo())
    server.start()
    try:
        stub = TrainerStub(create_channel(addr))
        assert stub.StartTrain(
            proto.TrainRequest(rank=2, world=8), timeout=5
        ).message == b"2/8"
        assert stub.SendModel(
            proto.SendModelRequest(model=b"abc"), timeout=5
        ).reply == b"cba"
        assert probe(stub, timeout=5).status == 1
        assert stub.CheckIfPrimaryUp(
            proto.PingRequest(req=b"1"), timeout=5
        ).value == 1
    finally:
        server.stop(0)


def test_probe_unreachable_returns_none():
    pytest.importorskip("grpc")
    from fedtpu.transport.service import TrainerStub, create_channel, probe

    stub = TrainerStub(create_channel(f"localhost:{free_port()}"))
    assert probe(stub, timeout=0.5) is None


def test_payload_kind_flag():
    """Frame flag bit 1 stamps the payload kind so receivers dispatch on it
    explicitly instead of template-guessing (VERDICT r3 weak #6)."""
    import numpy as np

    tree = {"a": np.arange(4, dtype=np.float32)}
    assert wire.payload_kind(wire.encode(tree)) == "model"
    assert wire.payload_kind(wire.encode(tree, kind="replica")) == "replica"
    rz = wire.encode(tree, compress=True, kind="replica")
    assert wire.payload_kind(rz) == "replica"  # composes with zlib flag
    out = wire.decode(rz, {"a": np.zeros(4, np.float32)})
    np.testing.assert_array_equal(out["a"], tree["a"])
    with pytest.raises(ValueError):
        wire.encode(tree, kind="bogus")
    with pytest.raises(wire.WireError):
        wire.payload_kind(b"not a frame")
