"""CLI run loop — fused-block eval/checkpoint cadences.

--fused N honors eval/checkpoint cadences by interval-crossing at block
boundaries (mid-block model states never exist on the host); these tests pin
the exact rounds that get evals and the exact checkpoint files written.
"""

import json
import os

from fedtpu.cli import run as cli_run


def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh]


def test_fused_cadences_write_expected_evals_and_checkpoints(tmp_path):
    metrics = str(tmp_path / "m.jsonl")
    ckpt = str(tmp_path / "ckpt")
    rc = cli_run.main([
        "--platform", "cpu",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-clients", "3", "--rounds", "10", "--num-examples", "192",
        "--batch-size", "4", "--steps-per-round", "2", "--lr", "0.05",
        "--partition", "iid",
        "--fused", "4", "--eval-every", "5",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "4",
        "--metrics", metrics,
    ])
    assert rc == 0
    rows = _read_jsonl(metrics)
    assert [r["step"] for r in rows] == list(range(10))
    # Blocks end after rounds 4, 8, 10; eval-every=5 crossings land on the
    # last round of the crossing block: rounds 7 (block 4..7) and 9 (8..9).
    eval_rounds = [r["step"] for r in rows if "test_acc" in r]
    assert eval_rounds == [7, 9], eval_rounds
    # checkpoint-every=4 crossings at block boundaries 4 and 8, plus the
    # final-round save at 10. Each generation carries its digest manifest
    # (the hardened store's verify-on-read sidecar).
    files = sorted(os.listdir(ckpt))
    assert [f for f in files if f.endswith(".fckpt")] == [
        "round_10.fckpt", "round_4.fckpt", "round_8.fckpt"
    ]
    assert [f for f in files if f.endswith(".manifest.json")] == [
        "round_10.fckpt.manifest.json", "round_4.fckpt.manifest.json",
        "round_8.fckpt.manifest.json",
    ]


def test_fused_1_matches_per_round_cadence(tmp_path):
    """--fused 1 must degrade to the exact per-round cadence semantics."""
    metrics = str(tmp_path / "m.jsonl")
    rc = cli_run.main([
        "--platform", "cpu",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-clients", "2", "--rounds", "6", "--num-examples", "128",
        "--batch-size", "4", "--steps-per-round", "2", "--lr", "0.05",
        "--partition", "iid",
        "--eval-every", "2",
        "--metrics", metrics,
    ])
    assert rc == 0
    rows = _read_jsonl(metrics)
    assert [r["step"] for r in rows if "test_acc" in r] == [1, 3, 5]


def test_async_checkpoint_resume_continues_to_total(tmp_path):
    """Async-mode --checkpoint-dir/-r: the first run saves at cadence and
    on completion; the resumed run continues from the restored update to
    the TOTAL --async-updates (sync semantics) with step numbering carrying
    on, and leaves a final-checkpoint file."""
    metrics = str(tmp_path / "m.jsonl")
    ckpt = str(tmp_path / "ckpt")
    base = [
        "--platform", "cpu",
        "--model", "mlp", "--dataset", "synthetic",
        "--num-clients", "3", "--num-examples", "192",
        "--batch-size", "4", "--steps-per-round", "2", "--lr", "0.05",
        "--partition", "iid", "--buffer-k", "2",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
        "--metrics", metrics,
    ]
    assert cli_run.main(base + ["--async-updates", "3"]) == 0
    assert "round_3.fckpt" in os.listdir(ckpt)
    rows = _read_jsonl(metrics)
    assert [r["step"] for r in rows] == [0, 1, 2]

    assert cli_run.main(base + ["--async-updates", "5", "-r"]) == 0
    rows = _read_jsonl(metrics)
    # Appended rows resume at update 3 and stop at the TOTAL of 5.
    assert [r["step"] for r in rows] == [0, 1, 2, 3, 4]
    assert "round_5.fckpt" in os.listdir(ckpt)
