"""bf16 momentum buffers (opt-in non-parity mode) + the avg-pool ablation.

Round-5 roofline experiments (VERDICT r4 #4): optimizer-state HBM traffic
(``OptimizerConfig.momentum_dtype='bfloat16'``) and pool cost
(``smallcnn_avgpool``). These tests pin the semantics the on-chip bench legs
rely on: the f32 default is BITWISE unchanged (parity must not move), the
bf16 mode differs only by one storage round-trip, and the avg-pool variant
is parameter-identical to smallcnn.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu import models
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import optim


def _params(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(8,)).astype(np.float32)),
    }


def _grads(seed=1):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(8,)).astype(np.float32)),
    }


def test_f32_default_is_bitwise_legacy():
    """momentum_dtype='float32' must be a no-op refactor: same bits as the
    pre-round-5 implementation (upcast of an f32 buffer and astype-f32 store
    are both identities)."""
    cfg = OptimizerConfig(learning_rate=0.1, momentum=0.9, weight_decay=5e-4)
    params, grads = _params(), _grads()
    state = optim.init(params, cfg)
    assert all(
        leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(state.momentum)
    )

    # Legacy update, written out explicitly (the pre-momentum_dtype code).
    decayed = jax.tree.map(lambda g, p: g + cfg.weight_decay * p, grads, params)
    legacy_buf = jax.tree.map(lambda b, g: cfg.momentum * b + g,
                              state.momentum, decayed)
    legacy_params = jax.tree.map(lambda p, d: p - 0.1 * d, params, legacy_buf)

    new_params, new_state = optim.apply(params, grads, state, 0.1, cfg)
    for a, b in zip(jax.tree_util.tree_leaves(legacy_params),
                    jax.tree_util.tree_leaves(new_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(legacy_buf),
                    jax.tree_util.tree_leaves(new_state.momentum)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_momentum_is_one_storage_roundtrip():
    """bf16 mode: buffers stored bf16; the step equals the f32 step computed
    from the ROUNDED previous buffer — i.e. the only divergence source is
    the storage rounding, never low-precision accumulation."""
    cfg16 = OptimizerConfig(momentum_dtype="bfloat16", weight_decay=5e-4)
    cfg32 = dataclasses.replace(cfg16, momentum_dtype="float32")
    params, grads = _params(), _grads()

    state16 = optim.init(params, cfg16)
    assert all(
        leaf.dtype == jnp.bfloat16
        for leaf in jax.tree_util.tree_leaves(state16.momentum)
    )

    # Two steps in bf16 mode.
    p16, s16 = optim.apply(params, grads, state16, 0.1, cfg16)
    p16, s16 = optim.apply(p16, grads, s16, 0.1, cfg16)

    # Oracle: f32 mode, but manually rounding the carried buffer between
    # steps exactly once — must match the bf16 mode bit-for-bit.
    p32, s32 = optim.apply(params, grads, optim.init(params, cfg32), 0.1, cfg32)
    rounded = optim.SGDState(momentum=jax.tree.map(
        lambda b: b.astype(jnp.bfloat16).astype(jnp.float32), s32.momentum))
    p32b, s32b = optim.apply(p32, grads, rounded, 0.1, cfg32)
    for a, b in zip(jax.tree_util.tree_leaves(p16),
                    jax.tree_util.tree_leaves(p32b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(s16.momentum),
                    jax.tree_util.tree_leaves(s32b.momentum)):
        np.testing.assert_array_equal(
            np.asarray(a.astype(jnp.float32)),
            np.asarray(b.astype(jnp.bfloat16).astype(jnp.float32)),
        )

    # And the drift vs pure-f32 is small (bf16 has ~8 mantissa bits).
    p32_pure, _ = optim.apply(p32, grads, s32, 0.1, cfg32)
    for a, b in zip(jax.tree_util.tree_leaves(p16),
                    jax.tree_util.tree_leaves(p32_pure)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=5e-3)


def test_unknown_momentum_dtype_rejected_cheaply():
    with pytest.raises(ValueError, match="momentum_dtype"):
        optim.init(_params(), OptimizerConfig(momentum_dtype="float16"))

    from fedtpu.core.engine import Federation

    cfg = RoundConfig(
        model="mlp", num_classes=10,
        opt=OptimizerConfig(momentum_dtype="float16"),
        data=DataConfig(dataset="mnist", batch_size=8, num_examples=64),
        fed=FedConfig(num_clients=2), steps_per_round=2,
    )
    with pytest.raises(ValueError, match="momentum_dtype"):
        Federation(cfg, seed=0)


def test_bf16_momentum_trains_end_to_end():
    """Engine smoke in the non-parity mode: state carries bf16 buffers and
    the model still learns the easy synthetic task."""
    from fedtpu.core.engine import Federation

    cfg = RoundConfig(
        model="mlp", num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, momentum_dtype="bfloat16"),
        data=DataConfig(dataset="mnist", batch_size=16, partition="iid",
                        num_examples=256),
        fed=FedConfig(num_clients=2), steps_per_round=4,
    )
    fed = Federation(cfg, seed=0)
    assert all(
        leaf.dtype == jnp.bfloat16
        for leaf in jax.tree_util.tree_leaves(fed.state.opt_state.momentum)
    )
    first = fed.run(num_rounds=1)
    last = fed.run(num_rounds=5)
    assert float(last.loss) < float(first.loss)
    assert all(
        leaf.dtype == jnp.bfloat16
        for leaf in jax.tree_util.tree_leaves(fed.state.opt_state.momentum)
    )


def test_avgpool_variant_is_parameter_identical():
    """smallcnn_avgpool: same param tree (pools are parameter-free), so its
    bench leg isolates the pooling op and nothing else."""
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    m_max = models.create("smallcnn", num_classes=10)
    m_avg = models.create("smallcnn_avgpool", num_classes=10)
    v_max = m_max.init(jax.random.PRNGKey(0), x, train=False)
    v_avg = m_avg.init(jax.random.PRNGKey(0), x, train=False)
    shapes = lambda v: jax.tree.map(lambda p: (p.shape, str(p.dtype)), v)
    assert shapes(v_max) == shapes(v_avg)
    # Same seed -> same weights; outputs must still differ (different op).
    x2 = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    out_max = m_max.apply(v_max, x2, train=False)
    out_avg = m_avg.apply(v_avg, x2, train=False)
    assert not np.allclose(np.asarray(out_max), np.asarray(out_avg))


def test_bench_variant_field(monkeypatch):
    """bench.py must label variant runs so an experiment artifact can never
    masquerade as the parity headline. mlp (not the real smallcnn variant)
    keeps this seconds-scale: the labeling logic is model-agnostic and the
    smallcnn path itself is covered by test_measure_contract."""
    monkeypatch.syspath_prepend(".")
    import bench as bench_mod

    monkeypatch.setattr(bench_mod, "NUM_CLIENTS", 4)
    monkeypatch.setattr(bench_mod, "STEPS_PER_ROUND", 2)
    monkeypatch.setattr(bench_mod, "BATCH", 8)
    monkeypatch.setattr(bench_mod, "TIMED_ROUNDS", 2)
    monkeypatch.setattr(bench_mod, "TRIALS", 1)
    monkeypatch.setattr(bench_mod, "BENCH_MODEL", "mlp")
    monkeypatch.setattr(bench_mod, "MOMENTUM_DTYPE", "bfloat16")
    result = bench_mod._measure()
    assert result["variant"] == {
        "model": "mlp", "momentum_dtype": "bfloat16",
        "compute_dtype": "float32", "megabatch_clients": 0,
    }
    assert result["value"] > 0
