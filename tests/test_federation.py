"""End-to-end distributed federation over real gRPC on localhost.

The in-process analogue of the reference's README run instructions (start
backup, primary, clients on distinct ports — its de facto integration test,
SURVEY §4), plus the failure drills that the reference could only do by
killing processes: client death mid-federation, heartbeat revival, and
backup promotion/demotion.

Everything runs tiny (MLP on synthetic data) so the jitted local updates
compile in seconds on the CPU mesh.
"""

import dataclasses
import socket
import threading
import time

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.transport import proto, wire
from fedtpu.transport.federation import (
    BackupServer,
    ClientAgent,
    PrimaryServer,
    serve_client,
)
from fedtpu.transport.service import TrainerStub, create_channel


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(num_clients=2) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=8,
            eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=2),
        steps_per_round=2,
    )


@pytest.fixture()
def two_clients():
    cfg = tiny_cfg()
    addrs, servers, agents = [], [], []
    for i in range(2):
        addr = f"localhost:{free_port()}"
        server, agent = serve_client(addr, cfg, seed=i)
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    yield cfg, addrs, agents
    for s in servers:
        s.stop(0)


def test_two_client_round(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    rec = primary.round()
    assert rec["participants"] == 2
    assert rec["alive"] == [True, True]
    # Both clients installed + evaluated the broadcast global model.
    assert agents[0].last_eval is not None
    assert agents[1].last_eval is not None


def test_training_actually_learns(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    for _ in range(6):
        primary.round()
    # Synthetic data is linearly-ish separable; 6 rounds of federated MLP
    # training should beat chance (0.25) clearly on the client-side eval.
    accs = [agent.last_eval[1] for agent in agents]
    assert max(accs) > 0.5, accs


def test_client_failure_marks_dead_and_round_survives(two_clients):
    cfg, addrs, agents = two_clients
    dead_addr = f"localhost:{free_port()}"  # nothing listening -> fails fast
    primary = PrimaryServer(cfg, [addrs[0], dead_addr])
    rec = primary.round()
    assert rec["participants"] == 1
    assert rec["alive"] == [True, False]
    # The dead client is excluded from the next round's rank fan-out but
    # world stays at the full registry size (reference: src/server.py:126-129).
    assert primary.registry.active_clients() == [addrs[0]]


def test_heartbeat_revives_and_resyncs(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    primary.round()
    primary.registry.mark_failed(addrs[1])
    agents[1].last_eval = None
    recovered = primary.monitor.tick()
    assert recovered == [addrs[1]]
    # Revival pushed the current global model (SendModel -> eval ran).
    assert agents[1].last_eval is not None
    assert primary.registry.alive_mask().tolist() == [True, True]


def test_sparse_compressed_federation_learns():
    """-c Y parity, upgraded: clients ship top-k sparse deltas (after the
    initial sync), the server reconstructs and aggregates them, and the
    federation still learns."""
    import dataclasses

    from fedtpu.config import FedConfig

    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg,
        fed=FedConfig(num_clients=2, num_rounds=2, compression="topk",
                      topk_fraction=0.25),
    )
    addrs, servers, agents = [], [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
            agents.append(agent)
        primary = PrimaryServer(cfg, addrs)
        primary.sync_clients()  # run() does this; round() alone needs it
        assert all(a.trainer.synced for a in agents)
        for _ in range(6):
            rec = primary.round()
            assert rec["participants"] == 2
        # Sparse mode engaged: clients now hold edge residuals.
        assert agents[0].trainer.edge_residual is not None
        accs = [agent.last_eval[1] for agent in agents]
        assert max(accs) > 0.5, accs
        # And the sparse payload is much smaller than the dense one.
        dense = len(primary.model_bytes())
        sparse_payload = agents[0].trainer.train_round(0, 2)
        from fedtpu.transport import sparse as sparse_mod

        assert sparse_mod.is_sparse_payload(sparse_payload)
        # topk at fraction f costs ~8f bytes/param (idx+val) vs 4 dense:
        # f=0.25 -> ~half the dense size (+ small ties/header slack).
        assert len(sparse_payload) < dense * 0.55
    finally:
        for s in servers:
            s.stop(0)


def test_model_replicates_to_backup(two_clients):
    cfg, addrs, agents = two_clients
    backup_addr = f"localhost:{free_port()}"
    backup = BackupServer(cfg, addrs, watchdog_timeout=3600.0)
    backup_server = backup.start(backup_addr)
    try:
        primary = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary.round()
        assert backup.latest_model is not None
        # The replicated payload decodes into the current global model.
        from fedtpu.transport.federation import _model_template

        params, stats = _model_template(primary.model, cfg)
        tree = wire.decode(
            backup.latest_model, {"params": params, "batch_stats": stats}
        )
        ours = np.concatenate(
            [np.ravel(x) for x in map(np.asarray, _leaves(primary.params))]
        )
        theirs = np.concatenate(
            [np.ravel(x) for x in map(np.asarray, _leaves(tree["params"]))]
        )
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)
    finally:
        backup.watchdog.stop()
        backup_server.stop(0)


def _leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def test_backup_promotes_and_demotes(two_clients):
    """Kill the primary (stop pinging), watch the backup take over rounds,
    then bring the primary back and watch it yield."""
    cfg, addrs, agents = two_clients
    backup_addr = f"localhost:{free_port()}"
    backup = BackupServer(cfg, addrs, watchdog_timeout=1.0)
    backup.machine.clock = time.monotonic  # real clock, short window
    backup_server = backup.start(backup_addr)
    stub = TrainerStub(create_channel(backup_addr))
    try:
        # Seed replication state, as the primary would every round, and arm
        # the watchdog with one liveness ping (the pinger thread would).
        primary = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary.round()
        stub.CheckIfPrimaryUp(proto.PingRequest(req=b"0"), timeout=5)
        # Primary goes silent -> watchdog fires within ~2 ticks.
        deadline = time.time() + 15
        while backup.acting is None and time.time() < deadline:
            time.sleep(0.2)
        assert backup.acting is not None, "backup never promoted"
        # Acting primary actually drives rounds with the replicated model.
        deadline = time.time() + 30
        while not backup.acting.history and time.time() < deadline:
            time.sleep(0.2)
        assert backup.acting.history, "acting primary ran no rounds"
        # The real primary returns: its recovering ping demotes the backup
        # AND pulls the acting primary's newer model (FetchModel) before
        # training — progress from the failover window survives.
        primary2 = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary2.run(num_rounds=0)  # run() pings synchronously before rounds
        from fedtpu.ft import Role

        assert backup.machine.role is Role.BACKUP
        import jax

        ours = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree.leaves(primary2.params)]
        )
        theirs = np.concatenate(
            [
                np.ravel(np.asarray(x))
                for x in jax.tree.leaves(backup.acting.params)
            ]
        )
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)
    finally:
        backup.watchdog.stop()
        backup_server.stop(0)

def test_round_deadline_skips_stragglers_without_killing_them():
    """A client whose StartTrain exceeds the round deadline is aggregated
    around (not marked dead): participants drops, stragglers is reported,
    alive stays true, and the slow client still receives the broadcast."""
    import time as _time

    from fedtpu.transport.federation import ClientAgent
    from fedtpu.transport.service import create_server

    cfg = tiny_cfg()

    class SlowAgent(ClientAgent):
        """Sleeps from the SECOND StartTrain on: the first (deadline-free)
        warmup round absorbs jit compilation on both clients, so the timed
        round's deadline races only the sleep, not a compiler."""

        calls = 0

        def StartTrain(self, request, context):
            SlowAgent.calls += 1
            if SlowAgent.calls > 1:
                _time.sleep(8.0)
            return super().StartTrain(request, context)

    addrs, servers, agents = [], [], []
    for i, cls in enumerate([ClientAgent, SlowAgent]):
        addr = f"localhost:{free_port()}"
        agent = cls(cfg, seed=i)
        server = create_server(addr, agent)
        server.start()
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    try:
        primary = PrimaryServer(cfg, addrs, round_deadline_s=None)
        warm = primary.round()  # compile both clients, no deadline
        assert warm["participants"] == 2
        primary.round_deadline_s = 3.0
        t0 = time.monotonic()
        rec = primary.round()
        elapsed = time.monotonic() - t0
        assert rec["participants"] == 1
        assert rec["stragglers"] == 1
        assert rec["alive"] == [True, True], rec
        assert elapsed < 8.0, elapsed  # did not block on the slow client
        # Warmup's broadcast reached both; the straggler round's broadcast
        # still targets the straggler (it stays active).
        assert agents[1].last_eval is not None
        # Immediate next round: the straggler's StartTrain is STILL in
        # flight, so it is skipped (no second concurrent call on its
        # trainer) and reported as a straggler again.
        calls_before = SlowAgent.calls
        rec2 = primary.round()
        assert rec2["participants"] == 1
        assert rec2["stragglers"] == 1
        assert SlowAgent.calls == calls_before
    finally:
        for s in servers:
            s.stop(0)


# --------------------------------------------------------------------------
# Round-4 regressions: replica payload typing, lineage round counter, stable
# ranks under participation sampling, in-flight tracking across rounds.
# --------------------------------------------------------------------------


class _RecordingStub:
    """Wraps a TrainerStub, recording StartTrain ranks and optionally
    blocking calls on an event (to fabricate stragglers/slow broadcasts
    without a special servicer)."""

    def __init__(self, real):
        self._real = real
        self.ranks = []
        self.send_calls = 0
        self.block_train = None   # threading.Event: wait before forwarding
        self.block_send_after = None  # (n, Event): block send calls > n

    def StartTrain(self, request, timeout=None):
        self.ranks.append(request.rank)
        if self.block_train is not None:
            self.block_train.wait()
        return self._real.StartTrain(request, timeout=timeout)

    def SendModel(self, request, timeout=None):
        self.send_calls += 1
        if (
            self.block_send_after is not None
            and self.send_calls > self.block_send_after[0]
        ):
            self.block_send_after[1].wait()
        return self._real.SendModel(request, timeout=timeout)

    def __getattr__(self, name):
        return getattr(self._real, name)


def _three_clients(cfg):
    from fedtpu.transport.federation import serve_client as _serve

    addrs, servers = [], []
    for i in range(3):
        addr = f"localhost:{free_port()}"
        server, _ = _serve(addr, cfg, seed=i)
        addrs.append(addr)
        servers.append(server)
    return addrs, servers


def test_sampled_clients_keep_registry_rank():
    """With participation_fraction < 1, each sampled client must train its
    OWN registry-order shard — positional ranks would retrain shards 0..k-1
    forever and never touch the rest (ADVICE r3)."""
    cfg = tiny_cfg(num_clients=3)
    cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, participation_fraction=0.34)
    )
    addrs, servers = _three_clients(cfg)
    try:
        primary = PrimaryServer(cfg, addrs)
        stubs = {c: _RecordingStub(primary._stubs[c]) for c in addrs}
        primary._stubs = stubs
        for _ in range(6):
            primary.round()
        index = {c: i for i, c in enumerate(addrs)}
        seen_ranks = set()
        for c, stub in stubs.items():
            for r in stub.ranks:
                assert r == index[c], (c, stub.ranks)
                seen_ranks.add(r)
        # Sampling rotated through more than one client across 6 rounds, so
        # a nonzero rank was actually exercised (positional assignment would
        # have sent rank 0 every time at k=1).
        assert seen_ranks != {0}, seen_ranks
    finally:
        for s in servers:
            s.stop(0)


def test_inflight_straggler_survives_multiple_rounds():
    """A straggler whose StartTrain is still running TWO rounds later must
    stay in _inflight (and keep sitting rounds out) — rebuilding _inflight
    from only the current round's threads would hand it a second concurrent
    StartTrain (ADVICE r3)."""
    cfg = tiny_cfg(num_clients=3)
    addrs, servers = _three_clients(cfg)
    try:
        primary = PrimaryServer(cfg, addrs, round_deadline_s=None)
        primary.round()  # warmup: compile all clients, no deadline
        stubs = {c: _RecordingStub(primary._stubs[c]) for c in addrs}
        primary._stubs = stubs
        gate = threading.Event()
        stubs[addrs[0]].block_train = gate
        primary.round_deadline_s = 2.0
        rec1 = primary.round()
        assert rec1["stragglers"] == 1
        assert addrs[0] in primary._inflight
        calls_after_r1 = len(stubs[addrs[0]].ranks)
        rec2 = primary.round()  # straggler STILL in flight
        assert rec2["stragglers"] == 1
        # Regression: the straggler thread survived the _inflight rebuild...
        assert addrs[0] in primary._inflight, "straggler dropped from _inflight"
        assert primary._inflight[addrs[0]].is_alive()
        rec3 = primary.round()  # ...so round 3 still does not re-launch it
        assert rec3["stragglers"] == 1
        assert len(stubs[addrs[0]].ranks) == calls_after_r1
        gate.set()
        primary._inflight[addrs[0]].join(timeout=30)
    finally:
        for s in servers:
            s.stop(0)


def test_broadcast_send_threads_tracked():
    """A SendModel broadcast still in flight from the previous round must
    not be raced by this round's broadcast to the same client (ADVICE r3):
    the client sits the broadcast out until its stale send drains."""
    cfg = tiny_cfg(num_clients=3)
    addrs, servers = _three_clients(cfg)
    try:
        primary = PrimaryServer(cfg, addrs, round_deadline_s=None)
        primary.round()  # warmup + initial sync
        stubs = {c: _RecordingStub(primary._stubs[c]) for c in addrs}
        primary._stubs = stubs
        gate = threading.Event()
        stubs[addrs[0]].block_send_after = (0, gate)  # block every send
        primary.round_deadline_s = 2.0
        primary.round()
        assert addrs[0] in primary._sends
        assert primary._sends[addrs[0]].is_alive()
        sends_after_r1 = stubs[addrs[0]].send_calls
        primary.round()
        # No concurrent second SendModel was issued to the blocked client.
        assert stubs[addrs[0]].send_calls == sends_after_r1
        assert addrs[0] in primary._sends
        gate.set()
        primary._sends[addrs[0]].join(timeout=30)
    finally:
        for s in servers:
            s.stop(0)


def test_truncated_replica_raises_loudly():
    """A corrupted replica payload must raise (explicit payload-kind flag),
    never silently downgrade to model-only-and-drop-the-moments
    (VERDICT r3 weak #6)."""
    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, server_optimizer="momentum")
    )
    primary = PrimaryServer(cfg, [])
    primary._round_counter = 3
    data = primary.replica_bytes()
    other = PrimaryServer(cfg, [])
    with pytest.raises(wire.WireError):
        other._install(data[: len(data) // 2])  # truncated: CRC mismatch
    flipped = bytearray(data)
    flipped[-1] ^= 0xFF
    with pytest.raises(wire.WireError):
        other._install(bytes(flipped))  # bit flip: CRC mismatch
    # And a config-mismatched replica (sender has no moments, receiver
    # expects them) fails loudly instead of installing partial state.
    plain_cfg = tiny_cfg()
    sender = PrimaryServer(plain_cfg, [])
    with pytest.raises(wire.WireError):
        other._install(sender.replica_bytes())
    # The intact replica installs fully: model + moments + round counter.
    other._install(data)
    assert other._round_counter == 3


def test_replica_counter_continuity_across_promotion():
    """The DP-noise / subsampling round counter must ride the replica so a
    promoted backup (history restarts at 0) never replays round 0's PRNG
    draws (ADVICE r3). Also covers: model-only payloads leave it alone."""
    cfg = tiny_cfg()
    primary = PrimaryServer(cfg, [])
    primary._round_counter = 41
    promoted = PrimaryServer(cfg, [], initial_model=primary.replica_bytes())
    assert promoted._round_counter == 41
    # A plain model broadcast (kind=model) must NOT reset the counter.
    promoted._install(primary.model_bytes())
    assert promoted._round_counter == 41


def test_full_state_checkpoint_roundtrip(tmp_path):
    """state_tree/install_state checkpoint: FedOpt moments and the round
    counter survive a save/restore cycle (the server CLI resume path)."""
    import jax

    from fedtpu.checkpoint import Checkpointer

    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, server_optimizer="adam")
    )
    primary = PrimaryServer(cfg, [])
    primary._round_counter = 7
    # Perturb the moments so the restore is distinguishable from init.
    primary._server_opt_state = jax.tree.map(
        lambda x: x + 1.25, primary._server_opt_state
    )
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(6, primary.state_tree())
    fresh = PrimaryServer(cfg, [])
    r, tree = ckpt.restore_latest(fresh.state_template())
    fresh.install_state(tree)
    assert r == 6
    assert fresh._round_counter == 7
    a = np.concatenate([
        np.ravel(np.asarray(x))
        for x in jax.tree.leaves(primary._server_opt_state)
    ])
    b = np.concatenate([
        np.ravel(np.asarray(x))
        for x in jax.tree.leaves(fresh._server_opt_state)
    ])
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_promotion_survives_corrupted_replica():
    """A corrupted replica blob must not silently kill the watchdog's
    promotion (leaving NO primary): the backup logs loudly and promotes
    with a fresh model instead."""
    cfg = tiny_cfg()
    backup = BackupServer(cfg, [], watchdog_timeout=3600.0)
    good = PrimaryServer(cfg, [])
    blob = bytearray(good.replica_bytes())
    blob[-1] ^= 0xFF  # CRC mismatch
    backup.latest_model = bytes(blob)
    backup._promote()
    try:
        assert backup.acting is not None, "promotion died on corrupt replica"
    finally:
        backup._stop_acting(wait=30.0)


# ------------------------------------------------ codec frontier / adaptive
def _serve_fleet(cfg, n=2):
    addrs, servers, agents = [], [], []
    for i in range(n):
        addr = f"localhost:{free_port()}"
        server, agent = serve_client(addr, cfg, seed=i)
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    return addrs, servers, agents


# Tier-2: the adaptive-policy test below already drives BOTH sketch codecs
# over live gRPC every tier-1 run (each warmup round uses one), and their
# decode parity/replay pins live in test_sparse_wire; this longer
# convergence leg rides the slow tier.
@pytest.mark.slow
@pytest.mark.parametrize("codec", ["rotq", "randk"])
def test_sketch_codec_federation_learns(codec):
    """Static rotq/randk fleets over live gRPC: records decode through the
    barrier path, per-codec byte accounting labels every reply, the wire
    really shrinks, and the federation still learns under EF."""
    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg,
        fed=FedConfig(
            num_clients=2, num_rounds=2, compression=codec,
            topk_fraction=0.05, rotq_bits=4, delta_layout="flat",
            error_feedback=True,
        ),
    )
    addrs, servers, agents = _serve_fleet(cfg)
    try:
        primary = PrimaryServer(cfg, addrs)
        primary.sync_clients()
        recs = [primary.round() for _ in range(6)]
        for rec in recs:
            assert rec["participants"] == 2
            by_codec = rec["bytes_up_by_codec"]
            assert set(by_codec) == {codec}
            assert by_codec[codec] == rec["bytes_up"]
        # Cumulative statusz ledger matches the per-round records.
        snap = primary.status_snapshot()
        assert snap["codec_bytes_up"][codec] == sum(
            r["bytes_up"] for r in recs
        )
        # Labeled byte counter rides next to the unlabeled authoritative one.
        reg = primary.telemetry.registry
        assert reg.counter(
            "fedtpu_rpc_bytes_up_total", labels={"codec": codec}
        ).value == sum(r["bytes_up"] for r in recs)
        # Wire really shrank: both sketch records beat dense at these knobs.
        dense = len(primary.model_bytes())
        assert recs[-1]["bytes_up"] / 2 < dense * 0.5
        assert agents[0].trainer.edge_residual is not None
        assert max(a.last_eval[1] for a in agents) > 0.5
    finally:
        for s in servers:
            s.stop(0)


def test_adaptive_codec_policy_switches_codecs_live():
    """codec_policy='adaptive' over live gRPC: the coordinator probes every
    candidate codec in order during warmup (one per round, shipped via
    TrainRequest.codec), then converges on the cheapest by observed
    bytes x RTT — and error feedback survives every switch (training stays
    healthy through the probe sequence)."""
    from fedtpu.transport.codec_policy import DEFAULT_CANDIDATES

    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg,
        fed=FedConfig(
            num_clients=2, num_rounds=2, compression="none",
            codec_policy="adaptive", delta_layout="flat",
            topk_fraction=0.05, rotq_bits=4, error_feedback=True,
        ),
    )
    addrs, servers, agents = _serve_fleet(cfg)
    try:
        primary = PrimaryServer(cfg, addrs)
        primary.sync_clients()
        recs = [primary.round() for _ in range(len(DEFAULT_CANDIDATES) + 2)]
        # Warmup: round r uses candidate r for every client (both clients
        # warm up in lockstep — same unobserved-candidate frontier).
        for r, want in enumerate(DEFAULT_CANDIDATES):
            assert set(recs[r]["bytes_up_by_codec"]) == {want}, (
                r, recs[r]["bytes_up_by_codec"]
            )
        # Post-warmup: a lossy codec won on bytes x RTT over loopback
        # (dense is ~20x the bytes at equal RTT — it cannot be argmin).
        for rec in recs[len(DEFAULT_CANDIDATES):]:
            chosen = set(rec["bytes_up_by_codec"])
            assert chosen and "none" not in chosen
        snap = primary.status_snapshot()
        policy = snap["codec_policy"]
        for rank in ("0", "1"):
            assert set(policy[rank]) == set(DEFAULT_CANDIDATES)
            assert all(
                v["observations"] >= 1 and v["ewma_cost"] > 0
                for v in policy[rank].values()
            )
        # EF survived the switches: the run is healthy end to end.
        assert all(r["participants"] == 2 for r in recs)
        assert max(a.last_eval[1] for a in agents) > 0.5
        for a in agents:
            assert np.isfinite(a.last_eval[0])
    finally:
        for s in servers:
            s.stop(0)


def test_adaptive_codec_policy_unit():
    """Warmup probes candidates in order, then argmin EWMA(bytes x RTT);
    unknown codecs (legacy clients) are ignored rather than poisoning a
    candidate's estimate."""
    from fedtpu.transport.codec_policy import AdaptiveCodecPolicy

    pol = AdaptiveCodecPolicy(candidates=("none", "int8", "topk"))
    assert pol.choose(0) == "none"
    pol.observe(0, "none", bytes_up=1000, rtt_s=0.1)
    assert pol.choose(0) == "int8"
    pol.observe(0, "int8", bytes_up=250, rtt_s=0.1)
    assert pol.choose(0) == "topk"
    pol.observe(0, "topk", bytes_up=100, rtt_s=0.1)
    assert pol.choose(0) == "topk"  # cheapest cost product
    # A dramatically slower topk RTT eventually flips the choice to int8.
    for _ in range(20):
        pol.observe(0, "topk", bytes_up=100, rtt_s=60.0)
    assert pol.choose(0) == "int8"
    # Unknown codec: ignored, table unchanged.
    pol.observe(0, "gzip", bytes_up=1, rtt_s=0.001)
    assert "gzip" not in pol.snapshot()["0"]
    # Per-rank isolation: a new client starts its own warmup.
    assert pol.choose(7) == "none"


def test_adaptive_codec_policy_config_validation():
    """Adaptive policy needs the flat delta layout (sketch codecs) and the
    plain mean aggregator; bad static codec names fail fast too."""
    cfg = tiny_cfg()
    bad_layout = dataclasses.replace(
        cfg, fed=FedConfig(num_clients=2, codec_policy="adaptive")
    )
    with pytest.raises(ValueError):
        PrimaryServer(bad_layout, ["localhost:1"])
    bad_name = dataclasses.replace(
        cfg, fed=FedConfig(num_clients=2, compression="gzip")
    )
    with pytest.raises(ValueError):
        PrimaryServer(bad_name, ["localhost:1"])
    bad_policy = dataclasses.replace(
        cfg, fed=FedConfig(num_clients=2, codec_policy="sometimes")
    )
    with pytest.raises(ValueError):
        PrimaryServer(bad_policy, ["localhost:1"])
