"""End-to-end distributed federation over real gRPC on localhost.

The in-process analogue of the reference's README run instructions (start
backup, primary, clients on distinct ports — its de facto integration test,
SURVEY §4), plus the failure drills that the reference could only do by
killing processes: client death mid-federation, heartbeat revival, and
backup promotion/demotion.

Everything runs tiny (MLP on synthetic data) so the jitted local updates
compile in seconds on the CPU mesh.
"""

import socket
import threading
import time

import numpy as np
import pytest

grpc = pytest.importorskip("grpc")

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.transport import proto, wire
from fedtpu.transport.federation import (
    BackupServer,
    ClientAgent,
    PrimaryServer,
    serve_client,
)
from fedtpu.transport.service import TrainerStub, create_channel


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(num_clients=2) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=8,
            eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=2),
        steps_per_round=2,
    )


@pytest.fixture()
def two_clients():
    cfg = tiny_cfg()
    addrs, servers, agents = [], [], []
    for i in range(2):
        addr = f"localhost:{free_port()}"
        server, agent = serve_client(addr, cfg, seed=i)
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    yield cfg, addrs, agents
    for s in servers:
        s.stop(0)


def test_two_client_round(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    rec = primary.round()
    assert rec["participants"] == 2
    assert rec["alive"] == [True, True]
    # Both clients installed + evaluated the broadcast global model.
    assert agents[0].last_eval is not None
    assert agents[1].last_eval is not None


def test_training_actually_learns(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    for _ in range(6):
        primary.round()
    # Synthetic data is linearly-ish separable; 6 rounds of federated MLP
    # training should beat chance (0.25) clearly on the client-side eval.
    accs = [agent.last_eval[1] for agent in agents]
    assert max(accs) > 0.5, accs


def test_client_failure_marks_dead_and_round_survives(two_clients):
    cfg, addrs, agents = two_clients
    dead_addr = f"localhost:{free_port()}"  # nothing listening -> fails fast
    primary = PrimaryServer(cfg, [addrs[0], dead_addr])
    rec = primary.round()
    assert rec["participants"] == 1
    assert rec["alive"] == [True, False]
    # The dead client is excluded from the next round's rank fan-out but
    # world stays at the full registry size (reference: src/server.py:126-129).
    assert primary.registry.active_clients() == [addrs[0]]


def test_heartbeat_revives_and_resyncs(two_clients):
    cfg, addrs, agents = two_clients
    primary = PrimaryServer(cfg, addrs)
    primary.round()
    primary.registry.mark_failed(addrs[1])
    agents[1].last_eval = None
    recovered = primary.monitor.tick()
    assert recovered == [addrs[1]]
    # Revival pushed the current global model (SendModel -> eval ran).
    assert agents[1].last_eval is not None
    assert primary.registry.alive_mask().tolist() == [True, True]


def test_sparse_compressed_federation_learns():
    """-c Y parity, upgraded: clients ship top-k sparse deltas (after the
    initial sync), the server reconstructs and aggregates them, and the
    federation still learns."""
    import dataclasses

    from fedtpu.config import FedConfig

    cfg = tiny_cfg()
    cfg = dataclasses.replace(
        cfg,
        fed=FedConfig(num_clients=2, num_rounds=2, compression="topk",
                      topk_fraction=0.25),
    )
    addrs, servers, agents = [], [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
            agents.append(agent)
        primary = PrimaryServer(cfg, addrs)
        primary.sync_clients()  # run() does this; round() alone needs it
        assert all(a.trainer.synced for a in agents)
        for _ in range(6):
            rec = primary.round()
            assert rec["participants"] == 2
        # Sparse mode engaged: clients now hold edge residuals.
        assert agents[0].trainer.edge_residual is not None
        accs = [agent.last_eval[1] for agent in agents]
        assert max(accs) > 0.5, accs
        # And the sparse payload is much smaller than the dense one.
        dense = len(primary.model_bytes())
        sparse_payload = agents[0].trainer.train_round(0, 2)
        from fedtpu.transport import sparse as sparse_mod

        assert sparse_mod.is_sparse_payload(sparse_payload)
        # topk at fraction f costs ~8f bytes/param (idx+val) vs 4 dense:
        # f=0.25 -> ~half the dense size (+ small ties/header slack).
        assert len(sparse_payload) < dense * 0.55
    finally:
        for s in servers:
            s.stop(0)


def test_model_replicates_to_backup(two_clients):
    cfg, addrs, agents = two_clients
    backup_addr = f"localhost:{free_port()}"
    backup = BackupServer(cfg, addrs, watchdog_timeout=3600.0)
    backup_server = backup.start(backup_addr)
    try:
        primary = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary.round()
        assert backup.latest_model is not None
        # The replicated payload decodes into the current global model.
        from fedtpu.transport.federation import _model_template

        params, stats = _model_template(primary.model, cfg)
        tree = wire.decode(
            backup.latest_model, {"params": params, "batch_stats": stats}
        )
        ours = np.concatenate(
            [np.ravel(x) for x in map(np.asarray, _leaves(primary.params))]
        )
        theirs = np.concatenate(
            [np.ravel(x) for x in map(np.asarray, _leaves(tree["params"]))]
        )
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)
    finally:
        backup.watchdog.stop()
        backup_server.stop(0)


def _leaves(tree):
    import jax

    return jax.tree.leaves(tree)


def test_backup_promotes_and_demotes(two_clients):
    """Kill the primary (stop pinging), watch the backup take over rounds,
    then bring the primary back and watch it yield."""
    cfg, addrs, agents = two_clients
    backup_addr = f"localhost:{free_port()}"
    backup = BackupServer(cfg, addrs, watchdog_timeout=1.0)
    backup.machine.clock = time.monotonic  # real clock, short window
    backup_server = backup.start(backup_addr)
    stub = TrainerStub(create_channel(backup_addr))
    try:
        # Seed replication state, as the primary would every round, and arm
        # the watchdog with one liveness ping (the pinger thread would).
        primary = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary.round()
        stub.CheckIfPrimaryUp(proto.PingRequest(req=b"0"), timeout=5)
        # Primary goes silent -> watchdog fires within ~2 ticks.
        deadline = time.time() + 15
        while backup.acting is None and time.time() < deadline:
            time.sleep(0.2)
        assert backup.acting is not None, "backup never promoted"
        # Acting primary actually drives rounds with the replicated model.
        deadline = time.time() + 30
        while not backup.acting.history and time.time() < deadline:
            time.sleep(0.2)
        assert backup.acting.history, "acting primary ran no rounds"
        # The real primary returns: its recovering ping demotes the backup
        # AND pulls the acting primary's newer model (FetchModel) before
        # training — progress from the failover window survives.
        primary2 = PrimaryServer(cfg, addrs, backup_address=backup_addr)
        primary2.run(num_rounds=0)  # run() pings synchronously before rounds
        from fedtpu.ft import Role

        assert backup.machine.role is Role.BACKUP
        import jax

        ours = np.concatenate(
            [np.ravel(np.asarray(x)) for x in jax.tree.leaves(primary2.params)]
        )
        theirs = np.concatenate(
            [
                np.ravel(np.asarray(x))
                for x in jax.tree.leaves(backup.acting.params)
            ]
        )
        np.testing.assert_allclose(ours, theirs, rtol=1e-6)
    finally:
        backup.watchdog.stop()
        backup_server.stop(0)

def test_round_deadline_skips_stragglers_without_killing_them():
    """A client whose StartTrain exceeds the round deadline is aggregated
    around (not marked dead): participants drops, stragglers is reported,
    alive stays true, and the slow client still receives the broadcast."""
    import time as _time

    from fedtpu.transport.federation import ClientAgent
    from fedtpu.transport.service import create_server

    cfg = tiny_cfg()

    class SlowAgent(ClientAgent):
        """Sleeps from the SECOND StartTrain on: the first (deadline-free)
        warmup round absorbs jit compilation on both clients, so the timed
        round's deadline races only the sleep, not a compiler."""

        calls = 0

        def StartTrain(self, request, context):
            SlowAgent.calls += 1
            if SlowAgent.calls > 1:
                _time.sleep(8.0)
            return super().StartTrain(request, context)

    addrs, servers, agents = [], [], []
    for i, cls in enumerate([ClientAgent, SlowAgent]):
        addr = f"localhost:{free_port()}"
        agent = cls(cfg, seed=i)
        server = create_server(addr, agent)
        server.start()
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    try:
        primary = PrimaryServer(cfg, addrs, round_deadline_s=None)
        warm = primary.round()  # compile both clients, no deadline
        assert warm["participants"] == 2
        primary.round_deadline_s = 3.0
        t0 = time.monotonic()
        rec = primary.round()
        elapsed = time.monotonic() - t0
        assert rec["participants"] == 1
        assert rec["stragglers"] == 1
        assert rec["alive"] == [True, True], rec
        assert elapsed < 8.0, elapsed  # did not block on the slow client
        # Warmup's broadcast reached both; the straggler round's broadcast
        # still targets the straggler (it stays active).
        assert agents[1].last_eval is not None
        # Immediate next round: the straggler's StartTrain is STILL in
        # flight, so it is skipped (no second concurrent call on its
        # trainer) and reported as a straggler again.
        calls_before = SlowAgent.calls
        rec2 = primary.round()
        assert rec2["participants"] == 1
        assert rec2["stragglers"] == 1
        assert SlowAgent.calls == calls_before
    finally:
        for s in servers:
            s.stop(0)
