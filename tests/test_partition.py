"""Partitioner properties: disjoint cover, reference-exact round-robin rule."""

import numpy as np
import pytest

from fedtpu.data import partition


def test_round_robin_matches_reference_rule():
    # Reference rule (src/main.py:141-144): rank r keeps batch i iff
    # (i + 1) % world == r — pre-increment, rank 0 takes wraparound batches.
    n, bs, world = 1280, 128, 4  # 10 batches
    idx, mask = partition.round_robin(n, world, bs)
    for r in range(world):
        own_batches = {int(i) // bs for i in idx[r][mask[r]]}
        expected = {i for i in range(n // bs) if (i + 1) % world == r}
        assert own_batches == expected


def test_round_robin_disjoint_cover():
    n, bs, world = 1280, 128, 3
    idx, mask = partition.round_robin(n, world, bs)
    all_idx = np.concatenate([idx[c][mask[c]] for c in range(world)])
    assert len(all_idx) == len(set(all_idx.tolist()))
    # All full batches covered (remainder dropped by design).
    assert set(all_idx.tolist()) == set(range((n // bs) * bs))


def test_iid_disjoint_cover():
    idx, mask = partition.iid(1000, 7, seed=3)
    all_idx = np.concatenate([idx[c][mask[c]] for c in range(7)])
    assert sorted(all_idx.tolist()) == list(range(1000))


def test_dirichlet_cover_and_skew():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, 5000)
    idx, mask = partition.dirichlet(labels, 8, alpha=0.5, seed=1)
    all_idx = np.concatenate([idx[c][mask[c]] for c in range(8)])
    assert sorted(all_idx.tolist()) == list(range(5000))
    # Low alpha should produce label skew: client label histograms differ.
    hists = np.stack(
        [np.bincount(labels[idx[c][mask[c]]], minlength=10) for c in range(8)]
    )
    props = hists / hists.sum(1, keepdims=True)
    assert props.std(axis=0).mean() > 0.02


def test_make_client_batches_shapes_and_wraparound():
    images = np.arange(40, dtype=np.float32).reshape(40, 1)
    labels = np.arange(40, dtype=np.int32) % 10
    idx, mask = partition.iid(40, 4, seed=0)
    x, y, sm = partition.make_client_batches(images, labels, idx, mask, 5, 3)
    assert x.shape == (4, 3, 5, 1)
    assert y.shape == (4, 3, 5)
    assert sm.shape == (4, 3)
    assert sm.all()  # every client has data
    # Each client's batches only contain its own examples.
    for c in range(4):
        own = set(idx[c][mask[c]].tolist())
        assert set(int(v) for v in x[c].ravel()) <= own


def test_make_client_batches_empty_client_masked():
    images = np.ones((10, 1), np.float32)
    labels = np.zeros((10,), np.int32)
    idx = np.zeros((2, 10), np.int32)
    mask = np.zeros((2, 10), bool)
    mask[0, :] = True  # client 1 has nothing
    x, y, sm = partition.make_client_batches(images, labels, idx, mask, 2, 2)
    assert sm[0].all() and not sm[1].any()
