"""On-disk dataset readers (fedtpu.data.datasets).

No real datasets exist in this environment, so the disk code paths (CIFAR
python pickles, MNIST idx files) would otherwise never execute. These tests
synthesize byte-exact on-disk formats in a temp dir and pin: correct
decode/normalisation/layout, the 'disk' source tag, and gz handling.
"""

import gzip
import os
import pickle
import struct

import numpy as np
import pytest

from fedtpu.data import datasets


@pytest.fixture()
def data_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("FEDTPU_DATA_DIR", str(tmp_path))
    return tmp_path


def _write_cifar10(root, n_per_batch=4):
    d = root / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    all_data, all_labels = [], []
    for i in range(1, 6):
        data = rng.integers(0, 256, size=(n_per_batch, 3072), dtype=np.int64
                            ).astype(np.uint8)
        labels = rng.integers(0, 10, size=n_per_batch).tolist()
        with open(d / f"data_batch_{i}", "wb") as fh:
            pickle.dump({b"data": data, b"labels": labels}, fh)
        all_data.append(data)
        all_labels.extend(labels)
    test = rng.integers(0, 256, size=(n_per_batch, 3072), dtype=np.int64
                        ).astype(np.uint8)
    with open(d / "test_batch", "wb") as fh:
        pickle.dump({b"data": test, b"labels": [1] * n_per_batch}, fh)
    return np.concatenate(all_data), np.asarray(all_labels)


def test_cifar10_disk_decode_layout_and_normalisation(data_dir):
    raw, labels = _write_cifar10(data_dir)
    x, y = datasets.load_cifar10("train")
    assert datasets.data_source("cifar10", "train") == "disk"
    assert x.shape == (20, 32, 32, 3) and x.dtype == np.float32
    np.testing.assert_array_equal(y, labels)
    # CHW->HWC transpose + mean/std normalisation, checked on one pixel.
    img0 = raw[0].reshape(3, 32, 32).transpose(1, 2, 0).astype(np.float32)
    expect = (img0 / 255.0 - datasets.CIFAR10_MEAN) / datasets.CIFAR10_STD
    np.testing.assert_allclose(x[0], expect, rtol=1e-5)


def test_cifar100_disk(data_dir):
    d = data_dir / "cifar-100-python"
    d.mkdir()
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(6, 3072), dtype=np.int64).astype(np.uint8)
    fine = rng.integers(0, 100, size=6).tolist()
    with open(d / "train", "wb") as fh:
        pickle.dump({b"data": data, b"fine_labels": fine}, fh)
    x, y = datasets.load_cifar100("train")
    assert datasets.data_source("cifar100", "train") == "disk"
    assert x.shape == (6, 32, 32, 3)
    np.testing.assert_array_equal(y, fine)


def _idx_bytes(arr):
    ndim = arr.ndim
    magic = struct.pack(">I", (0x08 << 8) | ndim)  # unsigned byte dtype
    dims = b"".join(struct.pack(">I", d) for d in arr.shape)
    return magic + dims + arr.tobytes()


@pytest.mark.parametrize("gz", [False, True])
def test_mnist_idx_disk_with_and_without_gzip(data_dir, gz):
    rng = np.random.default_rng(2)
    images = rng.integers(0, 256, size=(5, 28, 28), dtype=np.int64
                          ).astype(np.uint8)
    labels = rng.integers(0, 10, size=5, dtype=np.int64).astype(np.uint8)
    suffix = ".gz" if gz else ""
    opener = gzip.open if gz else open
    with opener(data_dir / f"train-images-idx3-ubyte{suffix}", "wb") as fh:
        fh.write(_idx_bytes(images))
    with opener(data_dir / f"train-labels-idx1-ubyte{suffix}", "wb") as fh:
        fh.write(_idx_bytes(labels))
    x, y = datasets.load_mnist("train")
    assert datasets.data_source("mnist", "train") == "disk"
    assert x.shape == (5, 28, 28, 1) and x.dtype == np.float32
    np.testing.assert_array_equal(y, labels.astype(np.int32))
    expect = (images[0].astype(np.float32) / 255.0 - datasets.MNIST_MEAN) / (
        datasets.MNIST_STD
    )
    np.testing.assert_allclose(x[0, :, :, 0], expect, rtol=1e-5)


def test_missing_test_batch_raises_rather_than_synthesizing(data_dir):
    """The directory exists but a file is missing: loading must raise (a
    half-present dataset is an install error), never silently synthesize —
    and the train split's 'disk' tag must survive."""
    _write_cifar10(data_dir)
    datasets.load_cifar10("train")
    os.remove(data_dir / "cifar-10-batches-py" / "test_batch")
    with pytest.raises(FileNotFoundError):
        datasets.load_cifar10("test")


# ---------------------------------------------------------------- hard tasks
def test_hard_task_is_deterministic_and_nonsaturating():
    """The *_hard benchmark tasks (VERDICT r3 weak #4): deterministic across
    calls (memoised AND stream-stable), label-noise rate ~10%, and distinct
    train/test noise from shared prototypes."""
    import numpy as np

    from fedtpu.data import load
    from fedtpu.data.datasets import _synthetic_hard

    x1, y1 = load("cifar10_hard", "train", num=512)
    x2, y2 = load("cifar10_hard", "train", num=512)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert x1.shape == (512, 32, 32, 3) and x1.dtype == np.float32

    # Label noise: ~10% of labels disagree with the nearest-prototype class
    # structure. Rebuild the clean assignment from the generator directly.
    xr, yr = _synthetic_hard(4096, (32, 32, 3), 10, 40, "train",
                             label_noise=0.0)
    xn, yn = _synthetic_hard(4096, (32, 32, 3), 10, 40, "train",
                             label_noise=0.1)
    np.testing.assert_array_equal(xr, xn)  # images unaffected by label noise
    flip_rate = float((yr != yn).mean())
    assert 0.06 < flip_rate < 0.14, flip_rate  # ~0.1 * (1 - 1/classes)

    # Train and test share the task (prototypes) but not the noise draws.
    tx, ty = load("cifar10_hard", "test", num=512)
    assert tx.shape[0] == 512
    assert not np.array_equal(x1[:512], tx)


def test_hard_task_no_fallback_warning(recwarn):
    """*_hard is a deliberate benchmark task, not a missing-file fallback —
    loading it must not emit the synthetic-fallback UserWarning."""
    from fedtpu.data import load

    load("cifar100_hard", "train", num=64)
    assert not [w for w in recwarn.list
                if "falling back" in str(w.message)]


def test_hard_dataset_info_and_source():
    from fedtpu.data import data_source, dataset_info, load

    assert dataset_info("cifar10_hard") == ((32, 32, 3), 10)
    assert dataset_info("cifar100_hard") == ((32, 32, 3), 100)
    load("cifar10_hard", "train", num=64)
    assert data_source("cifar10_hard", "train") == "synthetic"
