"""Flat-buffer delta pipeline (fedtpu.ops.flat + FedConfig.delta_layout).

Pins the tentpole invariants:

- pack/unpack round-trips exactly (padding dropped, dtypes restored);
- ``layout='flat'`` is BIT-IDENTICAL to ``per_leaf`` for
  ``compression='none'`` and ``'int8'`` (codec level on two many-leaf zoo
  architectures, round-step level on mlp), error feedback on and off;
- ``topk`` flat implements the documented-equivalent GLOBAL budget: the
  keep threshold spans the whole model instead of being quantised per leaf;
- the flat wire record (one contiguous block + offsets table) round-trips;
- the flat codec+aggregation stage issues <= 10% of the per-leaf stage's
  op dispatches on a many-leaf model (the perf acceptance gate).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu import models
from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import round as round_lib
from fedtpu.ops import compression, flat as flat_ops

MANY_LEAF_ARCHS = ["densenet_cifar", "mobilenetv2"]


def arch_delta_tree(name, clients=2, seed=0):
    """[clients, ...]-stacked random deltas shaped like a zoo model's params
    — via eval_shape, so no forward pass is ever executed."""
    model = models.create(name, num_classes=10)
    params = jax.eval_shape(
        lambda r, x: model.init(r, x, train=False),
        jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.float32),
    )["params"]
    rng = np.random.default_rng(seed)
    deltas = jax.tree.map(
        lambda s: jnp.asarray(
            rng.normal(size=(clients,) + tuple(s.shape)).astype(np.float32)
        ),
        params,
    )
    return params, deltas


# ------------------------------------------------------------ pack / unpack
def test_pack_unpack_roundtrip(rng):
    tree = {
        "w": jnp.asarray(rng.normal(size=(3, 7, 9)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(3, 5)).astype(np.float32)),
    }
    lay = flat_ops.make_layout_stacked(tree)
    assert lay.total == 7 * 9 + 5
    assert lay.padded % flat_ops.LANE == 0 and lay.padded >= lay.total
    # tree_flatten orders dict keys alphabetically: "b" (5) before "w" (63).
    assert lay.sizes == (5, 63)
    assert lay.offsets == (0, 5)
    flat = flat_ops.pack_stacked(lay, tree)
    assert flat.shape == (3, lay.padded)
    # Padding region is zero.
    np.testing.assert_array_equal(np.asarray(flat[:, lay.total :]), 0.0)
    back = flat_ops.unpack_stacked(lay, flat)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back[k]), np.asarray(tree[k]))
    # Single-row form.
    single = {k: v[0] for k, v in tree.items()}
    row = flat_ops.pack(lay, single)
    back1 = flat_ops.unpack(lay, row)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(back1[k]), np.asarray(single[k]))


def test_layout_is_static_and_lane_aligned():
    params = {"a": np.zeros((130,), np.float32), "b": np.zeros((2, 2), np.float32)}
    lay = flat_ops.make_layout(params)
    assert lay.sizes == (130, 4)
    assert lay.total == 134
    assert lay.padded == 256  # next multiple of 128
    ids = flat_ops.segment_ids(lay)
    assert ids.shape == (256,)
    assert (ids[:130] == 0).all() and (ids[130:134] == 1).all()
    assert (ids[134:] == 2).all()  # padding segment


def test_pack_rejects_wrong_tree():
    lay = flat_ops.make_layout({"a": np.zeros((4,), np.float32)})
    with pytest.raises(ValueError):
        flat_ops.pack_stacked(lay, {"a": jnp.zeros((2, 4)), "b": jnp.zeros((2, 1))})


# ------------------------------------- codec parity on many-leaf zoo models
@pytest.mark.parametrize("arch", MANY_LEAF_ARCHS)
@pytest.mark.parametrize("error_feedback", [True, False])
def test_int8_flat_bit_identical_on_arch(arch, error_feedback):
    params, deltas = arch_delta_tree(arch)
    per = compression.make_int8(error_feedback=error_feedback)
    fl = compression.make_int8(error_feedback=error_feedback, layout="flat")
    s_per = per.init(params, 2)
    s_fl = fl.init(params, 2)
    # Deliberately NOT jitted: tracing+compiling a 360-leaf program twice
    # per param set would dominate tier-1 runtime; op-by-op execution is
    # numerically identical (each op is still compiled individually).
    o_per, n_per = per.apply(deltas, s_per)
    o_fl, n_fl = fl.apply(deltas, s_fl)
    for a, b in zip(jax.tree.leaves(o_per), jax.tree.leaves(o_fl)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    if error_feedback:
        # Residuals identical too (flat state compared leaf-wise via unpack).
        lay = flat_ops.make_layout(params)
        n_fl_tree = flat_ops.unpack_stacked(lay, n_fl)
        for a, b in zip(jax.tree.leaves(n_per), jax.tree.leaves(n_fl_tree)):
            np.testing.assert_array_equal(
                np.asarray(a).reshape(np.shape(b)), np.asarray(b)
            )


@pytest.mark.parametrize("arch", MANY_LEAF_ARCHS)
@pytest.mark.parametrize("error_feedback", [True, False])
def test_topk_flat_global_budget_on_arch(arch, error_feedback):
    """Documented-equivalent semantics: ONE global keep budget
    ``ceil(f * total)`` spent on the globally largest coordinates, vs the
    per-leaf codec's leaf-quantised budgets."""
    fraction = 0.01
    params, deltas = arch_delta_tree(arch)
    fl = compression.make_topk(
        fraction, error_feedback=error_feedback, layout="flat"
    )
    state = fl.init(params, 2)
    lay = flat_ops.make_layout(params)
    y = flat_ops.pack_stacked(lay, deltas)
    out, new_state = fl.apply_flat(y, state, lay)
    out_np = np.asarray(out)
    k = math.ceil(fraction * lay.total)
    for c in range(2):
        row = np.asarray(y)[c]
        kept = out_np[c] != 0
        # Budget: exactly k kept (random gaussians don't tie), global.
        assert k <= kept.sum() <= k + 8
        # Every kept coordinate is >= every dropped REAL coordinate.
        dropped = ~kept
        dropped[lay.total :] = False  # padding is not a real coordinate
        assert np.abs(row[kept]).min() >= np.abs(row[dropped]).max() - 1e-6
    if error_feedback:
        # Mass conservation on the flat buffer.
        np.testing.assert_allclose(
            out_np + np.asarray(new_state), np.asarray(y), atol=1e-6
        )
        # Padding region of the residual stays zero.
        np.testing.assert_array_equal(
            np.asarray(new_state)[:, lay.total :], 0.0
        )


# --------------------------------------------- round-step bit parity (mlp)
def _mlp_setup(kind, layout, error_feedback=True):
    cfg = RoundConfig(
        model="mlp",
        num_classes=4,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=8),
        fed=FedConfig(
            num_clients=4,
            compression=kind,
            topk_fraction=0.1,
            error_feedback=error_feedback,
            delta_layout=layout,
        ),
        steps_per_round=3,
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    comp = compression.make_compressor(cfg.fed)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32), comp
    )
    step = jax.jit(round_lib.make_round_step(model, cfg, compressor=comp))
    rng = np.random.default_rng(0)
    n, s, b = 4, 3, 8
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 6)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 4, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n, ), bool),
    )
    return state, step, batch


@pytest.mark.parametrize(
    "kind,error_feedback",
    [("none", True), ("int8", True), ("int8", False)],
)
def test_round_step_layouts_bit_identical(kind, error_feedback):
    results = {}
    for layout in ("per_leaf", "flat"):
        state, step, batch = _mlp_setup(kind, layout, error_feedback)
        for _ in range(3):
            state, m = step(state, batch)
        results[layout] = (state, m)
    s_pl, m_pl = results["per_leaf"]
    s_fl, m_fl = results["flat"]
    for a, b in zip(jax.tree.leaves(s_pl.params), jax.tree.leaves(s_fl.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(m_pl.loss) == float(m_fl.loss)


def test_round_step_topk_flat_trains():
    state, step, batch = _mlp_setup("topk", "flat")
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m.loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # Flat residual state: ONE [clients, P] buffer, nonzero after rounds.
    assert isinstance(state.comp_state, jnp.ndarray)
    assert state.comp_state.ndim == 2
    assert float(jnp.abs(state.comp_state).max()) > 0


def test_round_step_rejects_layout_mismatch():
    cfg = RoundConfig(
        model="mlp",
        num_classes=4,
        data=DataConfig(dataset="synthetic"),
        fed=FedConfig(num_clients=2, compression="topk", delta_layout="flat"),
    )
    model = models.create("mlp", num_classes=4)
    per_leaf = compression.make_topk(0.1)
    with pytest.raises(ValueError, match="flat"):
        round_lib.make_round_step(model, cfg, compressor=per_leaf)
    flat_comp = compression.make_topk(0.1, layout="flat")
    cfg2 = RoundConfig(
        model="mlp",
        num_classes=4,
        data=DataConfig(dataset="synthetic"),
        fed=FedConfig(num_clients=2, compression="topk", delta_layout="per_leaf"),
    )
    with pytest.raises(ValueError, match="per_leaf"):
        round_lib.make_round_step(model, cfg2, compressor=flat_comp)


# ----------------------------------------------------------- mesh topology
@pytest.mark.parametrize("kind", ["none", "int8"])
def test_mesh_flat_vs_per_leaf_bit_identical(eight_devices, kind):
    """The layout-parity invariant holds ON THE MESH too: shard_map rounds
    with delta_layout='flat' produce bit-identical params to per_leaf at the
    same topology (comp_state shards as one [clients, P] buffer)."""
    from fedtpu.core.engine import Federation
    from fedtpu.parallel import client_mesh

    def build(layout):
        cfg = RoundConfig(
            model="mlp",
            num_classes=10,
            opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
            data=DataConfig(
                dataset="synthetic", batch_size=8, partition="iid",
                num_examples=256,
            ),
            fed=FedConfig(num_clients=8, compression=kind, delta_layout=layout),
            steps_per_round=2,
        )
        return Federation(cfg, seed=0, mesh=client_mesh(8, cfg.mesh_axis))

    f_pl, f_fl = build("per_leaf"), build("flat")
    for _ in range(2):
        f_pl.step()
        f_fl.step()
    for a, b in zip(
        jax.tree.leaves(f_pl.state.params), jax.tree.leaves(f_fl.state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------- dispatch budget
def test_flat_dispatch_count_within_budget():
    """Acceptance gate: the flat codec+aggregation stage traces to <= 10%
    of the per-leaf stage's jaxpr equations on a many-leaf model (trace
    only — nothing executes)."""
    from fedtpu.core.round import _mean_over_clients

    params, deltas = arch_delta_tree("densenet_cifar")
    lay = flat_ops.make_layout(params)
    weights = jnp.ones((2,), jnp.float32)

    for make_per, make_fl in [
        (
            lambda: compression.make_topk(0.01),
            lambda: compression.make_topk(0.01, layout="flat"),
        ),
        (
            lambda: compression.make_int8(),
            lambda: compression.make_int8(layout="flat"),
        ),
    ]:
        per, fl = make_per(), make_fl()
        s_per, s_fl = per.init(params, 2), fl.init(params, 2)

        def per_stage(d, s):
            out, new = per.apply(d, s)
            return _mean_over_clients(out, weights, None)[0], new

        def fl_stage(y, s):
            out, new = fl.apply_flat(y, s, lay)
            return _mean_over_clients(out, weights, None)[0], new

        y0 = jax.eval_shape(lambda d: flat_ops.pack_stacked(lay, d), deltas)
        n_per = len(jax.make_jaxpr(per_stage)(deltas, s_per).eqns)
        n_fl = len(jax.make_jaxpr(fl_stage)(y0, s_fl).eqns)
        assert n_fl <= 0.10 * n_per, (n_fl, n_per)
