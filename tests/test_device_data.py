"""Device-resident data pipeline (fedtpu.data.device).

The hot path gathers each round's batches on device from the HBM-resident
dataset; these tests pin its equivalence to the host-side
``partition.make_client_batches`` (the reference-semantics oracle,
``src/main.py:140-144``) and the loud-synthetic-fallback tagging.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.data import partition
from fedtpu.data import datasets
from fedtpu.data.device import round_take_indices


def _cfg(**kw):
    base = dict(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    base.update(kw)
    return RoundConfig(**base)


def test_unshuffled_take_matches_host_tile_rule():
    idx, mask = partition.round_robin(96, 3, 4)
    need = 2 * 4
    take = np.asarray(round_take_indices(jnp.asarray(idx), jnp.asarray(mask), need))
    for c in range(3):
        own = idx[c][mask[c]]
        expect = np.tile(own, int(np.ceil(need / len(own))))[:need]
        np.testing.assert_array_equal(take[c], expect)


def test_shuffled_take_is_a_permutation_of_the_shard():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=200)
    idx, mask = partition.dirichlet(labels, 4, alpha=0.5, seed=0)
    need = 8
    take = np.asarray(
        round_take_indices(
            jnp.asarray(idx), jnp.asarray(mask), need, jax.random.PRNGKey(1)
        )
    )
    for c in range(4):
        own = set(idx[c][mask[c]].tolist())
        assert set(take[c].tolist()) <= own
        if len(own) >= need:
            # Big-enough shards are sampled without replacement per round.
            assert len(set(take[c].tolist())) == need


def test_shuffle_differs_across_rounds_but_is_deterministic():
    idx, mask = partition.iid(64, 2, seed=0)
    a = np.asarray(round_take_indices(jnp.asarray(idx), jnp.asarray(mask), 16,
                                      jax.random.PRNGKey(5)))
    b = np.asarray(round_take_indices(jnp.asarray(idx), jnp.asarray(mask), 16,
                                      jax.random.PRNGKey(6)))
    c = np.asarray(round_take_indices(jnp.asarray(idx), jnp.asarray(mask), 16,
                                      jax.random.PRNGKey(5)))
    assert not np.array_equal(a, b)
    np.testing.assert_array_equal(a, c)


def test_engine_device_path_matches_host_batch_path():
    """One round through the on-device gather must equal the same round fed
    with host-materialised batches (round_robin is unshuffled on both paths,
    so the data order is bit-identical)."""
    cfg = _cfg()
    fed_dev = Federation(cfg, seed=0)
    fed_host = Federation(cfg, seed=0)

    fed_dev.step()  # device-resident path
    fed_host.step(fed_host.round_batch(0))  # explicit host path

    for a, b in zip(
        jax.tree_util.tree_leaves(fed_dev.state.params),
        jax.tree_util.tree_leaves(fed_host.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert int(fed_dev.state.round_idx) == 1


def test_engine_device_path_respects_dead_clients():
    cfg = _cfg()
    fed = Federation(cfg, seed=0)
    fed.set_alive(1, False)
    m = fed.step()
    assert int(m.num_active) == 2


def test_synthetic_fallback_is_loud_and_tagged(tmp_path, monkeypatch):
    monkeypatch.setenv("FEDTPU_DATA_DIR", str(tmp_path))  # guaranteed-empty dir
    datasets._WARNED.discard("cifar10")
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        datasets.load("cifar10", "train", num=64)
    assert datasets.data_source("cifar10") == "synthetic"
    # The explicit synthetic dataset is tagged but never warns.
    datasets.load("synthetic", "train", num=64)
    assert datasets.data_source("synthetic") == "synthetic"


def test_engine_mesh_path_matches_single_program(eight_devices):
    """Federation(mesh=...) — shard_map + psum + on-device sharded gather —
    must produce the same round as the single-program path (round_robin is
    unshuffled, so data order matches bit-for-bit)."""
    from fedtpu.parallel import client_mesh

    cfg = _cfg(
        fed=FedConfig(num_clients=8),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))

    m1 = single.step()
    m2 = meshed.step()
    assert int(m2.num_active) == 8
    np.testing.assert_allclose(float(m1.loss), float(m2.loss), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(single.state.params),
        jax.tree_util.tree_leaves(meshed.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_engine_mesh_path_dead_client(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = _cfg(fed=FedConfig(num_clients=8))
    fed = Federation(cfg, seed=0, mesh=client_mesh(8))
    fed.set_alive(5, False)
    m = fed.step()
    assert int(m.num_active) == 7


def test_stream_gather_matches_materialized_path():
    """stream=True (per-step gather inside the scan — the big-model HBM
    lever) must be numerically identical to the materialized gather."""
    from fedtpu.data.device import make_data_round_step

    cfg = _cfg()
    a = Federation(cfg, seed=0)
    b = Federation(cfg, seed=0)
    b._data_step = jax.jit(
        make_data_round_step(b.model, b.cfg, b._steps, shuffle=False,
                             stream=True),
        donate_argnums=(0,),
    )
    ma = a.step()
    mb = b.step()
    np.testing.assert_allclose(float(ma.loss), float(mb.loss), atol=1e-6)
    for x, y in zip(
        jax.tree_util.tree_leaves(a.state.params),
        jax.tree_util.tree_leaves(b.state.params),
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_remat_resnet_params_and_grads_match(rng):
    """remat=True must change neither the param tree (names pinned) nor the
    gradients — only the memory/time trade."""
    import optax
    from fedtpu import models

    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)).astype(np.float32))
    y = jnp.asarray([1, 3])
    outs = {}
    for remat in (False, True):
        m = models.create("resnet18", num_classes=10, remat=remat)
        v = m.init(jax.random.PRNGKey(0), x, train=False)

        def loss(params, v=v, m=m):
            logits, _ = m.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        outs[remat] = (v, jax.jit(jax.grad(loss))(v["params"]))
    va, ga = outs[False]
    vb, gb = outs[True]
    assert jax.tree_util.tree_structure(va) == jax.tree_util.tree_structure(vb)
    for a, b in zip(jax.tree_util.tree_leaves(ga), jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_remat_unsupported_model_raises():
    from fedtpu import models
    with pytest.raises(ValueError, match="does not support remat"):
        models.create("lenet", num_classes=10, remat=True)
    # remat=False is accepted everywhere (a no-op).
    models.create("lenet", num_classes=10, remat=False)


def test_sharded_gather_shuffle_decorrelates_across_shards(eight_devices):
    """The gather layout's per-shard permutation keys fold the mesh axis
    index (device.py): give all 8 clients IDENTICAL data and identical
    assignment rows — then with one client per device, any per-client loss
    difference can ONLY come from different batch ORDER, so distinct
    losses pin the fold (without it every shard would draw byte-identical
    permutations and all 8 losses would coincide). Control: unshuffled,
    the same setup must produce identical losses."""
    from fedtpu import models
    from fedtpu.core import round as round_lib
    from fedtpu.data.device import make_sharded_data_round_step
    from fedtpu.parallel import client_mesh

    n, steps, batch, dim = 8, 2, 4, 48
    cfg = _cfg(
        fed=FedConfig(num_clients=n),
        data=DataConfig(dataset="synthetic", batch_size=batch,
                        partition="iid", num_examples=64,
                        device_layout="gather"),
    )
    mdl = models.create("mlp", num_classes=cfg.num_classes)
    rng = np.random.default_rng(3)
    images = jnp.asarray(rng.normal(size=(64, dim)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, size=64).astype(np.int32))
    idx = jnp.broadcast_to(jnp.arange(64, dtype=jnp.int32), (n, 64))
    mask = jnp.ones((n, 64), bool)
    mesh = client_mesh(8, cfg.mesh_axis)
    state = round_lib.init_state(
        mdl, cfg, jax.random.PRNGKey(0), jnp.zeros((1, dim), jnp.float32)
    )

    losses = {}
    for shuffle in (True, False):
        step = make_sharded_data_round_step(
            mdl, cfg, steps, mesh, shuffle=shuffle, donate=False,
            image_shape=(dim,), layout="gather",
        )
        _, m = step(state, images, labels, idx, mask,
                    jnp.ones((n,), jnp.float32), jnp.ones((n,), bool),
                    jax.random.PRNGKey(5))
        losses[shuffle] = np.asarray(m.per_client_loss)

    # Unshuffled control: identical shards -> identical per-client losses.
    assert len({round(float(v), 6) for v in losses[False]}) == 1, losses[False]
    # Shuffled: the axis-index fold gives each shard its own permutation.
    assert len({round(float(v), 6) for v in losses[True]}) > 1, losses[True]
