"""Observability + engine knobs: progress bar, profiler hook, local_epochs,
multihost helpers, wire-byte accounting, and the PR-3 telemetry stack
(modes, FT transition events, engine spans). Exporter schemas live in
tests/test_obs_exporters.py."""

import io
import os

import jax
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.utils import ProgressBar, format_time, profile_rounds


def test_progress_bar_headless():
    """Must not touch the tty (the reference's bar calls `stty size` at
    import and dies headless, src/utils.py:45-46)."""
    buf = io.StringIO()  # not a tty
    bar = ProgressBar(total=3, out=buf)
    for i in range(3):
        bar.update(i, msg=f"loss {i}")
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 3
    assert "3/3" in lines[-1]
    assert "loss 2" in lines[-1]


def test_format_time():
    assert format_time(0.25) == "250ms"
    assert format_time(61) == "1m1s"
    assert format_time(3661) == "1h1m1s"


def test_profile_rounds_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    with profile_rounds(d):
        jax.numpy.zeros((8, 8)).sum().block_until_ready()
    # jax writes plugins/profile/<ts>/*; just require non-empty output.
    found = [f for _, _, fs in os.walk(d) for f in fs]
    assert found


def test_profile_rounds_none_is_noop():
    with profile_rounds(None):
        pass


def test_local_epochs_multiplies_steps():
    def fed_with(epochs):
        return Federation(
            RoundConfig(
                model="mlp",
                num_classes=10,
                opt=OptimizerConfig(),
                data=DataConfig(dataset="synthetic", batch_size=8,
                                num_examples=128, partition="iid"),
                fed=FedConfig(num_clients=2, local_epochs=epochs),
                steps_per_round=3,
            ),
            seed=0,
        )

    b1 = fed_with(1).round_batch(0)
    b3 = fed_with(3).round_batch(0)
    assert b1.x.shape[1] == 3
    assert b3.x.shape[1] == 9  # 3 steps x 3 local epochs


def test_multihost_helpers_single_process():
    from fedtpu.parallel import multihost

    # Single-process environment: initialize is a no-op, we are coordinator.
    multihost.initialize()
    assert multihost.is_coordinator()
    s = multihost.local_client_slice(8)
    assert (s.start, s.stop) == (0, 8)

def test_per_client_loss_vector_flags_the_outlier():
    """per_client_loss exposes which client diverges — the observability
    hook that pairs with robust aggregation."""
    import numpy as np
    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    probe = Federation(cfg, seed=0)
    imgs = np.asarray(probe.images).copy()
    labels = np.asarray(probe.labels).copy()
    own = probe.client_idx[1][probe.client_mask[1]]
    imgs[own] *= 40.0  # client 1 ships garbage
    fed = Federation(cfg, seed=0, data=(imgs, labels))
    fed.set_alive(2, False)
    m = fed.step()
    pcl = np.asarray(m.per_client_loss)
    assert pcl.shape == (3,)
    assert pcl[2] == 0.0                      # dead client masked out
    assert pcl[1] == pcl.max() and pcl[1] > pcl[0] * 5, pcl
    # Mean metric == masked mean of the vector.
    np.testing.assert_allclose(float(m.loss), pcl[:2].mean(), rtol=1e-5)


def test_per_client_loss_through_fused_scan_and_mesh(eight_devices):
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    stacked = meshed.run_on_device(2)
    pcl = np.asarray(stacked.per_client_loss)
    assert pcl.shape == (2, 8)
    assert np.isfinite(pcl).all()
    single = Federation(cfg, seed=0)
    s = single.run_on_device(2)
    np.testing.assert_allclose(pcl, np.asarray(s.per_client_loss), atol=1e-5)


def test_debug_per_batch_prints_from_jitted_epoch(capfd):
    """RoundConfig(debug_per_batch=True) reproduces the reference's
    mid-epoch per-batch console feedback (src/utils.py:51-92) from INSIDE
    the jitted local epoch (VERDICT r3 missing #3)."""
    import dataclasses

    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05),
        data=DataConfig(dataset="synthetic", batch_size=8, num_examples=64),
        fed=FedConfig(num_clients=2),
        steps_per_round=2,
        debug_per_batch=True,
    )
    fed = Federation(cfg, seed=0)
    fed.step()
    jax.effects_barrier()
    out = capfd.readouterr().out
    # 2 clients x 2 steps = 4 per-batch lines.
    assert out.count("batch: loss") == 4, out
    # And it is OFF by default (the flag is a debugging aid).
    quiet = Federation(dataclasses.replace(cfg, debug_per_batch=False), seed=0)
    quiet.step()
    jax.effects_barrier()
    assert "batch: loss" not in capfd.readouterr().out


# ----------------------------------------------------- telemetry (fedtpu.obs)
def test_telemetry_modes_gate_spans_and_metrics():
    from fedtpu.obs import Telemetry

    off = Telemetry("off")
    with off.span("x") as s:
        assert s.id is None  # shared no-op span
    off.counter("c").inc()
    off.histogram("h").observe(1.0)
    assert off.registry.snapshot() == {}  # nothing reached the registry
    assert off.trace_events() == []

    basic = Telemetry("basic")
    basic.counter("c").inc(2)
    with basic.span("x") as s:
        assert s.id is None  # metrics yes, spans no
    assert basic.registry.snapshot()["c"][0]["value"] == 2
    assert basic.trace_events() == []

    trace = Telemetry("trace")
    with trace.span("x"):
        pass
    assert [e["name"] for e in trace.trace_events()] == ["x"]

    with pytest.raises(ValueError, match="telemetry"):
        Telemetry("verbose")


def test_engine_rejects_bad_telemetry_mode_before_building():
    from fedtpu.config import DataConfig, FedConfig, RoundConfig

    with pytest.raises(ValueError, match="telemetry"):
        Federation(
            RoundConfig(
                model="mlp",
                num_classes=10,
                data=DataConfig(dataset="synthetic", num_examples=64),
                fed=FedConfig(num_clients=2, telemetry="loud"),
            ),
            seed=0,
        )


def test_engine_step_emits_round_span_and_counter():
    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig

    fed = Federation(
        RoundConfig(
            model="mlp",
            num_classes=10,
            opt=OptimizerConfig(learning_rate=0.05),
            data=DataConfig(dataset="synthetic", batch_size=8,
                            num_examples=64, partition="iid"),
            fed=FedConfig(num_clients=2, telemetry="trace"),
            steps_per_round=2,
        ),
        seed=0,
    )
    fed.step()
    fed.run_on_device(3)
    names = [e["name"] for e in fed.telemetry.trace_events()]
    assert names.count("round") == 1
    assert names.count("fused_rounds") == 1
    snap = fed.telemetry.registry.snapshot()
    assert snap["fedtpu_rounds_completed_total"][0]["value"] == 4


def test_client_registry_transitions_are_logged_and_counted(caplog):
    """Satellite: heartbeat-detected deaths/recoveries are structured
    events — a log line + a counter — not silent dict flips. Redundant
    re-marks must NOT inflate the counters."""
    import logging

    from fedtpu.ft import ClientRegistry
    from fedtpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    clients = ClientRegistry(["a", "b"], metrics=reg)
    with caplog.at_level(logging.INFO, logger="fedtpu.ft"):
        clients.mark_failed("a")
        clients.mark_failed("a")  # already dead: no event
        clients.mark_alive("a")
        clients.mark_alive("a")   # already alive: no event
        clients.mark_alive("b")   # alive from construction: no event
    warnings = [r for r in caplog.records if "marked dead" in r.message]
    recoveries = [r for r in caplog.records if "recovered" in r.message]
    assert len(warnings) == 1 and "a" in warnings[0].getMessage()
    assert len(recoveries) == 1
    snap = reg.snapshot()
    assert snap["fedtpu_ft_client_deaths_total"][0]["value"] == 1
    assert snap["fedtpu_ft_client_recoveries_total"][0]["value"] == 1


def test_heartbeat_monitor_counts_misses_and_resync_failures():
    from fedtpu.ft import ClientRegistry, HeartbeatMonitor
    from fedtpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    clients = ClientRegistry(["a", "b"], metrics=reg)
    clients.mark_failed("a")
    clients.mark_failed("b")
    alive_probe = {"a": False, "b": True}
    resync_ok = {"b": False}  # heartbeat up but resync push fails once

    def resync(c):
        if not resync_ok.get(c, True):
            resync_ok[c] = True
            raise RuntimeError("push failed")

    mon = HeartbeatMonitor(
        clients, probe=lambda c: alive_probe[c], resync=resync, metrics=reg,
    )
    assert mon.tick() == []        # a: miss; b: probe ok, resync fails
    assert mon.tick() == ["b"]     # a: miss; b recovers
    snap = reg.snapshot()
    assert snap["fedtpu_ft_heartbeat_misses_total"][0]["value"] == 2
    assert snap["fedtpu_ft_resync_failures_total"][0]["value"] == 1
    assert snap["fedtpu_ft_client_recoveries_total"][0]["value"] == 1


def test_failover_transitions_are_logged_and_counted(caplog):
    """Satellite: FailoverStateMachine role changes emit log.warning +
    labelled transition counters (they used to be silent unless the
    callbacks logged)."""
    import logging

    from fedtpu.ft import FailoverStateMachine
    from fedtpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    now = [0.0]
    m = FailoverStateMachine(timeout=10.0, clock=lambda: now[0], metrics=reg)
    with caplog.at_level(logging.WARNING, logger="fedtpu.ft"):
        m.on_ping(recovering=False)
        now[0] = 11.0
        assert m.check_watchdog() is True   # backup -> acting_primary
        assert m.on_ping(recovering=True) == 1  # acting -> backup
    msgs = [r.getMessage() for r in caplog.records if "failover:" in r.message]
    assert any("backup -> acting_primary" in s for s in msgs)
    assert any("acting_primary -> backup" in s for s in msgs)
    snap = reg.snapshot()
    by_label = {
        tuple(sorted(e["labels"].items())): e["value"]
        for e in snap["fedtpu_ft_failover_transitions_total"]
    }
    assert by_label[(("to", "acting_primary"),)] == 1
    assert by_label[(("to", "backup"),)] == 1
