"""Observability + engine knobs: progress bar, profiler hook, local_epochs,
multihost helpers, wire-byte accounting."""

import io
import os

import jax
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.utils import ProgressBar, format_time, profile_rounds


def test_progress_bar_headless():
    """Must not touch the tty (the reference's bar calls `stty size` at
    import and dies headless, src/utils.py:45-46)."""
    buf = io.StringIO()  # not a tty
    bar = ProgressBar(total=3, out=buf)
    for i in range(3):
        bar.update(i, msg=f"loss {i}")
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 3
    assert "3/3" in lines[-1]
    assert "loss 2" in lines[-1]


def test_format_time():
    assert format_time(0.25) == "250ms"
    assert format_time(61) == "1m1s"
    assert format_time(3661) == "1h1m1s"


def test_profile_rounds_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    with profile_rounds(d):
        jax.numpy.zeros((8, 8)).sum().block_until_ready()
    # jax writes plugins/profile/<ts>/*; just require non-empty output.
    found = [f for _, _, fs in os.walk(d) for f in fs]
    assert found


def test_profile_rounds_none_is_noop():
    with profile_rounds(None):
        pass


def test_local_epochs_multiplies_steps():
    def fed_with(epochs):
        return Federation(
            RoundConfig(
                model="mlp",
                num_classes=10,
                opt=OptimizerConfig(),
                data=DataConfig(dataset="synthetic", batch_size=8,
                                num_examples=128, partition="iid"),
                fed=FedConfig(num_clients=2, local_epochs=epochs),
                steps_per_round=3,
            ),
            seed=0,
        )

    b1 = fed_with(1).round_batch(0)
    b3 = fed_with(3).round_batch(0)
    assert b1.x.shape[1] == 3
    assert b3.x.shape[1] == 9  # 3 steps x 3 local epochs


def test_multihost_helpers_single_process():
    from fedtpu.parallel import multihost

    # Single-process environment: initialize is a no-op, we are coordinator.
    multihost.initialize()
    assert multihost.is_coordinator()
    s = multihost.local_client_slice(8)
    assert (s.start, s.stop) == (0, 8)

def test_per_client_loss_vector_flags_the_outlier():
    """per_client_loss exposes which client diverges — the observability
    hook that pairs with robust aggregation."""
    import numpy as np
    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    probe = Federation(cfg, seed=0)
    imgs = np.asarray(probe.images).copy()
    labels = np.asarray(probe.labels).copy()
    own = probe.client_idx[1][probe.client_mask[1]]
    imgs[own] *= 40.0  # client 1 ships garbage
    fed = Federation(cfg, seed=0, data=(imgs, labels))
    fed.set_alive(2, False)
    m = fed.step()
    pcl = np.asarray(m.per_client_loss)
    assert pcl.shape == (3,)
    assert pcl[2] == 0.0                      # dead client masked out
    assert pcl[1] == pcl.max() and pcl[1] > pcl[0] * 5, pcl
    # Mean metric == masked mean of the vector.
    np.testing.assert_allclose(float(m.loss), pcl[:2].mean(), rtol=1e-5)


def test_per_client_loss_through_fused_scan_and_mesh(eight_devices):
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    stacked = meshed.run_on_device(2)
    pcl = np.asarray(stacked.per_client_loss)
    assert pcl.shape == (2, 8)
    assert np.isfinite(pcl).all()
    single = Federation(cfg, seed=0)
    s = single.run_on_device(2)
    np.testing.assert_allclose(pcl, np.asarray(s.per_client_loss), atol=1e-5)


def test_debug_per_batch_prints_from_jitted_epoch(capfd):
    """RoundConfig(debug_per_batch=True) reproduces the reference's
    mid-epoch per-batch console feedback (src/utils.py:51-92) from INSIDE
    the jitted local epoch (VERDICT r3 missing #3)."""
    import dataclasses

    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05),
        data=DataConfig(dataset="synthetic", batch_size=8, num_examples=64),
        fed=FedConfig(num_clients=2),
        steps_per_round=2,
        debug_per_batch=True,
    )
    fed = Federation(cfg, seed=0)
    fed.step()
    jax.effects_barrier()
    out = capfd.readouterr().out
    # 2 clients x 2 steps = 4 per-batch lines.
    assert out.count("batch: loss") == 4, out
    # And it is OFF by default (the flag is a debugging aid).
    quiet = Federation(dataclasses.replace(cfg, debug_per_batch=False), seed=0)
    quiet.step()
    jax.effects_barrier()
    assert "batch: loss" not in capfd.readouterr().out
