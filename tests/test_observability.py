"""Observability + engine knobs: progress bar, profiler hook, local_epochs,
multihost helpers, wire-byte accounting."""

import io
import os

import jax
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.utils import ProgressBar, format_time, profile_rounds


def test_progress_bar_headless():
    """Must not touch the tty (the reference's bar calls `stty size` at
    import and dies headless, src/utils.py:45-46)."""
    buf = io.StringIO()  # not a tty
    bar = ProgressBar(total=3, out=buf)
    for i in range(3):
        bar.update(i, msg=f"loss {i}")
    lines = buf.getvalue().strip().splitlines()
    assert len(lines) == 3
    assert "3/3" in lines[-1]
    assert "loss 2" in lines[-1]


def test_format_time():
    assert format_time(0.25) == "250ms"
    assert format_time(61) == "1m1s"
    assert format_time(3661) == "1h1m1s"


def test_profile_rounds_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    with profile_rounds(d):
        jax.numpy.zeros((8, 8)).sum().block_until_ready()
    # jax writes plugins/profile/<ts>/*; just require non-empty output.
    found = [f for _, _, fs in os.walk(d) for f in fs]
    assert found


def test_profile_rounds_none_is_noop():
    with profile_rounds(None):
        pass


def test_local_epochs_multiplies_steps():
    def fed_with(epochs):
        return Federation(
            RoundConfig(
                model="mlp",
                num_classes=10,
                opt=OptimizerConfig(),
                data=DataConfig(dataset="synthetic", batch_size=8,
                                num_examples=128, partition="iid"),
                fed=FedConfig(num_clients=2, local_epochs=epochs),
                steps_per_round=3,
            ),
            seed=0,
        )

    b1 = fed_with(1).round_batch(0)
    b3 = fed_with(3).round_batch(0)
    assert b1.x.shape[1] == 3
    assert b3.x.shape[1] == 9  # 3 steps x 3 local epochs


def test_multihost_helpers_single_process():
    from fedtpu.parallel import multihost

    # Single-process environment: initialize is a no-op, we are coordinator.
    multihost.initialize()
    assert multihost.is_coordinator()
    s = multihost.local_client_slice(8)
    assert (s.start, s.stop) == (0, 8)
