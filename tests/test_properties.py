"""Property-based tests (hypothesis) for core invariants.

The fixed-case oracles elsewhere pin known inputs; these generalise the
invariants over randomized shapes/sizes: partitioners are exact disjoint
covers, DP clipping always respects its bound, the threshold codec conserves
mass exactly, robust combiners match NumPy on arbitrary masks, and the wire
codec roundtrips arbitrary pytrees and detects corruption.
"""

import functools
import zlib

import numpy as np
import jax
import jax.numpy as jnp
import pytest

# Environment gate, not a correctness gate: the container has no
# `hypothesis` wheel and installs are not allowed. Where hypothesis exists
# these tests run under it in full (shrinking, example database, coverage-
# guided generation); where it is absent they fall back to a deterministic
# stub that draws the same number of examples from seeded numpy — weaker
# exploration, but the invariants still execute on every tier-1 run instead
# of skipping wholesale.
try:
    from hypothesis import given, settings, strategies as st

    HAS_HYPOTHESIS = True
except ImportError:  # deterministic fallback — no new dependency
    HAS_HYPOTHESIS = False

    # Tier-1 time budget: the stub draws far fewer examples than
    # hypothesis's default 100 — shrinking/coverage come back whenever
    # the real library is installed; the stub only keeps the properties
    # EXERCISED (seeded, so a failing draw is reproducible by name).
    _STUB_EXAMPLES = 6

    class _Strategy:
        """Minimal strategy: a seeded-rng -> value draw, composable with
        the two combinators this module uses (map / flatmap)."""

        def __init__(self, draw):
            self._draw = draw

        def map(self, f):
            return _Strategy(lambda rng: f(self._draw(rng)))

        def flatmap(self, f):
            return _Strategy(lambda rng: f(self._draw(rng))._draw(rng))

    class _St:
        @staticmethod
        def integers(lo, hi):
            return _Strategy(lambda rng: int(rng.integers(lo, hi + 1)))

        @staticmethod
        def floats(lo, hi):
            return _Strategy(lambda rng: float(rng.uniform(lo, hi)))

        @staticmethod
        def booleans():
            return _Strategy(lambda rng: bool(rng.integers(0, 2)))

        @staticmethod
        def sampled_from(seq):
            items = list(seq)
            return _Strategy(
                lambda rng: items[int(rng.integers(0, len(items)))]
            )

        @staticmethod
        def dictionaries(keys, values, min_size=0, max_size=5):
            def draw(rng):
                size = int(rng.integers(max(min_size, 1), max_size + 1))
                out = {}
                for _ in range(4 * size):  # duplicate keys collapse
                    if len(out) >= size:
                        break
                    out[keys._draw(rng)] = values._draw(rng)
                return out

            return _Strategy(draw)

    st = _St()

    def given(**kw):
        def deco(fn):
            @functools.wraps(fn)
            def run():
                # Per-test seed from the name: stable across runs and
                # independent of execution order.
                rng = np.random.default_rng(zlib.crc32(fn.__name__.encode()))
                for _ in range(_STUB_EXAMPLES):
                    fn(**{k: s._draw(rng) for k, s in kw.items()})

            # pytest follows __wrapped__ to the original signature and
            # would demand fixtures named after the drawn arguments.
            del run.__wrapped__
            return run

        return deco

    def settings(**_kw):
        return lambda fn: fn


from fedtpu.core.round import _dp_clip, _robust_over_clients  # noqa: E402
from fedtpu.data import partition  # noqa: E402
from fedtpu.transport import sparse, wire  # noqa: E402

_slow = settings(max_examples=25, deadline=None)

# The suites above the sketch-codec section predate the stub: without real
# hypothesis this module used to skip wholesale, so running them under the
# stub re-buys ~9 s of tier-1 wall for coverage the seed never had.  They
# stay hypothesis-only; the sketch-codec properties below run in both modes.
_hypothesis_only = pytest.mark.skipif(
    not HAS_HYPOTHESIS,
    reason="needs real hypothesis; the stub runs only the sketch-codec properties",
)


@_hypothesis_only
@_slow
@given(
    n_examples=st.integers(4, 300),
    n_clients=st.integers(1, 9),
    batch=st.integers(1, 8),
)
def test_round_robin_is_an_exact_disjoint_cover(n_examples, n_clients, batch):
    idx, mask = partition.round_robin(n_examples, n_clients, batch)
    taken = idx[mask]
    n_batches = n_examples // batch  # trailing partial batch is dropped
    assert sorted(taken.tolist()) == list(range(n_batches * batch))


@_hypothesis_only
@_slow
@given(n_examples=st.integers(2, 400), n_clients=st.integers(1, 10),
       seed=st.integers(0, 5))
def test_iid_is_an_exact_disjoint_cover(n_examples, n_clients, seed):
    idx, mask = partition.iid(n_examples, n_clients, seed=seed)
    taken = sorted(idx[mask].tolist())
    assert taken == list(range(n_examples))


@_hypothesis_only
@_slow
@given(n=st.integers(20, 200), clients=st.integers(2, 8),
       alpha=st.floats(0.1, 5.0), seed=st.integers(0, 3))
def test_dirichlet_is_an_exact_disjoint_cover(n, clients, alpha, seed):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n)
    idx, mask = partition.dirichlet(labels, clients, alpha=alpha, seed=seed)
    assert sorted(idx[mask].tolist()) == list(range(n))


@_hypothesis_only
@_slow
@given(
    rows=st.integers(1, 6),
    cols=st.integers(1, 40),
    clip=st.floats(1e-3, 10.0),
    scale=st.floats(0.01, 100.0),
    seed=st.integers(0, 10),
)
def test_dp_clip_bound_always_holds(rows, cols, clip, scale, seed):
    rng = np.random.default_rng(seed)
    tree = {
        "a": jnp.asarray(scale * rng.normal(size=(rows, cols)).astype(np.float32)),
        "b": jnp.asarray(scale * rng.normal(size=(rows, 3)).astype(np.float32)),
    }
    clipped = _dp_clip(tree, clip)
    sq = sum(
        np.sum(np.square(np.asarray(x, np.float64)), axis=1)
        for x in jax.tree_util.tree_leaves(clipped)
    )
    assert (np.sqrt(sq) <= clip * (1 + 1e-4) + 1e-7).all()


@_hypothesis_only
@_slow
@given(
    n=st.integers(1, 9),
    cols=st.integers(1, 30),
    n_dead=st.integers(0, 3),
    seed=st.integers(0, 10),
)
def test_masked_median_matches_numpy(n, cols, n_dead, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, cols)).astype(np.float32)
    w = np.ones((n,), np.float32)
    dead = rng.choice(n, size=min(n_dead, n - 1) if n > 1 else 0, replace=False)
    w[dead] = 0.0
    out = _robust_over_clients(
        {"a": jnp.asarray(x)}, jnp.asarray(w), None, "median", 0.1
    )["a"]
    expect = np.median(x[w > 0], axis=0)
    np.testing.assert_allclose(np.asarray(out), expect, atol=1e-5)


def _tree_strategy():
    arr = st.integers(1, 12).flatmap(
        lambda k: st.integers(0, 6).map(
            lambda s: np.arange(k * (s + 1), dtype=np.float32).reshape(
                (k, s + 1)
            )
        )
    )
    return st.dictionaries(
        st.sampled_from(["w", "b", "m", "v"]), arr, min_size=1, max_size=4
    )


@_hypothesis_only
@_slow
@given(tree=_tree_strategy(), compress=st.booleans())
def test_wire_roundtrip_arbitrary_trees(tree, compress):
    blob = wire.encode(tree, compress=compress)
    like = {k: np.zeros_like(v) for k, v in tree.items()}
    out = wire.decode(blob, like)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]), tree[k])


@_hypothesis_only
@_slow
@given(tree=_tree_strategy(), pos_frac=st.floats(0.0, 1.0))
def test_wire_detects_payload_corruption(tree, pos_frac):
    blob = bytearray(wire.encode(tree, compress=False))
    header = 10  # magic(4) + version(1) + flags(1) + crc(4)
    if len(blob) <= header:
        return
    pos = header + int(pos_frac * (len(blob) - header - 1))
    blob[pos] ^= 0xFF
    like = {k: np.zeros_like(v) for k, v in tree.items()}
    with pytest.raises(ValueError):
        wire.decode(bytes(blob), like)


@_hypothesis_only
@_slow
@given(
    n=st.integers(2, 8),
    cols=st.integers(2, 20),
    trim=st.floats(0.0, 0.45),
    seed=st.integers(0, 10),
)
def test_trimmed_mean_stays_within_live_range(n, cols, trim, seed):
    """The trimmed mean of live clients always lies within [min, max] of the
    live values per coordinate, and the band is never empty."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, cols)).astype(np.float32) * 10
    out = np.asarray(
        _robust_over_clients(
            {"a": jnp.asarray(x)}, jnp.ones((n,)), None, "trimmed_mean", trim
        )["a"]
    )
    lo, hi = x.min(axis=0), x.max(axis=0)
    assert (out >= lo - 1e-4).all() and (out <= hi + 1e-4).all()


@_hypothesis_only
@_slow
@given(
    n=st.integers(3, 10),
    cols=st.integers(2, 64),
    seed=st.integers(0, 10),
    zmax=st.floats(0.5, 8.0),
    cos_min=st.floats(-1.0, 0.9),
)
def test_screening_stats_are_permutation_equivariant(
    n, cols, seed, zmax, cos_min
):
    """Reordering the client rows reorders verdicts and stats identically
    (the reference statistics — median direction, median/MAD — are
    order-free reductions), so screening can never depend on arrival
    order: the stream and barrier server pipelines, which see rows in
    different orders, must produce the same per-client verdicts."""
    from fedtpu.ops.flat import screen_rows

    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(n, cols)).astype(np.float32)
    alive = (rng.uniform(size=n) > 0.2).astype(np.float32)
    perm = rng.permutation(n)
    keep, stats = screen_rows(
        jnp.asarray(rows), jnp.asarray(alive), 0.0, zmax, cos_min
    )
    keep_p, stats_p = screen_rows(
        jnp.asarray(rows[perm]), jnp.asarray(alive[perm]), 0.0, zmax,
        cos_min,
    )
    np.testing.assert_array_equal(np.asarray(keep)[perm], np.asarray(keep_p))
    for key in ("norm", "cos", "z"):
        np.testing.assert_allclose(
            np.asarray(stats[key])[perm], np.asarray(stats_p[key]),
            rtol=1e-5, atol=1e-5,
        )


@_hypothesis_only
@_slow
@given(
    n=st.integers(3, 10),
    cols=st.integers(2, 64),
    seed=st.integers(0, 10),
    scale=st.floats(1e-3, 1e3),
)
def test_screening_relative_stats_are_scale_invariant(n, cols, seed, scale):
    """Scaling EVERY row by a common positive factor scales the norms
    linearly (equivariance) but leaves cosine and the median/MAD z-score
    unchanged — the relative checks need no per-model calibration, which
    is what lets one zmax/cos_min config cover mlp and densenet alike."""
    from fedtpu.ops.flat import screen_rows

    rng = np.random.default_rng(seed)
    rows = rng.normal(size=(n, cols)).astype(np.float32)
    alive = np.ones((n,), np.float32)
    keep_a, stats_a = screen_rows(
        jnp.asarray(rows), jnp.asarray(alive), 0.0, 3.0, 0.0
    )
    keep_b, stats_b = screen_rows(
        jnp.asarray(rows * scale), jnp.asarray(alive), 0.0, 3.0, 0.0
    )
    np.testing.assert_array_equal(np.asarray(keep_a), np.asarray(keep_b))
    np.testing.assert_allclose(
        np.asarray(stats_b["norm"]), np.asarray(stats_a["norm"]) * scale,
        rtol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(stats_b["cos"]), np.asarray(stats_a["cos"]),
        rtol=2e-3, atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(stats_b["z"]), np.asarray(stats_a["z"]),
        rtol=2e-3, atol=2e-3,
    )


@_hypothesis_only
@_slow
@given(
    n=st.integers(2, 12),
    k=st.integers(1, 4),
    power=st.floats(0.1, 3.0),
    seed=st.integers(0, 10),
)
def test_fedbuff_damped_update_never_exceeds_normalized(n, k, power, seed):
    """Staleness damping (round 5), exercised through the ENGINE's own
    combiner (fedtpu.core.async_engine.fedbuff_combine): the damped update
    equals the normalized mean scaled by damp = sum(disc*w)/sum(w) with
    0 < damp <= 1; with power > 0, damp == 1 exactly when every arrival
    has staleness 0 — for ANY staleness pattern, weights, and buffer
    size."""
    from fedtpu.core.async_engine import fedbuff_combine

    k = min(k, n)
    rng = np.random.default_rng(seed)
    arrive = np.zeros(n, bool)
    arrive[rng.choice(n, size=k, replace=False)] = True
    staleness = jnp.asarray(
        rng.integers(0, 6, size=n).astype(np.float32))
    weights = rng.uniform(0.5, 4.0, size=n).astype(np.float32)
    raw_w = jnp.asarray(weights * arrive)
    deltas = {"a": jnp.asarray(rng.normal(size=(n, 7)).astype(np.float32))}

    damped = np.asarray(fedbuff_combine(
        deltas, raw_w, staleness, power, staleness_damping=True)["a"])
    normalized = np.asarray(fedbuff_combine(
        deltas, raw_w, staleness, power, staleness_damping=False)["a"])

    # Oracle: the paper's closed form sum(disc*w*d)/sum(w), in numpy.
    disc_w = np.asarray(raw_w) / (1.0 + np.asarray(staleness)) ** power
    oracle = (disc_w[:, None] * np.asarray(deltas["a"])).sum(0) / (
        np.asarray(raw_w).sum())
    np.testing.assert_allclose(damped, oracle, rtol=2e-5, atol=1e-6)

    damp = disc_w.sum() / np.asarray(raw_w).sum()
    assert 0.0 < damp <= 1.0 + 1e-6
    assert np.linalg.norm(damped) <= np.linalg.norm(normalized) + 1e-5
    stale_arrivals = np.asarray(staleness)[arrive]
    if np.all(stale_arrivals == 0):
        np.testing.assert_allclose(damped, normalized, rtol=1e-6)
    else:
        assert damp < 1.0  # power > 0 and a stale arrival MUST damp


# --------------------------------------------------------------------------
# Sketch-codec invariants (rotq / randk wire records). These are the three
# properties the adaptive codec controller leans on: unbiasedness (so codec
# switches don't inject drift), bit-identical seeded replay (so a retried
# or replayed round re-encodes the same bytes), and EF algebra (so the
# residual really is the dropped mass).


@_slow
@given(n=st.integers(16, 400), seed=st.integers(0, 1000))
def test_rotq_wire_is_unbiased_over_seeds(n, seed):
    """E_seed[decode(encode(x))] == x: the rotation pair is exactly inverse
    and stochastic rounding is conditionally unbiased, so averaging the
    reconstruction over many sketch seeds must beat any single seed's
    quantization error by ~1/sqrt(S) — a bias would plateau instead."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    like = {"a": np.zeros_like(x)}
    S = 32
    recons, errs = [], []
    for s in range(S):
        payload, _ = sparse.encode_rotq_flat(
            {"a": x}, bits=2, collect_residual=False, seed=seed * S + s
        )
        got = np.asarray(sparse.decode(payload, like)[0]["a"], np.float64)
        recons.append(got)
        errs.append(float(np.linalg.norm(got - x)))
    mean_err = float(np.linalg.norm(np.mean(recons, axis=0) - x))
    avg_err = float(np.mean(errs))
    if avg_err > 1e-6:  # degenerate constant rows quantize exactly
        # Unbiased averaging over 32 seeds predicts ~avg/sqrt(32) ~ 0.18x;
        # 0.6x leaves headroom for seed-to-seed variance without letting a
        # real bias (which would keep mean_err ~ avg_err) through.
        assert mean_err < 0.6 * avg_err, (mean_err, avg_err)


@_slow
@given(n=st.integers(16, 400), frac=st.floats(0.05, 0.5),
       seed=st.integers(0, 1000))
def test_randk_wire_is_unbiased_over_seeds(n, frac, seed):
    """Without error feedback the kept coordinates are rescaled by total/k,
    so E_seed[decode(encode(x))] == x over the uniform coordinate draw."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    like = {"a": np.zeros_like(x)}
    S = 64
    recons, errs = [], []
    for s in range(S):
        payload, _ = sparse.encode_randk_flat(
            {"a": x}, frac, collect_residual=False, seed=seed * S + s
        )
        got = np.asarray(sparse.decode(payload, like)[0]["a"], np.float64)
        recons.append(got)
        errs.append(float(np.linalg.norm(got - x)))
    mean_err = float(np.linalg.norm(np.mean(recons, axis=0) - x))
    avg_err = float(np.mean(errs))
    if avg_err > 1e-6:  # keep-all budgets reconstruct exactly
        assert mean_err < 0.6 * avg_err, (mean_err, avg_err)


@_slow
@given(n=st.integers(16, 300), seed=st.integers(0, 10_000),
       bits=st.sampled_from([1, 2, 4, 8]), frac=st.floats(0.05, 0.5))
def test_sketch_wire_replay_is_bit_identical(n, seed, bits, frac):
    """Same (input, seed) -> byte-identical payload; a different seed
    rotates/samples differently. This is what lets a replayed round
    (recovery, retry) re-encode the exact bytes the first attempt shipped."""
    rng = np.random.default_rng(seed)
    x = {"a": rng.normal(size=n).astype(np.float32)}
    p1, _ = sparse.encode_rotq_flat(x, bits=bits, collect_residual=False,
                                    seed=seed)
    p2, _ = sparse.encode_rotq_flat(x, bits=bits, collect_residual=False,
                                    seed=seed)
    assert p1 == p2
    p3, _ = sparse.encode_rotq_flat(x, bits=bits, collect_residual=False,
                                    seed=seed + 1)
    assert p1 != p3  # fresh sign vector over >=16 coords
    q1, _ = sparse.encode_randk_flat(x, frac, collect_residual=False,
                                     seed=seed)
    q2, _ = sparse.encode_randk_flat(x, frac, collect_residual=False,
                                     seed=seed)
    assert q1 == q2


@_slow
@given(n=st.integers(8, 300), frac=st.floats(0.05, 0.6),
       seed=st.integers(0, 1000))
def test_randk_wire_ef_residual_is_exactly_the_dropped_mass(n, frac, seed):
    """With error feedback the kept values ship UNSCALED and the residual
    is the complement: decode(payload) + residual == input bit-exactly
    (disjoint coordinate sets — no float cancellation)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    payload, res = sparse.encode_randk_flat(
        {"a": x}, frac, collect_residual=True, seed=seed
    )
    got = np.asarray(sparse.decode(payload, {"a": np.zeros_like(x)})[0]["a"])
    np.testing.assert_array_equal(got + np.asarray(res["a"]), x)
    # Contraction: the residual is a strict subset of the input's mass.
    assert np.linalg.norm(res["a"]) <= np.linalg.norm(x) + 1e-7


@_slow
@given(n=st.integers(8, 300), seed=st.integers(0, 1000))
def test_rotq_wire_ef_residual_closes_the_algebra(n, seed):
    """decode(payload) + residual == input up to f32 addition rounding —
    the encoder derives the residual from the SAME dequantized values the
    decoder reconstructs (shared _rotq_dequant), so EF never drifts from
    what the server actually applied. At 8 bits the quantization noise
    (and with it the residual) is small next to the input."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    payload, res = sparse.encode_rotq_flat(
        {"a": x}, bits=8, collect_residual=True, seed=seed
    )
    got = np.asarray(sparse.decode(payload, {"a": np.zeros_like(x)})[0]["a"])
    np.testing.assert_allclose(got + np.asarray(res["a"]), x,
                               rtol=1e-5, atol=1e-5)
    nx = float(np.linalg.norm(x))
    if nx > 1e-6:
        assert float(np.linalg.norm(res["a"])) < 0.1 * nx
