"""Loss-proportional client importance sampling
(FedConfig.participation_sampling='loss').

Observations live in ``FederatedState.last_client_loss`` — updated per round
ON DEVICE (so fused scans accumulate every round, not just the block's
last), NaN until first observed, checkpointed with the state. Never-observed
clients sample at the optimistic fill (max observed loss), so a small
first-round subset cannot permanently starve the rest.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation


def _cfg(**fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.01, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=160,
        ),
        fed=FedConfig(num_clients=5, **fed_kw),
        steps_per_round=2,
    )


def test_unknown_sampling_mode_raises():
    with pytest.raises(ValueError, match="participation_sampling"):
        Federation(_cfg(participation_sampling="softmax"), seed=0)


def test_state_starts_nan_and_observes_sampled_clients_only():
    fed = Federation(
        _cfg(participation_fraction=0.4, participation_sampling="loss"),
        seed=0,
    )
    assert np.isnan(np.asarray(fed.state.last_client_loss)).all()
    m = fed.step()
    obs = np.asarray(fed.state.last_client_loss)
    sampled = np.asarray(m.per_client_loss) > 0
    assert (~np.isnan(obs[sampled])).all()
    assert np.isnan(obs[~sampled]).all()


def test_high_loss_client_is_sampled_more_often():
    """Force one client's observed loss far above the rest and count picks
    over many mask draws: it must be selected much more often than an
    average client under loss sampling, and ~uniformly under uniform."""
    fed = Federation(
        _cfg(participation_fraction=0.4, participation_sampling="loss"),
        seed=0,
    )
    fed.state = fed.state._replace(
        last_client_loss=jnp.asarray([0.1, 0.1, 0.1, 0.1, 10.0], jnp.float32)
    )
    picks = np.zeros(5)
    for r in range(300):
        picks += fed._alive_for_round(1000 + r)
    assert picks[4] > 250, picks              # hot client nearly always in
    assert picks[:4].max() < picks[4], picks

    uni = Federation(_cfg(participation_fraction=0.4), seed=0)
    upicks = np.zeros(5)
    for r in range(300):
        upicks += uni._alive_for_round(1000 + r)
    assert upicks.std() < 30, upicks          # roughly even


def test_never_observed_clients_are_explored_not_starved():
    """Clients with NaN observations sample at the optimistic fill (max
    observed), so a tiny first-round subset cannot freeze out the rest."""
    fed = Federation(
        _cfg(participation_fraction=0.4, participation_sampling="loss"),
        seed=0,
    )
    # Two clients observed at a LOW loss, three never observed.
    fed.state = fed.state._replace(
        last_client_loss=jnp.asarray(
            [0.05, 0.05, np.nan, np.nan, np.nan], jnp.float32
        )
    )
    picks = np.zeros(5)
    for r in range(300):
        picks += fed._alive_for_round(2000 + r)
    # The unobserved majority must be picked at least as often as the
    # observed low-loss clients.
    assert picks[2:].min() >= picks[:2].max() * 0.8, picks


def test_dead_client_keeps_last_observation():
    fed = Federation(
        _cfg(participation_fraction=0.6, participation_sampling="loss"),
        seed=0,
    )
    fed.step()
    before = np.asarray(fed.state.last_client_loss).copy()
    fed.set_alive(2, False)
    fed.step()
    after = np.asarray(fed.state.last_client_loss)
    np.testing.assert_allclose(after[2], before[2])


def test_fused_block_accumulates_every_rounds_observations():
    """The state updates per scan iteration, so a client sampled in ANY
    round of the block keeps its freshest observation — not only the
    block's final round."""
    fed = Federation(
        _cfg(participation_fraction=0.5, participation_sampling="loss"),
        seed=0,
    )
    m = fed.run_on_device(4)
    pcl = np.asarray(m.per_client_loss)  # [4, 5]
    obs = np.asarray(fed.state.last_client_loss)
    ever = (pcl > 0).any(axis=0)
    assert (~np.isnan(obs[ever])).all()
    # Each observed value equals that client's LAST positive round.
    for c in np.flatnonzero(ever):
        last = pcl[:, c][pcl[:, c] > 0][-1]
        np.testing.assert_allclose(obs[c], last, rtol=1e-6)


def test_observations_survive_checkpoint_roundtrip(tmp_path):
    from fedtpu.checkpoint import Checkpointer

    cfg = _cfg(participation_fraction=0.5, participation_sampling="loss")
    fed = Federation(cfg, seed=0)
    fed.step()
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(1, fed.state)
    fresh = Federation(cfg, seed=1)
    _, restored = ckpt.restore_latest(like=fresh.state)
    a = np.asarray(fed.state.last_client_loss)
    b = np.asarray(restored.last_client_loss)
    np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
    np.testing.assert_allclose(a[~np.isnan(a)], b[~np.isnan(b)])


def test_mid_generation_checkpoint_restores(tmp_path):
    """A blob with server_opt_state but WITHOUT last_client_loss (written
    between the two schema additions) must restore via the progressive
    legacy fallback, refilling only the missing field."""
    from fedtpu.checkpoint import Checkpointer, checkpoint
    from fedtpu.transport import wire

    fed = Federation(_cfg(), seed=0)
    fed.step()
    legacy = {
        k: v for k, v in fed.state._asdict().items()
        if k != "last_client_loss"
    }
    with open(checkpoint._wire_path(str(tmp_path), 2), "wb") as fh:
        fh.write(wire.encode(legacy, compress=True))
    fresh = Federation(_cfg(), seed=1)
    rnd, restored = Checkpointer(str(tmp_path), backend="wire").restore_latest(
        like=fresh.state
    )
    assert rnd == 2
    for a, b in zip(
        np.asarray(fed.state.params["Dense_0"]["kernel"]).ravel()[:5],
        np.asarray(restored.params["Dense_0"]["kernel"]).ravel()[:5],
    ):
        np.testing.assert_allclose(a, b)
    assert np.isnan(np.asarray(restored.last_client_loss)).all()
