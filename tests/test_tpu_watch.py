"""Unit tests for the TPU-window watcher's capture bookkeeping.

`tools/tpu_watch.py` guards a scarce resource: live tunnel windows open
rarely and every mis-fire (re-running a captured job, clobbering a sibling
watcher's done-list, continuing after the tunnel re-wedges) burns minutes
of the only hardware access the round gets. These tests pin the state
machine with stubbed jobs — no TPU, no subprocesses.
"""

import importlib
import os

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def watch(tmp_path, monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(_REPO_ROOT, "tools"))
    import tpu_watch as mod

    mod = importlib.reload(mod)
    # Redirect every filesystem touchpoint into the sandbox.
    monkeypatch.setattr(mod, "REPO", str(tmp_path))
    monkeypatch.setattr(mod, "ART", str(tmp_path / "artifacts"))
    monkeypatch.setattr(mod, "STATE_PATH", str(tmp_path / "state.json"))
    monkeypatch.setattr(mod, "LOCK_PATH", str(tmp_path / "lock"))
    (tmp_path / "artifacts").mkdir()
    (tmp_path / "tools").mkdir()
    return mod


def _lock(watch):
    return open(watch.LOCK_PATH, "w")


def test_run_pending_skips_done_and_records_success(watch, monkeypatch):
    calls = []

    def ok_job(name):
        def run():
            calls.append(name)
            return True, "fine"
        return run

    monkeypatch.setattr(watch, "JOBS", [("a", ok_job("a")), ("b", ok_job("b"))])
    state = {"done": ["a"], "history": []}
    watch.save_state(state)
    assert watch.run_pending(state, _lock(watch)) is True
    assert calls == ["b"]  # 'a' was already captured — never re-fired
    assert set(state["done"]) == {"a", "b"}
    # Persisted for a restarted watcher.
    assert set(watch.load_state()["done"]) == {"a", "b"}


def test_run_pending_stops_on_first_failure(watch, monkeypatch):
    calls = []
    monkeypatch.setattr(watch, "JOBS", [
        ("a", lambda: (calls.append("a"), (False, "tunnel dropped"))[1]),
        ("b", lambda: (calls.append("b"), (True, "fine"))[1]),
    ])
    state = {"done": [], "history": []}
    assert watch.run_pending(state, _lock(watch)) is False
    # A failed job means the tunnel likely re-wedged: later jobs must NOT
    # burn what's left of the window.
    assert calls == ["a"]
    assert state["done"] == []
    assert state["history"][-1]["ok"] is False


def test_run_pending_survives_job_exception(watch, monkeypatch):
    def boom():
        raise RuntimeError("child machinery exploded")

    monkeypatch.setattr(watch, "JOBS", [("a", boom)])
    state = {"done": [], "history": []}
    assert watch.run_pending(state, _lock(watch)) is False
    assert "exception" in state["history"][-1]["detail"]


def test_run_pending_merges_sibling_watchers_done_list(watch, monkeypatch):
    # Another watcher captured 'a' while we blocked on the lock: the
    # post-lock reload must absorb its done-list so we only run 'b', and
    # saving must not clobber 'a'.
    watch.save_state({"done": ["a"], "history": [{"job": "a", "ok": True}]})
    calls = []
    monkeypatch.setattr(watch, "JOBS", [
        ("a", lambda: (calls.append("a"), (True, ""))[1]),
        ("b", lambda: (calls.append("b"), (True, ""))[1]),
    ])
    state = {"done": [], "history": []}  # stale pre-lock snapshot
    assert watch.run_pending(state, _lock(watch)) is True
    assert calls == ["b"]
    persisted = watch.load_state()
    assert set(persisted["done"]) == {"a", "b"}
    assert {"job": "a", "ok": True} in persisted["history"]


def test_state_roundtrip_tolerates_missing_and_corrupt(watch, tmp_path):
    # Missing file -> clean slate.
    assert watch.load_state() == {"done": [], "history": []}
    # Corrupt file (watcher killed mid-write happens; writes are atomic via
    # os.replace, but a foreign writer might not be) -> clean slate, no raise.
    (tmp_path / "state.json").write_text("{truncated")
    assert watch.load_state() == {"done": [], "history": []}
    watch.save_state({"done": ["x"], "history": []})
    assert watch.load_state()["done"] == ["x"]


def test_run_pending_skips_any_job_whose_script_is_missing(watch, monkeypatch):
    # Script-job existence guard (a script landed mid-round once): a job
    # whose script_path doesn't exist yet is skipped this window — NOT
    # failed (which would stop-on-first-failure the rest of the queue), NOT
    # marked done — and later jobs still run. The guard is derived from the
    # job's own script path, not its name (round-4 advisor finding: the
    # name-matched guard covered exactly one job).
    calls = []

    def missing():
        calls.append("missing")
        return True, ""

    missing.script_path = str(watch.REPO) + "/tools/not_yet_written.py"

    def present():
        calls.append("present")
        return True, ""

    present.script_path = os.path.join(watch.REPO, "tools", "present.py")
    open(present.script_path, "w").write("# exists")
    monkeypatch.setattr(watch, "JOBS", [
        ("missing", missing),
        ("present", present),
        ("plain", lambda: (calls.append("plain"), (True, ""))[1]),
    ])
    state = {"done": [], "history": []}
    assert watch.run_pending(state, _lock(watch)) is True
    assert calls == ["present", "plain"]
    assert state["done"] == ["present", "plain"]


def test_script_and_bench_jobs_expose_guards_and_env(watch):
    # Every _script_job carries its script path for the skip guard; the
    # real queue's script jobs must all point at existing tools. Bench jobs
    # run bench.py (always present) so they carry no guard.
    # JOBS paths were resolved against the REAL repo at module (re)load,
    # before the fixture redirected watch.REPO into the sandbox.
    for name, job in watch.JOBS:
        path = getattr(job, "script_path", None)
        if path is not None:
            assert os.path.exists(path), (
                f"queued job {name} points at a missing script: {path}"
            )


def test_bench_job_mfu_gate(watch, monkeypatch):
    """min_mfu makes measured MFU part of the pass condition: a capture
    below the floor is BANKED (evidence either way) but the leg fails and
    stays pending for a retried window; at-or-above passes and stamps the
    gate verdict on the artifact."""
    import json

    mfu = [0.05]

    class _Proc:
        returncode = 0
        stderr = ""

        @property
        def stdout(self):
            return json.dumps(
                {"value": 42.0, "unit": "client-epochs/sec/chip",
                 "mfu": mfu[0]})

    monkeypatch.setattr(
        watch.subprocess, "run", lambda *a, **kw: _Proc())
    job = watch._bench_job(
        "GATED.json", min_mfu=0.10,
        env={"FEDTPU_COMPUTE_DTYPE": "bfloat16_mixed"})

    ok, detail = job()
    assert ok is False and "mfu gate FAILED" in detail
    with open(os.path.join(watch.ART, "GATED.json")) as fh:
        banked = json.load(fh)
    assert banked["mfu_gate"] == {"min_mfu": 0.10, "passed": False}
    assert banked["captured_env"]["FEDTPU_COMPUTE_DTYPE"] == "bfloat16_mixed"

    mfu[0] = 0.12
    ok, detail = job()
    assert ok is True
    with open(os.path.join(watch.ART, "GATED.json")) as fh:
        assert json.load(fh)["mfu_gate"] == {"min_mfu": 0.10, "passed": True}


def test_queue_carries_bf16_megabatch_leg_with_mfu_gate(watch):
    """The mixed-precision PR's on-chip verdict is queued: bf16+megabatch
    env knobs with the ISSUE's >=10% MFU pass condition."""
    jobs = dict(watch.JOBS)
    leg = jobs["bench_bf16mega_r07"]
    assert leg.min_mfu == 0.10
    assert leg.env == {"FEDTPU_COMPUTE_DTYPE": "bfloat16_mixed",
                       "FEDTPU_MEGABATCH_CLIENTS": "8"}
    assert leg.budget_s <= 360
    # Gated experiment legs never displace the guaranteed headline capture.
    assert [n for n, _ in watch.JOBS][0] == "bench_fused_r06"


def test_queue_is_driver_bench_first_with_hard_budgets(watch):
    """Round-6 queue shape (VERDICT r5 "Next round" #1): the driver-path
    headline bench is job #1 with a ~5-minute hard budget, and EVERY job
    carries a finite per-job wall-clock budget so one hung job can never
    eat a whole window. Any window >= 5 min therefore yields at least the
    BENCH_LIVE_r06 headline capture."""
    names = [name for name, _ in watch.JOBS]
    assert names[0] == "bench_fused_r06"
    for name, job in watch.JOBS:
        budget = getattr(job, "budget_s", None)
        assert budget is not None and budget > 0, (
            f"job {name} has no hard wall-clock budget"
        )
    # The headline job's budget is the ~5-minute window bound.
    assert watch.JOBS[0][1].budget_s <= 360
    # The expensive acc-full parity run fires only after the quick wins.
    assert names[-1] == "acc_full_fedtpu"
