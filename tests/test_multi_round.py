"""Fused multi-round scan (fedtpu.data.device.make_multi_round_step).

``Federation.run_on_device(R)`` runs R rounds as one XLA program; these tests
pin it numerically identical to R sequential ``step()`` calls — including
per-round shuffling, dead clients, participation sampling, and the mesh path.
"""

import numpy as np
import jax
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation


def _cfg(**kw):
    base = dict(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3),
        steps_per_round=2,
    )
    base.update(kw)
    return RoundConfig(**base)


def _assert_states_equal(a, b, atol=1e-6):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


def test_fused_rounds_match_sequential_steps():
    cfg = _cfg()
    seq = Federation(cfg, seed=0)
    fused = Federation(cfg, seed=0)

    per_round = [seq.step() for _ in range(3)]
    stacked = fused.run_on_device(3)

    assert stacked.loss.shape == (3,)
    for r, m in enumerate(per_round):
        np.testing.assert_allclose(
            float(m.loss), float(stacked.loss[r]), atol=1e-6
        )
    _assert_states_equal(seq.state.params, fused.state.params)
    _assert_states_equal(seq.state.opt_state, fused.state.opt_state)
    assert int(fused.state.round_idx) == 3


def test_fused_rounds_match_with_shuffled_partition():
    """dirichlet partition shuffles per round via the round_idx-folded key —
    the scan must reproduce the exact same per-round batches."""
    cfg = _cfg(
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="dirichlet",
            num_examples=96,
        ),
    )
    seq = Federation(cfg, seed=0)
    fused = Federation(cfg, seed=0)
    for _ in range(2):
        seq.step()
    fused.run_on_device(2)
    _assert_states_equal(seq.state.params, fused.state.params)


def test_fused_rounds_respect_dead_and_sampled_clients():
    cfg = _cfg(
        fed=FedConfig(num_clients=4, participation_fraction=0.5),
    )
    seq = Federation(cfg, seed=0)
    fused = Federation(cfg, seed=0)
    seq.set_alive(2, False)
    fused.set_alive(2, False)

    per_round = [seq.step() for _ in range(3)]
    stacked = fused.run_on_device(3)

    for r, m in enumerate(per_round):
        assert int(m.num_active) == int(stacked.num_active[r])
        # 0.5 of 3 live clients → 2 sampled each round.
        assert int(stacked.num_active[r]) == 2
    _assert_states_equal(seq.state.params, fused.state.params)


def test_fused_rounds_continue_from_prior_steps():
    """Mixing step() and run_on_device() keeps one consistent round counter."""
    cfg = _cfg()
    seq = Federation(cfg, seed=0)
    mixed = Federation(cfg, seed=0)
    for _ in range(4):
        seq.step()
    mixed.step()
    mixed.run_on_device(2)
    mixed.step()
    assert int(mixed.state.round_idx) == 4
    _assert_states_equal(seq.state.params, mixed.state.params)


def test_fused_rounds_mesh_matches_single_program(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = _cfg(
        fed=FedConfig(num_clients=8),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))

    m1 = single.run_on_device(2)
    m2 = meshed.run_on_device(2)
    np.testing.assert_allclose(
        np.asarray(m1.loss), np.asarray(m2.loss), atol=1e-5
    )
    _assert_states_equal(single.state.params, meshed.state.params, atol=1e-5)
