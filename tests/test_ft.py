"""Fault tolerance: heartbeat recovery + primary/backup failover.

The reference's only failure test was manually killing processes (SURVEY §4);
these drive the same protocol in-process with fake probes and a fake clock.
"""

import numpy as np
import pytest

from fedtpu.ft import (
    ClientRegistry,
    FailoverStateMachine,
    HeartbeatMonitor,
    Role,
)


# ------------------------------------------------------------- registry
def test_registry_masks_and_ranks():
    reg = ClientRegistry(["a", "b", "c"])
    assert reg.active_clients() == ["a", "b", "c"]
    reg.mark_failed("b")
    # Ranks go to active clients in registry order; world stays 3
    # (reference: src/server.py:126-129).
    assert reg.active_clients() == ["a", "c"]
    np.testing.assert_array_equal(reg.alive_mask(), [True, False, True])
    reg.mark_alive("b")
    assert reg.active_clients() == ["a", "b", "c"]


# ------------------------------------------------------------ heartbeat
def test_heartbeat_recovery_resyncs_before_revive():
    reg = ClientRegistry(["a", "b"])
    reg.mark_failed("b")
    events = []
    up = {"b": False}

    monitor = HeartbeatMonitor(
        reg,
        probe=lambda c: up[c],
        resync=lambda c: events.append(("resync", c, reg.is_alive(c))),
    )
    assert monitor.tick() == []          # still down
    assert not reg.is_alive("b")
    up["b"] = True
    assert monitor.tick() == ["b"]       # probe succeeds -> resync + revive
    # Resync ran while the client was still marked dead (so no StartTrain
    # can race ahead of the model push — reference order src/server.py:95-99).
    assert events == [("resync", "b", False)]
    assert reg.is_alive("b")
    assert monitor.tick() == []          # idempotent


def test_heartbeat_resync_failure_keeps_dead():
    reg = ClientRegistry(["a"])
    reg.mark_failed("a")

    def bad_resync(c):
        raise RuntimeError("connection dropped mid-push")

    monitor = HeartbeatMonitor(reg, probe=lambda c: True, resync=bad_resync)
    assert monitor.tick() == []
    assert not reg.is_alive("a")


# -------------------------------------------------------------- failover
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_watchdog_promotes_after_timeout():
    clock = FakeClock()
    events = []
    m = FailoverStateMachine(
        timeout=10.0,
        on_promote=lambda: events.append("promote"),
        on_demote=lambda: events.append("demote"),
        clock=clock,
    )
    assert m.role is Role.BACKUP
    m.on_ping(recovering=False)         # first ping arms the watchdog
    clock.advance(9.0)
    assert not m.check_watchdog()       # inside window
    m.on_ping(recovering=False)         # ping resets the window
    clock.advance(9.0)
    assert not m.check_watchdog()
    clock.advance(2.0)
    assert m.check_watchdog()           # 11 s of silence -> promote
    assert m.role is Role.ACTING_PRIMARY
    assert events == ["promote"]
    # No double promotion.
    clock.advance(100.0)
    assert not m.check_watchdog()


def test_watchdog_unarmed_until_first_ping():
    """No primary has ever pinged: never promote (the reference promotes a
    model-less backup ~10 s after boot, src/server.py:254-264 — a bug we
    deliberately fix; arm_without_ping=True restores it)."""
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    clock.advance(1000.0)
    assert not m.check_watchdog()
    assert m.role is Role.BACKUP
    assert m.seconds_since_ping() == float("inf")

    legacy = FailoverStateMachine(timeout=10.0, clock=clock,
                                  arm_without_ping=True)
    clock.advance(11.0)
    assert legacy.check_watchdog()      # reference-parity behavior


def test_recovering_primary_demotes_acting_primary():
    clock = FakeClock()
    events = []
    m = FailoverStateMachine(
        timeout=10.0,
        on_promote=lambda: events.append("promote"),
        on_demote=lambda: events.append("demote"),
        clock=clock,
    )
    m.on_ping(recovering=False)         # arm
    clock.advance(11.0)
    m.check_watchdog()
    assert m.role is Role.ACTING_PRIMARY
    # Ordinary pings (no recovering flag) do NOT demote.
    assert m.on_ping(recovering=False) == 0
    assert m.role is Role.ACTING_PRIMARY
    # The returning primary's recovering ping does; reply value 1 tells the
    # primary the backup was acting (reference: src/server.py:244-252).
    assert m.on_ping(recovering=True) == 1
    assert m.role is Role.BACKUP
    assert events == ["promote", "demote"]


def test_recovering_ping_in_backup_role_is_noop():
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    assert m.on_ping(recovering=True) == 0
    assert m.role is Role.BACKUP


def test_full_failover_cycle():
    """backup -> acting primary -> demoted -> promoted again."""
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    m.on_ping(recovering=False)
    clock.advance(11.0)
    assert m.check_watchdog()
    assert m.on_ping(recovering=True) == 1
    assert m.role is Role.BACKUP
    # Primary dies again.
    clock.advance(11.0)
    assert m.check_watchdog()
    assert m.role is Role.ACTING_PRIMARY

def test_chaos_kill_revive_schedule_still_converges():
    """Randomized fault schedule over 20 rounds: every round each client
    flips dead/alive with some probability (at least one always lives).
    Training must stay finite, count participants correctly, and still
    reach a better loss than round 0 — the simulated form of the
    reference's manual kill/restart drills (SURVEY SS4)."""
    import numpy as np
    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, partition="iid",
            num_examples=512,
        ),
        fed=FedConfig(num_clients=6),
        steps_per_round=2,
    )
    fed = Federation(cfg, seed=0)
    rng = np.random.default_rng(7)
    first = None
    for r in range(20):
        alive = rng.random(6) > 0.35
        if not alive.any():
            alive[rng.integers(6)] = True
        for c in range(6):
            fed.set_alive(c, bool(alive[c]))
        m = fed.step()
        assert int(m.num_active) == int(alive.sum())
        loss = float(m.loss)
        assert np.isfinite(loss)
        if first is None:
            first = loss
    assert int(fed.state.round_idx) == 20
    for leaf in jax.tree_util.tree_leaves(fed.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(m.loss) < first, (first, float(m.loss))
