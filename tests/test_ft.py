"""Fault tolerance: heartbeat recovery + primary/backup failover.

The reference's only failure test was manually killing processes (SURVEY §4);
these drive the same protocol in-process with fake probes and a fake clock.
"""

import numpy as np
import pytest

from fedtpu.ft import (
    ClientRegistry,
    FailoverStateMachine,
    HeartbeatMonitor,
    Role,
)


# ------------------------------------------------------------- registry
def test_registry_masks_and_ranks():
    reg = ClientRegistry(["a", "b", "c"])
    assert reg.active_clients() == ["a", "b", "c"]
    reg.mark_failed("b")
    # Ranks go to active clients in registry order; world stays 3
    # (reference: src/server.py:126-129).
    assert reg.active_clients() == ["a", "c"]
    np.testing.assert_array_equal(reg.alive_mask(), [True, False, True])
    reg.mark_alive("b")
    assert reg.active_clients() == ["a", "b", "c"]


# ------------------------------------------------------------ heartbeat
def test_heartbeat_recovery_resyncs_before_revive():
    reg = ClientRegistry(["a", "b"])
    reg.mark_failed("b")
    events = []
    up = {"b": False}

    monitor = HeartbeatMonitor(
        reg,
        probe=lambda c: up[c],
        resync=lambda c: events.append(("resync", c, reg.is_alive(c))),
    )
    assert monitor.tick() == []          # still down
    assert not reg.is_alive("b")
    up["b"] = True
    assert monitor.tick() == ["b"]       # probe succeeds -> resync + revive
    # Resync ran while the client was still marked dead (so no StartTrain
    # can race ahead of the model push — reference order src/server.py:95-99).
    assert events == [("resync", "b", False)]
    assert reg.is_alive("b")
    assert monitor.tick() == []          # idempotent


def test_heartbeat_resync_failure_keeps_dead():
    reg = ClientRegistry(["a"])
    reg.mark_failed("a")

    def bad_resync(c):
        raise RuntimeError("connection dropped mid-push")

    monitor = HeartbeatMonitor(reg, probe=lambda c: True, resync=bad_resync)
    assert monitor.tick() == []
    assert not reg.is_alive("a")


# -------------------------------------------------------------- failover
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_watchdog_promotes_after_timeout():
    clock = FakeClock()
    events = []
    m = FailoverStateMachine(
        timeout=10.0,
        on_promote=lambda: events.append("promote"),
        on_demote=lambda: events.append("demote"),
        clock=clock,
    )
    assert m.role is Role.BACKUP
    m.on_ping(recovering=False)         # first ping arms the watchdog
    clock.advance(9.0)
    assert not m.check_watchdog()       # inside window
    m.on_ping(recovering=False)         # ping resets the window
    clock.advance(9.0)
    assert not m.check_watchdog()
    clock.advance(2.0)
    assert m.check_watchdog()           # 11 s of silence -> promote
    assert m.role is Role.ACTING_PRIMARY
    assert events == ["promote"]
    # No double promotion.
    clock.advance(100.0)
    assert not m.check_watchdog()


def test_watchdog_unarmed_until_first_ping():
    """No primary has ever pinged: never promote (the reference promotes a
    model-less backup ~10 s after boot, src/server.py:254-264 — a bug we
    deliberately fix; arm_without_ping=True restores it)."""
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    clock.advance(1000.0)
    assert not m.check_watchdog()
    assert m.role is Role.BACKUP
    assert m.seconds_since_ping() == float("inf")

    legacy = FailoverStateMachine(timeout=10.0, clock=clock,
                                  arm_without_ping=True)
    clock.advance(11.0)
    assert legacy.check_watchdog()      # reference-parity behavior


def test_recovering_primary_demotes_acting_primary():
    clock = FakeClock()
    events = []
    m = FailoverStateMachine(
        timeout=10.0,
        on_promote=lambda: events.append("promote"),
        on_demote=lambda: events.append("demote"),
        clock=clock,
    )
    m.on_ping(recovering=False)         # arm
    clock.advance(11.0)
    m.check_watchdog()
    assert m.role is Role.ACTING_PRIMARY
    # Ordinary pings (no recovering flag) do NOT demote.
    assert m.on_ping(recovering=False) == 0
    assert m.role is Role.ACTING_PRIMARY
    # The returning primary's recovering ping does; reply value 1 tells the
    # primary the backup was acting (reference: src/server.py:244-252).
    assert m.on_ping(recovering=True) == 1
    assert m.role is Role.BACKUP
    assert events == ["promote", "demote"]


def test_recovering_ping_in_backup_role_is_noop():
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    assert m.on_ping(recovering=True) == 0
    assert m.role is Role.BACKUP


def test_full_failover_cycle():
    """backup -> acting primary -> demoted -> promoted again."""
    clock = FakeClock()
    m = FailoverStateMachine(timeout=10.0, clock=clock)
    m.on_ping(recovering=False)
    clock.advance(11.0)
    assert m.check_watchdog()
    assert m.on_ping(recovering=True) == 1
    assert m.role is Role.BACKUP
    # Primary dies again.
    clock.advance(11.0)
    assert m.check_watchdog()
    assert m.role is Role.ACTING_PRIMARY

class LockedClock(FakeClock):
    """FakeClock safe to read/advance from racing threads."""

    def __init__(self):
        super().__init__()
        import threading

        self._lk = threading.Lock()

    def __call__(self):
        with self._lk:
            return self.t

    def advance(self, dt):
        with self._lk:
            self.t += dt


def test_failover_threaded_watchdog_promotes_exactly_once():
    """Race: N watchdog threads all observe an expired window and call
    check_watchdog simultaneously. The machine's lock must collapse them
    into EXACTLY one promotion — one True return, one callback, one
    transition metric — never a double-promote (each would spin up its own
    acting-primary round loop)."""
    import threading

    from fedtpu.obs import MetricsRegistry

    for _trial in range(20):
        clock = LockedClock()
        events = []
        reg = MetricsRegistry()
        m = FailoverStateMachine(
            timeout=10.0, clock=clock, metrics=reg,
            on_promote=lambda: events.append("promote"),
        )
        m.on_ping(recovering=False)      # arm
        clock.advance(11.0)
        barrier = threading.Barrier(8)
        results, res_lock = [], threading.Lock()

        def worker():
            barrier.wait()
            fired = m.check_watchdog()
            with res_lock:
                results.append(fired)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(results) == 1, "watchdog race double-promoted"
        assert events == ["promote"]
        assert m.role is Role.ACTING_PRIMARY
        assert reg.counter(
            "fedtpu_ft_failover_transitions_total",
            labels={"to": "acting_primary"},
        ).value == 1


def test_failover_threaded_ping_vs_watchdog_keeps_invariants():
    """Race: a recovering primary's on_ping lands WHILE the watchdog
    thread keeps firing on expired windows. Both transitions run under the
    machine's lock, so whatever the interleaving: promotes and demotes
    strictly alternate (counts never diverge by more than one), the final
    role is exactly the transition parity, and every transition fired its
    metric exactly once — none doubled, none skipped."""
    import threading

    from fedtpu.obs import MetricsRegistry

    clock = LockedClock()
    reg = MetricsRegistry()
    counts = {"promote": 0, "demote": 0}
    cnt_lock = threading.Lock()

    def bump(key):
        with cnt_lock:
            counts[key] += 1

    m = FailoverStateMachine(
        timeout=10.0, clock=clock, metrics=reg,
        on_promote=lambda: bump("promote"),
        on_demote=lambda: bump("demote"),
    )
    m.on_ping(recovering=False)          # arm
    iters = 200
    start = threading.Barrier(2)

    def watchdog_side():
        start.wait()
        for _ in range(iters):
            clock.advance(11.0)          # every check sees an expired window
            m.check_watchdog()

    def ping_side():
        start.wait()
        for _ in range(iters):
            m.on_ping(recovering=True)   # demotes whenever acting

    threads = [threading.Thread(target=watchdog_side),
               threading.Thread(target=ping_side)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # Strict alternation promote/demote from BACKUP: the counts can never
    # diverge by more than one, and the residue must match the role.
    assert counts["demote"] <= counts["promote"] <= counts["demote"] + 1
    assert (m.role is Role.ACTING_PRIMARY) == (
        counts["promote"] == counts["demote"] + 1
    )
    assert counts["promote"] >= 1, "the race never promoted at all"
    # Every transition produced exactly one metric increment.
    assert reg.counter(
        "fedtpu_ft_failover_transitions_total",
        labels={"to": "acting_primary"},
    ).value == counts["promote"]
    assert reg.counter(
        "fedtpu_ft_failover_transitions_total",
        labels={"to": "backup"},
    ).value == counts["demote"]
    # Settle: one more recovering ping must leave it cleanly in BACKUP.
    m.on_ping(recovering=True)
    assert m.role is Role.BACKUP


def test_chaos_kill_revive_schedule_still_converges():
    """Randomized fault schedule over 20 rounds: every round each client
    flips dead/alive with some probability (at least one always lives).
    Training must stay finite, count participants correctly, and still
    reach a better loss than round 0 — the simulated form of the
    reference's manual kill/restart drills (SURVEY SS4)."""
    import numpy as np
    import jax

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core import Federation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, partition="iid",
            num_examples=512,
        ),
        fed=FedConfig(num_clients=6),
        steps_per_round=2,
    )
    fed = Federation(cfg, seed=0)
    rng = np.random.default_rng(7)
    first = None
    for r in range(20):
        alive = rng.random(6) > 0.35
        if not alive.any():
            alive[rng.integers(6)] = True
        for c in range(6):
            fed.set_alive(c, bool(alive[c]))
        m = fed.step()
        assert int(m.num_active) == int(alive.sum())
        loss = float(m.loss)
        assert np.isfinite(loss)
        if first is None:
            first = loss
    assert int(fed.state.round_idx) == 20
    for leaf in jax.tree_util.tree_leaves(fed.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    assert float(m.loss) < first, (first, float(m.loss))
