"""Differential privacy (DP-FedAvg: per-client clipping + server noise).

The reference has no DP of any kind; this is a fedtpu capability extension.
Pins: the clip bound actually holds per client, noise is seeded/deterministic
and scales as clip*mult/n, mesh parity, and the build-time guards.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation
from fedtpu.core.round import _dp_clip, _dp_noise


def _cfg(**fed_kw):
    fed_kw.setdefault("weighted", False)
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3, **fed_kw),
        steps_per_round=2,
    )


def _global_norms(stacked):
    leaves = jax.tree_util.tree_leaves(stacked)
    sq = sum(
        np.sum(np.square(np.asarray(x, np.float64)),
               axis=tuple(range(1, x.ndim)))
        for x in leaves
    )
    return np.sqrt(sq)


def test_clip_bounds_per_client_global_norm():
    rng = np.random.default_rng(0)
    tree = {
        "a": jnp.asarray(rng.normal(size=(4, 10)).astype(np.float32) * 5),
        "b": jnp.asarray(rng.normal(size=(4, 3, 3)).astype(np.float32) * 5),
    }
    clipped = _dp_clip(tree, 1.0)
    norms = _global_norms(clipped)
    assert (norms <= 1.0 + 1e-5).all(), norms
    # Clients already under the bound are untouched.
    small = jax.tree.map(lambda x: x * 1e-3, tree)
    same = _dp_clip(small, 1.0)
    for a, b in zip(jax.tree_util.tree_leaves(small),
                    jax.tree_util.tree_leaves(same)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_noise_is_seeded_and_scaled():
    tree = {"w": jnp.zeros((8, 8))}
    a = _dp_noise(tree, jnp.asarray(0.1), jnp.asarray(3), seed=7)
    b = _dp_noise(tree, jnp.asarray(0.1), jnp.asarray(3), seed=7)
    c = _dp_noise(tree, jnp.asarray(0.1), jnp.asarray(4), seed=7)
    np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))
    assert not np.array_equal(np.asarray(a["w"]), np.asarray(c["w"]))
    big = _dp_noise(tree, jnp.asarray(10.0), jnp.asarray(3), seed=7)
    assert np.abs(np.asarray(big["w"])).mean() > np.abs(np.asarray(a["w"])).mean()


def test_dp_round_runs_and_differs_from_plain():
    plain = Federation(_cfg(), seed=0)
    dp = Federation(
        _cfg(dp_clip_norm=0.05, dp_noise_multiplier=0.5), seed=0
    )
    plain.step()
    dp.step()
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(
            jax.tree_util.tree_leaves(plain.state.params),
            jax.tree_util.tree_leaves(dp.state.params),
        )
    ]
    assert max(diffs) > 1e-6
    for leaf in jax.tree_util.tree_leaves(dp.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_dp_is_deterministic_across_runs():
    a = Federation(_cfg(dp_clip_norm=0.1, dp_noise_multiplier=1.0), seed=0)
    b = Federation(_cfg(dp_clip_norm=0.1, dp_noise_multiplier=1.0), seed=0)
    a.step()
    b.step()
    for x, y in zip(
        jax.tree_util.tree_leaves(a.state.params),
        jax.tree_util.tree_leaves(b.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_dp_mesh_matches_single_program(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(
            num_clients=8, weighted=False, dp_clip_norm=0.1,
            dp_noise_multiplier=0.5,
        ),
        steps_per_round=2,
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    single.step()
    meshed.step()
    for a, b in zip(
        jax.tree_util.tree_leaves(single.state.params),
        jax.tree_util.tree_leaves(meshed.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_dp_guards():
    with pytest.raises(ValueError, match="compression"):
        Federation(
            _cfg(dp_clip_norm=0.1, compression="topk"), seed=0
        )
    with pytest.raises(ValueError, match="uniform weighting"):
        Federation(
            _cfg(dp_clip_norm=0.1, weighted=True), seed=0
        )
    with pytest.raises(ValueError, match="mean aggregator|aggregator='mean'"):
        Federation(
            _cfg(dp_clip_norm=0.1, aggregator="median"), seed=0
        )


def test_dp_rejects_batchnorm_models():
    """BN running stats are released unclipped — DP must refuse BN models
    rather than silently voiding the sensitivity bound."""
    import dataclasses

    cfg = dataclasses.replace(_cfg(dp_clip_norm=0.1), model="mobilenet")
    with pytest.raises(ValueError, match="BatchNorm-free"):
        Federation(cfg, seed=0)


def test_distributed_edge_applies_dp():
    """PrimaryServer clips per-client deltas and adds seeded noise — the
    same math as the engine, not a silent no-op."""
    from fedtpu.transport.federation import PrimaryServer

    cfg = _cfg(dp_clip_norm=0.01, dp_noise_multiplier=0.0)
    srv = PrimaryServer(cfg, clients=[], seed=0)
    # One well-behaved client and one with a huge delta.
    deltas = jax.tree.map(
        lambda p: jnp.stack([jnp.ones_like(p) * 1e-5, jnp.ones_like(p) * 100.0]),
        {"params": srv.params, "batch_stats": srv.batch_stats},
    )
    g = {"params": srv.params, "batch_stats": srv.batch_stats}
    out, _ = srv._aggregate(
        g, deltas, jnp.ones((2,)), srv._server_opt_state,
        jnp.asarray(0, jnp.int32),
    )
    # Unclipped mean would move params by ~50; the clipped mean moves each
    # client by at most clip/2 = 0.005 in global L2.
    move = _global_norms(
        jax.tree.map(
            lambda a, b: (np.asarray(a) - np.asarray(b))[None],
            out["params"], srv.params,
        )
    )
    assert move[0] <= 0.01 + 1e-5, move
    # Noise path is seeded/deterministic.
    cfg_n = _cfg(dp_clip_norm=0.01, dp_noise_multiplier=1.0)
    s1 = PrimaryServer(cfg_n, clients=[], seed=0)
    s2 = PrimaryServer(cfg_n, clients=[], seed=0)
    o1, _ = s1._aggregate(g, deltas, jnp.ones((2,)), s1._server_opt_state,
                          jnp.asarray(0, jnp.int32))
    o2, _ = s2._aggregate(g, deltas, jnp.ones((2,)), s2._server_opt_state,
                          jnp.asarray(0, jnp.int32))
    for a, b in zip(
        jax.tree_util.tree_leaves(o1["params"]),
        jax.tree_util.tree_leaves(o2["params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_through_fused_scan():
    seq = Federation(_cfg(dp_clip_norm=0.1, dp_noise_multiplier=0.5), seed=0)
    fused = Federation(_cfg(dp_clip_norm=0.1, dp_noise_multiplier=0.5), seed=0)
    for _ in range(2):
        seq.step()
    fused.run_on_device(2)
    for a, b in zip(
        jax.tree_util.tree_leaves(seq.state.params),
        jax.tree_util.tree_leaves(fused.state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
