"""Server-side optimizers (fedtpu.core.server_opt — the FedOpt family).

The reference applies the mean delta directly (``src/server.py:170-179``);
that is server_optimizer="none". These tests pin: the reduction of
momentum(lr=1, m=0) to exact FedAvg, that momentum/adam actually change the
trajectory, state threading through the fused scan and the mesh path, and
checkpoint roundtrip of the server moments.
"""

import dataclasses

import numpy as np
import jax
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.core import Federation


def _cfg(**fed_kw):
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=4,
            partition="round_robin",
            num_examples=96,
        ),
        fed=FedConfig(num_clients=3, **fed_kw),
        steps_per_round=2,
    )


def _leaves(t):
    return jax.tree_util.tree_leaves(t)


def test_momentum_lr1_m0_is_exactly_fedavg():
    plain = Federation(_cfg(), seed=0)
    degenerate = Federation(
        _cfg(server_optimizer="momentum", server_lr=1.0, server_momentum=0.0),
        seed=0,
    )
    for _ in range(3):
        plain.step()
        degenerate.step()
    for a, b in zip(_leaves(plain.state.params), _leaves(degenerate.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


@pytest.mark.parametrize("name", ["momentum", "adam", "yogi"])
def test_server_opt_changes_trajectory_and_threads_state(name):
    plain = Federation(_cfg(), seed=0)
    fedopt = Federation(
        _cfg(server_optimizer=name, server_lr=0.5), seed=0
    )
    assert _leaves(fedopt.state.server_opt_state), "server opt state is empty"
    for _ in range(2):
        plain.step()
        fedopt.step()
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(_leaves(plain.state.params), _leaves(fedopt.state.params))
    ]
    assert max(diffs) > 1e-6, f"{name} produced the same params as FedAvg"
    for leaf in _leaves(fedopt.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_server_opt_through_fused_scan():
    seq = Federation(_cfg(server_optimizer="momentum", server_lr=0.5), seed=0)
    fused = Federation(_cfg(server_optimizer="momentum", server_lr=0.5), seed=0)
    for _ in range(3):
        seq.step()
    fused.run_on_device(3)
    for a, b in zip(_leaves(seq.state.params), _leaves(fused.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for a, b in zip(
        _leaves(seq.state.server_opt_state), _leaves(fused.state.server_opt_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_server_opt_mesh_matches_single_program(eight_devices):
    from fedtpu.parallel import client_mesh

    cfg = dataclasses.replace(
        _cfg(server_optimizer="adam", server_lr=0.1),
        data=DataConfig(
            dataset="synthetic", batch_size=4, partition="round_robin",
            num_examples=128,
        ),
        fed=FedConfig(num_clients=8, server_optimizer="adam", server_lr=0.1),
    )
    single = Federation(cfg, seed=0)
    meshed = Federation(cfg, seed=0, mesh=client_mesh(8))
    for _ in range(2):
        single.step()
        meshed.step()
    # atol 2e-4: adam's update divides by sqrt(v_hat) + eps, and in round 1
    # v_hat is tiny, so the mesh psum's different reduction order (vs the
    # single-program sum over clients) amplifies last-ulp mean-delta
    # differences by ~1/sqrt(v) — observed on this CPU backend: 2 of 786k
    # elements at 5.7e-5 under atol 1e-5. Plain-FedAvg mesh parity stays
    # pinned at tight tolerances in tests/test_sharded.py; this test's
    # subject is the server-optimizer moments riding the mesh, not psum ulps.
    for a, b in zip(_leaves(single.state.params), _leaves(meshed.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_server_opt_state_checkpoint_roundtrip(tmp_path):
    from fedtpu.checkpoint import Checkpointer

    fed = Federation(_cfg(server_optimizer="adam"), seed=0)
    fed.step()
    ckpt = Checkpointer(str(tmp_path), backend="wire")
    ckpt.save(1, fed.state)

    fresh = Federation(_cfg(server_optimizer="adam"), seed=0)
    rnd, restored = ckpt.restore_latest(like=fresh.state)
    assert rnd == 1
    for a, b in zip(
        _leaves(fed.state.server_opt_state), _leaves(restored.server_opt_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_unknown_server_optimizer_raises():
    from fedtpu.core import server_opt

    with pytest.raises(ValueError, match="unknown server_optimizer"):
        server_opt.make_server_optimizer(
            FedConfig(server_optimizer="nesterov")
        )


def test_replica_payload_carries_server_moments():
    """Failover must not desync FedOpt moments from the model: the backup
    replication payload includes server_opt_state, and _install restores it.
    A model-only payload (from a server_optimizer=none generation) still
    installs, keeping the receiver's current moments."""
    import jax.numpy as jnp

    from fedtpu.transport.federation import PrimaryServer

    cfg = _cfg(server_optimizer="adam", server_lr=0.5)
    src = PrimaryServer(cfg, clients=[], seed=0)
    # Advance the source's moments so they are distinguishable from init.
    deltas = jax.tree.map(
        lambda p: jnp.stack([jnp.ones_like(p) * 0.01]),
        {"params": src.params, "batch_stats": src.batch_stats},
    )
    g = {"params": src.params, "batch_stats": src.batch_stats}
    out, src._server_opt_state = src._aggregate(
        g, deltas, jnp.asarray([1.0]), src._server_opt_state,
        jnp.asarray(0, jnp.int32),
    )
    src.params = out["params"]

    dst = PrimaryServer(cfg, clients=[], seed=1)
    dst._install(src.replica_bytes())
    for a, b in zip(
        _leaves(src._server_opt_state), _leaves(dst._server_opt_state)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    for a, b in zip(_leaves(src.params), _leaves(dst.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # Model-only payload from a "none" generation: installs the model,
    # leaves the receiver's moments untouched.
    plain = PrimaryServer(_cfg(), clients=[], seed=2)
    before = [np.asarray(x).copy() for x in _leaves(dst._server_opt_state)]
    dst._install(plain.model_bytes())
    for a, b in zip(before, _leaves(dst._server_opt_state)):
        np.testing.assert_allclose(a, np.asarray(b))


def test_distributed_edge_applies_server_opt():
    """The gRPC PrimaryServer's jitted aggregate honors the server optimizer:
    momentum(lr=1, m=0) == plain mean; adam != plain mean."""
    import jax.numpy as jnp

    from fedtpu.transport.federation import PrimaryServer

    def mk(fed_kw):
        cfg = _cfg(**fed_kw)
        return PrimaryServer(cfg, clients=[], seed=0)

    plain = mk({})
    degen = mk(dict(server_optimizer="momentum", server_lr=1.0,
                    server_momentum=0.0))
    adam = mk(dict(server_optimizer="adam", server_lr=0.5))

    deltas = jax.tree.map(
        lambda p: jnp.stack([jnp.ones_like(p) * 0.01, jnp.ones_like(p) * 0.03]),
        {"params": plain.params, "batch_stats": plain.batch_stats},
    )
    w = jnp.asarray([1.0, 1.0])

    def agg(srv):
        g = {"params": srv.params, "batch_stats": srv.batch_stats}
        out, _ = srv._aggregate(g, deltas, w, srv._server_opt_state,
                                jnp.asarray(0, jnp.int32))
        return out["params"]

    p_plain, p_degen, p_adam = agg(plain), agg(degen), agg(adam)
    for a, b in zip(_leaves(p_plain), _leaves(p_degen)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(_leaves(p_plain), _leaves(p_adam))
    ]
    assert max(diffs) > 1e-6
