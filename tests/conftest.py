"""Test configuration: force an 8-device virtual CPU platform.

This is the standard JAX trick for testing pjit/shard_map/psum multi-device
code without TPU hardware (SURVEY.md §4): must run before jax initialises.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The environment's TPU plugin registers itself regardless of JAX_PLATFORMS;
# the config update below actually forces the virtual 8-device CPU platform.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
