"""Chaos harness + transient-fault resilience: the fault-injection layer,
retry/backoff policy, round quorum, and wire integrity versioning.

The acceptance spine: a real-gRPC federation under a seeded >=30% transient
fault schedule completes every round with ZERO clients marked dead
(retries absorb the faults: ``fedtpu_rpc_retries_total`` > 0, only
exhausted budgets ever reach ``mark_failed``), corrupt payloads are
rejected by the wire CRC and re-requested, sub-quorum rounds abort with a
bit-identical global model, and a SIGKILLed primary fails over to the
backup which keeps committing rounds with the full fleet. The
multi-process 20-round soak (``tools/chaos_soak.py``) runs as ``slow``;
everything else here is the fast deterministic tier-1 leg.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RetryPolicy,
    RoundConfig,
    validate_retry_policy,
)
from fedtpu.ft.chaos import FaultRule, FaultSchedule, parse_spec
from fedtpu.transport import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import chaos_soak  # noqa: E402


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(num_clients=2, rounds=2, **fed_kw) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(num_clients=num_clients, num_rounds=rounds, **fed_kw),
        steps_per_round=2,
    )


# ----------------------------------------------------------- spec parsing
def test_dsl_parse_round_trips_options():
    sched = parse_spec(
        "error@StartTrain:p=0.3,seed=7;"
        "delay@SendModel:p=0.5,delay=0.25,peer=localhost:1,rounds=3-5;"
        "kill@StartTrain:rounds=8,max=1;"
        "corrupt@StartTrain:p=0.1,code=UNAVAILABLE"
    )
    assert sched.seed == 7
    assert [r.kind for r in sched.rules] == [
        "error", "delay", "kill", "corrupt",
    ]
    assert sched.rules[0].p == 0.3 and sched.rules[0].rpc == "StartTrain"
    assert sched.rules[1].delay_s == 0.25
    assert sched.rules[1].peer == "localhost:1"
    assert sched.rules[1].rounds == (3, 5)
    assert sched.rules[2].rounds == (8, 9)       # single round -> [8, 9)
    assert sched.rules[2].max_injections == 1
    # describe() names every armed rule (the startup-log contract).
    assert "seed=7" in sched.describe() and "kill@StartTrain" in sched.describe()


def test_json_parse_and_errors():
    sched = parse_spec(
        '{"seed": 3, "rules": [{"kind": "error", "rpc": "StartTrain",'
        ' "p": 0.5, "max_injections": 2}]}'
    )
    assert sched.seed == 3 and sched.rules[0].max_injections == 2
    assert parse_spec(None) is None
    assert parse_spec("  ") is None
    for bad in (
        "explode@StartTrain",            # unknown kind
        "error@NoSuchRpc",               # unknown rpc
        "error@StartTrain:p=1.5",        # p out of range
        "error@StartTrain:frequency=2",  # unknown option
        "error@StartTrain:p",            # not key=value
        '{"rules": []}',                 # no rules
        "{not json",
    ):
        with pytest.raises(ValueError):
            parse_spec(bad)


def test_disk_kinds_parse_and_stay_in_their_class():
    """ckpt_fail/ckpt_torn/ckpt_rot (the durability fault class): bare
    specs normalize to the pseudo-RPC 'Disk', wildcard wire rules never
    fire on the Disk consult and disk rules never fire on wire RPCs —
    kind classes never cross, same contract as the Attack class."""
    sched = parse_spec(
        "ckpt_rot:p=1.0,rounds=4,max=1;"
        "ckpt_torn@Disk:p=1.0,rounds=5;"
        "ckpt_fail:p=0.5"
    )
    assert [r.kind for r in sched.rules] == [
        "ckpt_rot", "ckpt_torn", "ckpt_fail",
    ]
    assert all(r.rpc == "Disk" for r in sched.rules)
    assert sched.rules[0].rounds == (4, 5)
    # A wildcard WIRE rule must not fire on the Disk consult, and a disk
    # rule must not fire on a wire RPC.
    wire_sched = parse_spec("error@*:p=1.0")
    assert wire_sched.decide("Disk") is None
    disk_sched = parse_spec("ckpt_fail:p=1.0")
    assert disk_sched.decide("StartTrain", "peer") is None
    assert disk_sched.decide("Disk") is not None
    # Class-crossing specs are parse errors, not silent no-ops.
    for bad in ("ckpt_rot@StartTrain:p=1", "error@Disk:p=1",
                "kill@Attack:p=1"):
        with pytest.raises(ValueError):
            parse_spec(bad)
    assert "ckpt_rot@Disk" in sched.describe()


def test_net_kinds_parse_groups_windows_and_stay_in_their_class():
    """partition/flaky (the link-fault class): ride the wire interceptors
    but model the LINK — group-keyed peers (peer=a|b) cut a whole side
    with one rule, wall-clock windows (window=lo-hi seconds since arm)
    bound the outage on paths that never learn a round number, and the
    class never crosses into the pseudo-RPCs."""
    grpc = pytest.importorskip("grpc")
    sched = parse_spec(
        "partition@StartTrain:peer=a|b,window=0-30;"
        "flaky@CheckIfPrimaryUp:p=0.5,delay=0.05,code=UNAVAILABLE,seed=3"
    )
    part, flaky = sched.rules
    assert part.is_net and flaky.is_net
    assert part.peer == "a|b" and part.window == (0.0, 30.0)
    assert "window=0-30" in sched.describe()
    # Group-keyed match: both sides of the group are cut, others pass.
    assert sched.decide("StartTrain", "a").kind == "partition"
    assert sched.decide("StartTrain", "b").kind == "partition"
    assert sched.decide("StartTrain", "c") is None
    # partition severs FAST: immediate UNAVAILABLE, no blackhole sleep.
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError) as exc:
        sched.apply_precall(part, "StartTrain")
    assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
    assert "partitioned" in exc.value.details()
    assert time.monotonic() - t0 < 0.2
    # flaky is the gray link: stalls delay_s, then fails with `code`.
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError) as exc:
        sched.apply_precall(flaky, "CheckIfPrimaryUp")
    assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
    assert "flaky" in exc.value.details()
    assert time.monotonic() - t0 >= 0.05
    # Class discipline + window sanity are parse errors, not silent no-ops.
    for bad in ("partition@Round:p=1", "partition@Attack:p=1",
                "partition@Disk:p=1", "flaky@Round:p=1",
                "partition@StartTrain:window=5-2",
                "partition@StartTrain:window=30"):
        with pytest.raises(ValueError):
            parse_spec(bad)


def test_net_window_heals_on_wall_clock():
    """A window=lo-hi rule matches only while the schedule's wall clock is
    inside [lo, hi) — 'the partition healed' is simply the window closing.
    Pinned by rebasing the schedule's arm time, not by sleeping."""
    sched = parse_spec("partition@StartTrain:peer=a,window=5-10")
    # t=0: before the cut opens.
    assert sched.decide("StartTrain", "a") is None
    # t~7: inside the cut.
    sched._t0 = time.monotonic() - 7.0
    assert sched.decide("StartTrain", "a").kind == "partition"
    # t~12: healed; the same rule goes silent.
    sched._t0 = time.monotonic() - 12.0
    assert sched.decide("StartTrain", "a") is None


# ----------------------------------------------------- schedule semantics
def test_schedule_is_deterministic_and_seed_sensitive():
    def draws(seed):
        sched = FaultSchedule(
            [FaultRule(kind="error", rpc="StartTrain", p=0.3)], seed=seed
        )
        return [
            sched.decide("StartTrain", f"peer{i % 3}") is not None
            for i in range(60)
        ]

    a, b = draws(7), draws(7)
    assert a == b, "same seed must inject identically"
    assert any(a) and not all(a)  # p=0.3 fires sometimes, not always
    assert draws(8) != a, "different seed must change the pattern"


def test_schedule_matching_window_cap_and_counters():
    sched = FaultSchedule(
        [
            FaultRule(kind="error", rpc="StartTrain", p=1.0,
                      rounds=(2, 4), max_injections=3),
            FaultRule(kind="delay", rpc="SendModel", peer="a", p=1.0),
        ],
        seed=0,
    )
    # Out-of-window round: rule 1 silent; peer-mismatched rule 2 silent.
    sched.set_round(0)
    assert sched.decide("StartTrain", "a") is None
    assert sched.decide("SendModel", "b") is None
    assert sched.decide("SendModel", "a").kind == "delay"
    # In-window: fires, but only max_injections times in total.
    sched.set_round(2)
    fired = [sched.decide("StartTrain", "a") for _ in range(5)]
    assert [f.kind if f else None for f in fired] == [
        "error", "error", "error", None, None,
    ]
    assert sched.injected_total() == 4  # 3 errors + 1 delay
    # Wrong rpc never matches anything.
    assert sched.decide("HeartBeat", "a") is None


def test_consec_cap_bounds_every_failure_run():
    """``consec=k``: no (rule, rpc, peer) stream ever fires more than k
    times in a row, for ANY seed/peer — the property that lets a soak
    pair ``consec < retry attempts`` and assert zero transient deaths
    deterministically. Only a drawn pass re-arms the streak, so two
    capped rules cannot alternate into an unbounded outage either."""
    for seed in range(5):
        sched = parse_spec(
            f"error@StartTrain:p=0.9,consec=2,seed={seed};"
            "corrupt@StartTrain:p=0.9,consec=1"
        )
        run, worst = 0, 0
        for _ in range(400):
            if sched.decide("StartTrain", "peerX") is not None:
                run += 1
                worst = max(worst, run)
            else:
                run = 0
        assert sched.injected_total() > 0
        # Worst interleaved run is bounded by 2*consec_a + consec_b.
        assert worst <= 5, f"seed {seed}: failure run of {worst}"
    # DSL surface: consec round-trips and validates.
    rule = parse_spec("error@StartTrain:consec=3").rules[0]
    assert rule.max_consecutive == 3
    with pytest.raises(ValueError):
        parse_spec("error@StartTrain:consec=0")


def test_p_zero_rule_never_fires():
    sched = FaultSchedule([FaultRule(kind="error", p=0.0)], seed=1)
    assert all(sched.decide("StartTrain", "x") is None for _ in range(200))
    assert sched.injected_total() == 0


# ----------------------------------------------------------- retry policy
def test_retry_policy_defaults_reproduce_old_constants():
    """The resolved deadline surface must equal the constants it replaced:
    600s data plane, 2.0s backup ping, 1.0s probe, 10s watchdog, 1.0s
    heartbeat period and async poll — the no-fault bit-identical contract."""
    fed = FedConfig()
    rp = fed.retry
    assert (rp.start_train_timeout_s, rp.send_model_timeout_s,
            rp.fetch_model_timeout_s) == (600.0, 600.0, 600.0)
    assert rp.backup_ping_timeout_s == 2.0
    assert rp.probe_timeout_s == 1.0
    assert fed.ft_watchdog_timeout_s == 10.0
    assert fed.ft_heartbeat_period_s == 1.0
    assert fed.async_poll_s == 1.0
    assert fed.round_quorum == 0.0
    validate_retry_policy(rp)
    with pytest.raises(ValueError):
        validate_retry_policy(RetryPolicy(max_attempts=0))
    with pytest.raises(ValueError):
        validate_retry_policy(RetryPolicy(backoff_multiplier=0.5))


def test_call_with_retry_classification_and_exhaustion():
    grpc = pytest.importorskip("grpc")
    from fedtpu.ft.chaos import ChaosRpcError
    from fedtpu.obs import Telemetry
    from fedtpu.transport.retry import backoff_s, call_with_retry, is_transient

    policy = RetryPolicy(max_attempts=3, backoff_s=0.001, jitter=0.0)
    tel = Telemetry("basic")
    sleeps = []

    def run(fails, exc_of):
        calls = [0]

        def attempt():
            calls[0] += 1
            if calls[0] <= fails:
                raise exc_of()
            return "ok"

        out = call_with_retry(policy, "StartTrain", attempt, telemetry=tel,
                              sleep=sleeps.append)
        return out, calls[0]

    transient = lambda: ChaosRpcError(grpc.StatusCode.UNAVAILABLE, "x")
    # Two transient failures -> third attempt succeeds.
    assert run(2, transient) == ("ok", 3)
    assert tel.registry.counter(
        "fedtpu_rpc_retries_total", labels={"rpc": "StartTrain"}
    ).value == 2
    # Exhaustion re-raises the transient error.
    with pytest.raises(grpc.RpcError):
        run(3, transient)
    # Fatal codes fail on the FIRST attempt, no retry.
    fatal = lambda: ChaosRpcError(grpc.StatusCode.UNIMPLEMENTED, "x")
    with pytest.raises(grpc.RpcError):
        run(1, fatal)
    # Corrupt payloads are transient (reject-and-retry).
    assert run(1, lambda: wire.WireError("crc"))[0] == "ok"
    assert is_transient(wire.WireError("crc"), policy)
    assert not is_transient(RuntimeError("bug"), policy)
    # Backoff grows exponentially and caps.
    assert backoff_s(policy, 1, rand=lambda: 0.0) == pytest.approx(0.001)
    assert backoff_s(policy, 2, rand=lambda: 0.0) == pytest.approx(0.002)
    big = RetryPolicy(backoff_s=1.0, backoff_max_s=1.5, jitter=0.0)
    assert backoff_s(big, 10, rand=lambda: 0.0) == pytest.approx(1.5)
    assert all(s >= 0 for s in sleeps)


# ------------------------------------------------------- wire versioning
def test_wire_v1_frames_still_decode():
    """Old (v1, payload-only CRC) frames from pre-v2 peers or checkpoints
    must keep decoding; v2 is what we now emit."""
    tree = {"w": np.arange(8, dtype=np.float32)}
    like = {"w": np.zeros(8, np.float32)}
    v2 = wire.encode(tree)
    assert v2[4] == 2  # version byte
    np.testing.assert_array_equal(wire.decode(v2, like)["w"], tree["w"])
    # Hand-build a v1 frame of the same payload.
    v1 = wire.frame(b"FTP1", v2[10:], 0, version=1)
    assert v1[4] == 1
    np.testing.assert_array_equal(wire.decode(v1, like)["w"], tree["w"])
    assert wire.payload_kind(v1) == "model"
    # Future versions are rejected, not misparsed.
    v9 = bytearray(v2)
    v9[4] = 9
    with pytest.raises(wire.WireError):
        wire.decode(bytes(v9), like)


def test_wire_v2_crc_covers_header():
    """v2 closes the v1 header hole: a bit-flipped flags byte (which could
    silently re-kind or un-zlib a payload) now fails the CRC at decode."""
    tree = {"w": np.arange(8, dtype=np.float32)}
    like = {"w": np.zeros(8, np.float32)}
    data = bytearray(wire.encode(tree, kind="replica"))
    data[5] ^= wire._FLAG_REPLICA  # flip the kind bit
    # payload_kind reads flags only (header-level dispatch) — but the
    # decode behind it must reject the frame.
    with pytest.raises(wire.WireError):
        wire.decode(bytes(data), like)
    # The SAME flip on a v1 frame decodes silently — the hole v2 closes.
    v1 = bytearray(wire.frame(b"FTP1", wire.encode(tree)[10:], 0, version=1))
    v1[5] ^= wire._FLAG_REPLICA
    assert wire.payload_kind(bytes(v1)) == "replica"  # undetected re-kind
    # Payload corruption is caught in both versions.
    for version in (1, 2):
        framed = bytearray(
            wire.frame(b"FTP1", b"payload-bytes", 0, version=version)
        )
        framed[-1] ^= 0xFF
        with pytest.raises(wire.WireError):
            wire.unframe(b"FTP1", bytes(framed))


# ---------------------------------------- the fast tier-1 chaos leg (gRPC)
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_transient_chaos_round_survives_without_deaths():
    """Seeded >=30% transient error injection on every StartTrain: all
    rounds must commit with the FULL fleet (retries absorb every fault;
    zero clients marked dead), retry and chaos counters must count, and
    training must stay finite. ``consec=2`` (< the 4-attempt budget)
    makes the rule transient BY CONSTRUCTION, so the zero-deaths assert
    is deterministic whatever peer addresses the ports draw."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = tiny_cfg(
        2, rounds=5,
        retry=RetryPolicy(max_attempts=5, backoff_s=0.01, backoff_max_s=0.05),
    )
    # Rule 1 fires EXACTLY twice (p=1, max=2) whatever peer strings the
    # test's ports produce — a deterministic injection floor; rule 2 is
    # the >=30%-rate Bernoulli stream. Worst interleaved failure run =
    # 2 + 2 = 4 < the 5-attempt budget, so zero deaths is guaranteed.
    chaos = parse_spec(
        "error@StartTrain:p=1.0,max=2,consec=2,seed=1234;"
        "error@StartTrain:p=0.35,consec=2"
    )
    servers, agents, addrs = [], [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        for _ in range(5):
            rec = primary.round()
            assert not rec.get("aborted")
            assert rec["participants"] == 2, (
                "a transient fault cost a client its round"
            )
            assert rec["alive"] == [True, True], (
                "a transient fault marked a client dead"
            )
        reg = primary.telemetry.registry.snapshot()
        retries = sum(
            e["value"] for e in reg.get("fedtpu_rpc_retries_total", [])
        )
        injected = sum(
            e["value"] for e in reg.get("fedtpu_chaos_injected_total", [])
        )
        deaths = sum(
            e["value"] for e in reg.get("fedtpu_ft_client_deaths_total", [])
        )
        # >= 2 is the deterministic floor from the p=1,max=2 rule; every
        # injected error must have been retried (never a death).
        assert injected >= 2, f"chaos barely injected: {injected}"
        assert retries >= injected * 0.9, (retries, injected)
        assert deaths == 0
        for agent in agents:
            loss, acc = agent.last_eval
            assert np.isfinite(loss) and np.isfinite(acc)
    finally:
        for s in servers:
            s.stop(0)


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_corrupt_reply_is_rejected_and_retried():
    """A payload corrupted in flight (wire CRC mismatch) must be
    re-requested — one retry, full participation, no dead client. Before
    the retry policy this reply silently vanished (the collect worker died
    with the WireError and the client sat the round out)."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = tiny_cfg(2, retry=RetryPolicy(max_attempts=3, backoff_s=0.01))
    chaos = parse_spec("corrupt@StartTrain:p=1.0,max=1,seed=0")
    servers, addrs = [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        rec = primary.round()
        assert rec["participants"] == 2 and rec["alive"] == [True, True]
        assert primary.telemetry.registry.counter(
            "fedtpu_rpc_retries_total", labels={"rpc": "StartTrain"}
        ).value == 1
        assert chaos.injected_total() == 1
    finally:
        for s in servers:
            s.stop(0)


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
# Both kinds share the CRC-reject/retry path; randk runs tier-1, the rotq
# twin rides the slow tier (its record-level corruption rejection is also
# pinned cheaply in test_sparse_wire).
@pytest.mark.parametrize(
    "codec", [pytest.param("rotq", marks=pytest.mark.slow), "randk"]
)
def test_corrupt_sketch_record_is_rejected_and_retried(codec):
    """A rotq/randk record corrupted in flight fails the FSP1 CRC like any
    other sparse reply: classified transient, re-requested once, full
    participation, nobody marked dead — the new record kinds inherit the
    whole retry path. The retried round's per-codec accounting still labels
    the bytes with the sketch codec."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = tiny_cfg(
        2,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.01),
        compression=codec,
        topk_fraction=0.1,
        delta_layout="flat",
        error_feedback=True,
    )
    chaos = parse_spec("corrupt@StartTrain:p=1.0,max=1,seed=0")
    servers, addrs = [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        rec = primary.round()
        assert rec["participants"] == 2 and rec["alive"] == [True, True]
        assert primary.telemetry.registry.counter(
            "fedtpu_rpc_retries_total", labels={"rpc": "StartTrain"}
        ).value == 1
        assert chaos.injected_total() == 1
        by_codec = rec["bytes_up_by_codec"]
        assert set(by_codec) == {codec} and by_codec[codec] > 0
    finally:
        for s in servers:
            s.stop(0)


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_exhausted_retries_do_reach_mark_failed():
    """The inverse contract: a NON-transient outage (faults outlasting the
    whole retry budget) must still mark the client dead — retries absorb
    blips, they must not mask real failures."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = tiny_cfg(2, retry=RetryPolicy(max_attempts=2, backoff_s=0.01))
    chaos = parse_spec("error@StartTrain:p=1.0,peer=PEER,seed=0")
    servers, addrs = [], []
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            servers.append(server)
            addrs.append(addr)
        # Re-key the rule to the first client only.
        import dataclasses

        chaos.rules[0] = dataclasses.replace(chaos.rules[0], peer=addrs[0])
        primary = PrimaryServer(cfg, addrs, chaos=chaos)
        rec = primary.round()
        assert rec["participants"] == 1
        assert rec["alive"] == [False, True]
    finally:
        for s in servers:
            s.stop(0)


def test_quorum_abort_restores_global_bit_identically():
    """Sub-quorum round -> clean abort: params, server-optimizer moments,
    and the round counter byte-for-byte untouched; the re-run (faults
    exhausted, clients revived) commits. Drives the same drill the soak
    tool runs as its phase 0."""
    pytest.importorskip("grpc")
    out = chaos_soak.quorum_drill(seed=7)
    assert out["aborted_round_bit_identical"]
    assert out["recommit_participants"] == 2


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_quorum_default_keeps_old_semantics():
    """round_quorum=0 (default): a round with zero survivors still
    'commits' exactly as before (no abort record, counter advances)."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import PrimaryServer

    cfg = tiny_cfg(1, retry=RetryPolicy(max_attempts=1))
    dead = f"localhost:{free_port()}"  # nothing listening
    primary = PrimaryServer(cfg, [dead])
    rec = primary.round()
    assert not rec.get("aborted")
    assert rec["participants"] == 0
    assert primary._round_counter == 1


def test_ft_timing_constants_are_lifted():
    """The lifted constants actually reach the components: heartbeat
    period, backup watchdog, per-RPC deadlines."""
    pytest.importorskip("grpc")
    from fedtpu.transport.federation import BackupServer, PrimaryServer

    cfg = tiny_cfg(
        1,
        ft_heartbeat_period_s=0.25,
        ft_watchdog_timeout_s=3.5,
        retry=RetryPolicy(
            start_train_timeout_s=11.0, send_model_timeout_s=12.0,
            backup_ping_timeout_s=0.5, probe_timeout_s=0.25,
        ),
    )
    primary = PrimaryServer(cfg, [])
    assert primary.monitor.period == 0.25
    assert primary._deadlines["StartTrain"] == 11.0
    assert primary._deadlines["SendModel"] == 12.0
    assert primary._deadlines["CheckIfPrimaryUp"] == 0.5
    assert primary._deadlines["HeartBeat"] == 0.25
    # Legacy blanket override still wins for the data plane.
    override = PrimaryServer(cfg, [], rpc_timeout=2.0)
    assert override._deadlines["StartTrain"] == 2.0
    assert override._deadlines["CheckIfPrimaryUp"] == 0.5
    backup = BackupServer(cfg, [])
    assert backup.machine.timeout == 3.5
    with pytest.raises(ValueError):
        PrimaryServer(tiny_cfg(1, round_quorum=1.5), [])


def test_cli_robustness_flags_reach_config():
    """--rpc-retries/--rpc-timeout/--round-quorum etc. flow through
    build_config into the typed FedConfig fields on every CLI parser."""
    import argparse

    from fedtpu.cli.common import (
        add_fed_flags, add_model_flags, add_robustness_flags, build_config,
    )

    p = argparse.ArgumentParser()
    add_model_flags(p)
    add_fed_flags(p)
    add_robustness_flags(p)
    args = p.parse_args([
        "--dataset", "synthetic",
        "--rpc-retries", "5", "--rpc-backoff", "0.2",
        "--rpc-timeout", "30", "--round-quorum", "0.75",
        "--backup-ping-timeout", "4.5", "--heartbeat-period", "0.5",
        "--async-poll", "0.3",
        "--chaos-spec", "error@StartTrain:p=0.3,seed=9",
    ])
    cfg = build_config(args, num_clients=2)
    assert cfg.fed.retry.max_attempts == 5
    assert cfg.fed.retry.backoff_s == 0.2
    assert cfg.fed.retry.start_train_timeout_s == 30.0
    assert cfg.fed.retry.backup_ping_timeout_s == 4.5
    assert cfg.fed.round_quorum == 0.75
    assert cfg.fed.ft_heartbeat_period_s == 0.5
    assert cfg.fed.async_poll_s == 0.3
    from fedtpu.cli.common import make_chaos

    chaos = make_chaos(args, role="test")
    assert chaos is not None and chaos.seed == 9


# ------------------------------------------- failover under fire (SIGKILL)
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_primary_sigkill_promotes_backup_and_rounds_keep_committing(tmp_path):
    """The acceptance failover drill against real processes: the primary
    (a genuine ``fedtpu.cli.server`` subprocess) is SIGKILLed mid-run;
    the in-process backup's watchdog must promote it to acting primary,
    and the acting primary must keep committing full-participation rounds
    with the SAME client fleet (clients rejoin without restart)."""
    pytest.importorskip("grpc")
    from fedtpu.obs import read_round_records
    from fedtpu.transport.federation import BackupServer, serve_client

    cfg = tiny_cfg(2, rounds=1000)
    servers, agents, addrs = [], [], []
    backup_srv = None
    proc = None
    try:
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, agent = serve_client(addr, cfg, seed=i)
            servers.append(server)
            agents.append(agent)
            addrs.append(addr)
        backup_port = free_port()
        backup = BackupServer(cfg, addrs, watchdog_timeout=2.5)
        backup_srv = backup.start(f"localhost:{backup_port}")

        metrics_path = str(tmp_path / "primary.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "fedtpu.cli.server",
                "--p", "y", "--platform", "cpu",
                "--model", "mlp", "--dataset", "synthetic",
                "--num-examples", "256", "--batch-size", "8",
                "--eval-batch-size", "8", "--rounds", "1000",
                "--clients", ",".join(addrs),
                "--backupAddress", "localhost",
                "--backupPort", str(backup_port),
                "--metrics", metrics_path,
                # Stretch each round so the kill lands mid-round.
                "--chaos-spec", "delay@StartTrain:p=1.0,delay=0.2,seed=0",
                "--seed", "0",
            ],
            cwd=REPO, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            if (os.path.exists(metrics_path)
                    and len(read_round_records(metrics_path)) >= 2):
                break
            if proc.poll() is not None:
                pytest.fail(f"primary exited early rc={proc.returncode}")
            time.sleep(0.2)
        else:
            pytest.fail("primary never committed 2 rounds within 180s")
        rounds_before = [a.trainer.round_idx for a in agents]

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if (backup.machine.role.value == "acting_primary"
                    and backup.acting is not None
                    and len(backup.acting.history) >= 2):
                break
            time.sleep(0.25)
        else:
            pytest.fail("backup never promoted / acting committed nothing")

        recs = [r for r in backup.acting.history if not r.get("aborted")]
        assert recs, "acting primary committed no rounds"
        assert recs[-1]["participants"] == 2, (
            "clients did not rejoin under the acting primary"
        )
        # Clients kept TRAINING across the failover (their local round
        # index advanced under the acting primary).
        assert sum(a.trainer.round_idx for a in agents) > sum(rounds_before)
        # The acting primary inherited the replicated model lineage: its
        # round counter continued past the dead primary's rounds.
        assert backup.acting._round_counter >= 2
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if backup_srv is not None:
            backup.watchdog.stop()
            backup._stop_acting(wait=15.0)
            backup_srv.stop(0)
        for s in servers:
            s.stop(0)


# --------------------------------------------------- the full soak (slow)
@pytest.mark.slow
@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_chaos_soak_twenty_rounds_with_primary_kill(tmp_path):
    """The acceptance soak end to end: 20 rounds, seeded >=30% transient
    faults + corruption, one chaos-scheduled mid-round primary SIGKILL,
    backup promotion, primary recovery, sub-quorum abort, finite final
    eval, zero transient deaths. ~2-3 minutes; marked slow."""
    pytest.importorskip("grpc")
    result = chaos_soak.run_soak(
        rounds=20, clients=3, kill_round=8, quorum=0.5, seed=7,
        workdir=str(tmp_path), verbose=False,
    )
    assert result["ok"]
    assert result["gen1_client_deaths"] == 0
    assert result["gen2_client_deaths"] == 0
    assert result["gen1_retries"] > 0
    assert result["total_committed"] >= 20
    assert result["gen1_aborted"] >= 1
    assert result["quorum_drill"]["aborted_round_bit_identical"]
