"""Exporter schemas + the telemetry=trace federation smoke (PR 3).

Pins the contracts downstream consumers lean on: JSONL round records
round-trip with a pinned ``schema_version``, Chrome trace output is
Perfetto-loadable with non-negative durations and an intact parent chain,
the Prometheus dump parses, ``tools/jsontail.py`` understands the
versioned schema — and a real 2-client/2-round gRPC federation at
``telemetry=trace`` produces non-empty, valid output from BOTH exporters.
"""

import json
import os
import socket
import sys
import threading

import pytest

from fedtpu.obs import (
    SCHEMA_VERSION,
    MetricsRegistry,
    RoundRecordWriter,
    SpanTracer,
    load_chrome_trace,
    parse_prometheus_text,
    prometheus_text,
    read_round_records,
    write_chrome_trace,
    write_prometheus,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools"),
)
import jsontail  # noqa: E402


# ------------------------------------------------------------------ JSONL
def test_round_records_roundtrip_with_pinned_schema(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with RoundRecordWriter(path, echo=False) as w:
        w.log(0, loss=1.25, pipeline="stream", bytes_up=1024)
        w.log(1, loss=0.5)
    recs = read_round_records(path)
    assert [r["step"] for r in recs] == [0, 1]
    assert all(r["schema_version"] == SCHEMA_VERSION for r in recs)
    assert SCHEMA_VERSION == 1  # bump deliberately, with a reader update
    assert recs[0]["loss"] == 1.25
    assert recs[0]["pipeline"] == "stream"  # non-numeric fields survive
    assert recs[0]["bytes_up"] == 1024.0
    assert recs[0]["t"] <= recs[1]["t"]


def test_read_round_records_tolerates_legacy_and_garbage(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as fh:
        fh.write('{"step": 0, "loss": 2.0}\n')       # legacy (PR-2) record
        fh.write("not json at all\n")
        fh.write('{"step": 1, "loss": 1.0, "schema_version": 1}\n')
        fh.write('{"truncated": \n')                  # killed writer
    recs = read_round_records(path)
    assert [r["schema_version"] for r in recs] == [0, 1]


def test_jsontail_understands_versioned_schema():
    text = "\n".join([
        '{"step": 0, "loss": 2.0}',                            # v0
        '{"step": 1, "loss": 1.0, "schema_version": 1}',
        '{"metric": "not_a_round_record", "value": 3}',        # no step
        '{"step": 2, "loss": 0.5, "schema_version": 99}',      # future
        "garbage",
    ])
    recs, skipped = jsontail.round_records(text)
    assert [r["step"] for r in recs] == [0, 1]
    assert recs[0]["schema_version"] == 0
    assert skipped == 1  # the future-schema line (bare garbage never counts)
    assert jsontail.last_round_record(text)["step"] == 1
    # The import-free tools-side pin must track the real schema version.
    assert jsontail.ROUND_RECORD_SCHEMA_VERSION == SCHEMA_VERSION


# ------------------------------------------------------------ chrome trace
def test_chrome_trace_validates_nested_nonnegative(tmp_path):
    tr = SpanTracer()
    with tr.span("round", round=0) as rs:
        with tr.span("aggregate"):
            pass

        def worker():
            # Cross-thread child: explicit parent, own tid.
            with tr.span("decode", parent=rs.id, client="c0"):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    path = str(tmp_path / "trace.json")
    write_chrome_trace(tr.events(), path)

    with open(path) as fh:
        doc = json.load(fh)
    assert isinstance(doc["traceEvents"], list)  # Perfetto-loadable object
    events = load_chrome_trace(path)
    assert len(events) == 3
    by_id = {e["args"]["span_id"]: e for e in events}
    rnd = by_id[[e for e in events if e["name"] == "round"][0]["args"]["span_id"]]
    for e in events:
        assert e["ph"] == "X" and e["dur"] >= 0 and e["ts"] >= 0
    for name in ("aggregate", "decode"):
        e = [x for x in events if x["name"] == name][0]
        # Parent chain AND time containment under the round span.
        assert e["args"]["parent_id"] == rnd["args"]["span_id"]
        assert rnd["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= rnd["ts"] + rnd["dur"] + 1e-3
    assert by_id[rnd["args"]["span_id"]]["args"]["round"] == 0


# -------------------------------------------------------------- prometheus
def test_prometheus_dump_parses(tmp_path):
    reg = MetricsRegistry()
    reg.counter("fedtpu_rounds_completed_total", "rounds").inc(3)
    reg.counter("fedtpu_rpc_failures_total", "fails",
                labels={"rpc": "StartTrain"}).inc()
    reg.gauge("fedtpu_client_compression_ratio").set(0.125)
    h = reg.histogram("fedtpu_round_phase_seconds",
                      labels={"phase": "decode"})
    for v in (0.002, 0.02, 0.2):
        h.observe(v)
    path = str(tmp_path / "m.prom")
    write_prometheus(reg, path)
    with open(path) as fh:
        text = fh.read()
    assert "# TYPE fedtpu_rounds_completed_total counter" in text
    assert "# TYPE fedtpu_round_phase_seconds histogram" in text
    parsed = parse_prometheus_text(text)
    assert parsed["fedtpu_rounds_completed_total"][""] == 3
    assert parsed["fedtpu_rpc_failures_total"]["rpc=StartTrain"] == 1
    assert parsed["fedtpu_client_compression_ratio"][""] == 0.125
    assert parsed["fedtpu_round_phase_seconds_count"]["phase=decode"] == 3
    assert parsed["fedtpu_round_phase_seconds_sum"]["phase=decode"] == \
        pytest.approx(0.222)
    # Cumulative bucket counts are monotone and end at the total.
    buckets = sorted(
        (float(k.split("le=")[1].split(",")[0]), v)
        for k, v in parsed["fedtpu_round_phase_seconds_bucket"].items()
        if "+Inf" not in k
    )
    counts = [v for _, v in buckets]
    assert counts == sorted(counts) and counts[-1] == 3


def test_prometheus_text_matches_own_parser_roundtrip():
    reg = MetricsRegistry()
    reg.counter("a_total").inc(2)
    text = prometheus_text(reg)
    assert parse_prometheus_text(text) == {"a_total": {"": 2.0}}


def test_registry_rejects_kind_collisions():
    reg = MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")


# ------------------------------------------- tier-1 federation trace smoke
def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_two_client_trace_run_feeds_both_exporters(tmp_path):
    """The CI smoke the ISSUE asks for: a 2-client, 2-round federation with
    telemetry=trace must leave BOTH exporters with non-empty, valid output
    — schema-versioned JSONL round records, a parsed Prometheus dump with
    the expected counts, and a Chrome trace whose decode/h2d/aggregate
    spans resolve (via parent_id) to a round span that time-contains
    them."""
    pytest.importorskip("grpc")
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )
    from fedtpu.transport.federation import PrimaryServer, serve_client

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(
            num_clients=2, num_rounds=2, telemetry="trace",
            server_pipeline="stream",  # exercises the h2d span too
        ),
        steps_per_round=2,
    )
    servers = []
    try:
        addrs = []
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
        primary = PrimaryServer(cfg, addrs)

        metrics_path = str(tmp_path / "metrics.jsonl")
        writer = RoundRecordWriter(metrics_path, echo=False)
        # Same shape the server CLI's on_round hook uses.
        primary.run(num_rounds=2, on_round=lambda r, rec: writer.log(r, **rec))
        writer.close()
    finally:
        for s in servers:
            s.stop(0)

    # JSONL exporter: 2 versioned records with the wire/phase fields.
    recs = read_round_records(metrics_path)
    assert len(recs) == 2
    for rec in recs:
        assert rec["schema_version"] == SCHEMA_VERSION
        assert rec["participants"] == 2
        assert rec["bytes_up"] > 0 and rec["bytes_down"] > 0
        assert rec["t_collect_s"] > 0 and rec["t_aggregate_s"] >= 0
        # Straggler attribution (performance observatory): whole-round wall
        # plus the per-client StartTrain latency spread with the named
        # slowest clients.
        assert rec["t_round_s"] >= rec["t_collect_s"]
        lat = rec["client_latency"]
        assert lat["n"] == 2
        assert 0 < lat["p50_s"] <= lat["p95_s"] <= lat["p99_s"] <= lat["max_s"]
        assert 1 <= len(lat["slowest"]) <= 3
        slowest_client, slowest_s = lat["slowest"][0]
        assert slowest_client in addrs and slowest_s == lat["max_s"]

    # Prometheus exporter: parses, and the counters carry the run.
    prom_path = str(tmp_path / "metrics.prom")
    primary.telemetry.export_prometheus(prom_path)
    with open(prom_path) as fh:
        parsed = parse_prometheus_text(fh.read())
    assert parsed["fedtpu_rounds_completed_total"][""] == 2
    assert parsed["fedtpu_rpc_bytes_up_total"][""] == sum(
        r["bytes_up"] for r in recs
    )
    assert parsed["fedtpu_round_phase_seconds_count"]["phase=decode"] == 2
    # One StartTrain latency observation per client per round.
    assert parsed["fedtpu_client_rpc_seconds_count"][""] == 4
    # The whole-round step-time gauge tracks the last round's record.
    assert parsed["fedtpu_step_time_seconds"][""] == pytest.approx(
        recs[-1]["t_round_s"], abs=5e-3
    )

    # Trace exporter: Perfetto-loadable, phases nest under their round.
    trace_path = str(tmp_path / "trace.json")
    primary.telemetry.export_trace(trace_path)
    events = load_chrome_trace(trace_path)
    assert events and all(e["dur"] >= 0 for e in events)
    by_id = {e["args"]["span_id"]: e for e in events}

    def root(e):
        while "parent_id" in e["args"]:
            e = by_id[e["args"]["parent_id"]]
        return e

    rounds = [e for e in events if e["name"] == "round"]
    assert len(rounds) == 2
    for name in ("decode", "h2d", "aggregate"):
        phase_events = [e for e in events if e["name"] == name]
        assert phase_events, f"no {name} spans"
        for e in phase_events:
            r = root(e)
            assert r["name"] == "round"
            assert r["ts"] - 1e-3 <= e["ts"]
            assert e["ts"] + e["dur"] <= r["ts"] + r["dur"] + 1e-3
