"""Compiler-level pinning of the sharded program's collective structure.

The mesh path's whole point is that aggregation happens as XLA collectives
over the interconnect. A refactor that silently drops the psum (e.g. an
axis_name that stops reaching `_mean_over_clients`) would still produce
running code — each shard would just average its local clients only — so
these tests inspect the COMPILED HLO: the mean path must contain
all-reduces and no all-gathers; the robust path must gather.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu import models
from fedtpu.core import round as round_lib
from fedtpu.parallel import (
    client_mesh,
    make_sharded_round_step,
    shard_batch,
    shard_state,
)


def _compiled_hlo(aggregator, eight_devices):
    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=4),
        fed=FedConfig(num_clients=8, aggregator=aggregator),
        steps_per_round=2,
    )
    m = models.create("mlp", num_classes=10)
    state = round_lib.init_state(
        m, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
    )
    mesh = client_mesh(8)
    rng = np.random.default_rng(0)
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(8, 2, 4, 32, 32, 3)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 10, size=(8, 2, 4)).astype(np.int32)),
        step_mask=jnp.ones((8, 2), bool),
        weights=jnp.ones((8,)),
        alive=jnp.ones((8,), bool),
    )
    step = make_sharded_round_step(m, cfg, mesh, donate=False)
    compiled = step.lower(
        shard_state(state, mesh, cfg.mesh_axis),
        shard_batch(batch, mesh, cfg.mesh_axis),
    ).compile()
    return compiled.as_text()


def test_mean_path_aggregates_via_all_reduce(eight_devices):
    hlo = _compiled_hlo("mean", eight_devices)
    assert hlo.count("all-reduce") > 0, "FedAvg psum vanished from the HLO"
    assert hlo.count("all-gather") == 0, (
        "mean aggregation should never materialise the full client axis"
    )


def test_median_path_gathers_the_client_axis(eight_devices):
    hlo = _compiled_hlo("median", eight_devices)
    assert hlo.count("all-gather") > 0, (
        "robust aggregation needs the global client axis (all_gather)"
    )


def test_async_mesh_tick_aggregates_via_all_reduce(eight_devices):
    """The async tick's buffer combine (and its damping-factor sums) must
    reach the interconnect as all-reduces, never by materialising the
    client axis — the same drop-the-psum refactor hazard as the sync path,
    now over fedbuff_combine."""
    from fedtpu.core import AsyncFederation

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=4, num_examples=128),
        fed=FedConfig(num_clients=8),
        steps_per_round=2,
    )
    fed = AsyncFederation(cfg, seed=0, buffer_k=2,
                          mesh=client_mesh(8, cfg.mesh_axis))
    d = fed._fed._ensure_device_data()
    arrive = jnp.zeros((8,), bool).at[:2].set(True)
    alive = jnp.ones((8,), bool)
    compiled = fed._step.lower(
        fed.state, *d, fed._fed.weights, arrive, alive, fed._fed._data_key
    ).compile()
    hlo = compiled.as_text()
    assert hlo.count("all-reduce") > 0, "async buffer psum vanished"
    assert hlo.count("all-gather") == 0, (
        "async mean aggregation should never materialise the client axis"
    )
