"""Core round-step semantics.

Property tests from SURVEY.md §4: our FedAvg equals a NumPy oracle over
client states; dead clients are excluded; momentum persists across rounds;
FedProx's proximal term shrinks local drift.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu import models
from fedtpu.core import round as round_lib
from fedtpu.core.client import make_local_update
from fedtpu.utils import trees


def tiny_cfg(**fed_kwargs) -> RoundConfig:
    return RoundConfig(
        model="mlp",
        num_classes=4,
        opt=OptimizerConfig(learning_rate=0.05, momentum=0.9, weight_decay=0.0),
        data=DataConfig(dataset="synthetic", batch_size=8),
        fed=FedConfig(num_clients=4, **fed_kwargs),
        steps_per_round=3,
    )


def make_batch(cfg, seed=0, alive=None, dim=6):
    rng = np.random.default_rng(seed)
    n, s, b = cfg.fed.num_clients, cfg.steps_per_round, cfg.data.batch_size
    x = rng.normal(size=(n, s, b, dim)).astype(np.float32)
    y = rng.integers(0, cfg.num_classes, size=(n, s, b)).astype(np.int32)
    return round_lib.RoundBatch(
        x=jnp.asarray(x),
        y=jnp.asarray(y),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool) if alive is None else jnp.asarray(alive),
    )


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_cfg()
    model = models.create(cfg.model, num_classes=cfg.num_classes)
    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32)
    )
    step = jax.jit(round_lib.make_round_step(model, cfg))
    local = make_local_update(model.apply, cfg)
    return cfg, model, state, step, local


def test_aggregate_matches_numpy_oracle(setup):
    """Global update == numpy mean of per-client locally-trained params."""
    cfg, model, state, step, local = setup
    batch = make_batch(cfg)

    # Run each client's local update independently (the oracle path).
    n = cfg.fed.num_clients
    rngs = jax.vmap(jax.random.fold_in)(
        state.client_rng, jnp.zeros((n,), jnp.int32)
    )
    client_params = []
    for c in range(n):
        out = local(
            state.params,
            state.batch_stats,
            jax.tree.map(lambda x: x[c], state.opt_state),
            batch.x[c],
            batch.y[c],
            batch.step_mask[c],
            rngs[c],
            state.round_idx,
        )
        client_params.append(out.params)

    expected = jax.tree.map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0),
        *client_params,
    )
    new_state, _ = step(state, batch)
    for e, g in zip(jax.tree.leaves(expected), jax.tree.leaves(new_state.params)):
        np.testing.assert_allclose(e, np.asarray(g), rtol=2e-4, atol=2e-5)


def test_dead_clients_excluded(setup):
    """A dead client contributes nothing — unlike the reference, which
    averages dead clients' stale checkpoint files (src/server.py:157-161)."""
    cfg, model, state, step, local = setup
    full = make_batch(cfg, seed=1)

    # Kill client 3; surviving clients' data unchanged.
    dead = round_lib.RoundBatch(
        x=full.x,
        y=full.y,
        step_mask=full.step_mask,
        weights=full.weights,
        alive=jnp.asarray([True, True, True, False]),
    )
    s_dead, m_dead = step(state, dead)
    assert float(m_dead.num_active) == 3.0

    # Oracle: mean over the three living clients only.
    n = cfg.fed.num_clients
    rngs = jax.vmap(jax.random.fold_in)(
        state.client_rng, jnp.zeros((n,), jnp.int32)
    )
    survivors = []
    for c in range(3):
        out = local(
            state.params,
            state.batch_stats,
            jax.tree.map(lambda x: x[c], state.opt_state),
            full.x[c], full.y[c], full.step_mask[c], rngs[c], state.round_idx,
        )
        survivors.append(out.params)
    expected = jax.tree.map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0), *survivors
    )
    for e, g in zip(jax.tree.leaves(expected), jax.tree.leaves(s_dead.params)):
        np.testing.assert_allclose(e, np.asarray(g), rtol=2e-4, atol=2e-5)


def test_all_dead_leaves_model_unchanged(setup):
    cfg, model, state, step, _ = setup
    batch = make_batch(cfg, seed=2, alive=np.zeros(4, bool))
    new_state, metrics = step(state, batch)
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(new_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_momentum_persists_across_rounds(setup):
    """Reference semantics: weights reload from global each round but the
    torch optimizer (momentum) lives on in the client process
    (src/main.py:99,130-134)."""
    cfg, model, state, step, _ = setup
    b0 = make_batch(cfg, seed=3)
    s1, _ = step(state, b0)
    # After one round momentum buffers must be nonzero and carried forward.
    mom = jax.tree.leaves(s1.opt_state.momentum)
    assert any(float(jnp.abs(m).max()) > 0 for m in mom)
    assert int(s1.round_idx) == 1


def test_weighted_vs_uniform_differ(setup):
    cfg, model, state, step, _ = setup
    batch = make_batch(cfg, seed=4)
    uneven = round_lib.RoundBatch(
        x=batch.x, y=batch.y, step_mask=batch.step_mask,
        weights=jnp.asarray([10.0, 1.0, 1.0, 1.0]), alive=batch.alive,
    )
    s_w, _ = step(state, uneven)

    cfg_u = dataclasses.replace(cfg, fed=dataclasses.replace(cfg.fed, weighted=False))
    step_u = jax.jit(round_lib.make_round_step(model, cfg_u))
    s_u, _ = step_u(state, uneven)
    diffs = [
        float(jnp.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(s_w.params), jax.tree.leaves(s_u.params))
    ]
    assert max(diffs) > 1e-6


def test_fedprox_reduces_drift():
    """With a large mu the locally-trained params stay closer to global."""
    drifts = {}
    for mu in (0.0, 10.0):
        cfg = tiny_cfg(algorithm="fedprox", fedprox_mu=mu)
        model = models.create(cfg.model, num_classes=cfg.num_classes)
        state = round_lib.init_state(
            model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.float32)
        )
        local = make_local_update(model.apply, cfg)
        batch = make_batch(cfg, seed=5)
        out = local(
            state.params, state.batch_stats,
            jax.tree.map(lambda x: x[0], state.opt_state),
            batch.x[0], batch.y[0], batch.step_mask[0],
            jax.random.PRNGKey(7), state.round_idx,
        )
        drifts[mu] = float(
            trees.tree_norm(trees.tree_sub(out.params, state.params))
        )
    assert drifts[10.0] < drifts[0.0]


def test_masked_steps_are_noops(setup):
    """Padding steps must not change params (static-shape ragged shards)."""
    cfg, model, state, step, local = setup
    batch = make_batch(cfg, seed=6)
    sm = np.ones((cfg.fed.num_clients, cfg.steps_per_round), bool)
    sm[:, -1] = False
    masked = round_lib.RoundBatch(
        x=batch.x, y=batch.y, step_mask=jnp.asarray(sm),
        weights=batch.weights, alive=batch.alive,
    )
    # Oracle: run with one fewer real step by zeroing the last step's data —
    # results must match running with the mask.
    out_masked = local(
        state.params, state.batch_stats,
        jax.tree.map(lambda x: x[0], state.opt_state),
        masked.x[0], masked.y[0], masked.step_mask[0],
        jax.random.PRNGKey(9), state.round_idx,
    )
    out_short = local(
        state.params, state.batch_stats,
        jax.tree.map(lambda x: x[0], state.opt_state),
        masked.x[0][:-1], masked.y[0][:-1],
        jnp.ones((cfg.steps_per_round - 1,), bool),
        jax.random.PRNGKey(9), state.round_idx,
    )
    # Same number of effective steps; params equal.
    assert float(out_masked.num_steps) == float(out_short.num_steps)
    for a, b in zip(jax.tree.leaves(out_masked.params), jax.tree.leaves(out_short.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
