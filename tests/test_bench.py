"""bench.py measurement-path regression.

bench.py is the driver's headline artifact; a silent breakage there costs a
whole round of evidence. This runs ``_measure`` at a shrunk configuration on
the CPU platform (same code path as the chip: engine construction, AOT
compile of the fused multi-round program, cost analysis, timed dispatches)
and checks the JSON contract.
"""

import sys

import pytest


@pytest.fixture()
def bench(monkeypatch):
    monkeypatch.syspath_prepend(".")
    import bench as bench_mod

    monkeypatch.setattr(bench_mod, "NUM_CLIENTS", 4)
    monkeypatch.setattr(bench_mod, "STEPS_PER_ROUND", 2)
    monkeypatch.setattr(bench_mod, "BATCH", 8)
    monkeypatch.setattr(bench_mod, "TIMED_ROUNDS", 3)
    monkeypatch.setattr(bench_mod, "TRIALS", 2)
    return bench_mod


def test_measure_contract(bench):
    result = bench._measure()
    assert result["metric"].startswith("fedavg_client_epochs_per_sec")
    assert result["unit"] == "client-epochs/sec/chip"
    assert result["value"] > 0
    assert result["rounds_per_sec"] > 0
    # Normalisation: value = rounds/sec * clients / devices.
    assert result["value"] == pytest.approx(
        result["rounds_per_sec"] * result["num_clients"] / result["n_devices"],
        rel=1e-2,
    )
    # Both fields are independently rounded in the JSON (value to 3 dp,
    # vs_baseline to 4 dp), so compare with an absolute slack of one ulp
    # of the coarser rounding.
    assert result["vs_baseline"] == pytest.approx(
        result["value"] / bench.TARGET_PER_CHIP, abs=1e-3
    )
    # FLOPs come from the single-round program (scan-body accounting).
    assert result.get("flops_per_round", 0) > 0


def test_variant_run_is_self_distinguishing(bench, monkeypatch):
    """A variant bench artifact must be unmistakable even to a consumer
    keyed on 'metric' alone (ADVICE r5): suffixed metric, no vs_baseline.
    Exercises the labeling helper directly — re-running a full _measure for
    this would cost ~1 min of tier-1 budget for no extra coverage."""
    base = {"metric": bench.METRIC, "value": 1.0, "vs_baseline": 0.005}
    # Parity config: labels untouched.
    assert bench._apply_variant_labels(dict(base)) == base
    monkeypatch.setattr(bench, "_TIMED_ROUNDS_ENV", "3")
    result = bench._apply_variant_labels(dict(base))
    assert result["metric"] == bench.METRIC + "_variant"
    assert "vs_baseline" not in result
    assert result["variant"]["timed_rounds"] == bench.TIMED_ROUNDS
    monkeypatch.setattr(bench, "_TIMED_ROUNDS_ENV", "")
    monkeypatch.setattr(bench, "MOMENTUM_DTYPE", "bfloat16")
    result = bench._apply_variant_labels(dict(base))
    assert result["metric"].endswith("_variant")
    assert result["variant"]["momentum_dtype"] == "bfloat16"
    assert "timed_rounds" not in result["variant"]


def test_compression_microbench_contract(bench, monkeypatch):
    """--compression-microbench JSON contract at a seconds-scale config:
    dispatch counts present and the flat stage strictly cheaper than the
    per-leaf stage (the <=10% acceptance gate itself is pinned on a
    many-leaf model in tests/test_flat_layout.py)."""
    monkeypatch.setenv("FEDTPU_MB_MODEL", "smallcnn")
    monkeypatch.setenv("FEDTPU_MB_CLIENTS", "2")
    monkeypatch.setenv("FEDTPU_MB_REPS", "1")
    result = bench._compression_microbench()
    assert result["metric"] == "compression_packed_vs_per_leaf"
    assert result["num_leaves"] > 0
    assert result["padded_row"] % 128 == 0
    for kind in ("topk", "int8"):
        c = result["codecs"][kind]
        assert 0 < c["flat_dispatches"] < c["per_leaf_dispatches"]
        assert c["dispatch_ratio"] == pytest.approx(
            c["flat_dispatches"] / c["per_leaf_dispatches"], abs=1e-3
        )
        assert c["per_leaf_host_ms"] > 0 and c["flat_host_ms"] > 0
    assert result["value"] == max(
        c["dispatch_ratio"] for c in result["codecs"].values()
    )


def test_server_pipeline_microbench_contract(bench, monkeypatch, tmp_path):
    """--server-pipeline-microbench at a seconds-scale config: schema,
    artifact emission, and the parity bit the acceptance criterion leans on
    (the >=2x densenet/64-client gate itself is pinned by the committed
    artifacts/SERVER_PIPELINE_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_SPB_MODELS", "smallcnn")
    monkeypatch.setenv("FEDTPU_SPB_CLIENTS", "4")
    monkeypatch.setenv("FEDTPU_SPB_REPS", "1")
    result = bench._server_pipeline_microbench()
    assert result["metric"] == "server_pipeline_post_barrier"
    assert result["num_clients"] == 4
    assert result["headline_model"] == "smallcnn"
    m = result["models"]["smallcnn"]
    assert m["padded_row"] % 128 == 0
    assert m["barrier"]["post_barrier_s"] > 0
    assert m["stream"]["post_barrier_s"] > 0
    assert m["barrier"]["decode_ms_per_reply"] > 0
    assert m["stream"]["decode_h2d_ms_per_reply"] > 0
    assert m["barrier"]["host_delta_bytes"] > 0
    assert m["stream"]["host_delta_bytes"] > 0
    assert m["post_barrier_speedup"] == pytest.approx(
        m["barrier"]["post_barrier_s"] / m["stream"]["post_barrier_s"],
        rel=0.02,
    )
    # The two paths must agree BITWISE on the aggregated params — the
    # stream pipeline is a perf change, never a numerics change.
    assert m["mean_bit_identical"] is True
    assert result["value"] == m["post_barrier_speedup"]
    # Artifact written atomically next to the JSON line.
    path = os.path.join(str(art), "SERVER_PIPELINE_MICROBENCH.json")
    assert os.path.exists(path)
    with open(path) as f:
        assert json_mod.load(f) == result


def test_salvage_json_takes_last_valid_object(bench):
    text = 'garbage\n{"a": 1}\nnot json\n{"metric": "x", "value": 1}\ntrailing'
    assert bench._salvage_json(text) == '{"metric": "x", "value": 1}'
    assert bench._salvage_json("no json here") is None
    assert bench._salvage_json("") is None


def test_peak_lookup_covers_observed_device_kinds(bench):
    assert bench._peak_for("TPU v5 lite") == 197e12
    assert bench._peak_for("TPU v5e") == 197e12
    assert bench._peak_for("TPU v4") == 275e12
    assert bench._peak_for("weird accelerator") is None


def test_acc_full_config_shape(monkeypatch):
    """The --acc-full harness mode must keep config 4's defining traits
    (reference ``BASELINE.json`` config 4: resnet18, cifar100, 5 local
    epochs) at the climbing-curve sizing both harnesses share — the torch
    row in ``artifacts/PARITY_ACC_FULL.jsonl`` was measured against exactly
    this shape, and a silent drift would desync the comparison."""
    monkeypatch.syspath_prepend(".")
    monkeypatch.delenv("FEDTPU_SMOKE", raising=False)
    import bench_parity

    (name, cfg), = list(bench_parity.acc_full_configs())
    assert name == "4_accfull_resnet18_cifar100h_4c_5ep"
    assert cfg.model == "resnet18"
    assert cfg.num_classes == 100
    assert cfg.data.dataset == "cifar100_hard"
    assert cfg.fed.local_epochs == 5
    assert cfg.fed.num_clients == 4
    assert cfg.fed.num_rounds == 12
    assert cfg.data.device_layout == "gather"  # committed-artifact semantics


def test_unreachable_diagnostic_carries_live_pointer(
    bench, monkeypatch, capsys, tmp_path
):
    """A wedged-tunnel bench moment must still record WHERE this round's
    live-captured number lives (value stays honestly 0.0 — the driver's
    number must be the driver's run). Uses a synthetic artifact dir so the
    test holds in any checkout (fresh export, pruned artifacts, code-only
    CI), not just ones carrying committed bench data."""
    import json

    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "BENCH_LIVE_r99_stale.json").write_text(json.dumps(
        {"value": 100.0, "unit": "client-epochs/sec/chip",
         "captured_at": "2026-01-01T00:00:00"}))
    (art / "BENCH_LIVE_r99.json").write_text(json.dumps(
        {"value": 123.4, "unit": "client-epochs/sec/chip",
         "captured_at": "2026-07-31T12:00:00", "device_kind": "TPU v5 lite"}))
    (art / "BENCH_LIVE_r99_truncated.json").write_text('{"value": 999.9, ')
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setattr(bench, "_backend_reachable", lambda: (False, "probe timed out"))
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert "backend unreachable" in out["error"]
    # Most recent VALID artifact wins; the truncated one must be skipped.
    assert out["live_artifact"] == "artifacts/BENCH_LIVE_r99.json"
    assert out["live_value"] == 123.4


def test_bench_model_wrapper_smoke(tmp_path, monkeypatch):
    """tools/bench_model_tpu.py end-to-end at a seconds-scale CPU config —
    the wrapper gates a TPU-window job, so a wrapper bug costs real chip
    time. FEDTPU_BM_PLATFORM=cpu pins the platform IN-PROCESS (the env var
    alone is ignored under the axon plugin)."""
    import json as json_mod
    import os
    import subprocess
    import sys as sys_mod

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               FEDTPU_BM_PLATFORM="cpu", FEDTPU_BM_MODEL="mlp",
               FEDTPU_BM_DATASET="synthetic", FEDTPU_BM_CLIENTS="4",
               FEDTPU_BM_BATCH="8", FEDTPU_BM_STEPS="2",
               FEDTPU_BM_ROUNDS="2", FEDTPU_BM_OUT="SMOKE_BM_TEST.json")
    try:
        proc = subprocess.run(
            [sys_mod.executable, os.path.join(repo, "tools", "bench_model_tpu.py")],
            capture_output=True, text=True, timeout=300, env=env, cwd=repo,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        line = json_mod.loads(proc.stdout.strip().splitlines()[-1])
        assert line["metric"] == "fedavg_rounds_per_sec_synthetic_mlp_4clients_1chip"
        assert line["rounds_per_sec"] > 0
        assert "error" not in line
        art = os.path.join(repo, "artifacts", "SMOKE_BM_TEST.json")
        assert os.path.exists(art)
    finally:
        try:
            os.remove(os.path.join(repo, "artifacts", "SMOKE_BM_TEST.json"))
        except OSError:
            pass


def test_obs_plane_microbench_contract(bench, monkeypatch, tmp_path):
    """--obs-plane-microbench at a seconds-scale config: schema + artifact
    emission (the <=1%-on-densenet acceptance gate itself is pinned by the
    committed artifacts/OBS_PLANE_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_OB_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_OB_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_OB_REPS", "2")
    result = bench._obs_plane_microbench()
    assert result["metric"] == "obs_plane_overhead"
    assert result["value"] > 0
    assert result["per_rpc_us"]["inject"] > 0
    assert result["per_rpc_us"]["extract"] > 0
    assert result["per_round_status_us"] > 0
    # The attributable arithmetic is auditable from its own parts.
    clients = result["num_clients"]
    per_round = clients * (
        result["per_rpc_us"]["inject"] + result["per_rpc_us"]["extract"]
    ) + result["per_round_status_us"]
    assert result["per_round_obs_us"] == pytest.approx(per_round, rel=1e-3)
    assert result["gate_pct"] == 1.0
    assert isinstance(result["passes_gate"], bool)
    assert result["noise_floor_pct"] >= 0
    assert set(result["round_ms"]) == {"bare", "obs"}
    assert all(v > 0 for v in result["round_ms"].values())
    path = os.path.join(str(art), "OBS_PLANE_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_chaos_overhead_microbench_contract(bench, monkeypatch, tmp_path):
    """--chaos-overhead-microbench at a seconds-scale config: schema +
    artifact emission (the <=1%-on-densenet acceptance gate itself is
    pinned by the committed artifacts/CHAOS_OVERHEAD_MICROBENCH.json run).
    """
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_CH_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_CH_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_CH_REPS", "2")
    result = bench._chaos_overhead_microbench()
    assert result["metric"] == "chaos_overhead"
    assert result["value"] > 0
    assert result["per_rpc_us"]["decide"] > 0
    # The attributable arithmetic is auditable from its own parts:
    # two consults (StartTrain + SendModel) per client per round.
    per_round = result["num_clients"] * 2 * result["per_rpc_us"]["decide"]
    assert result["per_round_chaos_us"] == pytest.approx(per_round, rel=1e-3)
    assert result["gate_pct"] == 1.0
    assert isinstance(result["passes_gate"], bool)
    assert result["noise_floor_pct"] >= 0
    assert set(result["round_ms"]) == {"bare", "chaos"}
    assert all(v > 0 for v in result["round_ms"].values())
    path = os.path.join(str(art), "CHAOS_OVERHEAD_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_screening_overhead_microbench_contract(bench, monkeypatch, tmp_path):
    """--screening-overhead-microbench at a seconds-scale config: schema +
    artifact emission (the <=1%-on-densenet acceptance gate itself is
    pinned by the committed artifacts/SCREENING_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_SC_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_SC_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_SC_REPS", "2")
    result = bench._screening_overhead_microbench()
    assert result["metric"] == "screening_overhead"
    assert result["value"] > 0
    assert result["per_round_screen_us"] > 0
    assert result["padded_row"] % 128 == 0
    # The attributable arithmetic is auditable from its own parts.
    assert result["value"] == pytest.approx(
        result["per_round_screen_us"]
        / (result["round_ms"]["bare"] * 1e3) * 100.0,
        rel=1e-2,
    )
    assert result["gate_pct"] == 1.0
    assert isinstance(result["passes_gate"], bool)
    assert result["noise_floor_pct"] >= 0
    assert set(result["round_ms"]) == {"bare", "screen"}
    assert all(v > 0 for v in result["round_ms"].values())
    path = os.path.join(str(art), "SCREENING_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_fencing_overhead_microbench_contract(bench, monkeypatch, tmp_path):
    """--fencing-overhead-microbench at a seconds-scale config: schema +
    artifact emission (the <=1%-on-densenet acceptance gate itself is
    pinned by the committed artifacts/FENCING_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_FE_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_FE_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_FE_REPS", "2")
    result = bench._fencing_overhead_microbench()
    assert result["metric"] == "fencing_overhead"
    assert result["value"] > 0
    assert result["per_rpc_us"]["inject_validate"] > 0
    # The attributable arithmetic is auditable from its own parts:
    # StartTrain + SendModel per client, plus ping + replica push.
    assert result["rpcs_per_round"] == result["num_clients"] * 2 + 2
    per_round = result["rpcs_per_round"] * result["per_rpc_us"]["inject_validate"]
    assert result["per_round_fencing_us"] == pytest.approx(per_round, rel=1e-3)
    assert result["gate_pct"] == 1.0
    assert isinstance(result["passes_gate"], bool)
    assert result["noise_floor_pct"] >= 0
    assert set(result["round_ms"]) == {"bare", "fenced"}
    assert all(v > 0 for v in result["round_ms"].values())
    path = os.path.join(str(art), "FENCING_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_fencing_microbench_committed_gate():
    """The committed densenet-scale artifact must actually pass the <=1%
    gate: per-RPC epoch inject + fence validation across every fenced RPC
    a synchronous round issues."""
    result = _committed_artifact("FENCING_MICROBENCH.json")
    assert result["metric"] == "fencing_overhead"
    assert result["model"] == "densenet_cifar"
    assert result["passes_gate"] is True
    assert result["value"] <= 1.0


def test_checkpoint_overhead_microbench_contract(bench, monkeypatch, tmp_path):
    """--checkpoint-overhead-microbench at a seconds-scale config: schema
    + artifact emission (the <=1%-on-densenet acceptance gate itself is
    pinned by the committed artifacts/CHECKPOINT_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_CK_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_CK_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_CK_REPS", "2")
    monkeypatch.setenv("FEDTPU_CK_SAVES", "4")
    result = bench._checkpoint_overhead_microbench()
    assert result["metric"] == "checkpoint_overhead"
    assert result["value"] > 0
    # The attributable arithmetic is auditable from its own parts.
    assert result["value"] == pytest.approx(
        result["per_save_ms"]["async_call"]
        / result["round_ms"]["bare"] * 100.0,
        rel=1e-2,
    )
    # The split the background writer exists for: the loop-side call must
    # be far cheaper than the full inline save it replaces, and the
    # writer-side write wall is reported so the overlap claim is
    # auditable.
    assert result["per_save_ms"]["async_call"] < result["per_save_ms"]["sync_full"]
    assert result["per_save_ms"]["writer_write"] > 0
    assert result["checkpoint_bytes"] > 0
    assert result["gate_pct"] == 1.0
    assert isinstance(result["passes_gate"], bool)
    assert result["noise_floor_pct"] >= 0
    assert set(result["round_ms"]) == {"bare", "ckpt"}
    assert all(v > 0 for v in result["round_ms"].values())
    path = os.path.join(str(art), "CHECKPOINT_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_checkpoint_microbench_committed_gate():
    """The committed densenet-scale artifact must actually pass the <=1%
    gate: loop-side cost of one background save per round."""
    result = _committed_artifact("CHECKPOINT_MICROBENCH.json")
    assert result["metric"] == "checkpoint_overhead"
    assert result["model"] == "densenet_cifar"
    assert result["passes_gate"] is True
    assert result["value"] <= 1.0


def test_disaster_soak_artifact_contract():
    """Schema + gate contract of the committed total-process-loss drill
    (tools/chaos_soak.py --disaster): the durability PR's acceptance
    evidence. The soak re-runs as `slow` (tests/test_disaster.py); this
    pins what it must have proven."""
    result = _committed_artifact("DISASTER_SOAK.json")
    assert result["ok"] is True
    cfg = result["config"]
    assert cfg["rounds"] >= 16
    assert 4 <= cfg["kill_round"] <= cfg["rounds"] - 2
    # The restart fell back past BOTH silently-corrupted generations
    # (torn newest + bit-rotten next) to the newest verified one — the
    # restore-time verification counter proves the fallback path ran.
    assert result["checkpoint_fallbacks"] == 2
    assert result["resume_round"] == cfg["expected_resume_round"]
    # Exact-cover monotone lineage under supersession: the crash voided
    # the never-durable tail; durable history + restart covers 0..N-1.
    lineage = result["lineage"]
    assert lineage["strictly_monotone"] and lineage["exact_cover"]
    assert lineage["committed"] == cfg["rounds"]
    assert lineage["superseded"] == cfg["kill_round"] - result["resume_round"]
    # Survivors resynced with no re-registration and no manual cleanup.
    assert result["post_restart_joins"] == 0
    assert result["manual_interventions"] == 0
    assert result["gen1_rc"] != 0 and result["gen2_rc"] == 0
    # The recovery was trajectory-neutral: bit-identical final model.
    assert result["bit_identical_vs_control"] is True
    assert (
        result["model_fingerprint"]["disaster"]
        == result["model_fingerprint"]["control"]
    )
    assert result["final_round"]["disaster"] == cfg["rounds"] - 1
    for e in result["final_evals"]:
        assert e["loss"] == e["loss"]


def test_partition_soak_artifact_contract():
    """Schema + gate contract of the committed three-leg partition-heal
    soak (tools/chaos_soak.py --partition): the split-brain-elimination
    PR's acceptance evidence. The soak re-runs as `slow`
    (tests/test_fencing.py); this pins what it must have proven."""
    result = _committed_artifact("PARTITION_SOAK.json")
    assert result["ok"] is True and result["soak"] == "partition"
    legs = result["legs"]
    assert set(legs) == {"symmetric", "asymmetric", "gray"}
    for leg in legs.values():
        assert leg["ok"] is True
        # Zero transient client deaths; a real fence + live rejection.
        assert leg["client_deaths"] == 0
        assert leg["fences"] >= 1
        assert leg["stale_rejections"] >= 1
        assert leg["acting_rounds"] >= 1
        # Bounded failover churn, every promotion eventually demoted.
        assert 1 <= leg["promotions"] <= 8
        assert leg["demotions"] == leg["promotions"]
        # The fenced side re-based PAST the winner (1 -> 2 -> >= 3).
        assert leg["final_epoch"] >= 3
    # Symmetric: cut side never forked, and the heal was
    # trajectory-neutral (bit-identical to the no-partition control).
    sym = legs["symmetric"]
    assert sym["bit_identical_vs_control"] is True
    assert sym["stale_fork_rounds"] == 0 and sym["promotions"] == 1
    # Asymmetric: a REAL split-brain — the stale primary committed >= 1
    # forked round that the epoch-supersession fold voided.
    assert legs["asymmetric"]["stale_fork_rounds"] >= 1


def test_byzantine_soak_artifact_contract():
    """Schema + gate contract of the committed 100-round Byzantine soak
    (tools/chaos_soak.py --byzantine): the attack-harness PR's acceptance
    evidence. The soak re-runs as `slow` (tests/test_byzantine.py); this
    pins what it must have proven."""
    result = _committed_artifact("BYZANTINE_SOAK.json")
    assert result["ok"] is True
    cfg = result["config"]
    assert cfg["rounds"] >= 100
    assert cfg["malicious"] >= round(0.28 * cfg["clients"])  # ~30% regime
    assert cfg["error_p"] >= 0.10                            # + wire faults
    # Monotone lineage, no lost rounds.
    lineage = result["lineage"]
    assert lineage["committed"] == cfg["rounds"]
    assert lineage["exact_cover"]
    obs = result["observed"]
    # Zero honest deaths; every attacker quarantined AND evicted through
    # the live membership machinery; no honest eviction, no honest client
    # left quarantined.
    assert obs["client_deaths"] == 0
    assert obs["quarantines"] >= cfg["malicious"]
    assert obs["evictions_quarantine"] == cfg["malicious"]
    assert result["attackers_still_members"] == []
    assert result["honest_evicted"] == []
    assert result["honest_quarantined_at_end"] == []
    # Every layer demonstrably fired: attacks, screening, wire chaos,
    # retries.
    assert obs["attack_injected"] > 0
    assert obs["screening_rejected"] >= cfg["malicious"]
    assert obs["chaos_injected"] > 0 and obs["rpc_retries"] > 0
    # Honest clients finished with finite evals.
    assert len(result["honest_final_evals"]) == cfg["clients"] - cfg["malicious"]
    for e in result["honest_final_evals"]:
        assert e["loss"] == e["loss"]


def test_cohort_scale_contract(bench, monkeypatch, tmp_path):
    """--cohort-scale at a seconds-scale config: schema + artifact emission
    and the two claims the acceptance criterion leans on — per-seat device
    state grows with the cohort, and is byte-identical under a different
    population (O(cohort), not O(population)). The 10k-clients-per-round
    gate itself is pinned by the committed artifacts/COHORT_SCALE.json run.
    """
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_CS_MODEL", "mlp_tiny")
    monkeypatch.setenv("FEDTPU_CS_POPULATION", "256")
    monkeypatch.setenv("FEDTPU_CS_COHORTS", "16,32")
    monkeypatch.setenv("FEDTPU_CS_ROUNDS", "1")
    monkeypatch.setenv("FEDTPU_CS_EXAMPLES", "1024")
    result = bench._cohort_scale()
    assert result["metric"] == "cohort_scale"
    assert result["population"] == 256
    assert result["value"] == 32  # largest cohort actually ran, fully live
    assert [p["cohort"] for p in result["curve"]] == [16, 32]
    for p in result["curve"]:
        assert p["clients_per_round"] == p["cohort"]  # everyone available
        assert p["round_s"] > 0 and p["clients_per_sec"] > 0
        assert p["seat_state_bytes"] > 0 and p["host_table_bytes"] > 0
        assert p["heterogeneity_index"] > 0  # the default scenario is skewed
    a, b = result["curve"]
    assert b["seat_state_bytes"] == 2 * a["seat_state_bytes"]  # O(cohort)
    mm = result["memory_model"]
    assert mm["o_cohort"] is True
    assert (
        mm["seat_state_bytes_full_population"]
        == mm["seat_state_bytes_half_population"]
    )
    path = os.path.join(str(art), "COHORT_SCALE.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def test_telemetry_microbench_contract(bench, monkeypatch, tmp_path):
    """--telemetry-microbench at a seconds-scale config: schema, artifact
    emission, and a valid trace-check leg (the <1%-on-densenet acceptance
    gate itself is pinned by the committed
    artifacts/TELEMETRY_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_TB_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_TB_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_TB_REPS", "1")
    result = bench._telemetry_microbench()
    assert result["metric"] == "telemetry_overhead"
    # Headline = attributable basic-mode cost: positive, and a real span
    # (trace) can never be cheaper than the no-op path it replaces.
    assert result["value"] == result["attributable_pct"]["basic"] > 0
    assert result["per_round_instrument_us"]["trace"] > \
        result["per_round_instrument_us"]["basic"]
    assert result["noise_floor_pct"] >= 0
    assert set(result["ab_delta_pct"]) == {"basic", "trace"}
    assert set(result["round_ms"]) == {"off", "basic", "trace"}
    assert all(v > 0 for v in result["round_ms"].values())
    assert result["instrument_ns"]["counter_inc"] > 0
    tc = result["trace_check"]
    assert tc["rounds"] == 2
    assert tc["nonnegative_durations"] is True
    assert tc["phases_nest_under_round"] is True
    assert all(v > 0 for v in tc["phase_span_counts"].values())
    # Both artifacts written.
    assert os.path.exists(os.path.join(str(art), "TELEMETRY_TRACE.json"))
    path = os.path.join(str(art), "TELEMETRY_MICROBENCH.json")
    with open(path) as f:
        assert json_mod.load(f) == result


def _committed_artifact(name):
    import json as json_mod
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", name)
    assert os.path.exists(path), f"committed artifact {name} missing"
    with open(path) as f:
        return json_mod.load(f)


def test_churn_soak_artifact_contract():
    """Schema + gate contract of the committed 1k-round churn-soak
    artifact (tools/chaos_soak.py --churn): the elastic-membership PR's
    acceptance evidence. The soak itself re-runs as a `slow` test
    (tests/test_membership.py); this pins what it must have proven."""
    result = _committed_artifact("CHURN_SOAK.json")
    assert result["ok"] is True
    cfg = result["config"]
    assert cfg["rounds"] >= 1000
    assert 0 < cfg["upgrade_round"] < cfg["rounds"]
    # Monotone lineage: every round committed exactly once across the
    # three coordinator generations.
    lineage = result["lineage"]
    assert lineage["committed"] == cfg["rounds"]
    assert lineage["strictly_monotone"] and lineage["exact_cover"]
    gens = result["generations"]
    assert gens["gen1"] == cfg["upgrade_round"]
    assert gens["acting"] >= 1 and gens["gen2"] >= 1
    assert sum(gens.values()) == cfg["rounds"]
    # Zero transient deaths: every observed death is a scheduled silent
    # leave; the chaos layer injected + the retry layer absorbed.
    obs = result["observed"]
    assert obs["client_deaths"] == result["expected_silent_deaths"]
    assert obs["chaos_injected"] > 0 and obs["rpc_retries"] > 0
    assert obs["round_aborts"] == 0
    # Churn actually churned, through the real Join/Leave RPCs.
    sched = result["scheduled"]
    assert min(sched["join"], sched["silent_leave"],
               sched["stale_rejoin"], sched["leave"], sched["rejoin"]) > 0
    assert obs["membership_joins"] == sched["join"] + sched["rejoin"]
    assert obs["membership_evictions"] == sched["leave"]
    # Zero lost rounds across the upgrade: bit-identical to the
    # unupgraded control, per-client round counts equal.
    assert result["bit_identical_vs_control"] is True
    counts = result["client_round_counts"]
    assert counts["control"] == counts["upgraded"]
    # Flat memory profile from the /statusz RSS gauge.
    mem = result["memory"]
    assert mem["settled_samples"] >= 8
    assert mem["growth_pct"] < 8.0
    assert mem["gate"].endswith("(enforced)")


def test_rolling_upgrade_artifact_contract():
    """Schema contract of the committed rolling-upgrade drill artifact
    (tools/rolling_upgrade.py): zero-loss + bit-identical handover."""
    result = _committed_artifact("ROLLING_UPGRADE.json")
    assert result["ok"] is True
    cfg = result["config"]
    lineage = result["lineage"]
    assert lineage["committed"] == cfg["rounds"]
    assert lineage["strictly_monotone"] and lineage["exact_cover"]
    gens = result["generations"]
    assert gens["gen1"] == cfg["upgrade_round"] and gens["acting"] >= 1
    assert result["bit_identical"] is True
    counts = result["client_round_counts"]
    assert counts["control"] == counts["upgraded"]
    # The mid-run joiner is in the final roster (one more than startup).
    assert result["roster"]["upgraded"]["size"] == cfg["clients"] + 1


# ------------------------------------------- performance observatory legs
@pytest.mark.slow
def test_mfu_profile_schema_contract(monkeypatch, tmp_path):
    """``bench.py --mfu-profile`` schema at a CPU smoke config: the sweep
    rows carry the timing + cost-analysis + roofline keys the MFU_PROFILE_*
    consumers read. The wrapper reloads tools/bench_profile_tpu so the
    FEDTPU_SMOKE/PLATFORM knobs bind; here we drive run() directly at an
    even smaller shape and redirect its artifact dir via __file__."""
    import importlib
    import json as json_mod
    import os

    monkeypatch.setenv("FEDTPU_PLATFORM", "cpu")
    monkeypatch.setenv("FEDTPU_SMOKE", "1")  # float32, no traced dispatch
    # Peak overrides so the roofline block derives on the CPU backend.
    monkeypatch.setenv("FEDTPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("FEDTPU_PEAK_HBM_BYTES", "5e10")
    tools = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
    )
    monkeypatch.syspath_prepend(tools)
    import bench_profile_tpu as bpt

    bpt = importlib.reload(bpt)  # bind the smoke constants
    monkeypatch.setattr(bpt, "NUM_CLIENTS", 2)
    monkeypatch.setattr(bpt, "STEPS_PER_ROUND", 1)
    monkeypatch.setattr(bpt, "TIMED_ROUNDS", 2)
    monkeypatch.setattr(bpt, "BATCHES", (8,))
    monkeypatch.setattr(bpt, "TRIALS", 1)
    assert bpt.TRACE_DISPATCH is False  # smoke default: no CPU op-trace
    # run() roots the artifacts dir off __file__ — point it into tmp.
    monkeypatch.setattr(
        bpt, "__file__", str(tmp_path / "tools" / "bench_profile_tpu.py")
    )
    result = bpt.run(tag="pytest")
    assert result["timed_rounds_per_dispatch"] == 2
    assert result["num_clients"] == 2
    assert result["steps_per_round"] == 1
    assert len(result["configs"]) == 1
    row = result["configs"][0]
    assert row["batch"] == 8
    assert row["rounds_per_sec"] > 0
    assert row["sec_per_fused_dispatch"] > 0
    assert len(row["trial_times_s"]) == 1
    assert row["device_kind"]
    assert row["flops_per_round"] > 0 and row["bytes_per_round"] > 0
    # Shared peak-table/roofline path (fedtpu.obs.profile): with peaks
    # overridden the MFU + roofline placement must all derive.
    assert 0 < row["mfu"] < 1
    assert row["hbm_util"] > 0
    assert row["arith_intensity_flops_per_byte"] == pytest.approx(
        row["flops_per_round"] / row["bytes_per_round"], rel=1e-2
    )
    assert row["ridge_point_flops_per_byte"] == pytest.approx(20.0)
    assert row["roofline_bound"] in ("compute", "bandwidth")
    assert row["roofline_utilization"] > 0
    # Incremental artifact persist landed in the redirected dir.
    with open(tmp_path / "artifacts" / "MFU_PROFILE_pytest.json") as fh:
        assert json_mod.load(fh) == result


@pytest.mark.slow
def test_mfu_microbench_contract(bench, monkeypatch, tmp_path):
    """``bench.py --mfu-microbench`` at a seconds-scale mlp config: schema,
    artifact emission, and the estimator invariants (attributable cost =
    per-round accounting over the bare round wall; the densenet-scale <=1%
    gate itself is pinned by the committed artifact in test_perf_obs.py)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_MF_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_MF_CLIENTS", "2")
    monkeypatch.setenv("FEDTPU_MF_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_MF_REPS", "1")
    monkeypatch.setenv("FEDTPU_MF_BATCH", "8")
    result = bench._mfu_microbench()
    assert result["metric"] == "mfu_accounting_overhead"
    assert result["gate_pct"] == 1.0
    assert result["value"] > 0
    assert result["passes_gate"] == (result["value"] <= 1.0)
    assert result["per_round_accounting_us"] > 0
    assert result["value"] == pytest.approx(
        result["per_round_accounting_us"]
        / (result["round_ms"]["off"] * 1e3) * 100.0,
        rel=0.05,
    )
    assert result["cost_model_build_s"] > 0
    assert result["flops_per_round"] > 0
    assert result["flops_source"] in ("analytic", "xla")
    # FEDTPU_PEAK_FLOPS defaulted in by the bench: the full gauge path ran.
    assert result["sample_mfu"] is not None and result["sample_mfu"] > 0
    assert result["model"] == "mlp" and result["num_clients"] == 2
    assert set(result["round_ms"]) == {"off", "mfu"}
    with open(art / "MFU_ACCOUNTING_MICROBENCH.json") as fh:
        assert json_mod.load(fh) == result


# --------------------------------------------- mixed-precision fast path
def test_variant_labels_cover_perf_knobs(bench, monkeypatch):
    """FEDTPU_COMPUTE_DTYPE / FEDTPU_MEGABATCH_CLIENTS runs must be
    self-distinguishing like every other experiment knob: suffixed metric,
    no vs_baseline, knob values recorded in the variant block."""
    base = {"metric": bench.METRIC, "value": 1.0, "vs_baseline": 0.005}
    monkeypatch.setattr(bench, "COMPUTE_DTYPE", "bfloat16_mixed")
    monkeypatch.setattr(bench, "MEGABATCH_CLIENTS", 8)
    result = bench._apply_variant_labels(dict(base))
    assert result["metric"] == bench.METRIC + "_variant"
    assert "vs_baseline" not in result
    assert result["variant"]["compute_dtype"] == "bfloat16_mixed"
    assert result["variant"]["megabatch_clients"] == 8


def test_mixed_precision_microbench_contract(bench, monkeypatch, tmp_path):
    """--mixed-precision-microbench at a seconds-scale mlp config: schema,
    artifact emission, and the analytic invariants (value = f32/fast byte
    ratio; bf16 alone already cuts analytic bytes; walls present for all
    three modes). The >=1.8x densenet-scale gate itself is pinned by the
    committed-artifact test below."""
    import json as json_mod

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_MP_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_MP_CLIENTS", "2")
    monkeypatch.setenv("FEDTPU_MP_COST_BATCH", "8")
    monkeypatch.setenv("FEDTPU_MP_COST_STEPS", "1")
    monkeypatch.setenv("FEDTPU_MP_BATCH", "4")
    monkeypatch.setenv("FEDTPU_MP_ROUNDS", "1")
    monkeypatch.setenv("FEDTPU_MP_REPS", "1")
    result = bench._mixed_precision_microbench()
    assert result["metric"] == "mixed_precision_bytes_drop"
    assert result["gate_x"] == 1.8
    analytic = result["analytic"]
    assert set(analytic) == {"f32", "bf16_mixed", "bf16_megabatch"}
    for row in analytic.values():
        assert row["flops_per_round"] > 0
        assert row["bytes_per_round"] > 0
        assert row["roofline_bound"] in ("compute", "bandwidth")
    # The headline value is the f32 -> bf16+megabatch byte ratio...
    assert result["value"] == pytest.approx(
        analytic["f32"]["bytes_per_round"]
        / analytic["bf16_megabatch"]["bytes_per_round"],
        abs=1e-3,
    )
    # ...and bf16 residency ALONE must already cut analytic bytes (the
    # backend-independent model sees the stated dtypes, not the CPU
    # backend's f32 emulation, whose xla_bytes INVERT this signal). The
    # magnitude is shape-dependent — at this tiny mlp config the f32
    # master/opt traffic dominates — so the pin is direction, not size;
    # the >=1.8x magnitude gate lives on the committed densenet artifact.
    assert result["bytes_drop_bf16_only"] > 1.0
    # No ordering pin between value and bytes_drop_bf16_only: megabatch's
    # byte effect is shape-dependent (weight-sharing wins are negligible on
    # this tiny mlp, while the mega path's masked-loss bookkeeping adds a
    # little traffic); the densenet-shape gate below is where it must win.
    assert result["value"] > 0
    assert result["passes_gate"] == (result["value"] >= 1.8)
    cfgrow = result["analytic_config"]
    assert cfgrow["model"] == "mlp" and cfgrow["megabatch_clients"] == 2
    walls = result["walls"]
    assert set(walls["round_ms"]) == {"f32", "bf16_mixed", "bf16_megabatch"}
    assert all(v > 0 for v in walls["round_ms"].values())
    with open(art / "MIXED_PRECISION_MICROBENCH.json") as fh:
        assert json_mod.load(fh) == result


def test_mixed_precision_microbench_committed_gate():
    """The committed densenet-scale artifact must pass the ISSUE gate:
    analytic bytes_per_round drops >= 1.8x under bf16+megabatch on the
    profile config, with roofline placement stamped."""
    result = _committed_artifact("MIXED_PRECISION_MICROBENCH.json")
    assert result["metric"] == "mixed_precision_bytes_drop"
    assert result["analytic_config"]["model"] == "densenet_cifar"
    assert result["passes_gate"] is True
    assert result["value"] >= 1.8
    fast = result["analytic"]["bf16_megabatch"]
    assert fast["arith_intensity_flops_per_byte"] > (
        result["analytic"]["f32"]["arith_intensity_flops_per_byte"]
    )
    assert fast["roofline_bound"] in ("compute", "bandwidth")


def test_unreachable_diagnostic_carries_predicted_roofline(
    bench, monkeypatch, capsys, tmp_path
):
    """When the backend is unreachable, the diagnostic line must surface
    the PREDICTED roofline delta (analytic bytes model) next to the live_*
    fallback — namespaced predicted_*, value honestly 0.0."""
    import json

    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "MIXED_PRECISION_MICROBENCH.json").write_text(json.dumps({
        "value": 2.1,
        "analytic": {
            "f32": {"bytes_per_round": 4.2e9},
            "bf16_megabatch": {
                "bytes_per_round": 2.0e9,
                "arith_intensity_flops_per_byte": 40.0,
                "roofline_bound": "bandwidth",
            },
        },
    }))
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setattr(
        bench, "_backend_reachable", lambda: (False, "probe timed out"))
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert out["predicted_artifact"] == (
        "artifacts/MIXED_PRECISION_MICROBENCH.json"
    )
    assert out["predicted_bytes_drop"] == 2.1
    assert out["predicted_bytes_per_round_f32"] == 4.2e9
    assert out["predicted_bytes_per_round_fast"] == 2.0e9
    assert out["predicted_arith_intensity_fast"] == 40.0
    assert out["predicted_roofline_bound_fast"] == "bandwidth"
    # A corrupt artifact degrades to no predicted_* keys, never a crash.
    (art / "MIXED_PRECISION_MICROBENCH.json").write_text('{"value": ')
    bench.main()
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["value"] == 0.0
    assert "predicted_bytes_drop" not in out


# --------------------------------------------- hierarchical fan-in (PR 14)
def test_fanin_microbench_contract(bench, monkeypatch, tmp_path):
    """--fanin-microbench at a seconds-scale config: schema + artifact
    emission over REAL localhost gRPC aggregators (the 10k-clients/round
    acceptance gate itself is pinned by the committed
    artifacts/FANIN_MICROBENCH.json run)."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_FB_DIM", "4096")
    monkeypatch.setenv("FEDTPU_FB_COHORT", "40")
    monkeypatch.setenv("FEDTPU_FB_AGGS", "2,4")
    monkeypatch.setenv("FEDTPU_FB_FIXED_AGGS", "2")
    monkeypatch.setenv("FEDTPU_FB_COHORTS", "20,40")
    monkeypatch.setenv("FEDTPU_FB_ROUNDS", "2")
    result = bench._fanin_microbench()
    assert result["metric"] == "fanin_microbench"
    assert result["flat_coords"] == 4096
    assert result["rounds_per_config"] == 2
    scale_out = result["sweeps"]["scale_out_fixed_cohort"]
    fan_in = result["sweeps"]["fan_in_fixed_aggregators"]
    assert [r["aggregators"] for r in scale_out] == [2, 4]
    assert [r["cohort"] for r in scale_out] == [40, 40]
    assert [r["cohort"] for r in fan_in] == [20, 40]
    for row in scale_out + fan_in:
        # Every simulated client produced a decoded reply each round.
        assert row["clients"] == row["aggregators"] * row["cohort"]
        assert row["serial_wall_s"] > 0
        assert row["root_decode_combine_s"] > 0
        assert row["leaf_max_s"] > 0
        # The deployed-topology wall: root work + slowest single leaf.
        assert row["critical_path_s"] == pytest.approx(
            row["root_decode_combine_s"] + row["leaf_max_s"], rel=0.01
        )
        assert row["critical_path_s"] <= row["serial_wall_s"]
    gates = result["gates"]
    assert gates["critical_path_sublinear"] == (
        gates["critical_path_exponent_vs_clients"] < 1.0
    )
    assert gates["root_work_o_aggregators"] == (
        gates["root_work_ratio_across_cohort_growth"] < 2.0
    )
    assert result["value"] == gates["critical_path_exponent_vs_clients"]
    path = os.path.join(str(art), "FANIN_MICROBENCH.json")
    assert os.path.exists(path)
    with open(path) as f:
        assert json_mod.load(f) == result


def test_fanin_microbench_committed_gate():
    """The committed artifact is the PR's acceptance evidence: 10k
    simulated clients/round through a real-gRPC 2-tier topology, root
    decode+combine work O(aggregators) not O(clients), and round
    wall-clock sublinear in total clients."""
    result = _committed_artifact("FANIN_MICROBENCH.json")
    assert result["metric"] == "fanin_microbench"
    assert result["max_clients_per_round"] >= 10000
    gates = result["gates"]
    assert gates["critical_path_sublinear"] is True
    assert gates["critical_path_exponent_vs_clients"] < 1.0
    assert gates["root_work_o_aggregators"] is True
    assert gates["root_work_ratio_across_cohort_growth"] < 2.0
    # The fan-in sweep really grew clients ~4x while root work stayed flat.
    assert gates["root_client_growth_ratio"] >= 3.5


@pytest.mark.slow
def test_codec_frontier_microbench_contract(bench, monkeypatch, tmp_path):
    """--codec-frontier-microbench at a shrunk mlp config: schema, artifact
    emission, and the sweep invariants (dense is the 1.0x reference with
    zero error; rotq bytes scale ~linearly in bit width; randk/topk land
    near 1/fraction). The >=10x-at-parity gate itself is pinned by the
    committed-artifact test below."""
    import json as json_mod
    import os

    art = tmp_path / "artifacts"
    monkeypatch.setattr(bench, "ARTIFACTS_DIR", str(art))
    monkeypatch.setenv("FEDTPU_CF_MODEL", "mlp")
    monkeypatch.setenv("FEDTPU_CF_REPS", "1")
    monkeypatch.setenv("FEDTPU_CF_CONV_ROUNDS", "2")
    monkeypatch.setenv("FEDTPU_CF_CONV_CLIENTS", "2")
    result = bench._codec_frontier_microbench()
    assert result["metric"] == "codec_frontier"
    assert result["gate_reduction_x"] == 10.0
    sweep = result["sweep"]["codecs"]
    assert set(sweep) == {
        "dense", "int8", "topk", "rotq@1b", "rotq@2b", "rotq@4b",
        "rotq@8b", "randk",
    }
    dense = sweep["dense"]
    assert dense["reduction_x"] == 1.0 and dense["rel_l2_error"] == 0.0
    for row in sweep.values():
        assert row["wire_bytes"] > 0
        assert row["encode_host_ms"] > 0 and row["decode_host_ms"] > 0
    # rotq payloads are dominated by the packed code block: bytes must
    # scale ~linearly with bit width (pad ratio is common to all widths).
    b1 = sweep["rotq@1b"]["wire_bytes"]
    for bits in (2, 4, 8):
        assert sweep[f"rotq@{bits}b"]["wire_bytes"] == pytest.approx(
            bits * b1, rel=0.02
        )
    # Quantization fidelity improves monotonically with bit width.
    assert (
        sweep["rotq@8b"]["rel_l2_error"]
        < sweep["rotq@4b"]["rel_l2_error"]
        < sweep["rotq@1b"]["rel_l2_error"]
    )
    # int8 is ~4x (one code byte per f32) with small error.
    assert sweep["int8"]["reduction_x"] == pytest.approx(4.0, rel=0.05)
    assert sweep["int8"]["rel_l2_error"] < 0.05
    conv = result["convergence"]
    assert set(conv["runs"]) == {"none", "randk"}
    assert conv["bytes_up_dense"] > conv["bytes_up_randk"] > 0
    assert result["value"] == conv["reduction_x"]
    assert result["passes_gate"] == (
        conv["reduction_x"] >= 10.0 and conv["acc_gap"] <= result["gate_acc_tol"]
    )
    path = os.path.join(str(art), "CODEC_FRONTIER_MICROBENCH.json")
    assert os.path.exists(path)
    with open(path) as f:
        assert json_mod.load(f) == result


def test_codec_frontier_committed_gate():
    """The committed artifact is the PR's acceptance evidence: the randk
    operating point (small keep-fraction, EF on, flat layout) cuts per-round
    uplink bytes >=10x — real wire encoders, not an analytic byte model —
    while the engine run converges to accuracy parity with the uncompressed
    control within the stamped tolerance."""
    result = _committed_artifact("CODEC_FRONTIER_MICROBENCH.json")
    assert result["metric"] == "codec_frontier"
    assert result["sweep"]["model"] == "densenet_cifar"
    assert result["passes_gate"] is True
    assert result["value"] >= 10.0
    conv = result["convergence"]
    assert conv["error_feedback"] is True
    assert conv["acc_gap"] <= result["gate_acc_tol"]
    assert conv["reduction_x"] >= 10.0
    # The sweep really exercised the whole family at the profile shape.
    assert set(result["sweep"]["codecs"]) >= {
        "dense", "int8", "topk", "rotq@4b", "randk",
    }
