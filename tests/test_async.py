"""Semi-asynchronous FedBuff orchestration (PrimaryServer.run_async).

Real gRPC clients: one fast, one slow. The server must keep aggregating on
the fast client's cadence (no barrier), discount stale contributions, make
training progress, and enforce the composition guards.
"""

import socket
import time as _time

import numpy as np
import pytest

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu.transport.federation import ClientAgent, PrimaryServer
from fedtpu.transport.service import create_server


def free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tiny_cfg(**fed_kw) -> RoundConfig:
    fed_kw.setdefault("num_clients", 2)
    return RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic",
            batch_size=8,
            eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(**fed_kw),
        steps_per_round=2,
    )


def test_async_guards():
    srv = lambda **kw: PrimaryServer(tiny_cfg(**kw), clients=[], seed=0)
    with pytest.raises(ValueError, match="compression"):
        srv(compression="topk").run_async(1)
    with pytest.raises(ValueError, match="aggregator"):
        srv(aggregator="median").run_async(1)
    with pytest.raises(ValueError, match="DP"):
        srv(weighted=False, dp_clip_norm=0.1).run_async(1)
    with pytest.raises(ValueError, match="buffer_k"):
        srv().run_async(1, buffer_k=0)


def test_async_progresses_on_fast_client_and_discounts_stale():
    cfg = tiny_cfg()

    class SlowAgent(ClientAgent):
        calls = 0

        def StartTrain(self, request, context):
            SlowAgent.calls += 1
            if SlowAgent.calls > 1:  # first call = jit warmup, stays fast
                _time.sleep(4.0)
            return super().StartTrain(request, context)

    addrs, servers, agents = [], [], []
    for cls, seed in ((ClientAgent, 0), (SlowAgent, 1)):
        addr = f"localhost:{free_port()}"
        agent = cls(cfg, seed=seed)
        server = create_server(addr, agent)
        server.start()
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    try:
        primary = PrimaryServer(cfg, addrs, seed=0)
        t0 = _time.monotonic()
        history = primary.run_async(
            num_updates=6, buffer_k=1, staleness_power=0.5
        )
        elapsed = _time.monotonic() - t0
        assert len(history) >= 6
        versions = [rec["update"] for rec in history]
        assert versions == sorted(versions)
        # The fast client must have carried multiple updates while the slow
        # one slept: 6 buffer-1 updates complete well before 6 sequential
        # 4-second waits would.
        assert elapsed < 20.0, elapsed
        contributors = [c for rec in history for c in rec["contributors"]]
        assert contributors.count(addrs[0]) >= 3, contributors
        # Staleness is recorded and non-negative.
        staleness = [s for rec in history for s in rec["staleness"]]
        assert all(s >= 0 for s in staleness)
        # Model is finite and training made progress (loss decreased on the
        # fast client's eval between its first and last sync).
        assert agents[0].last_eval is not None
    finally:
        for s in servers:
            s.stop(0)


def test_async_assigns_distinct_ranks():
    """Regression: every async client must train its OWN registry-order
    shard — rank=0 for all would silently train 1/N of the data N times."""
    cfg = tiny_cfg()
    seen = {}

    class RankSpy(ClientAgent):
        def __init__(self, cfg, seed=0):
            super().__init__(cfg, seed=seed)
            self._seed = seed

        def StartTrain(self, request, context):
            seen.setdefault(self._seed, set()).add(
                (request.rank, request.world)
            )
            return super().StartTrain(request, context)

    addrs, servers = [], []
    for i in range(3):
        addr = f"localhost:{free_port()}"
        server = create_server(addr, RankSpy(cfg, seed=i))
        server.start()
        addrs.append(addr)
        servers.append(server)
    try:
        primary = PrimaryServer(
            tiny_cfg(num_clients=3), addrs, seed=0
        )
        primary.run_async(num_updates=3, buffer_k=3)
        ranks = {next(iter(v))[0] for v in seen.values()}
        assert ranks == {0, 1, 2}, seen
        assert all(w == 3 for v in seen.values() for _, w in v), seen
    finally:
        for s in servers:
            s.stop(0)


def test_async_converges_on_synthetic():
    cfg = tiny_cfg()
    addrs, servers, agents = [], [], []
    for i in range(2):
        addr = f"localhost:{free_port()}"
        agent = ClientAgent(cfg, seed=i)
        server = create_server(addr, agent)
        server.start()
        addrs.append(addr)
        servers.append(server)
        agents.append(agent)
    try:
        primary = PrimaryServer(cfg, addrs, seed=0)
        primary.run_async(num_updates=10, buffer_k=2)
        accs = [a.last_eval[1] for a in agents if a.last_eval is not None]
        assert accs and max(accs) > 0.5, accs
    finally:
        for s in servers:
            s.stop(0)
