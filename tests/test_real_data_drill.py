"""Real-data readiness drill (VERDICT r4 #7).

Every committed fedtpu accuracy number is synthetic because no real dataset
exists in this environment (no egress). This drill proves the day real data
lands, ZERO code changes are needed: a committed fixture in the GENUINE
CIFAR-10 python-pickle byte layout (``tests/fixtures/cifar10_fixture``,
written by ``tools/make_cifar_fixture.py`` — the exact format torchvision
produces and the reference consumes, ``src/main.py:48-56``) drives the full
CLI path through the REAL disk loader (``fedtpu/data/datasets.py
load_cifar10``), and the run's own metrics must say so
(``data_source: "disk"`` — the tag that stops synthetic runs masquerading).
"""

import json
import os

import pytest

_FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "cifar10_fixture")


@pytest.fixture()
def fixture_data(monkeypatch):
    assert os.path.isdir(os.path.join(_FIXTURE, "cifar-10-batches-py"))
    monkeypatch.setenv("FEDTPU_DATA_DIR", _FIXTURE)


def test_loader_reads_fixture_from_disk(fixture_data):
    import numpy as np

    from fedtpu.data import data_source, load

    x, y = load("cifar10", "train")
    assert x.shape == (200, 32, 32, 3)  # 5 batches x 40, multi-file concat
    assert data_source("cifar10", "train") == "disk"
    xt, yt = load("cifar10", "test")
    assert xt.shape == (64, 32, 32, 3)
    assert data_source("cifar10", "test") == "disk"
    # Normalised real bytes, not the synthetic surrogate: values live in the
    # reference transform's range and every label class is in [0, 10).
    assert float(np.abs(x).max()) < 3.0
    assert set(np.unique(y)) <= set(range(10))


def test_cli_end_to_end_on_disk_fixture(fixture_data, tmp_path):
    """fedtpu-run trains + evals through the real CIFAR pickle path; its
    metrics rows carry data_source='disk' and the model beats chance on the
    class-structured fixture."""
    from fedtpu.cli import run as cli_run

    metrics = str(tmp_path / "m.jsonl")
    rc = cli_run.main([
        "--platform", "cpu",
        "--model", "mlp", "--dataset", "cifar10",
        "--num-clients", "2", "--rounds", "8", "--num-examples", "200",
        "--batch-size", "10", "--steps-per-round", "10", "--lr", "0.05",
        "--eval-batch-size", "32",  # the fixture's test split has 64 rows
        "--partition", "iid", "--eval-every", "8",
        "--metrics", metrics,
    ])
    assert rc == 0
    with open(metrics) as fh:
        rows = [json.loads(line) for line in fh]
    assert rows, "no metrics written"
    assert all(r["data_source"] == "disk" for r in rows)
    assert rows[-1]["dataset"] == "cifar10"
    evals = [r for r in rows if "test_acc" in r]
    assert evals, "no eval row"
    # The fixture is a learnable 10-class task (class prototypes + noise):
    # 8 MLP rounds on 200 examples measured ~0.23 test acc — comfortably
    # above the 0.1 chance floor (the drill proves the PLUMBING; accuracy
    # at scale is the TPU parity harness's job).
    assert evals[-1]["test_acc"] > 0.18, evals[-1]
