"""Sparse delta wire payloads (fedtpu.transport.sparse)."""

import numpy as np
import pytest

from fedtpu.transport import sparse
from fedtpu.transport.wire import WireError


def delta_tree(rng):
    return {
        "params": {
            "w": rng.normal(size=(32, 16)).astype(np.float32),
            "b": rng.normal(size=(16,)).astype(np.float32),
        },
        "batch_stats": {"mean": rng.normal(size=(16,)).astype(np.float32)},
    }


def zeros_like_tree(tree):
    import jax

    return jax.tree.map(np.zeros_like, tree)


def test_topk_roundtrip_keeps_largest(rng):
    tree = delta_tree(rng)
    payload, residual = sparse.encode_topk(
        tree, fraction=0.1, extra={"num_examples": np.float32(7)}
    )
    assert sparse.is_sparse_payload(payload)
    out, extra = sparse.decode(payload, zeros_like_tree(tree))
    assert float(extra["num_examples"]) == 7
    w, out_w = tree["params"]["w"].ravel(), out["params"]["w"].ravel()
    nnz = np.count_nonzero(out_w)
    assert 0.05 * w.size <= nnz <= 0.2 * w.size
    kept = np.abs(w[out_w != 0])
    dropped = np.abs(w[out_w == 0])
    assert kept.min() >= dropped.max() - 1e-6
    # Residual is the dropped mass: kept + residual == input.
    import jax

    for o, r, x in zip(
        jax.tree.leaves(out), jax.tree.leaves(residual), jax.tree.leaves(tree)
    ):
        np.testing.assert_allclose(o + r, x, atol=1e-6)


def test_topk_error_feedback_carries(rng):
    tree = delta_tree(rng)
    p1, res1 = sparse.encode_topk(tree, fraction=0.05)
    # Second round with residuals: selection sees delta + residual.
    p2, res2 = sparse.encode_topk(tree, fraction=0.05, residuals=res1)
    out2, _ = sparse.decode(p2, zeros_like_tree(tree))
    import jax

    for o, r2, x, r1 in zip(
        jax.tree.leaves(out2),
        jax.tree.leaves(res2),
        jax.tree.leaves(tree),
        jax.tree.leaves(res1),
    ):
        np.testing.assert_allclose(o + r2, x + r1, atol=1e-6)


def test_int8_roundtrip_error_bound(rng):
    tree = delta_tree(rng)
    payload, residual = sparse.encode_int8(
        tree, extra={"num_examples": np.float32(3)}
    )
    assert residual is None  # collect_residual defaults off
    out, extra = sparse.decode(payload, zeros_like_tree(tree))
    assert float(extra["num_examples"]) == 3
    import jax

    for o, x in zip(jax.tree.leaves(out), jax.tree.leaves(tree)):
        scale = np.abs(x).max() / 127.0
        assert np.abs(o - x).max() <= scale / 2 + 1e-7


def test_int8_error_feedback_residuals(rng):
    tree = delta_tree(rng)
    payload, res = sparse.encode_int8(tree, collect_residual=True)
    out, _ = sparse.decode(payload, zeros_like_tree(tree))
    import jax

    # residual == input - dequant(quant(input)), so out + residual == input.
    for o, r, x in zip(
        jax.tree.leaves(out), jax.tree.leaves(res), jax.tree.leaves(tree)
    ):
        np.testing.assert_allclose(o + r, x, atol=1e-6)


def test_topk_zero_leaf_stays_small(rng):
    """An all-zero leaf must encode as ~empty, not as n explicit zeros."""
    tree = {
        "w": rng.normal(size=(64, 64)).astype(np.float32),
        "frozen": np.zeros((512, 512), np.float32),
    }
    payload, res = sparse.encode_topk(tree, fraction=0.25)
    out, _ = sparse.decode(payload, zeros_like_tree(tree))
    assert not out["frozen"].any()
    # Far below the 8-bytes-per-entry cost of encoding the frozen leaf dense.
    assert len(payload) < tree["frozen"].size
    np.testing.assert_array_equal(res["frozen"], 0.0)


def test_topk_no_residual_when_disabled(rng):
    tree = delta_tree(rng)
    payload, res = sparse.encode_topk(tree, fraction=0.1, collect_residual=False)
    assert res is None
    out, _ = sparse.decode(payload, zeros_like_tree(tree))
    assert any(np.count_nonzero(l) for l in out["params"].values())


def test_decode_rejects_out_of_range_indices(rng):
    """Malicious/corrupt indices must raise, not scatter out of bounds."""
    from flax import serialization

    tree = {"w": np.zeros((16,), np.float32)}
    body = {
        "kind": "topk",
        "leaves": {"0": {"idx": np.array([99], np.int32),
                         "vals": np.array([1.0], np.float32),
                         "size": np.int64(16)}},
        "extra": {},
    }
    payload = sparse._frame(serialization.msgpack_serialize(body))
    with pytest.raises(WireError):
        sparse.decode(payload, tree)
    body["leaves"]["0"]["idx"] = np.array([-1], np.int32)
    payload = sparse._frame(serialization.msgpack_serialize(body))
    with pytest.raises(WireError):
        sparse.decode(payload, tree)


def test_sparse_wire_size_shrinks(rng):
    big = {"w": rng.normal(size=(512, 512)).astype(np.float32)}
    from fedtpu.transport import wire

    dense = wire.encode(big)
    topk, _ = sparse.encode_topk(big, fraction=0.01)
    int8, _ = sparse.encode_int8(big)
    assert len(topk) < len(dense) / 20
    assert len(int8) < len(dense) / 3


def test_sparse_rejects_corruption(rng):
    tree = delta_tree(rng)
    payload, _ = sparse.encode_topk(tree, fraction=0.1)
    bad = bytearray(payload)
    bad[-2] ^= 0x40
    with pytest.raises(WireError):
        sparse.decode(bytes(bad), zeros_like_tree(tree))


def test_sparse_rejects_template_mismatch(rng):
    tree = delta_tree(rng)
    payload, _ = sparse.encode_topk(tree, fraction=0.1)
    wrong = {"params": {"w": np.zeros((4, 4), np.float32)}}
    with pytest.raises(WireError):
        sparse.decode(payload, wrong)


# ------------------------------------------------------- flat wire records
def test_topk_flat_roundtrip(rng):
    """encode_topk_flat -> decode -> tree equal (kept + residual == input);
    the keep budget is GLOBAL over the concatenated vector."""
    import jax

    tree = delta_tree(rng)
    payload, res = sparse.encode_topk_flat(
        tree, fraction=0.1, extra={"num_examples": np.float32(5)}
    )
    assert sparse.is_sparse_payload(payload)  # same FSP1 frame
    out, extra = sparse.decode(payload, zeros_like_tree(tree))
    assert float(extra["num_examples"]) == 5
    for o, r, x in zip(
        jax.tree.leaves(out), jax.tree.leaves(res), jax.tree.leaves(tree)
    ):
        np.testing.assert_allclose(o + r, x, atol=1e-6)
    # Global budget: nnz over the WHOLE tree ~ ceil(0.1 * total); kept
    # coordinates are the globally largest, regardless of leaf.
    flat_in = np.concatenate([np.ravel(l) for l in jax.tree.leaves(tree)])
    flat_out = np.concatenate([np.ravel(l) for l in jax.tree.leaves(out)])
    k = int(np.ceil(0.1 * flat_in.size))
    nnz = np.count_nonzero(flat_out)
    assert k <= nnz <= k + 4
    kept = np.abs(flat_in[flat_out != 0])
    dropped = np.abs(flat_in[flat_out == 0])
    assert kept.min() >= dropped.max() - 1e-6


def test_topk_flat_error_feedback_carries(rng):
    import jax

    tree = delta_tree(rng)
    p1, res1 = sparse.encode_topk_flat(tree, fraction=0.05)
    p2, res2 = sparse.encode_topk_flat(tree, fraction=0.05, residuals=res1)
    out2, _ = sparse.decode(p2, zeros_like_tree(tree))
    for o, r2, x, r1 in zip(
        jax.tree.leaves(out2),
        jax.tree.leaves(res2),
        jax.tree.leaves(tree),
        jax.tree.leaves(res1),
    ):
        np.testing.assert_allclose(o + r2, x + r1, atol=1e-6)


def test_int8_flat_matches_per_leaf_reconstruction(rng):
    """Flat int8 keeps PER-LEAF scales, so its dense reconstruction is
    bit-identical to the per-leaf record's — the wire twin of the engine's
    layout-parity invariant."""
    import jax

    tree = delta_tree(rng)
    flat_payload, flat_res = sparse.encode_int8_flat(
        tree, collect_residual=True
    )
    leaf_payload, leaf_res = sparse.encode_int8(tree, collect_residual=True)
    out_flat, _ = sparse.decode(flat_payload, zeros_like_tree(tree))
    out_leaf, _ = sparse.decode(leaf_payload, zeros_like_tree(tree))
    for a, b in zip(jax.tree.leaves(out_flat), jax.tree.leaves(out_leaf)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jax.tree.leaves(flat_res), jax.tree.leaves(leaf_res)):
        np.testing.assert_array_equal(a, b)


def test_flat_record_is_one_block_and_smaller(rng):
    """On a many-leaf tree the flat record carries ONE contiguous block
    instead of N per-leaf map entries — strictly less framing overhead."""
    tree = {f"leaf_{i:03d}": rng.normal(size=(17,)).astype(np.float32)
            for i in range(200)}
    per_leaf, _ = sparse.encode_int8(tree)
    flat, _ = sparse.encode_int8_flat(tree)
    assert len(flat) < len(per_leaf)


def test_flat_decode_rejects_bad_indices_and_sizes(rng):
    from flax import serialization

    tmpl = {"w": np.zeros((16,), np.float32)}
    body = {
        "kind": "topk_flat",
        "sizes": np.array([16], np.int64),
        "idx": np.array([99], np.int32),
        "vals": np.array([1.0], np.float32),
        "extra": {},
    }
    payload = sparse._frame(serialization.msgpack_serialize(body))
    with pytest.raises(WireError):
        sparse.decode(payload, tmpl)
    body["idx"] = np.array([2], np.int32)
    body["sizes"] = np.array([8], np.int64)  # template mismatch
    payload = sparse._frame(serialization.msgpack_serialize(body))
    with pytest.raises(WireError):
        sparse.decode(payload, tmpl)


# ------------------------------------------------- decode-into-row (stream)
def _layout_sizes(tree):
    import jax

    return [int(np.size(l)) for l in jax.tree.leaves(tree)]


def _row_from_tree(tree):
    import jax

    return np.concatenate(
        [np.asarray(l, np.float32).ravel() for l in jax.tree.leaves(tree)]
    )


@pytest.mark.parametrize("encoder,kwargs", [
    (sparse.encode_topk, {"fraction": 0.1}),
    (sparse.encode_int8, {}),
    (sparse.encode_topk_flat, {"fraction": 0.1}),
    (sparse.encode_int8_flat, {}),
    (sparse.encode_rotq_flat, {"bits": 4, "seed": 3}),
    (sparse.encode_randk_flat, {"fraction": 0.1, "seed": 3}),
])
def test_decode_into_row_matches_tree_decode(rng, encoder, kwargs):
    """The streaming server's row-target decode reconstructs EXACTLY what
    the template decode reconstructs, for all four record kinds — just
    straight into the flat row, with no per-leaf pytrees."""
    tree = delta_tree(rng)
    payload, _ = encoder(
        tree, extra={"num_examples": np.float32(5)}, **kwargs
    )
    via_tree, extra_t = sparse.decode(payload, zeros_like_tree(tree))
    sizes = _layout_sizes(tree)
    total = sum(sizes)
    out = np.zeros((total + 128,), np.float32)  # padded row: pad stays 0
    extra_r = sparse.decode_into_row(payload, sizes, out)
    assert float(extra_r["num_examples"]) == 5
    assert float(extra_t["num_examples"]) == 5
    np.testing.assert_array_equal(out[:total], _row_from_tree(via_tree))
    np.testing.assert_array_equal(out[total:], 0.0)


def test_decode_into_row_rejects_mismatch_and_bad_indices(rng):
    tree = delta_tree(rng)
    sizes = _layout_sizes(tree)
    out = np.zeros((sum(sizes),), np.float32)
    payload, _ = sparse.encode_topk_flat(tree, 0.1)
    # Layout with a different leaf count / sizes -> WireError, like decode.
    with pytest.raises(WireError):
        sparse.decode_into_row(payload, sizes[:-1], out)
    roomy = np.zeros((sum(sizes) + 64,), np.float32)
    with pytest.raises(WireError):
        sparse.decode_into_row(payload, [s + 1 for s in sizes], roomy)
    # Out-of-range index in a hand-built record: heap-write guard.
    from flax import serialization

    body = {
        "kind": "topk_flat",
        "sizes": np.asarray(sizes, np.int64),
        "idx": np.array([sum(sizes)], np.int32),
        "vals": np.array([1.0], np.float32),
        "extra": {},
    }
    bad = sparse._frame(serialization.msgpack_serialize(body))
    with pytest.raises(WireError):
        sparse.decode_into_row(bad, sizes, out)
    # A too-small target row is a caller bug, raised loudly.
    with pytest.raises(ValueError):
        sparse.decode_into_row(payload, sizes, out[: sum(sizes) - 1])


def test_dense_wire_decode_into_row(rng):
    """wire.decode_into_row: dense full-weight payload -> delta-vs-base
    written straight into the row (the stream pipeline's unsynced-client /
    compression='none' fallback)."""
    from fedtpu.transport import wire

    model = delta_tree(rng)  # stands in for {"params","batch_stats"} weights
    base = delta_tree(rng)
    payload_tree = dict(model, num_examples=np.float32(11))
    data = wire.encode(payload_tree)
    like = zeros_like_tree(payload_tree)
    sizes = _layout_sizes(model)
    out = np.zeros((sum(sizes) + 64,), np.float32)
    extra = wire.decode_into_row(data, like, base, out)
    assert float(extra["num_examples"]) == 11
    expect = _row_from_tree(model) - _row_from_tree(base)
    np.testing.assert_array_equal(out[: sum(sizes)], expect)
    np.testing.assert_array_equal(out[sum(sizes):], 0.0)


# ------------------------------------------------- sketch records (rotq/randk)
def test_rotq_flat_roundtrip_error_bound(rng):
    """8-bit rotated-sketch record reconstructs within ~2% relative L2 and
    replays byte-identically from the same seed."""
    tree = delta_tree(rng)
    payload, _ = sparse.encode_rotq_flat(tree, bits=8, seed=11)
    replay, _ = sparse.encode_rotq_flat(tree, bits=8, seed=11)
    assert payload == replay
    other, _ = sparse.encode_rotq_flat(tree, bits=8, seed=12)
    assert payload != other
    got, extra = sparse.decode(payload, zeros_like_tree(tree))
    assert extra["_codec"] == "rotq_flat"
    ref, out = _row_from_tree(tree), _row_from_tree(got)
    assert np.linalg.norm(out - ref) < 0.02 * np.linalg.norm(ref)


def test_rotq_flat_error_feedback_carries(rng):
    """Residual == input - reconstruction, derived from the SAME dequantized
    values the decoder produces (shared helper, no encoder/decoder drift)."""
    import jax

    tree = delta_tree(rng)
    payload, res = sparse.encode_rotq_flat(tree, bits=4, seed=5)
    got, _ = sparse.decode(payload, zeros_like_tree(tree))
    lhs = _row_from_tree(jax.tree.map(np.add, got, res))
    np.testing.assert_allclose(lhs, _row_from_tree(tree), rtol=1e-5, atol=1e-5)


def test_randk_flat_ef_and_rescale_modes(rng):
    """EF on: unscaled values, decode + residual == input exactly. EF off:
    the decoded kept coordinates carry the total/k unbiasedness rescale."""
    import jax
    import math

    tree = delta_tree(rng)
    payload, res = sparse.encode_randk_flat(tree, 0.1, seed=9)
    got, extra = sparse.decode(payload, zeros_like_tree(tree))
    assert extra["_codec"] == "randk_flat"
    lhs = _row_from_tree(jax.tree.map(np.add, got, res))
    np.testing.assert_array_equal(lhs, _row_from_tree(tree))

    payload2, res2 = sparse.encode_randk_flat(
        tree, 0.1, seed=9, collect_residual=False
    )
    assert res2 is None
    got2, _ = sparse.decode(payload2, zeros_like_tree(tree))
    row, ref = _row_from_tree(got2), _row_from_tree(tree)
    total = ref.size
    k = max(1, int(math.ceil(0.1 * total)))
    mask = row != 0
    np.testing.assert_allclose(row[mask], ref[mask] * (total / k), rtol=1e-6)
    # Same seed -> same support in both modes.
    np.testing.assert_array_equal(mask, _row_from_tree(got) != 0)


def test_sketch_records_reject_corruption_and_bad_fields(rng):
    from flax import serialization

    tree = delta_tree(rng)
    for payload in (
        sparse.encode_rotq_flat(tree, bits=2, seed=1)[0],
        sparse.encode_randk_flat(tree, 0.1, seed=1)[0],
    ):
        blob = bytearray(payload)
        blob[len(blob) // 2] ^= 0xFF
        with pytest.raises(WireError):
            sparse.decode(bytes(blob), zeros_like_tree(tree))

    sizes = _layout_sizes(tree)
    total = sum(sizes)
    out = np.zeros((total,), np.float32)

    def frame(body):
        return sparse._frame(serialization.msgpack_serialize(body))

    # Unsupported bit width in a hand-built record.
    h = sparse._next_pow2(total)
    bad_bits = frame({
        "kind": "rotq_flat", "sizes": np.asarray(sizes, np.int64),
        "codes": np.zeros((h,), np.uint8),
        "extra": {"seed": np.uint64(0), "bits": np.int64(3),
                  "lo": np.float32(0), "scale": np.float32(1)},
    })
    with pytest.raises(WireError):
        sparse.decode_into_row(bad_bits, sizes, out)
    # Truncated code block.
    short = frame({
        "kind": "rotq_flat", "sizes": np.asarray(sizes, np.int64),
        "codes": np.zeros((3,), np.uint8),
        "extra": {"seed": np.uint64(0), "bits": np.int64(8),
                  "lo": np.float32(0), "scale": np.float32(1)},
    })
    with pytest.raises(WireError):
        sparse.decode_into_row(short, sizes, out)
    # randk with a value count that disagrees with k.
    bad_k = frame({
        "kind": "randk_flat", "sizes": np.asarray(sizes, np.int64),
        "vals": np.zeros((4,), np.float32),
        "extra": {"seed": np.uint64(0), "k": np.int64(9)},
    })
    with pytest.raises(WireError):
        sparse.decode_into_row(bad_k, sizes, out)
