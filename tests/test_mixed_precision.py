"""Mixed-precision device residency + client megabatching (perf fast path).

The two fast-path levers (``FedConfig.compute_dtype='bfloat16_mixed'``,
``FedConfig.megabatch_clients=k``) are PERF knobs with a precisely scoped
numerics contract, pinned here:

* ``megabatch_clients=1`` is BITWISE identical to the classic per-client
  vmapped path — stepped and fused, gather and presharded layouts. The
  masked-mean loss, group rng selection and wrapper reshapes are all exact
  identities at k=1, so any bit of drift means the mega body diverged from
  the reference body.
* Under ``bfloat16_mixed`` the AGGREGATION SURFACE stays f32: server
  params, optimizer state, the flat packed buffer and the checkpoint wire
  bytes are identical in dtype/size to a float32 run. Only the on-device
  compute/dataset residency changes.
* ``augment_crop=False`` is flip-only with the SAME flip decisions as the
  crop path (shared rng split structure).
* bf16-vs-f32 convergence stays within a documented tolerance on the easy
  synthetic task (the analogue of MOMENTUM_DTYPE_CONVERGENCE for the
  compute dtype).
* Misconfigurations fail loudly at construction, not silently mid-run.
"""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedtpu.config import (
    DataConfig,
    FedConfig,
    OptimizerConfig,
    RoundConfig,
    resolve_compute_dtype,
    validate_megabatch,
)
from fedtpu.core import Federation
from fedtpu.data.augment import augment_batch


def _cfg(layout="gather", compute="float32", mega=0, clients=4,
         model="mlp", dataset="synthetic", augment=False, **kw):
    base = dict(
        model=model,
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset=dataset,
            batch_size=4,
            partition="iid",
            num_examples=32 * clients,
            augment=augment,
            device_layout=layout,
        ),
        fed=FedConfig(
            num_clients=clients,
            compute_dtype=compute,
            megabatch_clients=mega,
        ),
        steps_per_round=2,
    )
    base.update(kw)
    return RoundConfig(**base)


def _state_leaves(fed):
    return (
        jax.tree_util.tree_leaves(fed.state.params)
        + jax.tree_util.tree_leaves(fed.state.batch_stats)
        + jax.tree_util.tree_leaves(fed.state.opt_state)
    )


def _assert_bitwise(fa, fb):
    for a, b in zip(_state_leaves(fa), _state_leaves(fb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(fa.state.last_client_loss),
        np.asarray(fb.state.last_client_loss),
    )


# ------------------------------------------------------- megabatch parity
@pytest.mark.parametrize("layout", ["gather", "presharded"])
@pytest.mark.parametrize("fused", [False, True])
def test_megabatch_k1_bitwise_identical(layout, fused):
    """k=1 engages the FULL mega path (masked-mean loss, group wrapper,
    broadcast/where recombination) against the classic path — the strongest
    cheap correctness pin the k>1 modes inherit."""
    fa = Federation(_cfg(layout=layout, mega=0), seed=0)
    fb = Federation(_cfg(layout=layout, mega=1), seed=0)
    if fused:
        fa.run_on_device(2)
        fb.run_on_device(2)
    else:
        for _ in range(2):
            fa.step()
            fb.step()
    _assert_bitwise(fa, fb)


def test_megabatch_k1_bitwise_with_augment_bn_dropout():
    """Same pin through the full stochastic client body: augmentation rng,
    BN batch stats and dropout all flow through the mega body's single
    [k*batch] pass. cifar-shaped so the conv stack and augment engage."""
    kw = dict(model="smallcnn", dataset="cifar10", augment=True,
              layout="presharded", clients=2)
    fa = Federation(_cfg(mega=0, **kw), seed=0)
    fb = Federation(_cfg(mega=1, **kw), seed=0)
    fa.step()
    fb.step()
    _assert_bitwise(fa, fb)


def test_megabatch_k2_trains_and_keeps_per_client_metrics():
    """k=2 is the documented-approximation regime: one group trajectory per
    k clients. It must still learn and still report PER-CLIENT metrics at
    the [num_clients] shape the sim/observability layers consume."""
    fed = Federation(_cfg(mega=2, clients=4, steps_per_round=4), seed=0)
    first = fed.run(num_rounds=1)
    last = fed.run(num_rounds=5)
    assert float(last.loss) < float(first.loss)
    assert fed.state.last_client_loss.shape == (4,)


# --------------------------------------------------- bf16 f32 surface pin
def test_bf16_mixed_keeps_aggregation_surface_f32(tmp_path):
    """bfloat16_mixed changes device residency, never server semantics:
    master params/opt stay f32, the flat packed buffer stays f32, and a
    checkpoint of the bf16-mode state is byte-for-byte the SIZE of the f32
    mode's (the wire format must not notice the compute dtype)."""
    from fedtpu.checkpoint.checkpoint import save
    from fedtpu.ops import flat as flat_ops

    f32 = Federation(_cfg(compute="float32"), seed=0)
    b16 = Federation(_cfg(compute="bfloat16_mixed"), seed=0)
    f32.step()
    b16.step()

    for leaf in jax.tree_util.tree_leaves(
        (b16.state.params, b16.state.opt_state)
    ):
        assert leaf.dtype == jnp.float32
    # Device-resident dataset IS stored bf16 (the HBM footprint win)...
    assert b16._ensure_device_data()[0].dtype == jnp.bfloat16
    assert f32._ensure_device_data()[0].dtype == jnp.float32

    # ...but the flat aggregation buffer the screening/compression stack
    # sees is structurally f32 either way.
    lay = flat_ops.make_layout(jax.device_get(b16.state.params))
    packed = flat_ops.pack(lay, b16.state.params)
    assert packed.dtype == jnp.float32

    # Checkpoint wire: identical byte count between the two modes.
    p32 = save(str(tmp_path / "f32"), 0, jax.device_get(f32.state))
    p16 = save(str(tmp_path / "b16"), 0, jax.device_get(b16.state))
    assert os.path.getsize(p32) == os.path.getsize(p16)


def test_bf16_convergence_within_documented_tolerance():
    """The compute-dtype analogue of MOMENTUM_DTYPE_CONVERGENCE: bf16
    training tracks f32 on the easy synthetic task. Tolerance is loose by
    design — bf16 has ~8 mantissa bits and the trajectories genuinely
    diverge — but both must LEARN, and the final losses must agree to 25%
    relative (measured headroom ~5x on this config)."""
    losses = {}
    for compute in ("float32", "bfloat16_mixed"):
        fed = Federation(
            _cfg(compute=compute, clients=2, steps_per_round=4), seed=0
        )
        first = fed.run(num_rounds=1)
        last = fed.run(num_rounds=3)
        assert float(last.loss) < float(first.loss)
        losses[compute] = float(last.loss)
    # 25% relative with a small absolute floor: the synthetic task drives
    # the loss to ~0, where a relative bound alone is ill-conditioned.
    diff = abs(losses["bfloat16_mixed"] - losses["float32"])
    assert diff < max(0.25 * losses["float32"], 0.05), losses


# -------------------------------------------------------- crop toggle pin
def test_crop_off_is_flip_only_with_identical_flip_draws():
    """augment_crop=False must change ONLY the crop: the flip decisions
    come from the same split(rng) slot in both modes, so crop-off output
    equals a hand-built flip using that slot — and flipping a crop=True
    output uses the same mask (mode-coupled determinism)."""
    rng = jax.random.PRNGKey(3)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 32, 32, 3), jnp.float32)
    _crop_rng, flip_rng = jax.random.split(rng)
    flip = jax.random.bernoulli(flip_rng, 0.5, (8,))
    expect = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)
    got = augment_batch(rng, x, crop=False)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expect))
    assert bool(np.asarray(flip).any()) and not bool(np.asarray(flip).all())


def test_crop_flag_flows_from_data_config():
    """DataConfig.augment_crop=False is bit-identical to flip-only through
    the engine; crop on-vs-off genuinely differ (the flag is not dead)."""
    kw = dict(model="smallcnn", dataset="cifar10", augment=True, clients=2)
    on = Federation(_cfg(**kw), seed=0)
    off = Federation(
        _cfg(**kw, data=dataclasses.replace(
            _cfg(**kw).data, augment_crop=False)),
        seed=0,
    )
    on.step()
    off.step()
    a = jax.tree_util.tree_leaves(on.state.params)
    b = jax.tree_util.tree_leaves(off.state.params)
    assert any(
        not np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(a, b)
    )


# ------------------------------------------------------------- validation
def test_megabatch_must_divide_cohort():
    with pytest.raises(ValueError, match="divide"):
        validate_megabatch(FedConfig(num_clients=4, megabatch_clients=3))
    with pytest.raises(ValueError, match="divide"):
        Federation(_cfg(mega=3, clients=4), seed=0)
    with pytest.raises(ValueError, match=">= 0"):
        validate_megabatch(FedConfig(num_clients=4, megabatch_clients=-1))


def test_unknown_compute_dtype_rejected_cheaply():
    with pytest.raises(ValueError, match="compute_dtype"):
        resolve_compute_dtype(_cfg(compute="float16"))
    with pytest.raises(ValueError, match="compute_dtype"):
        Federation(_cfg(compute="bf16"), seed=0)


def test_megabatch_rejects_debug_per_batch():
    with pytest.raises(ValueError, match="debug_per_batch"):
        fed = Federation(_cfg(mega=2, debug_per_batch=True), seed=0)
        fed.step()


# --------------------------------------------------------- CLI perf knobs
def test_perf_preset_resolution():
    """--perf-preset fast fills only the knobs the user left unset; parity
    and no-preset leave the dataclass defaults (f32, megabatching off) in
    charge; an odd cohort degrades megabatching to off, not to a crash."""
    import argparse

    from fedtpu.cli.common import add_perf_flags, resolve_perf_preset

    def parse(argv):
        p = argparse.ArgumentParser()
        add_perf_flags(p)
        return p.parse_args(argv)

    assert resolve_perf_preset(parse([]), 64) == ("float32", 0)
    assert resolve_perf_preset(
        parse(["--perf-preset", "parity"]), 64) == ("float32", 0)
    assert resolve_perf_preset(
        parse(["--perf-preset", "fast"]), 64) == ("bfloat16_mixed", 8)
    assert resolve_perf_preset(
        parse(["--perf-preset", "fast"]), 6) == ("bfloat16_mixed", 2)
    assert resolve_perf_preset(
        parse(["--perf-preset", "fast"]), 3) == ("bfloat16_mixed", 0)
    # Explicit flags beat the preset.
    assert resolve_perf_preset(
        parse(["--perf-preset", "fast", "--compute-dtype", "float32",
               "--megabatch-clients", "4"]), 64) == ("float32", 4)


def test_build_config_threads_perf_knobs():
    import argparse

    from fedtpu.cli import common

    p = argparse.ArgumentParser()
    common.add_model_flags(p)
    common.add_fed_flags(p)
    args = p.parse_args(
        ["--dataset", "synthetic", "--batch-size", "4",
         "--num-examples", "64", "--perf-preset", "fast"])
    cfg = common.build_config(args, num_clients=8, steps_per_round=2)
    assert cfg.fed.compute_dtype == "bfloat16_mixed"
    assert cfg.fed.megabatch_clients == 8
