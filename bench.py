#!/usr/bin/env python
"""Headline benchmark: FedAvg rounds/sec, CIFAR-10 CNN, 64 simulated clients.

Matches the driver's north-star metric (BASELINE.json): one "round" is the
full reference round semantics — every client does one local epoch of SGD on
its shard (6 batches of 128 at world=64, mirroring ~391/64 batches of the
reference's round-robin split, ``src/main.py:140-144``) followed by the
FedAvg aggregate. The whole round is one XLA program; rounds/sec counts
end-to-end jitted steps including the aggregation.

Normalisation: the 200 rounds/sec north-star target assumes a v4-64 (64
chips, one client per chip), i.e. 200 client-epochs/sec *per chip*. This
bench runs on however many devices are visible (typically ONE chip simulating
all 64 clients), so the reported metric is per-chip client-epoch throughput:
``rounds/sec x num_clients / num_devices``, directly comparable to the
north-star's 200/s-per-chip. ``vs_baseline`` is the ratio to that target
(the reference publishes no numbers of its own — BASELINE.md).

Timing is honest under the remote-tunnel device: a scalar metric is fetched
to the host every round (async-dispatch pipelines otherwise report absurd
rates because ``block_until_ready`` does not reliably block on the tunnel);
the median of several trials is reported to damp shared-device noise.

Prints exactly one JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
from fedtpu import models
from fedtpu.core import round as round_lib

NUM_CLIENTS = 64
STEPS_PER_ROUND = 391 // NUM_CLIENTS  # reference local-epoch share at world=64
BATCH = 128
WARMUP_ROUNDS = 2
TIMED_ROUNDS = 10
TRIALS = 3
TARGET_PER_CHIP = 200.0  # client-epochs/sec/chip implied by the north star


def main():
    cfg = RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="cifar10", batch_size=BATCH),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=STEPS_PER_ROUND,
        dtype="bfloat16",
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)

    rng = np.random.default_rng(0)
    n, s, b = NUM_CLIENTS, STEPS_PER_ROUND, BATCH
    x = rng.normal(size=(n, s, b, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, s, b)).astype(np.int32)

    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    devices = jax.devices()
    if len(devices) > 1 and NUM_CLIENTS % len(devices) == 0:
        from fedtpu.parallel import (
            client_mesh,
            make_sharded_round_step,
            shard_batch,
            shard_state,
        )

        mesh = client_mesh(len(devices), cfg.mesh_axis)
        step = make_sharded_round_step(model, cfg, mesh)
        batch = shard_batch(
            round_lib.RoundBatch(
                x=jnp.asarray(x),
                y=jnp.asarray(y),
                step_mask=jnp.ones((n, s), bool),
                weights=jnp.full((n,), float(s * b), jnp.float32),
                alive=jnp.ones((n,), bool),
            ),
            mesh,
            cfg.mesh_axis,
        )
        state = shard_state(state, mesh, cfg.mesh_axis)
    else:
        step = jax.jit(round_lib.make_round_step(model, cfg), donate_argnums=(0,))
        batch = round_lib.RoundBatch(
            x=jnp.asarray(x),
            y=jnp.asarray(y),
            step_mask=jnp.ones((n, s), bool),
            weights=jnp.full((n,), float(s * b), jnp.float32),
            alive=jnp.ones((n,), bool),
        )

    for _ in range(WARMUP_ROUNDS):
        state, metrics = step(state, batch)
        float(metrics.loss)

    rates = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        for _ in range(TIMED_ROUNDS):
            state, metrics = step(state, batch)
            float(metrics.loss)  # force real execution + host sync every round
        rates.append(TIMED_ROUNDS / (time.perf_counter() - t0))
    rounds_per_sec = sorted(rates)[len(rates) // 2]

    n_dev = len(devices)
    per_chip = rounds_per_sec * NUM_CLIENTS / n_dev
    print(
        json.dumps(
            {
                "metric": "fedavg_client_epochs_per_sec_per_chip_cifar10_cnn_64clients",
                "value": round(per_chip, 3),
                "unit": "client-epochs/sec/chip",
                "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
