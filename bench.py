#!/usr/bin/env python
"""Headline benchmark: FedAvg rounds/sec, CIFAR-10 CNN, 64 simulated clients.

Matches the driver's north-star metric (BASELINE.json): one "round" is the
full reference round semantics — every client does one local epoch of SGD on
its shard (6 batches of 128 at world=64, mirroring ~391/64 batches of the
reference's round-robin split, ``src/main.py:140-144``) followed by the
FedAvg aggregate. The whole round is one XLA program; rounds/sec counts
end-to-end jitted steps including the aggregation.

Normalisation: the 200 rounds/sec north-star target assumes a v4-64 (64
chips, one client per chip), i.e. 200 client-epochs/sec *per chip*. This
bench runs on however many devices are visible (typically ONE chip simulating
all 64 clients), so the reported metric is per-chip client-epoch throughput:
``rounds/sec x num_clients / num_devices``, directly comparable to the
north-star's 200/s-per-chip. ``vs_baseline`` is the ratio to that target
(the reference publishes no numbers of its own — BASELINE.md). The JSON line
also carries the raw ``rounds_per_sec``, ``n_devices``, ``device_kind``,
``flops_per_round`` (XLA cost analysis) and ``mfu`` so the normalisation is
auditable.

Robustness: backend acquisition on the remote-tunnel TPU can wedge (observed:
bare ``jax.devices()`` hanging >120 s), so the measurement runs in a child
process with a bounded timeout and is retried with backoff; on terminal
failure this script STILL prints exactly one JSON line (with an ``error``
field) and exits 0 so the artifact is diagnostic rather than empty.

Timing is honest under the remote-tunnel device: a scalar metric is fetched
to the host every round (async-dispatch pipelines otherwise report absurd
rates because ``block_until_ready`` does not reliably block on the tunnel);
the median of several trials is reported to damp shared-device noise.

Prints exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_CLIENTS = 64
BATCH = 128
STEPS_PER_ROUND = 391 // NUM_CLIENTS  # reference local-epoch share at world=64
WARMUP_ROUNDS = 2
TIMED_ROUNDS = 10
TRIALS = 3
TARGET_PER_CHIP = 200.0  # client-epochs/sec/chip implied by the north star
METRIC = "fedavg_client_epochs_per_sec_per_chip_cifar10_cnn_64clients"
UNIT = "client-epochs/sec/chip"

ATTEMPT_TIMEOUT_S = 1200  # first jit on the tunnel chip can take minutes
ATTEMPTS = 3
BACKOFF_S = 20
# Cheap reachability preflight: a bare jax.devices() against the tunnel
# backend either returns in seconds or wedges forever (observed: >180 s).
# Probing first turns a dead-relay run into a ~10-minute diagnostic instead
# of burning all three 20-minute measurement attempts.
PROBE_TIMEOUT_S = 240
PROBE_ATTEMPTS = 2

# Peak bf16 FLOPs/sec per chip by device kind (public figures), for MFU.
# Aliases cover the PJRT device_kind strings actually observed in the wild
# ("TPU v5 lite", "TPU v5e", "TPU v4", ...), matched on the space-stripped
# lowercase form.
_PEAK_FLOPS = (
    (("v6e", "v6lite", "trillium"), 918e12),
    (("v5p",), 459e12),
    (("v5e", "v5lite"), 197e12),
    (("v4",), 275e12),
    (("v3",), 123e12),
    (("v2",), 45e12),
)


def _peak_for(device_kind: str):
    kind = device_kind.lower().replace(" ", "").replace("-", "")
    for aliases, peak in _PEAK_FLOPS:
        if any(a in kind for a in aliases):
            return peak
    return None


def _measure():
    """Run the actual benchmark in this process and return the result dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu import models
    from fedtpu.core import round as round_lib

    cfg = RoundConfig(
        model="smallcnn",
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="cifar10", batch_size=BATCH),
        fed=FedConfig(num_clients=NUM_CLIENTS),
        steps_per_round=STEPS_PER_ROUND,
        dtype="bfloat16",
    )
    model = models.create(cfg.model, num_classes=cfg.num_classes)

    rng = np.random.default_rng(0)
    n, s, b = NUM_CLIENTS, STEPS_PER_ROUND, BATCH
    x = rng.normal(size=(n, s, b, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, s, b)).astype(np.int32)

    state = round_lib.init_state(
        model, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )
    devices = jax.devices()
    n_dev = len(devices)
    batch = round_lib.RoundBatch(
        x=jnp.asarray(x),
        y=jnp.asarray(y),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.full((n,), float(s * b), jnp.float32),
        alive=jnp.ones((n,), bool),
    )
    if len(devices) > 1 and NUM_CLIENTS % len(devices) == 0:
        from fedtpu.parallel import (
            client_mesh,
            make_sharded_round_step,
            shard_batch,
            shard_state,
        )

        mesh = client_mesh(len(devices), cfg.mesh_axis)
        step = make_sharded_round_step(model, cfg, mesh)
        batch = shard_batch(batch, mesh, cfg.mesh_axis)
        state = shard_state(state, mesh, cfg.mesh_axis)
        flops_per_round = None
    else:
        # Unsharded fallback executes on ONE device regardless of how many
        # are visible — normalise per-chip metrics accordingly.
        n_dev = 1
        jitted = jax.jit(round_lib.make_round_step(model, cfg), donate_argnums=(0,))
        # AOT-compile once and reuse the SAME executable for the timed loop
        # (lower().compile() does not populate jit's dispatch cache, so
        # calling `jitted` afterwards would compile a second time — minutes
        # on the tunnel chip).
        step = jitted.lower(state, batch).compile()
        flops_per_round = None
        try:
            analysis = step.cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            flops_per_round = float(analysis.get("flops", 0.0)) or None
        except Exception:
            pass

    for _ in range(WARMUP_ROUNDS):
        state, metrics = step(state, batch)
        float(metrics.loss)

    rates = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        for _ in range(TIMED_ROUNDS):
            state, metrics = step(state, batch)
            float(metrics.loss)  # force real execution + host sync every round
        rates.append(TIMED_ROUNDS / (time.perf_counter() - t0))
    rounds_per_sec = sorted(rates)[len(rates) // 2]

    device_kind = devices[0].device_kind
    per_chip = rounds_per_sec * NUM_CLIENTS / n_dev
    result = {
        "metric": METRIC,
        "value": round(per_chip, 3),
        "unit": UNIT,
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
        "rounds_per_sec": round(rounds_per_sec, 4),
        "n_devices": n_dev,
        "num_clients": NUM_CLIENTS,
        "device_kind": device_kind,
        "backend": jax.default_backend(),
    }
    if flops_per_round:
        result["flops_per_round"] = flops_per_round
        peak = _peak_for(device_kind)
        if peak:
            result["mfu"] = round(rounds_per_sec * flops_per_round / (n_dev * peak), 4)
    return result


def _salvage_json(text: str):
    """Last line of ``text`` that parses as a JSON object, or None. Guards
    against truncated lines from a killed child being shipped as the
    artifact."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
            except ValueError:
                continue
            return line
    return None


def _backend_reachable():
    """(ok, detail): can a fresh process enumerate devices in bounded time?"""
    probe = (
        "import jax; ds = jax.devices(); "
        "print(len(ds), ds[0].device_kind, jax.default_backend())"
    )
    last = None
    for attempt in range(PROBE_ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            last = f"probe timed out ({PROBE_TIMEOUT_S}s)"
            continue
        if proc.returncode == 0:
            return True, proc.stdout.strip()
        # Fast failure (broken install, plugin init error): report the real
        # cause, not a fictitious timeout.
        last = f"probe rc={proc.returncode}: {proc.stderr.strip()[-800:]}"
    return False, f"{PROBE_ATTEMPTS} attempts; last: {last}"


def main():
    if "--inner" in sys.argv:
        print(json.dumps(_measure()))
        return

    ok, detail = _backend_reachable()
    if not ok:
        print(
            json.dumps(
                {
                    "metric": METRIC,
                    "value": 0.0,
                    "unit": UNIT,
                    "vs_baseline": 0.0,
                    "error": f"backend unreachable: {detail}",
                    "backend": os.environ.get("JAX_PLATFORMS", "default"),
                }
            )
        )
        return

    last_err = "unknown"
    for attempt in range(ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S * attempt)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"],
                capture_output=True,
                text=True,
                timeout=ATTEMPT_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired as exc:
            # The child may have printed its measurement BEFORE wedging in
            # backend/interpreter teardown — salvage it from captured output.
            out = exc.stdout or b""
            line = _salvage_json(out.decode() if isinstance(out, bytes) else out)
            if line:
                print(line)
                return
            last_err = f"attempt {attempt + 1}: timeout after {ATTEMPT_TIMEOUT_S}s"
            continue
        # Accept a printed measurement even on nonzero exit: a backend that
        # segfaults during interpreter teardown (after the JSON was emitted)
        # must not cost two more 20-minute attempts.
        line = _salvage_json(proc.stdout)
        if line:
            print(line)
            return
        last_err = (
            f"attempt {attempt + 1}: rc={proc.returncode}, no JSON: "
            + proc.stderr.strip()[-1500:]
        )
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": UNIT,
                "vs_baseline": 0.0,
                "error": last_err,
                "backend": os.environ.get("JAX_PLATFORMS", "default"),
            }
        )
    )


if __name__ == "__main__":
    main()
